"""telemetry/prom — Prometheus textfile exporter.

The fleet story's scrape target: render every pvar as Prometheus
exposition text — scalars as gauges, ``CLASS_HISTOGRAM`` pvars as
native Prometheus histograms (cumulative ``_bucket{le=...}`` series
with ``+Inf``, ``_sum``, ``_count``) — labeled with ``rank`` /
``comm`` / ``func`` / ``sclass`` where the instrument carries them.

Intended use is the node-exporter *textfile collector*:
``write_textfile(path)`` writes atomically (tmp + rename, the
collector's torn-read contract) on whatever cadence the caller picks;
no HTTP listener, no dependency. Merged multi-rank exposition for the
single-scrape case rides the same renderer over mpitop's snapshot
files (``python -m ompi_tpu.tools.mpitop --format prom``).
"""
from __future__ import annotations

import os
import re
from typing import Any, Dict, Iterable, List, Mapping, Optional

from ompi_tpu.telemetry.hist import bucket_bounds

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
PREFIX = "ompi_tpu_"


def _metric_name(name: str) -> str:
    return PREFIX + _NAME_RE.sub("_", name)


def _labels(label_map: Mapping[str, Any]) -> str:
    items = [(k, str(v)) for k, v in sorted(label_map.items())
             if v is not None and v != ""]
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"' for k, v in items)
    return "{" + body + "}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _render_histogram(name: str, snap: Mapping[str, Any],
                      labels: Mapping[str, Any],
                      lines: List[str], seen: set) -> None:
    metric = _metric_name(name)
    if metric not in seen:
        seen.add(metric)
        lines.append(f"# HELP {metric} {snap.get('unit', 'us')} "
                     f"histogram (ompi_tpu telemetry)")
        lines.append(f"# TYPE {metric} histogram")
    cum = 0
    sparse = {int(k): int(v)
              for k, v in (snap.get("buckets") or {}).items()}
    for i in sorted(sparse):
        cum += sparse[i]
        le = bucket_bounds(i)[1]
        lab = dict(labels)
        lab["le"] = f"{le:g}"
        lines.append(f"{metric}_bucket{_labels(lab)} {cum}")
    lab = dict(labels)
    lab["le"] = "+Inf"
    count = int(snap.get("count", 0))
    lines.append(f"{metric}_bucket{_labels(lab)} {count}")
    lines.append(f"{metric}_sum{_labels(labels)} "
                 f"{float(snap.get('sum', 0.0)):g}")
    lines.append(f"{metric}_count{_labels(labels)} {count}")


def _render_gauge(name: str, value: Any, labels: Mapping[str, Any],
                  lines: List[str], seen: set) -> None:
    try:
        num = float(value)
    except (TypeError, ValueError):
        return                           # non-numeric scalar: skip
    metric = _metric_name(name)
    if metric not in seen:
        seen.add(metric)
        lines.append(f"# TYPE {metric} gauge")
    lines.append(f"{metric}{_labels(labels)} {num:g}")


def render(rank: Optional[int] = None,
           pvars: Optional[Iterable[Mapping[str, Any]]] = None,
           hist_rows: Optional[Iterable[Mapping[str, Any]]] = None
           ) -> str:
    """Exposition text for ONE process's telemetry. With no arguments,
    reads the live pvar surface and histogram registry; merged
    multi-rank rendering passes explicit rows (mpitop's path):
    ``pvars`` rows shaped like ``pvar_list()`` entries, ``hist_rows``
    shaped like ``telemetry.snapshot_hists()`` entries plus ``rank``.
    """
    from ompi_tpu import telemetry as _t
    lines: List[str] = []
    seen: set = set()
    base: Dict[str, Any] = {}
    if rank is None:
        from ompi_tpu import trace as _trace
        rank = _trace.process_rank()
    if rank is not None and int(rank) >= 0:
        base["rank"] = int(rank)

    if hist_rows is None:
        hist_rows = _t.snapshot_hists()
    hist_names = set()
    for row in hist_rows:
        labels = dict(base)
        labels.update(row.get("labels") or {})
        if "rank" in row:
            labels["rank"] = int(row["rank"])
        name = str(row["name"])
        hist_names.add(name)
        # per-comm-per-sclass series share ONE metric family per func:
        # the comm/func/sclass labels carry the dimensions. The suffix
        # is reconstructed from the labels (a left-anchored regex would
        # eat any earlier "_c" in the family name itself)
        family = name
        labs = row.get("labels") or {}
        if labs.get("comm") is not None and labs.get("sclass"):
            from ompi_tpu.telemetry import _cid_token
            suffix = f"_c{_cid_token(labs['comm'])}_{labs['sclass']}"
            if name.endswith(suffix):
                family = name[: -len(suffix)]
        _render_histogram(family, row.get("snap") or {}, labels,
                          lines, seen)

    if pvars is None:
        from ompi_tpu.mca import pvar as _pvar
        try:
            pvars = _pvar.pvar_list()
        except Exception:                # noqa: BLE001 — one raising
            pvars = []                   # read must not kill the scrape
    for ent in pvars:
        name = str(ent.get("name", ""))
        if not name or name in hist_names:
            continue
        if ent.get("class") == "histogram":
            continue                     # rendered from hist_rows
        labels = dict(base)
        if "rank" in ent:
            labels["rank"] = int(ent["rank"])
        val = ent.get("value")
        if isinstance(val, dict):
            # dict-valued pvars (watermark maps): one sample per key
            for k, v in sorted(val.items()):
                _render_gauge(name, v, {**labels, "key": str(k)},
                              lines, seen)
        else:
            _render_gauge(name, val, labels, lines, seen)
    return "\n".join(lines) + "\n" if lines else ""


def write_textfile(path: str, text: Optional[str] = None) -> str:
    """Atomic write for the node-exporter textfile collector (it
    requires rename-into-place — a torn read of a half-written file
    poisons the whole scrape)."""
    if text is None:
        text = render()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
    return path
