"""telemetry/health — the straggler health monitor.

A serving fleet's question is not "did rank 3 die" (the ft detector's
job) but "is rank 3 *slow*, and how slow, right now". This monitor
maintains rolling windows of peer-attributable delay and scores them:

- **recv-wait ingress** (pml recv completion): how long this rank sat
  blocked on each peer. Waits are scored against the cross-peer median
  of the same window — a straggler is an *outlier among peers*, so a
  uniformly slow phase (everyone computing) scores nobody.
- **heartbeat-gap ingress** (ft detector): inter-arrival gap of the
  ring predecessor's heartbeats beyond the configured period — the
  signal that works even when no data-plane traffic flows.

The **straggler score** of a peer is its excess blocked-seconds per
second of window (dimensionless; 0.2 means "this peer cost me 200 ms
of outlier wait per second"). Scores at or above
``mpi_base_telemetry_straggler_score`` make the peer a SUSPECT;
``mpi_base_telemetry_straggler_miss`` consecutive suspect samples
declare it — the ft detector's suspect->declare hysteresis, reused so
a one-off GC pause raises the score and then clears without paging.
Declaration fires the ``telemetry.straggler`` hook event, a trace
instant, and a flight-recorder snapshot; a declared peer whose score
falls below half the threshold is cleared (``telemetry.recovered``)
and may be re-declared later.

``telemetry.degraded`` is the self-health half: fired when this rank's
OWN pml send p99 exceeds ``mpi_base_telemetry_degraded_ms`` — the
"I am the straggler" signal (blocked-waiting is deliberately excluded
from self-slowness, mirroring the attribution layer's blocked vs in-op
split: waiting is the victim's symptom, not the straggler's).

Sampling is driven two ways: a low-priority progress callback (the
stacked/nbc spin loops) and opportunistic rate-limited ticks from the
ingress paths themselves (per-rank blocking waits don't spin the
progress engine) — both funnel into ``sample()``, which also takes a
synthetic clock for the hysteresis unit tests.
"""
from __future__ import annotations

import statistics
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ompi_tpu.mca import var as _var
from ompi_tpu.trace import core as _trace


class HealthMonitor:
    def __init__(self, rank: int, nprocs: int, *,
                 sample_s: Optional[float] = None,
                 window_s: Optional[float] = None,
                 threshold: Optional[float] = None,
                 miss: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        from ompi_tpu import telemetry as _t
        _t.register_params()
        self.rank = int(rank)
        self.nprocs = int(nprocs)
        self.sample_s = (float(_var.var_get("mpi_base_telemetry_sample_s",
                                            0.25))
                         if sample_s is None else float(sample_s))
        self.window_s = (float(_var.var_get("mpi_base_telemetry_window_s",
                                            5.0))
                         if window_s is None else float(window_s))
        self.threshold = (float(_var.var_get(
            "mpi_base_telemetry_straggler_score", 0.05))
            if threshold is None else float(threshold))
        self.miss = (int(_var.var_get("mpi_base_telemetry_straggler_miss",
                                      3))
                     if miss is None else int(miss))
        self.degraded_ms = float(_var.var_get(
            "mpi_base_telemetry_degraded_ms", 0.0))
        self._clock = clock
        self._lock = threading.Lock()
        self._waits: Dict[int, deque] = {}    # peer -> (t, wait_s)
        self._excess: Dict[int, deque] = {}   # peer -> (t, excess_s)
        self._misses: Dict[int, int] = {}
        self._scores: Dict[int, float] = {}
        self._declared: set = set()
        self._degraded = False
        self._last_sample = 0.0
        self.stats = {"samples": 0, "stragglers": 0, "recovered": 0,
                      "degraded": 0}
        self._pvars_registered = False

    # -- ingress (hot paths, gated on telemetry.active by callers) -----
    def note_wait(self, peer: int, wait_s: float) -> None:
        """pml recv completed after ``wait_s`` blocked on ``peer``."""
        if peer == self.rank or peer < 0:
            return
        now = self._clock()
        with self._lock:
            q = self._waits.get(peer)
            if q is None:
                q = self._waits[peer] = deque(maxlen=4096)
            q.append((now, float(wait_s)))
        self.maybe_sample(now)

    def note_heartbeat_gap(self, peer: int, gap_s: float,
                           period_s: float) -> None:
        """Ring heartbeat from ``peer`` arrived ``gap_s`` after the
        previous one; anything beyond 1.5 periods is excess."""
        excess = float(gap_s) - 1.5 * float(period_s)
        if excess <= 0.0 or peer == self.rank:
            return
        now = self._clock()
        with self._lock:
            q = self._excess.get(peer)
            if q is None:
                q = self._excess[peer] = deque(maxlen=4096)
            q.append((now, excess))
        self.maybe_sample(now)

    # -- scoring -------------------------------------------------------
    def maybe_sample(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        if now - self._last_sample >= self.sample_s:
            self.sample(now)

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        for table in (self._waits, self._excess):
            for q in table.values():
                while q and q[0][0] < horizon:
                    q.popleft()

    def sample(self, now: Optional[float] = None) -> Dict[int, float]:
        """One scoring pass; returns the per-peer scores. Separated
        from the progress callback (and clock-injectable) for the
        hysteresis unit tests — the ft detector's check_once shape."""
        now = self._clock() if now is None else now
        declare: List[tuple] = []
        recover: List[tuple] = []
        with self._lock:
            self._last_sample = now
            self.stats["samples"] += 1
            self._prune(now)
            all_waits = [w for q in self._waits.values()
                         for _, w in q]
            med = (statistics.median(all_waits)
                   if len(self._waits) >= 2 and all_waits else 0.0)
            peers = set(self._waits) | set(self._excess)
            scores: Dict[int, float] = {}
            for peer in peers:
                excess = sum(max(0.0, w - med)
                             for _, w in self._waits.get(peer, ()))
                excess += sum(e for _, e in self._excess.get(peer, ()))
                scores[peer] = round(excess / self.window_s, 6)
            self._scores = scores
            for peer, score in scores.items():
                if score >= self.threshold:
                    n = self._misses.get(peer, 0) + 1
                    self._misses[peer] = n
                    if n >= self.miss and peer not in self._declared:
                        self._declared.add(peer)
                        self.stats["stragglers"] += 1
                        declare.append((peer, score))
                else:
                    self._misses[peer] = 0
                    if peer in self._declared \
                            and score < self.threshold / 2.0:
                        self._declared.discard(peer)
                        self.stats["recovered"] += 1
                        recover.append((peer, score))
        for peer, score in declare:
            self._fire("telemetry.straggler", peer, score)
        for peer, score in recover:
            self._fire("telemetry.recovered", peer, score)
        self._check_degraded()
        return scores

    def _fire(self, event: str, peer: int, score: float) -> None:
        from ompi_tpu.utils import hooks as _hooks
        info = {"rank": peer, "by": self.rank, "score": score,
                "threshold": self.threshold}
        _hooks.fire(event, None, info)
        if _trace.active:
            _trace.instant(event, rank=peer, by=self.rank, score=score)
        if event == "telemetry.straggler":
            from ompi_tpu.telemetry import flightrec as _flightrec
            _flightrec.record("straggler", info)

    def _check_degraded(self) -> None:
        if self.degraded_ms <= 0.0:
            return
        from ompi_tpu import telemetry as _t
        own_hist = _t.PML_SEND
        if own_hist is None:
            return
        p99_us = own_hist.percentile(99)
        over = p99_us > self.degraded_ms * 1000.0
        fire = False
        with self._lock:
            if over and not self._degraded:
                self._degraded = True
                self.stats["degraded"] += 1
                fire = True
            elif not over:
                self._degraded = False
        if fire:
            from ompi_tpu.utils import hooks as _hooks
            _hooks.fire("telemetry.degraded", None,
                        {"rank": self.rank, "p99_us": round(p99_us, 1),
                         "limit_ms": self.degraded_ms})

    # -- surfaces ------------------------------------------------------
    def scores(self) -> Dict[int, float]:
        with self._lock:
            return dict(self._scores)

    def declared(self) -> List[int]:
        with self._lock:
            return sorted(self._declared)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"scores": {str(p): s
                               for p, s in self._scores.items()},
                    "declared": sorted(self._declared),
                    "misses": {str(p): n
                               for p, n in self._misses.items() if n},
                    "stats": dict(self.stats)}

    # -- wiring --------------------------------------------------------
    def _progress_cb(self) -> int:
        self.maybe_sample()
        return 0

    def _register_pvars(self) -> None:
        if self._pvars_registered:
            return
        self._pvars_registered = True
        from ompi_tpu.mca import pvar
        pvar.pvar_register(
            "tele_straggler_scores", self.scores,
            unit="ratio", var_class="level",
            help="Per-peer straggler score (excess blocked-seconds per "
                 "second of window; telemetry/health)")
        pvar.pvar_register(
            "tele_stragglers", lambda: self.stats["stragglers"],
            help="telemetry.straggler declarations fired by this "
                 "rank's health monitor")
        pvar.pvar_register(
            "tele_degraded", lambda: self.stats["degraded"],
            help="telemetry.degraded episodes (own pml send p99 over "
                 "mpi_base_telemetry_degraded_ms)")


_monitor: Optional[HealthMonitor] = None


def install(rank: int, nprocs: int, **kw) -> HealthMonitor:
    """Create and wire the process-wide monitor: pvars + a low-priority
    progress callback (ingress paths also tick it — per-rank blocking
    waits don't spin the progress engine)."""
    global _monitor
    uninstall()
    mon = HealthMonitor(rank, nprocs, **kw)
    mon._register_pvars()
    from ompi_tpu.runtime import progress as _progress
    _progress.register(mon._progress_cb, low_priority=True)
    _monitor = mon
    return mon


def uninstall() -> None:
    global _monitor
    mon = _monitor
    if mon is None:
        return
    _monitor = None
    from ompi_tpu.runtime import progress as _progress
    _progress.unregister(mon._progress_cb)


def monitor() -> Optional[HealthMonitor]:
    return _monitor


def note_wait(peer: int, wait_s: float) -> None:
    mon = _monitor
    if mon is not None:
        mon.note_wait(peer, wait_s)


def note_heartbeat_gap(peer: int, gap_s: float, period_s: float) -> None:
    mon = _monitor
    if mon is not None:
        mon.note_heartbeat_gap(peer, gap_s, period_s)


def scores_snapshot() -> Dict[str, Any]:
    mon = _monitor
    return mon.snapshot() if mon is not None else {}
