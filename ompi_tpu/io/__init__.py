from ompi_tpu.io.file import File, MODE_APPEND  # noqa: F401
from ompi_tpu.io.file import (MODE_CREATE, MODE_RDONLY, MODE_RDWR,  # noqa: F401
                              MODE_WRONLY, MODE_EXCL)
