"""MPI-IO for the per-rank execution model — N OS processes, ONE file.

Behavioral spec: ``ompi/mca/io/ompio`` orchestration where it matters
most — genuinely concurrent processes sharing a file:

- independent positioned IO (`MPI_File_read_at/write_at`) = pread/
  pwrite, no coordination (the fbtl/posix role);
- collective IO (`*_at_all`) = TWO-PHASE aggregation (the
  fcoll/dynamic design): ranks ship (offset, bytes) segments to the
  aggregator, which coalesces adjacent runs and issues few large
  writes — the whole point of collective IO on shared filesystems;
- the SHARED FILE POINTER (`sharedfp/sm` role) is a one-slot RMA
  window on rank 0: `write_shared` claims its region with a window
  fetch-and-add, so concurrent appends from different processes land
  disjoint by construction;
- ordered IO (`*_ordered`) = rank-ordered regions from an exscan of
  the contribution sizes on top of the shared pointer.

File views reduce to (displacement, etype) here; the strided-filetype
machinery stays with the single-controller `io/file.py` (the two share
the MODE_* surface).
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ompi_tpu.core import op as op_mod
from ompi_tpu.core.errhandler import ERR_ARG, MPIError
from ompi_tpu.io.file import (MODE_APPEND, MODE_CREATE, MODE_EXCL,
                              MODE_RDONLY, MODE_RDWR, MODE_WRONLY)
from ompi_tpu.osc.perrank import RankWindow

__all__ = ["RankFile", "MODE_RDONLY", "MODE_WRONLY", "MODE_RDWR",
           "MODE_CREATE", "MODE_EXCL", "MODE_APPEND"]


class RankFile:
    """One rank's handle on a collectively-opened file."""

    def __init__(self, comm, path: str,
                 amode: int = MODE_RDWR | MODE_CREATE,
                 etype=np.float64):
        self.comm = comm
        self.path = path
        self.amode = amode
        self.etype = np.dtype(etype)
        self._disp = 0
        # collective open (MPI_File_open): creation races are real
        # across processes — rank 0 creates and BROADCASTS the outcome
        # (a bare barrier would strand the other ranks if the create
        # raised: the collective-hang class) before everyone opens
        err = ""
        if comm.rank() == 0:
            try:
                fd = os.open(path, amode | os.O_CREAT
                             if amode & MODE_CREATE else amode, 0o644)
                os.close(fd)
            except OSError as e:
                err = str(e)
        err = comm.bcast(err, root=0)
        if err:
            raise MPIError(ERR_ARG, f"MPI_File_open: {err}")
        self.fd = os.open(path, amode & ~MODE_EXCL)
        # shared file pointer = one int64 slot on rank 0's window
        # (sharedfp/sm: a shared counter all processes atomically
        # bump); element units, like the reference's etype-relative
        # shared pointer
        self._sp = RankWindow(comm, 1, dtype=np.int64,
                              name=f"sharedfp:{os.path.basename(path)}")
        comm.barrier()

    @classmethod
    def open(cls, comm, path: str,
             amode: int = MODE_RDWR | MODE_CREATE,
             etype=np.float64) -> "RankFile":
        return cls(comm, path, amode, etype)

    # -- view ----------------------------------------------------------
    def set_view(self, disp: int = 0, etype=None) -> None:
        """MPI_File_set_view (displacement in BYTES + etype)."""
        self._disp = int(disp)
        if etype is not None:
            self.etype = np.dtype(etype)

    def get_view(self):
        return self._disp, self.etype

    def _byte_off(self, offset: int) -> int:
        return self._disp + int(offset) * self.etype.itemsize

    # -- sizes ---------------------------------------------------------
    def get_size(self) -> int:
        return os.fstat(self.fd).st_size

    def set_size(self, nbytes: int) -> None:
        """Collective (MPI_File_set_size); entry barrier for the same
        reason as seek_shared — a fast rank's truncate must not
        overtake a slow rank's pre-collective reads."""
        self.comm.barrier()
        if self.comm.rank() == 0:
            os.ftruncate(self.fd, nbytes)
        self.comm.barrier()

    def preallocate(self, nbytes: int) -> None:
        self.comm.barrier()
        if self.comm.rank() == 0 and self.get_size() < nbytes:
            os.ftruncate(self.fd, nbytes)
        self.comm.barrier()

    # -- independent positioned IO (fbtl/posix) ------------------------
    def write_at(self, offset: int, data) -> int:
        arr = np.ascontiguousarray(np.asarray(data, dtype=self.etype))
        os.pwrite(self.fd, arr.tobytes(), self._byte_off(offset))
        return arr.size

    def read_at(self, offset: int, count: int) -> np.ndarray:
        raw = os.pread(self.fd, count * self.etype.itemsize,
                       self._byte_off(offset))
        return np.frombuffer(raw, dtype=self.etype).copy()

    def iwrite_at(self, offset: int, data):
        return self.comm._nb(self.write_at, offset, data)

    def iread_at(self, offset: int, count: int):
        return self.comm._nb(self.read_at, offset, count)

    # -- collective IO: two-phase aggregation (fcoll/dynamic) ----------
    def write_at_all(self, offset: int, data) -> int:
        """Every rank contributes its own (offset, data); the
        aggregator coalesces adjacent byte runs and issues ONE write
        per run — interleaved per-rank patterns become large
        sequential IO (the two-phase optimization)."""
        arr = np.ascontiguousarray(np.asarray(data, dtype=self.etype))
        segs = self.comm.gather((self._byte_off(offset),
                                 arr.tobytes()), root=0)
        if self.comm.rank() == 0:
            for off, blob in self._coalesce(segs):
                os.pwrite(self.fd, blob, off)
            os.fsync(self.fd)
        self.comm.barrier()
        return arr.size

    @staticmethod
    def _coalesce(segs):
        """Sort segments by offset and merge touching/overlapping runs
        (later contributions win overlaps, matching rank order)."""
        runs = []
        for off, blob in sorted(segs, key=lambda s: s[0]):
            if runs and off <= runs[-1][0] + len(runs[-1][1]):
                prev_off, prev = runs[-1]
                cut = off - prev_off
                runs[-1] = (prev_off, prev[:cut] + blob) \
                    if cut + len(blob) >= len(prev) \
                    else (prev_off,
                          prev[:cut] + blob + prev[cut + len(blob):])
            else:
                runs.append((off, blob))
        return runs

    def read_at_all(self, offset: int, count: int) -> np.ndarray:
        """Aggregator reads the whole span once, scatters each rank's
        slice (two-phase read). A span extending past EOF zero-fills
        the tail (a short pread must neither raise on the aggregator —
        stranding the others in the scatter — nor misalign the element
        grid)."""
        my_off = self._byte_off(offset)
        nbytes = count * self.etype.itemsize
        spans = self.comm.allgather((my_off, nbytes))
        chunks = None
        if self.comm.rank() == 0:
            lo = min(s[0] for s in spans)
            hi = max(s[0] + s[1] for s in spans)
            blob = os.pread(self.fd, hi - lo, lo)
            if len(blob) < hi - lo:
                blob = blob + b"\0" * (hi - lo - len(blob))
            chunks = [np.frombuffer(
                blob[s[0] - lo:s[0] - lo + s[1]],
                dtype=self.etype).copy() for s in spans]
        return np.asarray(self.comm.scatter(chunks, root=0))

    # -- shared file pointer (sharedfp/sm over window atomics) ---------
    def write_shared(self, data) -> int:
        arr = np.ascontiguousarray(np.asarray(data, dtype=self.etype))
        start = int(self._sp.fetch_and_op(arr.size, 0, 0, op="sum"))
        os.pwrite(self.fd, arr.tobytes(), self._byte_off(start))
        return start

    def read_shared(self, count: int) -> np.ndarray:
        start = int(self._sp.fetch_and_op(count, 0, 0, op="sum"))
        return self.read_at(start, count)

    def seek_shared(self, offset: int) -> None:
        """Collective per MPI (all ranks same offset). The ENTRY
        barrier matters: every rank's pre-seek shared-pointer reads
        (get_position_shared is NOT collective) must land before the
        write, or a fast rank's seek overwrites the pointer a slow
        rank is still about to read — observed as a real race in
        c24_io_rma's ordered section."""
        self.comm.barrier()
        if self.comm.rank() == 0:
            self._sp.accumulate([offset], 0, 0, op="replace")
        self.comm.barrier()

    def get_position_shared(self) -> int:
        return int(self._sp.fetch_and_op(0, 0, 0, op="no_op"))

    # -- ordered IO (rank-ordered regions over the shared pointer) -----
    def write_ordered(self, data) -> int:
        arr = np.ascontiguousarray(np.asarray(data, dtype=self.etype))
        base = self.get_position_shared()
        before = self.comm.exscan(np.int64(arr.size), op_mod.SUM)
        before = 0 if before is None else int(before)
        os.pwrite(self.fd, arr.tobytes(),
                  self._byte_off(base + before))
        total = int(self.comm.allreduce(np.int64(arr.size), op_mod.SUM))
        self.seek_shared(base + total)
        return base + before

    def read_ordered(self, count: int) -> np.ndarray:
        base = self.get_position_shared()
        before = self.comm.exscan(np.int64(count), op_mod.SUM)
        before = 0 if before is None else int(before)
        out = self.read_at(base + before, count)
        total = int(self.comm.allreduce(np.int64(count), op_mod.SUM))
        self.seek_shared(base + total)
        return out

    # -- completion ----------------------------------------------------
    def sync(self) -> None:
        """MPI_File_sync: flush to storage, then a barrier so every
        rank's writes are visible to every rank's reads."""
        os.fsync(self.fd)
        self.comm.barrier()

    def close(self) -> None:
        """Collective (MPI_File_close)."""
        self.sync()
        os.close(self.fd)
        self._sp.free()

    def delete(self) -> None:
        self.comm.barrier()
        err = ""
        if self.comm.rank() == 0:
            try:
                os.unlink(self.path)
            except OSError as e:
                err = str(e)
        # outcome reaches every rank (a rank-0 raise between barriers
        # would strand the others)
        err = self.comm.bcast(err, root=0)
        if err:
            raise MPIError(ERR_ARG, f"MPI_File_delete: {err}")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
