"""fs framework — filesystem glue (mirrors ``ompi/mca/fs``).

The reference selects a component per file from the mounted filesystem
type (ufs default; lustre/gpfs/ime for parallel filesystems, each with
its own open/resize semantics — e.g. Lustre striping hints). Here
components carry the same query-by-path boundary: the mount table names
the filesystem type, each component claims the types it serves, and ufs
is the always-available fallback — so a Lustre-aware component drops in
without touching the file layer.
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

from ompi_tpu.mca import var
from ompi_tpu.mca.base import Component, register_framework

fs_framework = register_framework("fs")


def _mount_fstype(path: str) -> str:
    """Filesystem type of the mount holding ``path`` (from the mount
    table — the role of the reference's statfs magic checks)."""
    def _unescape(p: str) -> str:
        # /proc/mounts octal-escapes space/tab/newline/backslash
        for esc, ch in (("\\040", " "), ("\\011", "\t"),
                        ("\\012", "\n"), ("\\134", "\\")):
            p = p.replace(esc, ch)
        return p

    try:
        best, fstype = "", ""
        real = os.path.realpath(path)
        with open("/proc/mounts") as f:
            for line in f:
                parts = line.split()
                if len(parts) < 3:
                    continue
                mnt = _unescape(parts[1])
                # path-component boundary: /mnt/lustre must not claim
                # /mnt/lustrebackup
                if (real == mnt or real.startswith(mnt.rstrip("/") + "/")) \
                        and len(mnt) > len(best):
                    best, fstype = mnt, parts[2]
        return fstype
    except OSError:
        return ""


class FsModule:
    """Per-file fs operations (open/resize/sync)."""

    name = "ufs"

    def open(self, path: str, amode: int) -> int:
        return os.open(path, amode, 0o644)

    def resize(self, fd: int, nbytes: int) -> None:
        os.ftruncate(fd, nbytes)

    def sync(self, fd: int) -> None:
        os.fsync(fd)

    def delete(self, path: str) -> None:
        os.unlink(path)


class UfsComponent(Component):
    """Generic Unix filesystem (``ompi/mca/fs/ufs``) — serves any type."""

    name = "ufs"

    def register_params(self) -> None:
        var.var_register("fs", "ufs", "priority", vtype="int", default=10,
                         help="Selection priority of the generic Unix fs")

    def file_query(self, path: str, fstype: str
                   ) -> Optional[Tuple[int, FsModule]]:
        return (var.var_get("fs_ufs_priority", 10), FsModule())

    def comm_query(self, comm):                 # fs selects per file
        return None


class _ParallelFsComponent(Component):
    """Base for parallel-fs components: claims only its fstype(s) at a
    priority above ufs (the reference's lustre/gpfs pattern)."""

    fstypes: Tuple[str, ...] = ()

    def file_query(self, path: str, fstype: str
                   ) -> Optional[Tuple[int, FsModule]]:
        if fstype not in self.fstypes:
            return None
        m = FsModule()
        m.name = self.name
        return (50, m)

    def comm_query(self, comm):
        return None


class LustreComponent(_ParallelFsComponent):
    name = "lustre"
    fstypes = ("lustre",)


class GpfsComponent(_ParallelFsComponent):
    name = "gpfs"
    fstypes = ("gpfs",)


fs_framework.register(UfsComponent())
fs_framework.register(LustreComponent())
fs_framework.register(GpfsComponent())


def select_fs(path: str) -> FsModule:
    """Pick the highest-priority fs module for ``path`` (the per-file
    analogue of comm_select)."""
    fs_framework.open()
    fstype = _mount_fstype(path)
    best: Optional[Tuple[int, FsModule]] = None
    # honor fs_base_include like every comm-scoped framework does
    for comp in fs_framework._allowed():
        res = comp.file_query(path, fstype)
        if res is not None and (best is None or res[0] > best[0]):
            best = res
    if best is None:                 # include list excluded even ufs
        return FsModule()
    return best[1]
