"""fcoll framework — collective IO algorithms (``ompi/mca/fcoll``).

Reference components: *individual* (no aggregation — every rank issues
its own requests), *dynamic* / *dynamic_gen2* / *vulcan* (two-phase IO:
ranks exchange data so a few aggregators issue large contiguous
filesystem requests; vulcan fixes the aggregator count and domain
assignment up front).

TPU-native re-design: the controller already holds every rank's stacked
buffer, so phase one (the data exchange) is a host-side merge of
per-rank (offset, data) interleavings, and phase two is the aggregated
write. What remains honest — and measurable — is the *aggregation
policy*: `individual` writes each rank's runs separately (many small
syscalls when the view interleaves ranks), the two-phase components
merge-sort all ranks' element offsets, coalesce adjacent runs across
ranks, and split the result into aggregator domains issuing one vectored
request each. Selection via MCA var ``io_fcoll`` (dynamic default),
mirroring ``--mca fcoll vulcan``.
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ompi_tpu.mca import var
from ompi_tpu.io.fbtl import PosixFbtl, elem_runs_to_bytes


def _coalesce(offs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    from ompi_tpu.core.datatype import coalesce_runs
    return coalesce_runs(offs)


class IndividualFcoll:
    """No aggregation: one request stream per rank
    (``fcoll/individual``)."""

    name = "individual"

    def __init__(self, fbtl: PosixFbtl):
        self.fbtl = fbtl

    def write(self, fd: int, per_rank: List[Tuple[np.ndarray, np.ndarray]],
              ebytes: int) -> int:
        written = 0
        for offs, data in per_rank:
            starts, lens = _coalesce(offs)
            runs = elem_runs_to_bytes(starts, lens, ebytes)
            written += self.fbtl.pwritev_runs(fd, runs, data.tobytes())
        return written // ebytes

    def read(self, fd: int, per_rank_offs: List[np.ndarray],
             dtype: np.dtype) -> List[np.ndarray]:
        out = []
        for offs in per_rank_offs:
            starts, lens = _coalesce(offs)
            runs = elem_runs_to_bytes(starts, lens, dtype.itemsize)
            raw = self.fbtl.preadv_runs(fd, runs)
            out.append(np.frombuffer(raw, dtype, count=offs.size))
        return out


class TwoPhaseFcoll:
    """Two-phase aggregation (``fcoll/dynamic`` family): merge every
    rank's element offsets, coalesce across ranks, split into
    aggregator domains, one vectored request per domain."""

    name = "dynamic"

    def __init__(self, fbtl: PosixFbtl, n_aggregators: int = 1):
        self.fbtl = fbtl
        self.n_agg = max(1, n_aggregators)

    def _merge(self, per_rank: List[Tuple[np.ndarray, np.ndarray]]
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Phase one: the exchange. Produces (sorted element offsets,
        data reordered to match). Later ranks win offset collisions
        (MPI's unordered-conflict semantics made deterministic)."""
        offs = np.concatenate([o for o, _d in per_rank])
        data = np.concatenate([np.asarray(d).ravel()
                               for _o, d in per_rank])
        order = np.argsort(offs, kind="stable")
        return offs[order], data[order]

    def _domains(self, starts: np.ndarray, lens: np.ndarray
                 ) -> List[slice]:
        """Split coalesced runs into ~equal-bytes aggregator domains
        (vulcan's fixed assignment when n_agg is fixed)."""
        if len(starts) <= 1 or self.n_agg == 1:
            return [slice(0, len(starts))]
        csum = np.cumsum(lens)
        total = int(csum[-1])
        bounds = [0]
        for a in range(1, self.n_agg):
            target = total * a // self.n_agg
            bounds.append(int(np.searchsorted(csum, target)))
        bounds.append(len(starts))
        return [slice(bounds[i], bounds[i + 1])
                for i in range(len(bounds) - 1)
                if bounds[i] < bounds[i + 1]]

    def write(self, fd: int, per_rank: List[Tuple[np.ndarray, np.ndarray]],
              ebytes: int) -> int:
        offs, data = self._merge(per_rank)
        starts, lens = _coalesce(offs)
        payload = data.tobytes()
        written = 0
        pos = 0
        run_bytes = elem_runs_to_bytes(starts, lens, ebytes)
        for dom in self._domains(starts, lens):
            runs = run_bytes[dom]
            nbytes = sum(r[1] for r in runs)
            written += self.fbtl.pwritev_runs(
                fd, runs, payload[pos:pos + nbytes])
            pos += nbytes
        return written // ebytes

    def read(self, fd: int, per_rank_offs: List[np.ndarray],
             dtype: np.dtype) -> List[np.ndarray]:
        offs = np.concatenate(per_rank_offs)
        order = np.argsort(offs, kind="stable")
        starts, lens = _coalesce(offs[order])
        raw = bytearray()
        run_bytes = elem_runs_to_bytes(starts, lens, dtype.itemsize)
        for dom in self._domains(starts, lens):
            raw += self.fbtl.preadv_runs(fd, run_bytes[dom])
        merged = np.frombuffer(bytes(raw), dtype, count=offs.size)
        # scatter back to per-rank order (phase one, reversed)
        unsorted = np.empty_like(merged)
        unsorted[order] = merged
        out, pos = [], 0
        for o in per_rank_offs:
            out.append(unsorted[pos:pos + o.size])
            pos += o.size
        return out


class VulcanFcoll(TwoPhaseFcoll):
    """``fcoll/vulcan``: the two-phase engine with a fixed aggregator
    count (MCA var ``io_vulcan_aggregators``)."""

    name = "vulcan"

    def __init__(self, fbtl: PosixFbtl):
        super().__init__(fbtl, var.var_get("io_vulcan_aggregators", 4))


var.var_register("io", "base", "fcoll", vtype="str", default="dynamic",
                 help="Collective IO algorithm: "
                      "individual | dynamic | vulcan")
var.var_register("io", "vulcan", "aggregators", vtype="int", default=4,
                 help="Aggregator count for fcoll/vulcan")


def select_fcoll(fbtl: PosixFbtl):
    """Component selection for collective IO (``--mca fcoll X``)."""
    name = (var.var_get("io_base_fcoll", "dynamic") or "dynamic").strip()
    if name == "individual":
        return IndividualFcoll(fbtl)
    if name == "vulcan":
        return VulcanFcoll(fbtl)
    return TwoPhaseFcoll(fbtl)
