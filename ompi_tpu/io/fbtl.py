"""fbtl framework — individual file byte transfer (``ompi/mca/fbtl``).

The reference's fbtl/posix issues pread/pwrite per iovec entry; here the
run lists produced by the datatype index maps go through ``preadv`` /
``pwritev`` so one syscall covers many noncontiguous runs (the iovec
batching fbtl exists for).
"""
from __future__ import annotations

import os
from typing import List, Tuple

import numpy as np

_IOV_MAX = 1024


class PosixFbtl:
    """Vectored positioned IO over an fd. Runs are (byte_off, nbytes)."""

    name = "posix"

    def pwritev_runs(self, fd: int, runs: List[Tuple[int, int]],
                     payload: bytes) -> int:
        """Write ``payload`` split across ``runs``. Adjacent file runs
        are batched per contiguous file region (pwritev needs one file
        offset per call, so batching applies to the buffer side: one
        memoryview slice per run, one syscall per file-contiguous
        stretch)."""
        written = 0
        pos = 0
        mv = memoryview(payload)
        i = 0
        while i < len(runs):
            off, ln = runs[i]
            # widen across file-adjacent runs
            j = i + 1
            total = ln
            while j < len(runs) and runs[j][0] == off + total \
                    and j - i < _IOV_MAX:
                total += runs[j][1]
                j += 1
            written += os.pwrite(fd, mv[pos:pos + total], off)
            pos += total
            i = j
        return written

    def preadv_runs(self, fd: int, runs: List[Tuple[int, int]]
                    ) -> bytes:
        out = bytearray()
        i = 0
        while i < len(runs):
            off, ln = runs[i]
            j = i + 1
            total = ln
            while j < len(runs) and runs[j][0] == off + total \
                    and j - i < _IOV_MAX:
                total += runs[j][1]
                j += 1
            chunk = os.pread(fd, total, off)
            if len(chunk) < total:               # short read past EOF
                chunk = chunk + b"\0" * (total - len(chunk))
            out += chunk
            i = j
        return bytes(out)


def elem_runs_to_bytes(starts: np.ndarray, lens: np.ndarray,
                       ebytes: int) -> List[Tuple[int, int]]:
    return [(int(s) * ebytes, int(l) * ebytes)
            for s, l in zip(starts, lens)]
