"""fbtl framework — individual file byte transfer (``ompi/mca/fbtl``).

The reference's fbtl/posix issues one positioned request per iovec
entry. Runs arrive here already coalesced (``coalesce_runs`` merged
adjacent element offsets upstream in the datatype/fcoll layers), so the
transfer loop is one ``pread``/``pwrite`` per *disjoint* file run — the
minimal syscall count for the access pattern.
"""
from __future__ import annotations

import os
from typing import List, Tuple

import numpy as np


class PosixFbtl:
    """Positioned IO over an fd. Runs are (byte_off, nbytes), disjoint
    and sorted (the upstream coalescer's contract)."""

    name = "posix"

    def pwritev_runs(self, fd: int, runs: List[Tuple[int, int]],
                     payload: bytes) -> int:
        written = 0
        pos = 0
        mv = memoryview(payload)
        for off, ln in runs:
            written += os.pwrite(fd, mv[pos:pos + ln], off)
            pos += ln
        return written

    def preadv_runs(self, fd: int, runs: List[Tuple[int, int]]) -> bytes:
        out = bytearray()
        for off, ln in runs:
            chunk = os.pread(fd, ln, off)
            if len(chunk) < ln:                  # short read past EOF
                chunk = chunk + b"\0" * (ln - len(chunk))
            out += chunk
        return bytes(out)


def elem_runs_to_bytes(starts: np.ndarray, lens: np.ndarray,
                       ebytes: int) -> List[Tuple[int, int]]:
    return [(int(s) * ebytes, int(l) * ebytes)
            for s, l in zip(starts, lens)]
