"""sharedfp framework — shared file pointer (``ompi/mca/sharedfp``).

Reference components: *sm* (pointer in a shared-memory segment),
*lockedfile* (pointer in a sidecar file advanced under fcntl locks —
works across unrelated processes), *individual* (no shared pointer at
all: each process logs its writes locally with timestamps and the logs
are merged into the file in timestamp order at close/sync).

All three are real here: sm is the in-process pointer (controller
threads), lockedfile persists the pointer beside the file under an OS
file lock (two controller processes on one host coordinate through it),
individual defers ordering until sync exactly like the reference.
"""
from __future__ import annotations

import os
import struct
import threading
import time
from typing import List, Optional, Tuple

import numpy as np


class SmSharedfp:
    """In-process shared pointer under a lock (``sharedfp/sm``)."""

    name = "sm"

    def __init__(self, path: str):
        self._off = 0
        self._lock = threading.Lock()

    def fetch_add(self, nelems: int) -> int:
        with self._lock:
            off = self._off
            self._off += nelems
            return off

    def seek(self, offset: int) -> None:
        with self._lock:
            self._off = offset

    def get(self) -> int:
        return self._off

    def close(self) -> None:
        pass


class LockedFileSharedfp:
    """Pointer in a sidecar file under fcntl.flock
    (``sharedfp/lockedfile``): any process opening the same file shares
    the pointer through the filesystem."""

    name = "lockedfile"

    def __init__(self, path: str):
        self._path = path + ".sharedfp"
        self._fd = os.open(self._path, os.O_RDWR | os.O_CREAT, 0o644)
        # initialize under the same lock fetch_add takes — an unlocked
        # check-and-write could reset a pointer another process already
        # advanced (init racing its fetch_add)
        def init():
            if os.fstat(self._fd).st_size < 8:
                os.pwrite(self._fd, struct.pack("<q", 0), 0)
        self._locked(init)

    def _locked(self, fn):
        import fcntl
        fcntl.flock(self._fd, fcntl.LOCK_EX)
        try:
            return fn()
        finally:
            fcntl.flock(self._fd, fcntl.LOCK_UN)

    def fetch_add(self, nelems: int) -> int:
        def op():
            off = struct.unpack("<q", os.pread(self._fd, 8, 0))[0]
            os.pwrite(self._fd, struct.pack("<q", off + nelems), 0)
            return off
        return self._locked(op)

    def seek(self, offset: int) -> None:
        self._locked(lambda: os.pwrite(self._fd,
                                       struct.pack("<q", offset), 0))

    def get(self) -> int:
        return self._locked(
            lambda: struct.unpack("<q", os.pread(self._fd, 8, 0))[0])

    def close(self) -> None:
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1
            try:
                os.unlink(self._path)
            except OSError:
                pass


class IndividualSharedfp:
    """No live shared pointer (``sharedfp/individual``): writes are
    logged with timestamps and sequenced into file offsets at sync, in
    global timestamp order."""

    name = "individual"

    def __init__(self, path: str):
        self._log: List[Tuple[float, np.ndarray]] = []
        self._lock = threading.Lock()
        self._base = 0

    def log_write(self, arr: np.ndarray) -> None:
        with self._lock:
            self._log.append((time.monotonic(), arr.copy()))

    def drain(self) -> List[Tuple[int, np.ndarray]]:
        """Assign offsets in timestamp order; returns (offset, data)
        pairs and advances the base pointer."""
        with self._lock:
            entries = sorted(self._log, key=lambda e: e[0])
            self._log.clear()
            out = []
            off = self._base
            for _ts, arr in entries:
                out.append((off, arr))
                off += arr.size
            self._base = off
            return out

    # the shared pointer is only defined at sync boundaries
    def fetch_add(self, nelems: int) -> int:
        raise RuntimeError("sharedfp/individual has no live pointer; "
                           "writes are ordered at sync")

    def seek(self, offset: int) -> None:
        with self._lock:
            self._base = offset

    def get(self) -> int:
        return self._base

    def close(self) -> None:
        pass


from ompi_tpu.mca import var  # noqa: E402

var.var_register("io", "base", "sharedfp", vtype="str", default="sm",
                 help="Shared-file-pointer component: "
                      "sm | lockedfile | individual")


def select_sharedfp(path: str):
    name = (var.var_get("io_base_sharedfp", "sm") or "sm").strip()
    if name == "lockedfile":
        return LockedFileSharedfp(path)
    if name == "individual":
        return IndividualSharedfp(path)
    return SmSharedfp(path)
