"""Checkpoint / resume — application-level checkpointing.

The reference dropped transparent (BLCR) checkpointing after v1.6; its
modern story is application-level checkpointing + ULFM recovery
(``docs/tuning-apps/fault-tolerance/checkpoint-restart.rst:25-27``).
This module is that story made concrete for the TPU runtime: save and
restore communicator-distributed state (stacked device buffers, pytrees
of arrays) atomically, so a job revoked/shrunk via the ULFM-lite path
can resume. Orbax is used when available (async, fsspec-capable);
otherwise a plain NumPy .npz fallback.
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, Dict, Optional

import jax
import numpy as np


def _flatten(tree: Any):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, treedef


def save(path: str, state: Any, *, step: Optional[int] = None) -> None:
    """Atomically checkpoint ``state`` (a pytree of arrays — device
    buffers are fetched D2H) to directory ``path``."""
    leaves, treedef = _flatten(state)
    tmp = tempfile.mkdtemp(dir=os.path.dirname(os.path.abspath(path))
                           or ".")
    try:
        np.savez(os.path.join(tmp, "leaves.npz"),
                 **{f"l{i}": np.asarray(x) for i, x in enumerate(leaves)})
        meta = {"n_leaves": len(leaves), "step": step,
                "treedef": str(treedef)}
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        # Crash-safe publish: the previous checkpoint is parked at
        # ``<path>.old`` until the new one is in place — at no instant
        # is there zero recoverable checkpoint on disk (restore() falls
        # back to .old).
        old = path + ".old"
        if os.path.isdir(old):
            shutil.rmtree(old)
        if os.path.isdir(path):
            os.replace(path, old)
        os.replace(tmp, path)
        shutil.rmtree(old, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def restore(path: str, like: Any, *, comm=None) -> Any:
    """Restore a checkpoint into the structure of ``like``; stacked
    buffers are re-placed onto ``comm``'s mesh when given. Falls back to
    ``<path>.old`` if a crash interrupted the last save's publish."""
    if not os.path.isdir(path) and os.path.isdir(path + ".old"):
        path = path + ".old"
    leaves, treedef = _flatten(like)
    with np.load(os.path.join(path, "leaves.npz")) as data:
        loaded = [data[f"l{i}"] for i in range(len(leaves))]
    out = jax.tree_util.tree_unflatten(treedef, loaded)
    if comm is not None:
        out = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, comm.sharding)
            if (hasattr(x, "ndim") and x.ndim >= 1
                and x.shape[0] == comm.size) else x, out)
    return out


def latest_step(path: str) -> Optional[int]:
    try:
        with open(os.path.join(path, "meta.json")) as f:
            return json.load(f).get("step")
    except (OSError, ValueError):
        return None
