"""MPI-IO — parallel file I/O (mirrors ``ompi/mca/io/ompio`` +
``ompi/mca/common/ompio`` orchestration over real sub-frameworks):

- fs    (``io/fs.py``)       — filesystem glue selected per file from
  the mount table (ufs fallback; lustre/gpfs claim their types).
- fbtl  (``io/fbtl.py``)     — individual byte transfer: vectored
  positioned IO batching noncontiguous runs.
- fcoll (``io/fcoll.py``)    — collective algorithms: individual /
  two-phase dynamic / vulcan aggregation, selected by MCA var.
- sharedfp (``io/sharedfp.py``) — shared file pointer: sm / lockedfile /
  individual components.

File views (etype + filetype displacement maps) reuse the datatype
engine's index maps, so a strided view is the same object as a derived
datatype (``opal/datatype`` heritage).
"""
from __future__ import annotations

import os
import threading
from typing import Optional

import numpy as np

from ompi_tpu.accelerator import to_host
from ompi_tpu.core.datatype import Datatype
from ompi_tpu.core.errhandler import ERR_ARG, MPIError
from ompi_tpu.core.request import Request
from ompi_tpu.io.fbtl import PosixFbtl, elem_runs_to_bytes
from ompi_tpu.io.fcoll import select_fcoll
from ompi_tpu.io.fs import select_fs
from ompi_tpu.io.sharedfp import IndividualSharedfp, select_sharedfp

MODE_RDONLY = os.O_RDONLY
MODE_WRONLY = os.O_WRONLY
MODE_RDWR = os.O_RDWR
MODE_CREATE = os.O_CREAT
MODE_EXCL = os.O_EXCL
MODE_APPEND = os.O_APPEND


class File:
    """An MPI file handle over a communicator."""

    def __init__(self, comm, path: str, amode: int = MODE_RDWR | MODE_CREATE,
                 etype: Optional[np.dtype] = None):
        self.comm = comm
        self.path = path
        self.amode = amode
        self.etype = np.dtype(etype or np.uint8)
        self.fs = select_fs(path)
        self.fbtl = PosixFbtl()
        self.fcoll = select_fcoll(self.fbtl)
        self.sharedfp = select_sharedfp(path)
        self._fd = self.fs.open(path, amode)
        self._lock = threading.RLock()
        self._view_disp = 0                  # view displacement, elements
        self._view_type: Optional[Datatype] = None
        self.atomicity = False

    @classmethod
    def open(cls, comm, path: str,
             amode: int = MODE_RDWR | MODE_CREATE) -> "File":
        return cls(comm, path, amode)

    # -- geometry -------------------------------------------------------
    def _ebytes(self) -> int:
        return self.etype.itemsize

    def get_size(self) -> int:
        return os.fstat(self._fd).st_size // self._ebytes()

    def set_size(self, nelems: int) -> None:
        self.fs.resize(self._fd, nelems * self._ebytes())

    def preallocate(self, nelems: int) -> None:
        if self.get_size() < nelems:
            self.set_size(nelems)

    def get_amode(self) -> int:
        return self.amode

    def get_group(self):
        return self.comm.group

    # -- views (MPI_File_set_view) -------------------------------------
    def set_view(self, disp: int = 0, etype=None,
                 filetype: Optional[Datatype] = None) -> None:
        """disp in elements of ``etype``; ``filetype`` selects visible
        elements per extent window (the datatype engine's index map)."""
        if etype is not None:
            self.etype = np.dtype(etype if not isinstance(etype, Datatype)
                                  else etype.base)
        self._view_disp = int(disp)
        self._view_type = filetype

    def get_view(self):
        return self._view_disp, self.etype, self._view_type

    def _map_offset(self, offset: int, count: int) -> np.ndarray:
        """Element file-offsets for ``count`` elements starting at view
        element ``offset`` (applying the filetype's index map)."""
        if self._view_type is None:
            return np.arange(offset, offset + count) + self._view_disp
        ft = self._view_type
        inst0, within = divmod(offset, ft.count)
        n_inst = -(-(within + count) // ft.count)
        idx = ft.flat_indices(inst0 + n_inst)[inst0 * ft.count:]
        return idx[within:within + count] + self._view_disp

    # -- individual I/O (fbtl role) ------------------------------------
    def _runs_bytes(self, offs: np.ndarray):
        from ompi_tpu.core.datatype import coalesce_runs
        starts, lens = coalesce_runs(offs)
        return elem_runs_to_bytes(starts, lens, self._ebytes())

    def write_at(self, offset: int, data) -> int:
        """Write ``data`` (any array; device buffers are fetched D2H by
        the accelerator framework) at view offset (elements)."""
        arr = np.ascontiguousarray(to_host(data)).astype(self.etype,
                                                         copy=False).ravel()
        offs = self._map_offset(offset, arr.size)
        with self._lock:
            self.fbtl.pwritev_runs(self._fd, self._runs_bytes(offs),
                                   arr.tobytes())
        return arr.size

    def read_at(self, offset: int, count: int) -> np.ndarray:
        offs = self._map_offset(offset, count)
        with self._lock:
            raw = self.fbtl.preadv_runs(self._fd, self._runs_bytes(offs))
        return np.frombuffer(raw, self.etype, count=count).copy()

    # -- nonblocking ----------------------------------------------------
    def iwrite_at(self, offset: int, data) -> Request:
        return Request.completed(self.write_at(offset, data))

    def iread_at(self, offset: int, count: int) -> Request:
        return Request.completed(self.read_at(offset, count))

    # -- collective I/O (fcoll role) -----------------------------------
    def _per_rank_io(self, offset: int, host: np.ndarray):
        """Per-rank (element offsets, data) with each rank's block at
        ``offset + r*block`` of the view — the interleaving the fcoll
        aggregation policies operate on."""
        n = self.comm.size
        block = int(np.prod(host.shape[1:])) if host.ndim > 1 else 1
        out = []
        for r in range(n):
            offs = self._map_offset(offset + r * block, block)
            out.append((offs, np.ascontiguousarray(host[r]).astype(
                self.etype, copy=False).ravel()))
        return out

    def write_at_all(self, offset: int, stacked) -> int:
        """Collective write: rank r's block (stacked axis 0) lands at
        view offset ``offset + r*block``, aggregated by the selected
        fcoll component."""
        host = np.asarray(to_host(stacked))
        if host.shape[0] != self.comm.size:
            raise MPIError(ERR_ARG, "stacked buffer must have one block "
                                    "per rank")
        with self._lock:
            return self.fcoll.write(self._fd,
                                    self._per_rank_io(offset, host),
                                    self._ebytes())

    def read_at_all(self, offset: int, count_per_rank: int) -> np.ndarray:
        """Collective read: returns stacked (nranks, count_per_rank)."""
        n = self.comm.size
        per_rank = [self._map_offset(offset + r * count_per_rank,
                                     count_per_rank) for r in range(n)]
        with self._lock:
            chunks = self.fcoll.read(self._fd, per_rank, self.etype)
        return np.stack([c.reshape(count_per_rank) for c in chunks])

    def iwrite_at_all(self, offset: int, stacked) -> Request:
        return Request.completed(self.write_at_all(offset, stacked))

    def iread_at_all(self, offset: int, count_per_rank: int) -> Request:
        return Request.completed(self.read_at_all(offset, count_per_rank))

    # -- shared file pointer (sharedfp role) ---------------------------
    def write_shared(self, data) -> int:
        arr = np.ascontiguousarray(to_host(data)).astype(
            self.etype, copy=False).ravel()
        if isinstance(self.sharedfp, IndividualSharedfp):
            self.sharedfp.log_write(arr)      # ordered at sync
            return arr.size
        off = self.sharedfp.fetch_add(arr.size)
        return self.write_at(off, arr)

    def read_shared(self, count: int) -> np.ndarray:
        off = self.sharedfp.fetch_add(count)
        return self.read_at(off, count)

    def seek_shared(self, offset: int) -> None:
        self.sharedfp.seek(offset)

    def get_position_shared(self) -> int:
        return self.sharedfp.get()

    def write_ordered(self, stacked) -> int:
        """MPI_File_write_ordered: collective; rank r's block lands at
        the shared pointer after ranks < r, pointer advances by the
        total."""
        host = np.asarray(to_host(stacked))
        if host.shape[0] != self.comm.size:
            raise MPIError(ERR_ARG, "stacked buffer must have one block "
                                    "per rank")
        flat = np.ascontiguousarray(host).astype(self.etype,
                                                 copy=False)
        total = int(flat.size)
        if isinstance(self.sharedfp, IndividualSharedfp):
            self.sharedfp.log_write(flat.ravel())
            return total
        off = self.sharedfp.fetch_add(total)
        return self.write_at(off, flat.ravel())

    def read_ordered(self, count_per_rank: int) -> np.ndarray:
        n = self.comm.size
        off = self.sharedfp.fetch_add(count_per_rank * n)
        flat = self.read_at(off, count_per_rank * n)
        return flat.reshape(n, count_per_rank)

    # -- sync/close ----------------------------------------------------
    def sync(self) -> None:
        if isinstance(self.sharedfp, IndividualSharedfp):
            for off, arr in self.sharedfp.drain():
                self.write_at(off, arr)
        self.fs.sync(self._fd)

    def close(self) -> None:
        if self._fd >= 0:
            self.sync()
            self.sharedfp.close()
            os.close(self._fd)
            self._fd = -1

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
