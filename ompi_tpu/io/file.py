"""MPI-IO — parallel file I/O (mirrors ``ompi/mca/io/ompio`` +
``ompi/mca/common/ompio`` orchestration, with the sub-framework roles
collapsed where the TPU runtime makes them trivial):

- fs (filesystem glue: ufs/lustre/gpfs)  -> plain POSIX here; the locus
  that matters on TPU hosts is HBM<->host, handled by the accelerator
  framework before bytes reach the filesystem.
- fbtl (individual byte transfer: posix) -> ``pread``/``pwrite`` on the
  shared file descriptor, offsets in elements x etype.
- fcoll (collective algorithms: two-phase dynamic/vulcan) ->
  ``write_at_all``/``read_at_all`` aggregate the stacked rank buffers in
  the controller (which *is* the aggregator — the two-phase exchange
  degenerates to one gather/scatter over the mesh) and issue one large
  contiguous request, the same optimization two-phase IO exists for.
- sharedfp (shared file pointer: sm/lockedfile) -> a controller-side
  shared offset under a lock.

File views (etype + filetype displacement maps) reuse the datatype
engine's index maps, so a strided view is the same object as a derived
datatype (``opal/datatype`` heritage).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Optional

import numpy as np

from ompi_tpu.accelerator import to_host
from ompi_tpu.core.datatype import Datatype
from ompi_tpu.core.errhandler import ERR_ARG, MPIError
from ompi_tpu.core.request import Request

MODE_RDONLY = os.O_RDONLY
MODE_WRONLY = os.O_WRONLY
MODE_RDWR = os.O_RDWR
MODE_CREATE = os.O_CREAT
MODE_EXCL = os.O_EXCL
MODE_APPEND = os.O_APPEND


class File:
    """An MPI file handle over a communicator."""

    def __init__(self, comm, path: str, amode: int = MODE_RDWR | MODE_CREATE,
                 etype: Optional[np.dtype] = None):
        self.comm = comm
        self.path = path
        self.amode = amode
        self.etype = np.dtype(etype or np.uint8)
        self._fd = os.open(path, amode, 0o644)
        self._lock = threading.RLock()
        self._shared_ptr = 0                 # sharedfp: element offset
        self._view_disp = 0                  # view displacement, elements
        self._view_type: Optional[Datatype] = None
        self.atomicity = False

    @classmethod
    def open(cls, comm, path: str,
             amode: int = MODE_RDWR | MODE_CREATE) -> "File":
        return cls(comm, path, amode)

    # -- geometry -------------------------------------------------------
    def _ebytes(self) -> int:
        return self.etype.itemsize

    def get_size(self) -> int:
        return os.fstat(self._fd).st_size // self._ebytes()

    def set_size(self, nelems: int) -> None:
        os.ftruncate(self._fd, nelems * self._ebytes())

    def preallocate(self, nelems: int) -> None:
        if self.get_size() < nelems:
            self.set_size(nelems)

    # -- views (MPI_File_set_view) -------------------------------------
    def set_view(self, disp: int = 0, etype=None,
                 filetype: Optional[Datatype] = None) -> None:
        """disp in elements of ``etype``; ``filetype`` selects visible
        elements per extent window (the datatype engine's index map)."""
        if etype is not None:
            self.etype = np.dtype(etype if not isinstance(etype, Datatype)
                                  else etype.base)
        self._view_disp = int(disp)
        self._view_type = filetype

    def _map_offset(self, offset: int, count: int) -> np.ndarray:
        """Element file-offsets for ``count`` elements starting at view
        element ``offset`` (applying the filetype's index map)."""
        if self._view_type is None:
            return np.arange(offset, offset + count) + self._view_disp
        ft = self._view_type
        inst0, within = divmod(offset, ft.count)
        n_inst = -(-(within + count) // ft.count)
        idx = ft.flat_indices(inst0 + n_inst)[inst0 * ft.count:]
        return idx[within:within + count] + self._view_disp

    # -- individual I/O (fbtl/posix role) ------------------------------
    def write_at(self, offset: int, data) -> int:
        """Write ``data`` (any array; device buffers are fetched D2H by
        the accelerator framework) at view offset (elements)."""
        arr = np.ascontiguousarray(to_host(data)).astype(self.etype,
                                                         copy=False).ravel()
        offs = self._map_offset(offset, arr.size)
        with self._lock:
            return self._pwrite_elems(offs, arr)

    def read_at(self, offset: int, count: int) -> np.ndarray:
        offs = self._map_offset(offset, count)
        with self._lock:
            return self._pread_elems(offs)

    def _runs(self, offs: np.ndarray):
        from ompi_tpu.core.datatype import coalesce_runs
        starts, lens = coalesce_runs(offs)
        return list(zip(starts.tolist(), lens.tolist()))

    def _pwrite_elems(self, offs: np.ndarray, arr: np.ndarray) -> int:
        eb = self._ebytes()
        pos = 0
        for off, ln in self._runs(offs):
            os.pwrite(self._fd, arr[pos:pos + ln].tobytes(), off * eb)
            pos += ln
        return arr.size

    def _pread_elems(self, offs: np.ndarray) -> np.ndarray:
        eb = self._ebytes()
        out = np.empty(offs.size, self.etype)
        pos = 0
        for off, ln in self._runs(offs):
            raw = os.pread(self._fd, ln * eb, off * eb)
            out[pos:pos + ln] = np.frombuffer(raw, self.etype, count=ln)
            pos += ln
        return out

    # -- nonblocking ----------------------------------------------------
    def iwrite_at(self, offset: int, data) -> Request:
        return Request.completed(self.write_at(offset, data))

    def iread_at(self, offset: int, count: int) -> Request:
        return Request.completed(self.read_at(offset, count))

    # -- collective I/O (fcoll role) -----------------------------------
    def write_at_all(self, offset: int, stacked) -> int:
        """Collective write: rank r's block (stacked axis 0) lands at
        view offset ``offset + r*block``. The controller is the two-phase
        aggregator: one contiguous pwrite when the view allows."""
        host = np.asarray(to_host(stacked))
        if host.shape[0] != self.comm.size:
            raise MPIError(ERR_ARG, "stacked buffer must have one block "
                                    "per rank")
        flat = np.ascontiguousarray(host).astype(self.etype,
                                                 copy=False).ravel()
        offs = self._map_offset(offset, flat.size)
        with self._lock:
            return self._pwrite_elems(offs, flat)

    def read_at_all(self, offset: int, count_per_rank: int) -> np.ndarray:
        """Collective read: returns stacked (nranks, count_per_rank)."""
        n = self.comm.size
        offs = self._map_offset(offset, count_per_rank * n)
        with self._lock:
            flat = self._pread_elems(offs)
        return flat.reshape(n, count_per_rank)

    # -- shared file pointer (sharedfp role) ---------------------------
    def write_shared(self, data) -> int:
        arr = np.ascontiguousarray(to_host(data)).ravel()
        with self._lock:
            off = self._shared_ptr
            self._shared_ptr += arr.size
        return self.write_at(off, arr)

    def read_shared(self, count: int) -> np.ndarray:
        with self._lock:
            off = self._shared_ptr
            self._shared_ptr += count
        return self.read_at(off, count)

    def seek_shared(self, offset: int) -> None:
        with self._lock:
            self._shared_ptr = offset

    def get_position_shared(self) -> int:
        return self._shared_ptr

    # -- sync/close ----------------------------------------------------
    def sync(self) -> None:
        os.fsync(self._fd)

    def close(self) -> None:
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
