"""bml/r2 — the BTL multiplexer: per-peer transport selection.

Behavioral spec: ``ompi/mca/bml/r2`` over ``ompi/mca/bml/bml.h`` — each
peer endpoint carries arrays of eligible BTLs (eager / send / rdma);
the PML picks per message, small ones through the latency-best eager
BTL (sm for same-host peers), large ones through the bandwidth path.

TPU-native re-design: two planes exist in the per-rank world — the
shared-memory rings (btl/sm, same-host eager) and framed TCP (btl/tcp,
universal). This multiplexer exposes the exact TcpEndpoint surface the
Router binds (``send_frame`` / ``_connect`` / ``_peers`` / ``close``),
so the pml cannot tell it is riding a composite. Routing rule per
frame: self -> sink loopback (btl/self); same-host peer AND the frame
fits the ring -> sm; otherwise -> tcp. TCP connections are still wired
eagerly to every peer — the connection monitor IS the failure
detector, and sm rings cannot detect a dead peer.

Locality (the hwloc relative-locality modex): every rank publishes its
host + boot identity; peers sharing it are same-host. On the one-host
test worlds everything is local, but the check is real — a multi-host
job would route cross-host peers over tcp only.
"""
from __future__ import annotations

import itertools
import os
import queue
import socket
import threading
import time
import uuid
from typing import Callable, Dict, Optional, Tuple

from ompi_tpu.btl import shmseg as _shmseg
from ompi_tpu.btl.sm import SmEndpoint
from ompi_tpu.btl.tcp import TcpEndpoint
from ompi_tpu import telemetry as _tele
from ompi_tpu.ft import inject as _inject
from ompi_tpu.mca import pvar as _pvar
from ompi_tpu.mca import var
from ompi_tpu.runtime import progress as _progress
from ompi_tpu.trace import core as _trace

_BOOT_ID: Optional[str] = None


def _host_identity() -> str:
    """hostname + a per-boot token: two containers can share a
    hostname without sharing /dev/shm."""
    global _BOOT_ID
    if _BOOT_ID is None:
        try:
            with open("/proc/sys/kernel/random/boot_id") as f:
                _BOOT_ID = f.read().strip()
        except OSError:
            _BOOT_ID = uuid.uuid4().hex     # no proc: never matches,
            #                                 sm safely disabled
    return f"{socket.gethostname()}/{_BOOT_ID}"


# single source of truth for the tuning defaults: register_params and
# the var_get fallbacks below must never disagree
_DEF_RING_BYTES = 4 << 20
_DEF_MIN_BYTES = 32 << 10


def register_params() -> None:
    var.var_register("btl", "sm", "enable", vtype="bool", default=True,
                     help="Use shared-memory rings for same-host "
                          "pt2pt frames (bml routes the rest via tcp)")
    var.var_register("btl", "sm", "ring_bytes", vtype="int",
                     default=_DEF_RING_BYTES,
                     help="Per-peer SPSC ring capacity in bytes; frames "
                          "that cannot fit route via tcp (the eager "
                          "limit / protocol switch)")
    var.var_register("btl", "sm", "min_bytes", vtype="int",
                     default=_DEF_MIN_BYTES,
                     help="Smallest payload routed through the sm "
                          "bandwidth plane; smaller frames stay on the "
                          "tcp latency plane (socket wakeup beats any "
                          "poll cadence a GIL runtime can offer)")
    var.var_register("btl", "devxfer", "enable", vtype="bool",
                     default=True,
                     help="Move large jax.Array pt2pt payloads over "
                          "the PJRT cross-host transfer plane "
                          "(device-to-device rendezvous pull) instead "
                          "of the host byte path")
    var.var_register("btl", "devxfer", "min_bytes", vtype="int",
                     default=1 << 20,
                     help="Device-array payloads at or above this ride "
                          "the transfer plane (the rndv eager limit, "
                          "pml_ob1_sendreq.h:389-460 role)")
    var.var_register("mpi", "base", "btl_rails", vtype="int", default=1,
                     help="Channels per peer for large-message segment "
                          "striping (extra tcp connections with "
                          "independent send locks and sender threads); "
                          "1 = the single-rail byte-identical default "
                          "(docs/LARGEMSG.md)")
    # the zero-copy segment plane's vars (mpi_base_shm_*) register
    # alongside the ring tuning vars — docs/LARGEMSG.md
    _shmseg.register_params()
    # the resilience plane's vars register alongside the btl tuning
    # vars: injection (mpi_base_ft_inject_*) and the heartbeat
    # detector (mpi_base_ft_hb_*) — docs/RESILIENCE.md
    _inject.register_params()
    from ompi_tpu.ft import detector as _detector
    _detector.register_params()


def _probe_stream(chunk: int = 64 << 10, reps: int = 8,
                  probe_sm: bool = True) -> "tuple[float, float]":
    """~1 ms micro-probe of the two planes' stream mechanics on THIS
    host: bytes/sec pushing+popping records through a loopback
    /dev/shm ring (the sm bulk path's two memcpys and bookkeeping) vs
    writing+reading a local socketpair (the tcp path's kernel
    copies). Returns (sm_bps, tcp_bps); sm_bps is 0.0 when the ring
    probe is skipped (``probe_sm=False``) or fails — the tcp half
    always runs, because its number doubles as the per-rail bandwidth
    estimate the rail/segment decision rows reuse
    (coll/decision.pipeline_plan) instead of re-probing."""
    import socket
    import time

    payload = b"\x5a" * chunk

    sm_s = 0.0
    if probe_sm:
        try:
            from ompi_tpu.btl.sm import Ring
            ring = Ring(None,
                        capacity=max(2 * chunk + (1 << 12), 1 << 20),
                        create=True)
            try:
                ring.push(payload)       # warm the mapping
                ring.pop()
                t0 = time.perf_counter()
                for _ in range(reps):
                    ring.push(payload)
                    ring.pop()
                sm_s = time.perf_counter() - t0
            finally:
                ring.close()
        except Exception:                # noqa: BLE001 — no /dev/shm:
            sm_s = 0.0                   # the tcp half still matters
    a, b = socket.socketpair()
    try:
        a.sendall(payload)               # warm the buffers
        _drain_sock(b, chunk)
        t0 = time.perf_counter()
        for _ in range(reps):
            a.sendall(payload)
            _drain_sock(b, chunk)
        tcp_s = time.perf_counter() - t0
    finally:
        a.close()
        b.close()
    total = float(reps * chunk)
    sm_bps = total / max(sm_s, 1e-9) if sm_s > 0 else 0.0
    return sm_bps, total / max(tcp_s, 1e-9)


def _drain_sock(sock, n: int) -> None:
    got = 0
    while got < n:
        got += len(sock.recv(n - got))


class BmlEndpoint:
    """Composite endpoint: TcpEndpoint surface, sm fast path.

    Ordering: two transports per peer would break MPI's non-overtaking
    rule (a small sm frame could pass a large tcp frame sent earlier),
    so every outbound frame is stamped with a per-destination sequence
    number and the receive side delivers strictly in sequence, holding
    early arrivals back — ob1's recv-fragment sequencing
    (``pml_ob1_recvfrag.c:296-330``) at the bml boundary.
    """

    def __init__(self, rank: int, nprocs: int,
                 kv_set: Callable[[str, str], None],
                 kv_get: Callable[[str], str],
                 sink: Callable[[dict, bytes], None],
                 on_peer_lost: Optional[Callable[[int], None]] = None):
        register_params()
        # bind the injection plane to this process's world rank and
        # (re)compile the fault specs — a no-op leaving the gate cold
        # when mpi_base_ft_inject is unset
        _inject.refresh(rank)
        self.rank = rank
        self.nprocs = nprocs
        self._kv_get = kv_get
        self.sink = sink
        self._send_seq: Dict[int, "itertools.count"] = {
            p: itertools.count(1) for p in range(nprocs)}
        self._expect: Dict[int, int] = {}
        self._held: Dict[int, Dict[int, tuple]] = {}
        self._ready: Dict[int, object] = {}      # src -> deque
        self._draining: Dict[int, bool] = {}
        self._order_lock = threading.Lock()
        self.tcp = TcpEndpoint(rank, nprocs, kv_set, kv_get,
                               self._ordered_sink,
                               on_peer_lost=on_peer_lost)
        kv_set(f"ompi_tpu/btl/host/{rank}", _host_identity())
        self.sm: Optional[SmEndpoint] = None
        if var.var_get("btl_sm_enable", True) and nprocs > 1 \
                and not os.environ.get("OMPI_TPU_DISABLE_SM"):
            try:
                self.sm = SmEndpoint(
                    rank, nprocs, kv_set, kv_get, self._ordered_sink,
                    ring_bytes=int(var.var_get("btl_sm_ring_bytes",
                                               _DEF_RING_BYTES)))
            except Exception:            # noqa: BLE001 — no /dev/shm
                self.sm = None           # etc: tcp carries everything
        # the zero-copy segment plane (btl/shmseg): constructed
        # unconditionally in multi-rank worlds — it allocates nothing
        # until a send actually packs, and the receive side must be
        # able to adopt regardless of the local send gate. segfree ctl
        # frames ride the unsequenced tcp plane (the _smpoke
        # discipline).
        self.shm_seg: Optional[_shmseg.SegPlane] = None
        if nprocs > 1 and not os.environ.get("OMPI_TPU_DISABLE_SM"):
            try:
                self.shm_seg = _shmseg.SegPlane(
                    rank, kv_set, kv_get, ctl_send=self.tcp.send_frame)
            except Exception:            # noqa: BLE001 — ring/tcp
                self.shm_seg = None      # carry everything
        self._same_host: Dict[int, bool] = {}
        self._sm_min = int(var.var_get("btl_sm_min_bytes",
                                       _DEF_MIN_BYTES))
        # per-transport frame counts (the hook/comm_method selection
        # table's data source)
        self.stats = {"sm": 0, "tcp": 0, "self": 0}
        # -- multi-rail striping state (send_segment) ------------------
        self.rails = max(1, int(var.var_get("mpi_base_btl_rails", 1)))
        self._rail_lock = threading.Lock()
        self._rail_rr: Dict[int, "itertools.count"] = {}   # peer -> rr
        self._rail_seq: Dict[Tuple[int, int], "itertools.count"] = {}
        self._rail_expect: Dict[Tuple[int, int], int] = {}
        self._rail_qs: Dict[Tuple[int, int], "queue.Queue"] = {}
        # per-rail byte counters (send + receive on this endpoint),
        # surfaced as btl_rail_bytes_c<r> pvars — the bench's
        # rail_bytes_balanced contract row reads these
        self.rail_bytes: Dict[int, int] = {r: 0
                                           for r in range(self.rails)}
        self.rail_stats = {"ooo": 0, "fallback": 0, "recv_frames": 0}
        for r in range(self.rails):
            _pvar.pvar_register(
                f"btl_rail_bytes_c{r}",
                (lambda rr=r, ep=self: ep.rail_bytes.get(rr, 0)),
                unit="bytes",
                help=f"Segment payload bytes carried on rail {r} by "
                     f"this endpoint, send + receive "
                     f"(docs/LARGEMSG.md)")
        # routing earns its defaults from DATA (round-3 postmortem:
        # the sm "bandwidth plane" measurably lost to tcp on the CI
        # host and the decision layer still routed bulk to it). A ~1ms
        # local micro-probe measures both planes' stream mechanics; sm
        # is demoted for bulk unless it actually wins. A user-set
        # btl_sm_min_bytes (env/file/CLI) overrides the probe. The tcp
        # half always runs: its number doubles as the per-rail
        # bandwidth estimate (``rail_gbps``) the rail/segment decision
        # rows reuse instead of re-probing.
        self.probe_basis: Dict[str, object] = {"ran": False}
        user_min = var.var_source("btl_sm_min_bytes") \
            not in (None, var.SOURCE_DEFAULT)
        try:
            # a user-set btl_sm_min_bytes suppresses the ROUTING probe
            # (their threshold stands, "ran" stays False) — but the
            # tcp half still runs: its number doubles as the per-rail
            # bandwidth estimate (rail_gbps) regardless of routing
            probe_sm = self.sm is not None and not user_min
            sm_bps, tcp_bps = _probe_stream(probe_sm=probe_sm)
            self.probe_basis["rail_gbps"] = round(tcp_bps / 1e9, 3)
            if not user_min:
                self.probe_basis.update({
                    "ran": True,
                    "sm_gbps": round(sm_bps / 1e9, 3) if sm_bps else None,
                    "tcp_gbps": round(tcp_bps / 1e9, 3),
                    "sm_demoted": False,
                })
                if self.sm is not None and sm_bps > 0:
                    demote = sm_bps <= tcp_bps * 1.1
                    if demote:
                        self._sm_min = 1 << 62   # bulk stays on tcp
                    self.probe_basis["sm_demoted"] = bool(demote)
        except Exception:                # noqa: BLE001 — probe is
            pass                         # advisory, never fatal

    # -- the TcpEndpoint surface the Router binds ----------------------
    @property
    def _peers(self):
        return self.tcp._peers

    def _connect(self, peer: int):
        return self.tcp._connect(peer)

    def _is_same_host(self, peer: int) -> bool:
        cached = self._same_host.get(peer)
        if cached is not None:
            return cached
        try:
            theirs = self._kv_get(f"ompi_tpu/btl/host/{peer}")
            if isinstance(theirs, bytes):
                theirs = theirs.decode()
            same = theirs == _host_identity()
        except Exception:                # noqa: BLE001
            same = False
        self._same_host[peer] = same
        return same

    def _ordered_sink(self, header: dict, payload: bytes) -> None:
        """Deliver frames per-sender in sequence-number order; early
        arrivals (fast transport overtook the slow one) are held until
        their predecessors land. The sink itself runs OUTSIDE the
        order lock (it can trigger ack sends that block on a full
        ring); per-sender order is kept by a single-drainer queue."""
        if header.get("ctl") == "_smpoke":
            # transport doorbell: the peer parked payload-bearing
            # records in our shared-memory rings; drain them on this
            # (blocking, already-awake) reader thread — one wake batch
            # for the whole ring drain, however many records it pops
            if self.sm is not None:
                _progress.wake_begin()
                try:
                    self.sm.drain(header.get("peer"))
                finally:
                    _progress.wake_end()
            return
        rq = header.pop("_rq", None)
        if rq is not None:
            # rail-striped segment (send_segment): per-rail FIFO is
            # TRACKED (a gap means cross-rail overtaking or a dropped-
            # rail detour — counted, never held back) but delivery is
            # immediate: the pml reassembles by segment index, and MPI
            # matching order was already fixed by the train's init
            # frame on the ordered _sq stream. This generalizes the
            # ordered sink: rails trade total order for concurrency,
            # the index-keyed PipeStore buys it back.
            src, rail, rseq = rq
            with self._order_lock:
                key = (src, rail)
                exp = self._rail_expect.get(key, 1)
                if rseq != exp:
                    self.rail_stats["ooo"] += 1
                self._rail_expect[key] = max(exp, rseq + 1)
                self.rail_stats["recv_frames"] += 1
            # zero-copy detour: the sender parked the segment payload
            # in a shared slot and shipped only a descriptor. Only
            # offset-addressed ("off") pipesegs ride here, so the
            # PipeStore copies out synchronously inside sink() and
            # nothing retains the transient view past the free below.
            seg = header.pop("_seg", None)
            view = None
            if seg is not None and self.shm_seg is not None:
                view = self.shm_seg.view(seg)
                payload = view
            with self._rail_lock:        # rail_bytes shares the send-
                self.rail_bytes[rail] = (self.rail_bytes.get(rail, 0)
                                         + len(payload))  # side lock
            _progress.wake_note_frame()
            if view is None:
                self.sink(header, payload)
                return
            try:
                self.sink(header, payload)
            finally:
                view.release()
                self.shm_seg.send_free(seg["o"], seg["i"])
            return
        sq = header.pop("_sq", None)
        if sq is None:                   # unsequenced (foreign) frame
            _progress.wake_note_frame()
            self.sink(header, payload)
            return
        src, seq = sq
        from collections import deque
        with self._order_lock:
            exp = self._expect.setdefault(src, 1)
            held = self._held.setdefault(src, {})
            ready = self._ready.setdefault(src, deque())
            if seq != exp:
                held[seq] = (header, payload)
                return                   # predecessors still in flight
            ready.append((header, payload))
            exp += 1
            while exp in held:
                ready.append(held.pop(exp))
                exp += 1
            self._expect[src] = exp
            if self._draining.get(src):
                return                   # the active drainer takes it
            self._draining[src] = True
        # wakeup coalescing: ONE flush at drain end services every
        # match this batch of frames completes, instead of one cross-
        # thread wake per frame racing the still-draining reader for
        # the core (runtime/progress.py wake batch)
        _progress.wake_begin()
        try:
            while True:
                with self._order_lock:
                    if not ready:
                        self._draining[src] = False
                        return
                    h, p = ready.popleft()
                _progress.wake_note_frame()
                try:
                    self.sink(h, p)
                except Exception:        # noqa: BLE001
                    # one bad frame must drop only itself — an escaping
                    # exception would leave _draining stuck True and
                    # wedge this sender's stream forever (the tcp read
                    # loop makes the same promise)
                    import traceback
                    traceback.print_exc()
        finally:
            _progress.wake_end()

    def send_frame(self, peer: int, header: dict,
                   payload: bytes = b"") -> None:
        if peer == self.rank:            # btl/self loopback
            self.stats["self"] += 1
            self.sink(header, payload)
            return
        if _inject.active:
            # pml-plane fault hook (ft/inject): a "drop" fires HERE,
            # before the sequence stamp below, so the loss models a
            # message that never reached the wire — the receiver just
            # never matches it (no reorder-buffer hole is created; a
            # post-stamp drop would park every later frame from this
            # rank in the peer's _held map forever)
            act = _inject.frame_fault("pml", peer)
            if act is not None:
                if act[0] == "drop":
                    return
                _inject.delay_now(act[1])
        header = dict(header)
        header["_sq"] = (self.rank, next(self._send_seq[peer]))
        if (self.sm is not None and len(payload) >= self._sm_min
                and self._is_same_host(peer)):
            from ompi_tpu.runtime import ft
            pushed = False
            # a reader thread must never park behind a full peer ring
            # (up to the full 60 s producer window): try-push once and
            # let tcp carry the frame instead — the sequence number
            # keeps ordering regardless of which plane delivers
            timeout = 0.0 if getattr(self.tcp._reader_tls, "active",
                                     False) else 60.0
            try:
                pushed = not ft.is_failed(peer) and \
                    self.sm.try_send(peer, header, payload,
                                     timeout=timeout)
            except Exception:            # noqa: BLE001 — ring closed
                pushed = False           # mid-shutdown: tcp carries it
            if pushed:
                self.stats["sm"] += 1
                # doorbell: a tiny unsequenced tcp frame whose blocking
                # reader drains the ring at the peer. The frame is
                # PUBLISHED already — a poke failure must NOT fall back
                # to tcp (that would duplicate the sequence number and
                # park the copy in _held forever); a dead peer's drain
                # no longer matters, and a live peer's next poke or
                # inbound frame drains the backlog.
                try:
                    self.tcp.send_frame(peer, {"ctl": "_smpoke",
                                               "peer": self.rank})
                except Exception:        # noqa: BLE001
                    pass
                return                   # sm bandwidth plane took it
        self.stats["tcp"] += 1
        self.tcp.send_frame(peer, header, payload)

    # -- rail-striped segments (the pipelined rendezvous data plane) ---
    def send_segment(self, peer: int, header: dict, payload: bytes,
                     on_done=None) -> None:
        """Enqueue one unordered large-message segment, striped
        round-robin over ``mpi_base_btl_rails`` rails. Segments carry
        a per-(sender, rail) sequence stamp ``_rq`` instead of the
        ordered ``_sq`` — MPI ordering rides the train's init frame
        (pml/pipeline); segments reassemble by index, so rails may
        deliver in any order. Each (peer, rail) pair owns a dedicated
        sender thread: the caller returns immediately, so segment
        s+1's pack/stage/compress overlaps segment s's wire time, and
        rails overlap each other (under btl_tcp_sim_gbps each rail
        paces on its OWN lock — N rails aggregate like N NICs).
        ``on_done(wire_seconds)`` fires on the sender thread after the
        segment leaves (0.0 for loopback) — the pml's flow-control
        window and overlap accounting hang off it."""
        if peer == self.rank:            # btl/self loopback
            self.stats["self"] += 1
            with self._rail_lock:
                self.rail_bytes[0] = self.rail_bytes.get(0, 0) \
                    + len(payload)
            self.sink(dict(header), payload)
            if on_done is not None:
                on_done(0.0)
            return
        with self._rail_lock:
            rr = self._rail_rr.get(peer)
            if rr is None:
                rr = self._rail_rr[peer] = itertools.count()
            rail = next(rr) % self.rails
            key = (peer, rail)
            seq = self._rail_seq.get(key)
            if seq is None:
                seq = self._rail_seq[key] = itertools.count(1)
            rseq = next(seq)
            q = self._rail_qs.get(key)
            if q is None:
                q = self._rail_qs[key] = queue.Queue()
                threading.Thread(
                    target=self._rail_send_loop, args=(q, peer, rail),
                    daemon=True,
                    name=f"btl-rail-{self.rank}-{peer}-{rail}").start()
            self.rail_bytes[rail] = self.rail_bytes.get(rail, 0) \
                + len(payload)
        header = dict(header)
        header["_rq"] = (self.rank, rail, rseq)
        q.put((header, payload, on_done))

    def _rail_send_loop(self, q: "queue.Queue", peer: int,
                        rail: int) -> None:
        from ompi_tpu.runtime import ft
        while True:
            item = q.get()
            if item is None:
                return                   # close(): retire
            header, payload, on_done = item
            t0 = time.perf_counter()
            tok = (_trace.begin("btl.rail", rail=rail, peer=peer,
                                bytes=len(payload))
                   if _trace.active else None)
            if _tele.active:
                # telemetry: payload bytes per rail frame — the stripe
                # width the rendezvous scheduler actually produced
                hist = _tele.RAIL
                hist.record(len(payload))
            seg = None
            if (self.shm_seg is not None and _shmseg.enabled()
                    and "off" in header
                    and len(payload) >= self.shm_seg.min_bytes
                    and not ft.is_failed(peer)
                    and self._is_same_host(peer)):
                # zero-copy: park the stripe in a shared slot and ship
                # only the descriptor frame. Offset-addressed pipesegs
                # only — compressed segments lack "off" and are
                # RETAINED by the receiving PipeStore, so they must
                # never ride a transient slot view. pack() returning
                # None (pool dry: receiver still holds every slot)
                # falls back to the ring/tcp copy path below.
                seg = self.shm_seg.pack(peer, payload)
                if seg is not None:
                    header["_seg"] = seg
                    payload = b""
            sent = False
            try:
                if not ft.is_failed(peer):
                    if (self.sm is not None
                            and len(payload) >= self._sm_min
                            and self._is_same_host(peer)):
                        # same-host segments ride the ONE existing sm
                        # ring per peer (rails stripe the tcp plane;
                        # the ring's push lock serializes multi-rail
                        # pushes and index reassembly absorbs the
                        # interleaving) — this thread may block, it is
                        # not a reader
                        try:
                            sent = self.sm.try_send(peer, header,
                                                    payload,
                                                    timeout=60.0)
                        except Exception:    # noqa: BLE001
                            sent = False
                        if sent:
                            self.stats["sm"] += 1
                            try:
                                self.tcp.send_frame(
                                    peer, {"ctl": "_smpoke",
                                           "peer": self.rank})
                            except Exception:  # noqa: BLE001
                                pass
                    if not sent:
                        try:
                            self.tcp.send_frame_rail(peer, header,
                                                     payload, rail)
                            sent = True
                            self.stats["tcp"] += 1
                        except Exception:    # noqa: BLE001
                            # dropped rail: detour over the primary
                            # rail-0 socket — index reassembly makes
                            # the re-route invisible to the pml
                            try:
                                self.tcp.send_frame(peer, header,
                                                    payload)
                                sent = True
                                self.stats["tcp"] += 1
                                with self._rail_lock:
                                    self.rail_stats["fallback"] += 1
                            except Exception:  # noqa: BLE001
                                pass         # peer death: the failure
                                #              detector owns reporting
            finally:
                if seg is not None and not sent:
                    # descriptor never left: reclaim the slot locally
                    # (the receiver will never send the segfree ctl)
                    self.shm_seg.release(peer, seg["i"])
                if tok is not None:
                    _trace.end(tok, sent=sent)
                if on_done is not None:
                    on_done(time.perf_counter() - t0)

    def close(self) -> None:
        with self._rail_lock:
            rail_qs = list(self._rail_qs.values())
        for q in rail_qs:                # retire the rail senders
            q.put(None)
        if self.shm_seg is not None:
            self.shm_seg.close()
        if self.sm is not None:
            self.sm.close()
        self.tcp.close()
