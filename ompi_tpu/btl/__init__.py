"""btl — byte-transfer layer for per-rank (multi-controller) worlds.

The reference reaches remote peers through BTL components
(``opal/mca/btl/btl.h:1175``): tcp sockets for the inter-node tier,
self for loopback. The TPU-native framework needs a byte transport only
for the *per-rank* execution mode's point-to-point data plane (the DCN
tier); collectives ride XLA over ICI. ``btl.tcp`` is that transport.
"""
from ompi_tpu.btl.tcp import TcpEndpoint  # noqa: F401
