"""btl/shmseg — zero-copy shared-memory segment pools (the bulk plane).

Behavioral spec: the Process-in-Process observation (PAPERS.md,
arXiv:2305.10612) — same-node ranks sharing an address space can move
a payload with ~2 byte-touches instead of the ring path's copy-in /
copy-out per hop. The sm SPSC rings stay the FRAME plane (headers,
doorbells, everything under ``mpi_base_shm_seg_min_bytes``); payloads
at or above it are packed ONCE into a slot of a per-(sender, peer)
segment pool — a raw mmap file under /dev/shm with the same
``tag_for()`` naming/ownership discipline as the rings — and only a
tiny descriptor frame rides the existing ordered ring+poke ctl plane.
The receiver adopts the payload in place with ``np.frombuffer``:
single-copy pt2pt.

Reclaim is tied to MPI completion: a ``weakref.finalize`` on the
adopted array sends a tiny unsequenced ``segfree`` ctl frame back to
the owner when the LAST reference dies. The finalizer closes over slot
ids and the plane only — never the array itself (the PR-5
PipeStore/``_cancel_fn`` lesson: no closure cycle may pin a 32 MB
segment). A receiver that holds an adopted array forever just pins one
slot; the sender's pool runs dry and new sends fall back to the
ring/tcp path — graceful degradation, never corruption. POSIX
unlink-while-mapped semantics keep adopted views valid after the
owner unlinks at close.

On top of the pt2pt pools sits the in-segment FOLD workspace: one
fixed segment per (rank, communicator), modex'd through the KV, that
``core/rankcomm``'s node-local allreduce folds partner shards in
directly (reduce-scatter over segment slices, then in-place
allgather) — ~4 byte-touches per rank instead of the ring schedule's
~2·P (docs/LARGEMSG.md has the copy-count table).

Everything here is OFF by default (``mpi_base_shm_zerocopy=0``); the
off path is byte-identical to the ring data plane, gate-tested.
"""
from __future__ import annotations

import hashlib
import mmap
import os
import threading
import weakref
from typing import Dict, Optional, Tuple

import numpy as np

from ompi_tpu.btl.sm import _SHM_DIR, job_tag
from ompi_tpu.mca import pvar as _pvar
from ompi_tpu.mca import var
from ompi_tpu import telemetry as _tele
from ompi_tpu.trace import core as _trace

# the launcher's post-reap sweep globs on this prefix
# (tools/mpirun.py imports it) — prefix and glob must never diverge,
# same contract as the rings' otpusm_ prefix
SEG_PREFIX = "otpuseg"

# single source of truth for the tuning defaults (the bml convention)
_DEF_MIN_BYTES = 256 << 10
_DEF_SEG_BYTES = 32 << 20
_DEF_SEG_COUNT = 4


def register_params() -> None:
    var.var_register(
        "mpi", "base", "shm_zerocopy", vtype="bool", default=False,
        help="Zero-copy shared-memory bulk plane: same-host payloads "
             "at or above mpi_base_shm_seg_min_bytes are packed once "
             "into a per-peer segment pool and adopted in place by "
             "the receiver (single-copy pt2pt + the in-segment "
             "node-local fold); off keeps the ring data plane "
             "byte-identical (docs/LARGEMSG.md)")
    var.var_register(
        "mpi", "base", "shm_seg_min_bytes", vtype="int",
        default=_DEF_MIN_BYTES,
        help="Smallest payload routed through the zero-copy segment "
             "pool; smaller frames stay on the ring/tcp planes")
    var.var_register(
        "mpi", "base", "shm_seg_bytes", vtype="int",
        default=_DEF_SEG_BYTES,
        help="Per-slot capacity of the shared segment pools (also the "
             "per-communicator fold workspace size); payloads larger "
             "than one slot ride the pipelined rendezvous, whose "
             "segments reuse the pool slot by slot")
    var.var_register(
        "mpi", "base", "shm_seg_count", vtype="int",
        default=_DEF_SEG_COUNT,
        help="Slots per (sender, peer) segment pool; when every slot "
             "is pinned by an unreclaimed adoption, new sends fall "
             "back to the ring/tcp path")


def enabled() -> bool:
    register_params()
    return bool(var.var_get("mpi_base_shm_zerocopy", False))


def min_bytes() -> int:
    register_params()
    return int(var.var_get("mpi_base_shm_seg_min_bytes",
                           _DEF_MIN_BYTES))


def coll_token(cid) -> str:
    """Filesystem/KV-safe token for a communicator id — the fold
    workspace key (deterministic across ranks: cids agree by
    construction)."""
    return hashlib.md5(str(cid).encode()).hexdigest()[:8]


# -- pvars ------------------------------------------------------------------
stats = {"packs": 0, "adoptions": 0, "frees": 0, "no_slot": 0,
         "folds": 0}


def _register_pvars() -> None:
    _pvar.pvar_register(
        "btl_shm_adoptions", lambda: stats["adoptions"],
        help="Payloads adopted in place from a peer's shared segment "
             "(the zero-copy receive; docs/LARGEMSG.md)")
    _pvar.pvar_register(
        "btl_shm_seg_packs", lambda: stats["packs"],
        help="Payloads packed into a shared segment slot by this "
             "process (the single sender-side copy)")
    _pvar.pvar_register(
        "btl_shm_seg_frees", lambda: stats["frees"],
        help="Segment slots returned to this process's pools by "
             "peers' segfree ctl frames")
    _pvar.pvar_register(
        "btl_shm_seg_fallbacks", lambda: stats["no_slot"],
        help="Zero-copy-eligible sends that fell back to the ring/tcp "
             "path because every pool slot was pinned")
    _pvar.pvar_register(
        "btl_shm_fold_ops", lambda: stats["folds"],
        help="In-segment node-local reductions this rank "
             "participated in (core/rankcomm shm fold)")


class _PoolFile:
    """One raw mmap'd /dev/shm file: ``count`` fixed-size slots (or a
    single fold workspace). Same ownership discipline as btl/sm.Ring:
    the creator owns the path and unlinks at close; attachers never
    unlink. Close tolerates exported buffers (adopted arrays keep the
    mapping alive; POSIX keeps it valid past the unlink)."""

    def __init__(self, name: str, size: int, slot_bytes: int,
                 create: bool):
        path = os.path.join(_SHM_DIR, name)
        if create:
            try:                         # stale leftover from a crashed
                os.unlink(path)          # same-tag job: reclaim the name
            except OSError:
                pass
            self._fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR,
                               0o600)
            os.ftruncate(self._fd, size)
        else:
            self._fd = os.open(path, os.O_RDWR)
        self.name = name
        self.slot_bytes = slot_bytes
        self._path = path
        self._created = create
        self.buf = mmap.mmap(self._fd, size)

    def close(self) -> None:
        try:
            self.buf.close()
        except Exception:                # noqa: BLE001 — exported
            pass                         # buffers: mapping outlives us
        try:
            os.close(self._fd)
        except OSError:
            pass
        if self._created:
            try:
                os.unlink(self._path)
            except OSError:
                pass


def _send_free(plane: "SegPlane", owner: int, idx: int) -> None:
    """The adopted array's finalizer: return slot ``idx`` to ``owner``.
    A module function taking ids only — registering it with
    ``weakref.finalize`` must never close over the array (no cycle may
    pin the segment). Runs on whatever thread drops the last
    reference, possibly at interpreter exit: best-effort, never
    raises."""
    try:
        plane.send_free(owner, idx)
    except Exception:                    # noqa: BLE001
        pass


class SegPlane:
    """The rank's shared-segment plane: sender-owned per-peer slot
    pools, receiver-side attachments, and per-communicator fold
    workspaces. Constructed unconditionally by the bml (it allocates
    nothing until first use), so a peer whose gate differs can still
    adopt; the SEND side is what ``mpi_base_shm_zerocopy`` gates."""

    def __init__(self, rank: int, kv_set, kv_get, ctl_send=None):
        register_params()
        self.rank = rank
        self._kv_set = kv_set
        self._kv_get = kv_get
        self._ctl = ctl_send             # unsequenced ctl frame sender
        self.slot_bytes = max(64 << 10, int(var.var_get(
            "mpi_base_shm_seg_bytes", _DEF_SEG_BYTES)))
        self.slot_count = max(1, int(var.var_get(
            "mpi_base_shm_seg_count", _DEF_SEG_COUNT)))
        self.min_bytes = min_bytes()
        self._lock = threading.Lock()
        self._closed = False
        # sender side: peer -> (pool file, free-slot set)
        self._pools: Dict[int, Tuple[_PoolFile, set]] = {}
        # receiver side: owner -> attached pool file
        self._attached: Dict[int, _PoolFile] = {}
        # fold workspaces: token -> own segment; (token, owner) -> peer
        self._coll: Dict[str, _PoolFile] = {}
        self._coll_peers: Dict[Tuple[str, int], _PoolFile] = {}

    # -- sender side ---------------------------------------------------
    def _name_for(self, suffix: str) -> str:
        tag = job_tag()
        if tag:
            return f"{SEG_PREFIX}_{tag}_{self.rank}_{suffix}"
        return (f"{SEG_PREFIX}_{os.getpid():x}_{self.rank}_{suffix}_"
                f"{os.urandom(4).hex()}")

    def pack(self, peer: int, payload) -> Optional[dict]:
        """Copy ``payload`` into a free slot of the (rank -> peer)
        pool — the ONE sender-side copy. Returns the wire descriptor
        ``{"o", "i", "n"}`` or None (slot pressure / too big /
        closed): the caller falls back to the ring/tcp path, which
        stays fully correct."""
        mv = payload if isinstance(payload, (bytes, bytearray)) \
            else memoryview(payload).cast("B")
        n = len(mv)
        if n <= 0 or n > self.slot_bytes:
            return None
        publish = None
        with self._lock:
            if self._closed:
                return None
            ent = self._pools.get(peer)
            if ent is None:
                try:
                    pf = _PoolFile(self._name_for(str(peer)),
                                   self.slot_count * self.slot_bytes,
                                   self.slot_bytes, create=True)
                except OSError:
                    return None          # no /dev/shm headroom
                ent = self._pools[peer] = (pf,
                                           set(range(self.slot_count)))
                publish = (f"ompi_tpu/shmseg/{self.rank}/{peer}",
                           f"{pf.name}:{self.slot_count}:"
                           f"{self.slot_bytes}")
            pf, free = ent
            if not free:
                stats["no_slot"] += 1
                return None
            idx = free.pop()
        if publish is not None:
            # the modex write happens BEFORE the descriptor frame can
            # leave, so the receiver's lazy attach always finds the name
            self._kv_set(*publish)
        tok = (_trace.begin("btl.shm_seg", peer=peer, bytes=n)
               if _trace.active else None)
        ok = False
        try:
            off = idx * self.slot_bytes
            pf.buf[off:off + n] = mv
            ok = True
        finally:
            if tok is not None:
                _trace.end(tok, idx=idx, ok=ok)
            if not ok:                   # failed pack must not leak
                with self._lock:         # the slot
                    free.add(idx)
        stats["packs"] += 1
        if _tele.active:
            hist = _tele.SHMSEG
            if hist is not None:
                hist.record(n)
        return {"o": self.rank, "i": idx, "n": n}

    def release(self, peer: int, idx: int) -> None:
        """A segfree ctl frame arrived: the peer is done with slot
        ``idx`` of our pool for it (set semantics absorb a duplicate
        free)."""
        with self._lock:
            ent = self._pools.get(peer)
            if ent is not None and 0 <= idx < self.slot_count:
                ent[1].add(idx)
        stats["frees"] += 1

    def peer_failed(self, world_rank: int) -> None:
        """FT reclaim: slots in flight to a dead peer can never be
        freed remotely — reclaim the whole pool (the dead peer reads
        nothing)."""
        with self._lock:
            ent = self._pools.get(world_rank)
            if ent is not None:
                ent[1].update(range(self.slot_count))

    # -- receiver side -------------------------------------------------
    def _attach(self, owner: int) -> _PoolFile:
        with self._lock:
            pf = self._attached.get(owner)
        if pf is not None:
            return pf
        val = self._kv_get(f"ompi_tpu/shmseg/{owner}/{self.rank}")
        if isinstance(val, bytes):
            val = val.decode()
        name, count, slot_bytes = str(val).rsplit(":", 2)
        pf = _PoolFile(name, int(count) * int(slot_bytes),
                       int(slot_bytes), create=False)
        with self._lock:
            cur = self._attached.setdefault(owner, pf)
        if cur is not pf:
            pf.close()                   # lost the attach race (never
        return cur                       # unlinks: not the creator)

    def adopt(self, desc: dict, inner: dict):
        """``np.frombuffer`` view over the owner's slot — the
        zero-copy receive. The returned array references the shared
        mapping; its finalizer returns the slot when the last
        reference dies (reclaim tied to MPI completion)."""
        owner, idx, n = int(desc["o"]), int(desc["i"]), int(desc["n"])
        pf = self._attach(owner)
        dtype = np.dtype(inner["dtype"])
        flat = np.frombuffer(pf.buf, dtype=dtype,
                             count=n // max(dtype.itemsize, 1),
                             offset=idx * pf.slot_bytes)
        weakref.finalize(flat, _send_free, self, owner, idx)
        stats["adoptions"] += 1
        if _tele.active:
            hist = _tele.SHMSEG
            if hist is not None:
                hist.record(n)
        return flat.reshape(tuple(inner["shape"]))

    def view(self, desc: dict) -> memoryview:
        """Transient view over the owner's slot for callers that copy
        synchronously (the pipelined segment train: PipeStore assembles
        in place, then the bml frees the slot immediately)."""
        pf = self._attach(int(desc["o"]))
        off = int(desc["i"]) * pf.slot_bytes
        return memoryview(pf.buf)[off:off + int(desc["n"])]

    def send_free(self, owner: int, idx: int) -> None:
        """Return slot ``idx`` to ``owner`` via the unsequenced ctl
        plane (the _smpoke discipline: best-effort, a dead owner's
        pool no longer matters)."""
        send = self._ctl
        if send is None or self._closed:
            return
        try:
            send(owner, {"ctl": "segfree", "peer": self.rank,
                         "i": idx})
        except Exception:                # noqa: BLE001
            pass

    # -- fold workspaces (core/rankcomm in-segment reduction) ----------
    def coll_segment(self, token: str) -> _PoolFile:
        """This rank's fold workspace for communicator ``token`` —
        one slot-sized segment, created on first use, name modex'd so
        partners can attach. Collectives are serialized per comm, so
        one workspace per (rank, comm) needs no slot bookkeeping."""
        publish = None
        with self._lock:
            pf = self._coll.get(token)
            if pf is None:
                pf = _PoolFile(self._name_for(f"c{token}"),
                               self.slot_bytes, self.slot_bytes,
                               create=True)
                self._coll[token] = pf
                publish = (f"ompi_tpu/shmseg/coll/{token}/{self.rank}",
                           f"{pf.name}:1:{self.slot_bytes}")
        if publish is not None:
            self._kv_set(*publish)
        return pf

    def coll_attach(self, token: str, owner: int) -> _PoolFile:
        """Attach partner ``owner``'s fold workspace (call only after
        a barrier ordered their ``coll_segment`` publish before us)."""
        if owner == self.rank:
            return self.coll_segment(token)
        key = (token, owner)
        with self._lock:
            pf = self._coll_peers.get(key)
        if pf is not None:
            return pf
        val = self._kv_get(f"ompi_tpu/shmseg/coll/{token}/{owner}")
        if isinstance(val, bytes):
            val = val.decode()
        name, _count, slot_bytes = str(val).rsplit(":", 2)
        pf = _PoolFile(name, int(slot_bytes), int(slot_bytes),
                       create=False)
        with self._lock:
            cur = self._coll_peers.setdefault(key, pf)
        if cur is not pf:
            pf.close()
        return cur

    # -- lifecycle -----------------------------------------------------
    def close(self) -> None:
        """Unlink everything this rank created; attached mappings stay
        valid for any still-live adopted arrays (POSIX). Called from
        the bml's close on the runtime shutdown path; the launcher's
        post-reap sweep reclaims whatever a SIGKILL left behind."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            files = ([pf for pf, _ in self._pools.values()]
                     + list(self._coll.values())
                     + list(self._attached.values())
                     + list(self._coll_peers.values()))
            self._pools.clear()
            self._coll.clear()
            self._attached.clear()
            self._coll_peers.clear()
        for pf in files:
            pf.close()


def adopt(endpoint, d: dict):
    """Receiver-side hook (pml/perrank._incoming, desc kind
    "shmseg")."""
    plane = getattr(endpoint, "shm_seg", None)
    if plane is None:
        raise RuntimeError("shmseg descriptor with no segment plane "
                           "(mismatched mpi_base_shm_zerocopy config?)")
    return plane.adopt(d, d["inner"])


def maybe_send_zerocopy(engine, data, dest: int, tag: int,
                        synchronous: bool):
    """The pml's same-host protocol switch (mirrors
    pipeline.maybe_send_pipelined): returns a completed Request when
    the payload was packed into a shared segment and announced by a
    tiny ordered descriptor frame, or None to fall through. When it
    returns None, NOTHING here has touched the wire — the fallback
    stays byte-identical."""
    if not enabled():
        return None
    router = engine.router
    ep = router.endpoint
    plane = getattr(ep, "shm_seg", None)
    if plane is None:
        return None
    try:
        import jax
        if isinstance(data, jax.Array):
            # past devxfer's gate already (too small / disabled): the
            # D2H stage is the pack's source copy
            data = np.asarray(data)
    except Exception:                    # noqa: BLE001
        pass
    if not isinstance(data, np.ndarray) or data.dtype.hasobject:
        return None
    total = int(data.nbytes)
    if total < plane.min_bytes or total > plane.slot_bytes:
        return None
    wdest = engine.comm.world_rank_of(dest)
    if wdest == router.rank or not ep._is_same_host(wdest):
        return None
    arr = np.ascontiguousarray(data)
    seg = plane.pack(wdest, arr)
    if seg is None:
        return None                      # pool pressure: ring path
    me = engine.comm.rank()
    t = engine.traffic.setdefault((me, dest), [0, 0])
    t[0] += 1
    t[1] += total
    header = {"cid": engine.comm.cid, "src": me, "tag": tag,
              "desc": {"kind": "shmseg", "o": seg["o"], "i": seg["i"],
                       "n": seg["n"],
                       "inner": {"kind": "nd", "dtype": arr.dtype.str,
                                 "shape": tuple(arr.shape)}}}
    ent = aid = None
    if synchronous:
        aid, ent = router.new_ack()
        header["ack_id"] = aid
        header["wsrc"] = engine.comm.world_rank_of(me)
    # the descriptor rides the ORDERED stream: it is what matches, so
    # zero-copy and fallback sends to one peer can never overtake
    try:
        ep.send_frame(wdest, header, b"")
    except Exception:
        plane.release(wdest, seg["i"])   # undelivered descriptor: the
        raise                            # slot must not leak
    if ent is not None:
        if not ent[0].wait(600):
            router.cancel_ack(aid)
            from ompi_tpu.core.errhandler import ERR_PENDING, MPIError
            raise MPIError(ERR_PENDING,
                           "ssend timed out waiting for the receive")
    from ompi_tpu.core.request import Request
    return Request.completed()


register_params()
_register_pvars()
