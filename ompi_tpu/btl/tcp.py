"""btl/tcp — framed TCP byte transport between ranks of a per-rank world.

Behavioral spec: ``opal/mca/btl/tcp`` — libevent-driven sockets carrying
eager/rendezvous fragments between peers whose addresses were exchanged
through the PMIx modex (``btl_tcp_component.c:109,498-520``); plus
``btl/self`` loopback for same-process sends.

TPU-native re-design: in the per-rank execution model each OS process is
one MPI rank (``rank() == jax.process_index()``); point-to-point payloads
move over this host-side DCN-tier transport while collectives ride XLA
over ICI. The modex is the JAX coordination-service KV store (the PMIx
stand-in): every rank binds an ephemeral listening port and publishes
``ompi_tpu/btl/<rank> -> host:port``; peers resolve lazily on first send
(the reference's lazy endpoint connect). One frame = 4-byte magic +
8-byte header length + pickled header + raw payload bytes; numpy/jax
arrays travel as raw buffers described by (dtype, shape) in the header —
no pickling of bulk data. A per-connection reader thread delivers frames
to the registered sink (the per-rank matching engine), playing the role
of the BTL active-message callback into ob1's ``recv_frag_match``.
"""
from __future__ import annotations

import pickle
import queue
import socket
import struct
import time
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ompi_tpu import telemetry as _tele
from ompi_tpu.ft import inject as _inject
from ompi_tpu.trace import core as _trace

MAGIC = 0x7f4d5049          # "\x7fMPI"
_LEN = struct.Struct("!IQQ")  # magic, header_len, payload_len


class PeerDownError(ConnectionError):
    """A btl send hit a dead or broken peer link — the structured form
    of ``ConnectionResetError``/``BrokenPipeError``, carrying WHOSE
    link died so the layers above (pml ``wait()``, the rail detour,
    shrink) can map it to ``MPI_ERR_PROC_FAILED`` instead of leaking a
    raw socket exception to the application (docs/RESILIENCE.md)."""

    def __init__(self, world_rank: int, cause: Optional[BaseException]
                 = None):
        msg = f"peer rank {world_rank} connection down"
        if cause is not None:
            msg += f": {type(cause).__name__}: {cause}"
        super().__init__(msg)
        self.world_rank = world_rank

# ctl-queue backpressure bound in BYTES (see _ctl_submit): far above
# anything a live link queues, far below address-space trouble
_CTL_MAX_BYTES = 256 << 20
_CTL_FRAME_OVERHEAD = 256   # accounting estimate per queued frame

# Bulk data-plane thresholds (the large-message path, docs/LARGEMSG.md):
# payloads at least this big skip the header+payload concatenation on
# send (two sendalls under the same lock — the frame stays contiguous
# on the wire) and the bytearray->bytes copy on receive. A pipelined
# segment crosses this at every supported segment size (>= 64 KiB).
_BULK_MIN = 64 << 10
# Kernel socket buffers for peer/rail connections: one full pipeline
# segment (<= 4 MiB) must fit IN FLIGHT, so a sender's sendall returns
# and paces on its own clock instead of blocking on the moment the
# peer's reader thread gets scheduled — on a small host two ranks doing
# a bidirectional chunk exchange otherwise serialize on each other's
# reader wakeups (each sendall waits out the other side's drain).
_SOCK_BUF = 8 << 20
# Paced-wire catch-up credit: a pace sleep can wake a scheduler
# quantum late (tens of ms on a busy single-CPU host); the per-socket
# pacing clock lets a late frame start its wire slot where the
# previous slot ended, bounded by this much wall time, so the
# simulated rate holds in AGGREGATE instead of losing one quantum per
# frame (which punished many-small-frame senders — exactly the
# pipelined data plane — relative to one-big-frame senders).
_PACE_CREDIT = 0.05


def encode_payload(data: Any) -> Tuple[dict, bytes]:
    """(descriptor, raw bytes). Arrays go as raw buffers; anything else
    is pickled (the mpi4py generic-object convention)."""
    try:
        import jax
        if isinstance(data, jax.Array):
            data = np.asarray(data)
    except Exception:
        pass
    if isinstance(data, np.ndarray):
        arr = np.ascontiguousarray(data)
        return ({"kind": "nd", "dtype": arr.dtype.str,
                 "shape": arr.shape}, arr.tobytes())
    return {"kind": "obj"}, pickle.dumps(data)


def decode_payload(desc: dict, raw: bytes) -> Any:
    if desc.get("kind") == "nd":
        return np.frombuffer(raw, dtype=np.dtype(desc["dtype"])) \
                 .reshape(desc["shape"]).copy()
    return pickle.loads(raw)


class TcpEndpoint:
    """One per process: the rank's listen socket + lazy peer connections.

    ``sink(header, payload_bytes)`` is called from reader threads for
    every arriving frame; it must be thread-safe.
    """

    def __init__(self, rank: int, nprocs: int,
                 kv_set: Callable[[str, str], None],
                 kv_get: Callable[[str], str],
                 sink: Callable[[dict, bytes], None],
                 on_peer_lost: Optional[Callable[[int], None]] = None):
        self.rank = rank
        self.nprocs = nprocs
        self._kv_get = kv_get
        self.sink = sink
        # failure-detector ingress (the PRRTE-daemon-notices-a-dead-
        # process role): called with the peer rank when an identified
        # inbound connection hits EOF/error before close()
        self.on_peer_lost = on_peer_lost
        self._peers: Dict[int, socket.socket] = {}
        self._peer_locks: Dict[int, threading.Lock] = {}
        # multi-rail striping (bml.send_segment): rails >= 1 are EXTRA
        # connections to the same peer listener, each with its own send
        # lock so bulk/paced sends on different rails genuinely overlap
        # — rail 0 is the ordinary _peers socket
        self._rail_peers: Dict[Tuple[int, int], socket.socket] = {}
        self._rail_locks: Dict[Tuple[int, int], threading.Lock] = {}
        self._lock = threading.Lock()
        self._closed = False
        # reader threads must NEVER block sending (acks, RMA replies):
        # a reader stuck in sendall behind a full socket stops
        # recv()ing, and two ranks doing bidirectional bulk sends then
        # deadlock permanently (each app thread fills the socket, each
        # reader waits to ack). Reader-originated frames divert to a
        # PER-PEER ctl sender thread — readers always keep reading, so
        # kernel buffers always drain and every sendall eventually
        # progresses; per-peer queues keep one slow destination from
        # head-of-line-blocking acks to every other peer. The bound
        # gives backpressure against pathological reply floods (RMA
        # get storms) without reintroducing the reader-block cycle in
        # any realistic regime.
        self._reader_tls = threading.local()
        self._ctl_qs: Dict[int, "queue.Queue"] = {}
        self._ctl_failed: set = set()    # peers whose ctl link died:
        # reported to the failure detector ONCE, further frames dropped
        # ctl backpressure is BY BYTES, not frame count: a burst of
        # >1024 tiny acks is normal traffic on the sub-eager fast path
        # and must never read as a dead peer (the round-5 false-peer-
        # down); only a pathological flood — queued bytes past a bound
        # no healthy link accumulates — fails the link
        self._ctl_q_bytes: Dict[int, int] = {}
        # ctl-frame batching observability (the flush-window win):
        # frames in == sendall batches out + pokes deduplicated
        self.ctl_stats = {"frames": 0, "batches": 0, "poke_dedup": 0}

        # optional paced-wire mode: btl_tcp_sim_gbps > 0 floors each
        # frame's wall time at nbytes / rate — the slow-tier (DCN)
        # simulator for algorithm and compression A/Bs on hosts whose
        # loopback is far faster than any real cross-host fabric (the
        # reference's btl latency/bandwidth params made the same
        # tier-shape assumptions selectable). Off (0) by default:
        # byte-identical behavior and no extra clock reads.
        from ompi_tpu.mca import var as _var
        _var.var_register(
            "btl", "tcp", "sim_gbps", vtype="float", default=0.0,
            help="When > 0, pace tcp frame sends to this many GB/s "
                 "(wall-time floor per frame) — a simulated slow "
                 "tier for algorithm/compression A/Bs; 0 disables")
        self._sim_bps = float(_var.var_get("btl_tcp_sim_gbps", 0.0)) \
            * 1e9
        # per-socket pacing clocks (keyed by send-lock identity; each
        # entry is only touched under that lock — see _pace)
        self._pace_clock: Dict[int, float] = {}

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(max(nprocs, 8))
        host, port = self._listener.getsockname()
        kv_set(f"ompi_tpu/btl/{rank}", f"{host}:{port}")

        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"btl-tcp-accept-{rank}")
        self._accept_thread.start()

    # -- receive side --------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return                       # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                _SOCK_BUF)
            except OSError:
                pass
            t = threading.Thread(target=self._read_loop, args=(conn,),
                                 daemon=True,
                                 name=f"btl-tcp-read-{self.rank}")
            t.start()

    def _read_loop(self, conn: socket.socket) -> None:
        peer = -1                            # set by the hello frame
        rail = 0                             # ditto (extra-rail conns)
        self._reader_tls.active = True       # sends from this thread
        # divert to the ctl sender (see __init__: readers never block)
        # reusable bulk scratch: offset-addressed pipeline segments
        # ("off" in the header) are copied into their train's assembly
        # buffer synchronously inside sink() (pml/pipeline PipeStore),
        # so their payload can land in one per-connection buffer reused
        # across segments — the allocator churn of a fresh multi-MB
        # buffer per segment (and the glibc arena growth it causes on
        # long runs) disappears from the hot receive path
        scratch = bytearray()
        try:
            while not self._closed:
                head = self._read_exact(conn, _LEN.size)
                if head is None:
                    break
                magic, hlen, plen = _LEN.unpack(head)
                if magic != MAGIC:
                    peer = -1                # corrupt stream: drop the
                    break                    # conn, NOT a death report
                hraw = self._read_exact(conn, hlen)
                if hraw is None:
                    break
                try:
                    header = pickle.loads(hraw)
                except Exception:            # noqa: BLE001
                    header = None            # malformed: consume the
                #                              payload, stay framed
                if (header is not None and "pipeseg" in header
                        and "off" in header and plen >= _BULK_MIN):
                    if len(scratch) < plen:
                        scratch = bytearray(plen)
                    view = memoryview(scratch)
                    got = 0
                    while got < plen:
                        n = conn.recv_into(view[got:plen])
                        if not n:
                            got = -1
                            break
                        got += n
                    praw = view[:plen] if got == plen else None
                else:
                    praw = self._read_exact(conn, plen) if plen else b""
                if praw is None:
                    break
                if header is None:
                    continue
                try:
                    if header.get("ctl") == "hello":
                        peer = header["peer"]   # identify the sender
                        rail = int(header.get("rail", 0))
                        continue
                    self.sink(header, praw)
                except Exception:            # noqa: BLE001
                    # a malformed frame or failing handler must not
                    # kill the reader (the finally would then falsely
                    # report a LIVE peer dead); framing stays aligned —
                    # the lengths were already consumed exactly
                    import traceback
                    traceback.print_exc()
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            # EOF/error on an identified inbound connection while the
            # endpoint is alive == the peer process died (graceful
            # shutdown closes AFTER the fini fence, with _closed set).
            # Extra-rail connections (rail > 0) are exempt: a dropped
            # rail is degraded mode — segments detour to rail 0 (bml
            # fallback) — and real death still shows as rail 0's EOF.
            if peer >= 0 and rail == 0 and not self._closed \
                    and self.on_peer_lost:
                try:
                    self.on_peer_lost(peer)
                except Exception:            # noqa: BLE001
                    pass

    @staticmethod
    def _read_exact(conn: socket.socket, n: int) -> Optional[bytes]:
        if n >= _BULK_MIN:
            # bulk payloads: recv straight into the final buffer and
            # hand it out as-is — the recv-chunk concatenation AND the
            # bytes() copy both disappear (each was a full extra pass
            # over every large-message segment)
            buf = bytearray(n)
            view = memoryview(buf)
            got = 0
            while got < n:
                r = conn.recv_into(view[got:])
                if not r:
                    return None
                got += r
            return buf
        buf = bytearray()
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf.extend(chunk)
        return bytes(buf)

    # -- send side -----------------------------------------------------
    def _connect(self, peer: int) -> socket.socket:
        with self._lock:
            s = self._peers.get(peer)
            if s is not None:
                return s
        addr = self._kv_get(f"ompi_tpu/btl/{peer}")
        host, port = addr.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=60)
        # the 60 s budget is for the CONNECT only: data sends must
        # never carry it — a multi-GB sendall on a loaded host can
        # legitimately take minutes (observed: a 2.1 GB bigcount
        # frame spuriously timing out mid-transfer), and peer DEATH
        # is detected by the reader's EOF machinery, not send
        # timeouts (sendall fails fast with ECONNRESET when the
        # peer really dies)
        s.settimeout(None)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, _SOCK_BUF)
        except OSError:
            pass
        with self._lock:
            # lost race: keep the first connection
            cur = self._peers.setdefault(peer, s)
            won = cur is s
            self._peer_locks.setdefault(peer, threading.Lock())
        if not won:
            s.close()        # never sent a byte: unidentified, no
            return cur       # false positive at the peer's detector
        # identify ourselves so the peer's failure detector knows whose
        # EOF this connection's death would be
        hraw = pickle.dumps({"ctl": "hello", "peer": self.rank})
        with self._peer_locks[peer]:
            s.sendall(_LEN.pack(MAGIC, len(hraw), 0) + hraw)
        return s

    def _connect_rail(self, peer: int, rail: int) -> socket.socket:
        """An extra per-peer channel (multi-rail striping): rails >= 1
        open additional connections to the same published listener.
        The hello carries the rail index so the peer's reader knows
        this connection's EOF is a dropped RAIL, not a dead PROCESS —
        rail 0 remains the failure detector's wire."""
        key = (peer, rail)
        with self._lock:
            s = self._rail_peers.get(key)
            if s is not None:
                return s
        addr = self._kv_get(f"ompi_tpu/btl/{peer}")
        host, port = addr.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=60)
        s.settimeout(None)                   # same contract as _connect:
        s.setsockopt(socket.IPPROTO_TCP,     # death is the reader's EOF
                     socket.TCP_NODELAY, 1)  # business, never a timeout
        try:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, _SOCK_BUF)
        except OSError:
            pass
        with self._lock:
            cur = self._rail_peers.setdefault(key, s)
            won = cur is s
            self._rail_locks.setdefault(key, threading.Lock())
        if not won:
            s.close()                        # lost race, never sent
            return cur
        hraw = pickle.dumps({"ctl": "hello", "peer": self.rank,
                             "rail": rail})
        with self._rail_locks[key]:
            s.sendall(_LEN.pack(MAGIC, len(hraw), 0) + hraw)
        return s

    def evict_rail_socket(self, peer: int, rail: int) -> None:
        """Drop a broken rail connection; the next segment on this
        rail reconnects (the caller meanwhile detours via rail 0)."""
        with self._lock:
            s = self._rail_peers.pop((peer, rail), None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def send_frame_rail(self, peer: int, header: dict, payload: bytes,
                        rail: int) -> None:
        """Blocking send over one rail's dedicated socket (rail <= 0 ==
        the ordinary path). Each rail holds its OWN lock, so the paced
        wall-time floor (btl_tcp_sim_gbps) applies per rail — N rails
        aggregate simulated bandwidth exactly as N NICs would."""
        if rail <= 0 or peer == self.rank:
            self.send_frame(peer, header, payload)
            return
        try:
            s = self._connect_rail(peer, rail)
            self._sendmsg(s, self._rail_locks[(peer, rail)], header,
                          payload)
        except OSError as e:
            # broken rail: evict so the next attempt reconnects; the
            # caller (bml's rail sender) detours this segment to the
            # rail-0 socket — the same structured PeerDownError the
            # primary path raises, so detour logic never has to parse
            # raw socket exceptions
            self.evict_rail_socket(peer, rail)
            raise PeerDownError(peer, e) from e

    def _evict_peer_socket(self, peer: int) -> None:
        """Drop a broken cached connection so the next send
        reconnects (a retry against the same dead socket can never
        succeed)."""
        with self._lock:
            s = self._peers.pop(peer, None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def _ctl_peer_down(self, peer: int) -> None:
        """The peer's ctl link is dead or wedged: report ONCE to the
        failure detector (same contract as a reader-side EOF), drain
        and discard its queued frames (every later frame from this
        rank is undeliverable anyway), and drop future ones."""
        with self._lock:
            if peer in self._ctl_failed:
                return
            self._ctl_failed.add(peer)
            self._ctl_q_bytes[peer] = 0
            q = self._ctl_qs.get(peer)
        if q is not None:
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
        if not self._closed and self.on_peer_lost:
            try:
                self.on_peer_lost(peer)
            except Exception:                # noqa: BLE001
                pass

    def _ctl_send_loop(self, q: "queue.Queue", peer: int) -> None:
        try:
            self._ctl_send_loop_inner(q, peer)
        finally:
            # shutdown/abort hygiene: whatever exit path the loop took
            # (retire sentinel, dead link, injected rank-kill racing
            # close()), leave the queue EMPTY so no frame lingers as
            # replayable state and the thread exits instead of
            # spinning against a dead socket
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break

    def _ctl_send_loop_inner(self, q: "queue.Queue", peer: int) -> None:
        while True:
            item = q.get()
            if item is None or self._closed:
                return
            # adaptive flush window: everything already queued behind
            # this frame coalesces into ONE sendall (pokes, acks, and
            # small payload frames to the same peer batch naturally
            # under load); an isolated frame sees an empty queue and
            # goes out immediately — the bypass that keeps single-call
            # latency. Duplicate _smpoke doorbells inside one window
            # collapse to one: every poke in the window is pre-send,
            # so the ring records each announced are all published
            # before the surviving poke's drain runs at the peer.
            batch = [item]
            retire = False
            while True:
                try:
                    nxt = q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    retire = True            # close(): flush, then exit
                    break
                batch.append(nxt)
            cost = sum(len(p) + _CTL_FRAME_OVERHEAD for _, p in batch)
            if len(batch) > 1:
                seen_poke = False
                deduped = []
                for header, payload in batch:
                    if header.get("ctl") == "_smpoke":
                        if seen_poke:
                            self.ctl_stats["poke_dedup"] += 1
                            continue
                        seen_poke = True
                    deduped.append((header, payload))
                batch = deduped
            with self._lock:
                self._ctl_q_bytes[peer] = max(
                    0, self._ctl_q_bytes.get(peer, 0) - cost)
            # frames carry the bml's per-sender sequence number drawn
            # at enqueue: silently dropping one would park EVERY
            # later frame from this rank in the receiver's reorder
            # buffer forever. Retry transient failures (evicting the
            # cached socket so the retry actually reconnects); a
            # persistent failure is a dead link — fail the peer once
            # and stop, rather than wedge or thrash.
            # trace the flush window (span "btl_ctl_flush"): when the
            # timeline shows a collective blocked, this is where "the
            # ctl sender was wedged behind a big sendall" becomes
            # visible; free when tracing is off (one attribute read)
            tok = (_trace.begin("btl_ctl_flush", peer=peer,
                                frames=len(batch), bytes=cost)
                   if _trace.active else None)
            if _tele.active:
                # telemetry: flush-window width — frames coalesced per
                # sendall; a widening histogram means the ctl sender is
                # falling behind its queue
                hist = _tele.FLUSH
                hist.record(len(batch))
            sent = False
            try:
                for attempt in range(3):
                    try:
                        self._send_batch_blocking(peer, batch)
                        sent = True
                        break
                    except Exception:        # noqa: BLE001
                        if self._closed:
                            return
                        self._evict_peer_socket(peer)
                        time.sleep(0.05 * (attempt + 1))
            finally:
                if tok is not None:
                    _trace.end(tok, sent=sent)
            if not sent:
                self._ctl_peer_down(peer)
                return
            self.ctl_stats["frames"] += len(batch)
            self.ctl_stats["batches"] += 1
            if retire:
                return

    def _ctl_submit(self, peer: int, header: dict,
                    payload: bytes) -> None:
        with self._lock:
            if self._closed or peer in self._ctl_failed:
                return                       # undeliverable: drop
            # backpressure by BYTES with a large bound: a frame-count
            # cap read normal ack bursts as a dead peer (the round-5
            # false-peer-down at 1024 frames). The queue itself is
            # unbounded; only queued bytes no live link accumulates
            # (the ctl sender wedged behind an unbounded sendall for
            # the whole window) fail it.
            pending = self._ctl_q_bytes.get(peer, 0) \
                + len(payload) + _CTL_FRAME_OVERHEAD
            if pending > _CTL_MAX_BYTES:
                over = True
            else:
                over = False
                self._ctl_q_bytes[peer] = pending
                q = self._ctl_qs.get(peer)
                if q is None:
                    q = self._ctl_qs[peer] = queue.Queue()
                    threading.Thread(
                        target=self._ctl_send_loop, args=(q, peer),
                        daemon=True,
                        name=f"btl-tcp-ctl-{self.rank}-{peer}").start()
        if over:
            self._ctl_peer_down(peer)
            return
        try:
            # NEVER block the reader — not even on a wedged queue (a
            # blocking put here would reintroduce the exact
            # reader-block deadlock this path exists to prevent).
            q.put_nowait((header, payload))
        except queue.Full:                   # foreign bounded queue
            self._ctl_peer_down(peer)        # (tests): same contract

    def send_frame(self, peer: int, header: dict,
                   payload: bytes = b"") -> None:
        """Self-sends loop back without touching a socket (btl/self)."""
        if peer == self.rank:
            self.sink(header, payload)
            return
        if getattr(self._reader_tls, "active", False):
            # reader thread: never block on a socket send (deadlock
            # cycle with a peer whose reader is equally stuck) — hand
            # the frame to the peer's ctl sender and return to recv()
            self._ctl_submit(peer, header, payload)
            return
        if _inject.active:               # fault-injection plane: one
            self._inject_faults(peer)    # attribute read when off
        self._send_frame_blocking(peer, header, payload)

    # -- fault injection (ft/inject: the tcp-plane hook site) ----------
    def _inject_faults(self, peer: int) -> None:
        """Runs only on app/sender threads (never readers — a delayed
        reader would stall every peer's drain) with the gate open."""
        act = _inject.frame_fault("tcp", peer)
        if act is not None and act[0] == "delay":
            _inject.delay_now(act[1])
        if _inject.should_sever(peer):
            self._sever_peer(peer)
        if _inject.should_corrupt(peer):
            self._send_corrupt(peer)
            # evict our own socket too: the receiver is about to drop
            # its end at the bad magic, and any SEQUENCE-STAMPED frame
            # still in flight there would be lost — a permanent hole in
            # the peer's reorder buffer (unlike "drop", which fires
            # pre-stamp). A fresh connection carries the frame that
            # triggered the injection, so corruption costs exactly one
            # reconnect and zero sequenced frames.
            self._evict_peer_socket(peer)

    def _sever_peer(self, peer: int) -> None:
        """Abruptly cut the rail-0 connection (injected network cut):
        SO_LINGER 0 turns the close into an RST, so the peer's reader
        observes exactly what a process death looks like on the wire —
        an error on an identified connection."""
        with self._lock:
            s = self._peers.pop(peer, None)
        if s is None:
            return
        try:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                         struct.pack("ii", 1, 0))
        except OSError:
            pass
        try:
            s.close()
        except OSError:
            pass

    def _send_corrupt(self, peer: int) -> None:
        """Injected wire corruption: a frame whose magic is wrong. The
        peer's framing check drops the connection WITHOUT a death
        report (tcp _read_loop's corrupt-stream contract); the caller
        evicts this side's socket in the same breath, so the next send
        reconnects — the recovery the test_ft_corrupt_recovers drill
        asserts."""
        try:
            s = self._connect(peer)
            hraw = pickle.dumps({"ctl": "_corrupt"})
            bad = _LEN.pack(MAGIC ^ 0x00BAD000, len(hraw), 0) + hraw
            with self._peer_locks[peer]:
                s.sendall(bad)
        except OSError:
            pass

    def _pace(self, key: int, nbytes: int, t0: float) -> None:
        """Paced-wire floor (btl_tcp_sim_gbps): hold the sender until
        the frame's simulated wire slot has elapsed. Slots are issued
        from a per-socket clock — a frame's slot begins where the
        previous frame's slot ended (with at most _PACE_CREDIT of
        catch-up), so sleep-wakeup overshoot doesn't compound and a
        segment train paces at the same aggregate rate as one large
        frame. Callers hold the socket's send lock, which is what
        serializes access to this key's clock entry."""
        budget = nbytes / self._sim_bps
        clock = self._pace_clock.get(key)
        start = t0 if clock is None else max(clock, t0 - _PACE_CREDIT)
        deadline = start + budget
        self._pace_clock[key] = deadline
        remain = deadline - time.perf_counter()
        if remain > 0:
            time.sleep(remain)

    def _send_frame_blocking(self, peer: int, header: dict,
                             payload: bytes = b"") -> None:
        """One reconnect retry absorbs a stale cached socket (the peer
        dropped a corrupted stream, or an idle connection died); a
        failure on a FRESH connection is structural — raised as
        :class:`PeerDownError` so ``wait()`` surfaces
        MPI_ERR_PROC_FAILED, never a raw socket exception."""
        last: Optional[BaseException] = None
        for attempt in range(2):
            try:
                s = self._connect(peer)
                self._sendmsg(s, self._peer_locks[peer], header, payload)
                return
            except OSError as e:
                last = e
                self._evict_peer_socket(peer)
                if self._closed:
                    break
        raise PeerDownError(peer, last)

    def _sendmsg(self, s: socket.socket, lock: threading.Lock,
                 header: dict, payload) -> None:
        """Frame a header+payload pair onto one socket under its send
        lock. Bulk payloads go as a second sendall instead of being
        concatenated into the prefix (the concat copied every large
        segment once more); both sendalls sit under the same lock, so
        the frame stays contiguous on the wire and receive-side
        framing is untouched. Accepts any buffer (bytes, bytearray,
        memoryview) as payload."""
        hraw = pickle.dumps(header)
        nbytes = len(payload)
        head = _LEN.pack(MAGIC, len(hraw), nbytes) + hraw
        with lock:
            t0 = time.perf_counter() if self._sim_bps > 0 else 0.0
            if nbytes >= _BULK_MIN:
                s.sendall(head)
                s.sendall(payload)
            else:
                s.sendall(head + payload if nbytes else head)
            if self._sim_bps > 0:
                self._pace(id(lock), len(head) + nbytes, t0)

    def _send_batch_blocking(self, peer: int, frames) -> None:
        """One sendall for a whole flush window. Encoding happens
        outside the peer lock; the single syscall keeps the frames
        contiguous on the wire, so receive-side framing (and the
        bml's sequence ordering) is untouched."""
        if len(frames) == 1:
            header, payload = frames[0]
            self._send_frame_blocking(peer, header, payload)
            return
        s = self._connect(peer)
        parts = []
        for header, payload in frames:
            hraw = pickle.dumps(header)
            parts.append(_LEN.pack(MAGIC, len(hraw), len(payload)))
            parts.append(hraw)
            if payload:
                parts.append(payload)
        msg = b"".join(parts)
        lock = self._peer_locks[peer]
        with lock:
            if self._sim_bps > 0:
                t0 = time.perf_counter()
                s.sendall(msg)
                self._pace(id(lock), len(msg), t0)
            else:
                s.sendall(msg)

    def close(self) -> None:
        if self._closed:
            return                       # idempotent: finalize() and
        #                                  an abort path may both call
        self._closed = True
        with self._lock:
            ctl_qs = list(self._ctl_qs.values())
        for q in ctl_qs:                     # retire the ctl senders:
            try:                             # never block close() on a
                q.put_nowait(None)           # full queue — the sender
            except queue.Full:               # also exits on _closed,
                pass                         # unstuck by the socket
            # closes below
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            for s in self._peers.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._peers.clear()
            for s in self._rail_peers.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._rail_peers.clear()
