"""btl/devxfer — device-to-device payload plane for per-rank pt2pt.

Behavioral spec: ob1's rendezvous/RDMA protocol switch
(``pml_ob1_sendreq.h:389-460``) — above the eager limit, bulk payloads
leave the copy-in/copy-out byte path and ride an RDMA get: the sender
publishes the buffer, the receiver pulls it directly.

TPU-native re-design: the PJRT cross-host transfer service
(``jax.experimental.transfer``) is the RDMA-get engine. Each process
starts one transfer server and publishes its address through the
coordination-service KV (the PMIx modex, same as the btl/tcp
addresses). A large ``jax.Array`` send registers the buffer under a
fresh uuid (``await_pull``) and sends only a descriptor header over
the host matching plane; the receiver resolves it with ``pull`` —
device buffers move over the PJRT bulk transport (DCN sockets here,
the same engine that rides ICI/DCN on real TPU slices) and NEVER
round-trip through host pickle. Pulls are one-sided, so there is no
collective-ordering deadlock under THREAD_MULTIPLE.
"""
from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

_KV_PREFIX = "ompi_tpu/xfer/"

_lock = threading.Lock()
_state: Dict[str, Any] = {"server": None, "failed": False}
_conns: Dict[int, Any] = {}
_uuid = itertools.count(1)


def _enabled() -> bool:
    from ompi_tpu.mca import var
    return bool(var.var_get("btl_devxfer_enable", True))


def eager_limit() -> int:
    """Payloads at or above this ride the device plane (the
    btl_rndv_eager_limit role)."""
    from ompi_tpu.mca import var
    return int(var.var_get("btl_devxfer_min_bytes", 1 << 20))


def _server(router) -> Optional[Any]:
    """The process-wide transfer server, started lazily and modex'd.
    Returns None (and remembers the failure) where the PJRT transfer
    engine is unavailable — callers fall back to the host byte path."""
    with _lock:
        if _state["failed"]:
            return None
        srv = _state["server"]
        if srv is None:
            try:
                import jax
                import jax.experimental.transfer as xfer
                client = jax.local_devices()[0].client
                # explicit loopback transport: the default wildcard
                # address is not dialable and the CPU backend CHECKs
                # without a transport address list
                srv = xfer.start_transfer_server(
                    client, "127.0.0.1:0", ["127.0.0.1:0"])
                addr = srv.address().replace("[::]", "127.0.0.1")
                router.kv_set(_KV_PREFIX + str(router.rank), addr)
                _state["server"] = srv
            except Exception:            # noqa: BLE001 — engine absent
                _state["failed"] = True
                return None
        return srv


def try_register(router, data) -> Optional[dict]:
    """Sender-side protocol switch: if ``data`` is a device array at or
    above the eager limit and the transfer engine is up, register it
    for pulling and return the descriptor to ship instead of bytes."""
    if not _enabled():
        return None
    try:
        import jax
        if not isinstance(data, jax.Array):
            return None
    except Exception:                    # noqa: BLE001
        return None
    if data.nbytes < eager_limit() or data.ndim == 0:
        return None
    srv = _server(router)
    if srv is None:
        return None
    uid = next(_uuid)
    try:
        srv.await_pull(uid, [data])
    except Exception:                    # noqa: BLE001 — e.g. a
        return None                      # sharded array the engine
    #                                      rejects: host path instead
    return {"kind": "devrndv", "uuid": uid, "src": router.rank,
            "shape": tuple(data.shape), "dtype": str(data.dtype)}


class DevPayload:
    """Descriptor of a remote device buffer, resolved (pulled) lazily
    on the CONSUMER thread — reader threads stay free to deliver other
    frames. Carries the array metadata so probe/status byte counts are
    right before resolution."""

    def __init__(self, router, desc: dict):
        self._router = router
        self._desc = desc
        self._result = None
        self._done = False
        self._rlock = threading.Lock()
        self.shape = tuple(desc["shape"])
        self.dtype = np.dtype(desc["dtype"])
        self.size = int(np.prod(self.shape)) if self.shape else 1
        self.nbytes = self.size * self.dtype.itemsize

    def resolve(self):
        from ompi_tpu.core.errhandler import (ERR_OTHER,
                                              ERR_PROC_FAILED, MPIError)
        with self._rlock:                # exactly-once, thread-safe
            if self._done:
                return self._result
            import jax
            src = int(self._desc["src"])
            from ompi_tpu.runtime import ft
            if ft.is_failed(src):        # ULFM fail-fast, not a hang
                raise MPIError(ERR_PROC_FAILED,
                               f"device payload source rank {src} "
                               f"has failed before the pull")
            with _lock:
                conn = _conns.get(src)
            if conn is None:
                srv = _server(self._router)
                if srv is None:
                    raise MPIError(ERR_OTHER,
                                   "PJRT transfer engine unavailable "
                                   "on the receive side; peer sent a "
                                   "device-rendezvous payload")
                addr = self._router.kv_get(_KV_PREFIX + str(src))
                conn = srv.connect(addr)
                with _lock:
                    _conns[src] = conn
            sds = jax.ShapeDtypeStruct(
                self.shape, self.dtype,
                sharding=jax.sharding.SingleDeviceSharding(
                    jax.local_devices()[0]))
            try:
                [out] = conn.pull(int(self._desc["uuid"]), [sds])
            except Exception as e:       # noqa: BLE001 — a dying
                # sender breaks the transport (TCP RST) and the pull
                # raises; surface it as the process failure it is
                raise MPIError(ERR_PROC_FAILED,
                               f"device payload pull from rank {src} "
                               f"failed: {type(e).__name__}: {e}")
            self._result = out
            self._done = True
            return out


def maybe_resolve(data):
    """Consumer-side hook: pull a device payload through the transfer
    plane; anything else passes through untouched."""
    if isinstance(data, DevPayload):
        return data.resolve()
    return data


class SegmentStager:
    """Double-buffered device-to-host staging for the pipelined
    rendezvous (pml/pipeline): the async D2H copy of segment s+1 is
    issued when segment s is fetched, so staging overlaps the wire —
    the ``accelerator.h:280`` async-memcpy pattern over
    ``accelerator/framework.to_host_async``. Segments are element
    ranges of the flattened array; slicing stays on-device (a lazy
    JAX op), only the staged copy crosses to host."""

    def __init__(self, arr, elems_per_seg: int):
        from ompi_tpu.accelerator import framework as _fw
        self._mod = _fw.current_module()
        self._flat = arr.reshape(-1)
        self._eps = max(1, int(elems_per_seg))
        self._n = -(-int(self._flat.shape[0]) // self._eps)
        self._ahead: Dict[int, Any] = {}     # idx -> in-flight buffer

    @property
    def nseg(self) -> int:
        return self._n

    def _start(self, i: int) -> None:
        if 0 <= i < self._n and i not in self._ahead:
            seg = self._flat[i * self._eps:(i + 1) * self._eps]
            self._ahead[i] = self._mod.mem_copy_d2h_async(seg)

    def get(self, i: int) -> np.ndarray:
        self._start(i)                   # miss (first / out-of-order
        self._start(i + 1)               # consumer): issue now; then
        #                                  prefetch the NEXT segment
        return np.asarray(self._mod.mem_copy_d2h(self._ahead.pop(i)))


def reset() -> None:
    """Finalize: drop connections and the server (new jobs re-modex)."""
    with _lock:
        _conns.clear()
        _state["server"] = None
        _state["failed"] = False
