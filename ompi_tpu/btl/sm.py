"""btl/sm — shared-memory byte transport between same-host ranks.

Behavioral spec: ``opal/mca/btl/sm`` — per-peer lock-free FIFOs over
POSIX shared memory (``btl_sm_module.c:34-36,95-98``, ``btl_sm_fifo.h``):
each (sender, receiver) pair owns a dedicated single-producer/
single-consumer channel, the receiver polls its inbound set from the
progress loop, and only frames up to the eager limit travel this path
(larger ones switch protocol).

TPU-native re-design: one SPSC ring buffer per ordered rank pair,
backed by ``multiprocessing.shared_memory``. The receiver creates its
inbound rings at init and publishes their names through the
coordination-service KV (the modex); senders attach lazily on first
send (the lazy endpoint connect). Frames reuse btl/tcp's wire format
(magic + header-len + payload-len + pickled header + raw payload), so
the matching engine cannot tell which transport delivered a frame.

Wakeup model: the reference polls its fifos from opal_progress — free
on dedicated cores, but in a GIL runtime a spinning poll thread
convoys with the delivery path (measured: 8x worse ping-pong RTT than
blocking sockets). So this btl is the BANDWIDTH plane only: payload
bytes ride the ring, and the sender's bml follows each push with a
tiny tcp "poke" whose blocking reader thread drains the rings — the
latency plane stays the socket, the bulk bytes skip it. Drains are
serialized by a consumer lock (the SPSC single-consumer contract).

With ``mpi_base_shm_zerocopy`` on, the ring becomes the FRAME plane
only for bulk traffic: payloads at or above
``mpi_base_shm_seg_min_bytes`` are packed once into a shared segment
slot (``btl/shmseg``, same ``tag_for``/ownership discipline as the
rings here) and only the tiny descriptor frame rides the ring+poke
path — the ring's copy-in/copy-out is skipped entirely.

SPSC memory model: head (consumer-owned) and tail (producer-owned) are
monotonically increasing u64 counters at fixed offsets; data writes
happen before the tail store that publishes them, and each side only
ever stores to its own counter — the classic lock-free SPSC contract
(x86-TSO keeps the store order; CPython's opcode granularity means
each 8-byte struct store is a single C memcpy).
"""
from __future__ import annotations

import mmap
import os
import pickle
import struct
import threading
import time
from typing import Callable, Dict, Optional

from ompi_tpu.btl.tcp import MAGIC, _LEN
from ompi_tpu.ft import inject as _inject
from ompi_tpu.trace import core as _trace

_HDR = struct.Struct("<QQ")          # head, tail (bytes consumed/produced)
_REC = struct.Struct("<Q")           # per-record length prefix
# head and tail each own a full 64-byte cache line: the producer's
# tail stores must not invalidate the line the consumer's head loads
# ride on (false sharing on the hot SPSC path)
_TAIL_OFF = 64
DATA_OFF = 128


_SHM_DIR = "/dev/shm" if os.path.isdir("/dev/shm") else \
    os.environ.get("TMPDIR", "/tmp")


def tag_for(coord: str) -> str:
    """Deterministic job token from a coordination-service address.
    SHARED with the launcher's post-reap sweep (``tools/mpirun.py``
    imports this) — the ring-name prefix and the sweep glob must never
    diverge."""
    import hashlib
    return hashlib.md5(coord.encode()).hexdigest()[:10]


def job_tag() -> str:
    """This process's job token (empty outside a launched job)."""
    coord = os.environ.get("OMPI_TPU_MCA_mpi_base_coordinator", "")
    return tag_for(coord) if coord else ""


class Ring:
    """SPSC byte ring over one shared-memory segment.

    Layout: [head u64 @0][tail u64 @64][data @128 .. 128+capacity) —
    offsets from _TAIL_OFF/DATA_OFF, each counter on its own cache
    line.  head/tail count BYTES consumed/produced since creation
    (monotonic, never wrapped); the data offset is counter % capacity.

    Backing is a raw mmap'd file under /dev/shm — NOT
    ``multiprocessing.shared_memory``, whose resource-tracker child
    process measurably degrades scheduling on small hosts (an extra
    runnable process tripled same-host socket RTT on a 1-core box) and
    whose 3.12 tracker unlinks segments on any attacher's exit.  The
    creator owns the file and unlinks it at close.
    """

    def __init__(self, name: Optional[str], capacity: int = 1 << 20,
                 create: bool = False):
        self.capacity = capacity
        size = DATA_OFF + capacity
        if create:
            name = name or f"ompi_tpu_sm_{os.getpid():x}_" \
                           f"{os.urandom(6).hex()}"
            path = os.path.join(_SHM_DIR, name)
            self._fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR,
                               0o600)
            os.ftruncate(self._fd, size)
        else:
            path = os.path.join(_SHM_DIR, name)
            self._fd = os.open(path, os.O_RDWR)
        self.name = name
        self._path = path
        self._created = create
        self._buf = mmap.mmap(self._fd, size)
        if create:
            self._buf[:DATA_OFF] = b"\0" * DATA_OFF

    # -- counters ------------------------------------------------------
    def _head(self) -> int:
        return _REC.unpack_from(self._buf, 0)[0]

    def _tail(self) -> int:
        return _REC.unpack_from(self._buf, _TAIL_OFF)[0]

    def _set_head(self, v: int) -> None:
        _REC.pack_into(self._buf, 0, v)

    def _set_tail(self, v: int) -> None:
        _REC.pack_into(self._buf, _TAIL_OFF, v)

    # -- producer side -------------------------------------------------
    def fits(self, nbytes: int) -> bool:
        """Can a record of nbytes EVER fit? (static check: the eager
        limit; callers fall back to another btl when False)"""
        return _REC.size + nbytes <= self.capacity

    def push(self, record: bytes, timeout: float = 60.0) -> bool:
        """Producer: append one length-prefixed record, waiting for the
        consumer to drain space if needed. False on timeout.
        ``timeout=0`` is a strict try-push: one space check, no wait —
        the form reader-originated sends use (inbound progress must
        never park behind a full peer ring)."""
        need = _REC.size + len(record)
        if need > self.capacity:
            return False
        if timeout <= 0:
            if self.capacity - (self._tail() - self._head()) < need:
                return False
        else:
            deadline = time.monotonic() + timeout
            spins = 0
            while self.capacity - (self._tail() - self._head()) < need:
                spins += 1
                if spins > 200:
                    if time.monotonic() > deadline:
                        return False
                    time.sleep(0.00005)
        tail = self._tail()
        self._write(tail, _REC.pack(len(record)))
        self._write(tail + _REC.size, record)
        # publish AFTER the data is in place (SPSC contract)
        self._set_tail(tail + need)
        return True

    def _write(self, counter: int, data: bytes) -> None:
        off = counter % self.capacity
        first = min(len(data), self.capacity - off)
        base = DATA_OFF + off
        self._buf[base:base + first] = data[:first]
        if first < len(data):                    # wrap
            rest = len(data) - first
            self._buf[DATA_OFF:DATA_OFF + rest] = data[first:]

    # -- consumer side -------------------------------------------------
    def pop(self) -> Optional[bytes]:
        """Consumer: take one record, or None if the ring is empty."""
        head = self._head()
        if self._tail() - head < _REC.size:
            return None
        n = _REC.unpack(self._read(head, _REC.size))[0]
        record = self._read(head + _REC.size, n)
        self._set_head(head + _REC.size + n)
        return record

    def _read(self, counter: int, n: int) -> bytes:
        off = counter % self.capacity
        first = min(n, self.capacity - off)
        base = DATA_OFF + off
        out = bytes(self._buf[base:base + first])
        if first < n:
            out += bytes(self._buf[DATA_OFF:DATA_OFF + n - first])
        return out

    def close(self) -> None:
        try:
            self._buf.close()
        except Exception:                # noqa: BLE001
            pass
        try:
            os.close(self._fd)
        except OSError:
            pass
        if self._created:
            try:
                os.unlink(self._path)
            except OSError:
                pass


class SmEndpoint:
    """The rank's shared-memory plane: inbound rings (created here,
    names modex'd) + lazily-attached outbound rings, one per peer.

    Reuses btl/tcp's frame encoding so the sink sees identical
    (header, payload) pairs regardless of transport.
    """

    def __init__(self, rank: int, nprocs: int,
                 kv_set: Callable[[str, str], None],
                 kv_get: Callable[[str], str],
                 sink: Callable[[dict, bytes], None],
                 ring_bytes: int = 1 << 20):
        self.rank = rank
        self.nprocs = nprocs
        self._kv_get = kv_get
        self.sink = sink
        self.ring_bytes = ring_bytes
        self._closed = False
        self._out: Dict[int, Ring] = {}
        self._out_lock = threading.Lock()
        self._drain_lock = threading.Lock()  # single-consumer contract
        # the SPSC ring admits ONE producer; sends can arrive from the
        # app thread and tcp reader threads (RMA replies) concurrently,
        # so each outbound ring gets a producer lock (tcp's per-peer
        # _peer_locks discipline)
        self._push_locks: Dict[int, threading.Lock] = {}

        # receiver-created inbound rings (the btl/sm FIFO per peer).
        # Names carry the job tag so the launcher can sweep segments a
        # killed rank leaked (the shmem-framework cleanup role) — a
        # crash between create and close must not accrete in /dev/shm.
        tag = job_tag()
        self._in: Dict[int, Ring] = {}
        for src in range(nprocs):
            if src == rank:
                continue
            name = f"otpusm_{tag}_{rank}_{src}" if tag else None
            if name:
                try:                     # stale leftover from a crashed
                    os.unlink(os.path.join(_SHM_DIR, name))  # same-tag
                except OSError:          # job: reclaim the name
                    pass
            ring = Ring(name, ring_bytes, create=True)
            self._in[src] = ring
            kv_set(f"ompi_tpu/btlsm/{rank}/{src}", ring.name)

    # -- receive side --------------------------------------------------
    def drain(self, src: Optional[int] = None) -> int:
        """Pop and deliver every pending record (from one sender, or
        all); called from the tcp reader thread that received the poke.
        Returns the number of records delivered."""
        if self._closed:
            return 0
        rings = ([self._in[src]] if src is not None and src in self._in
                 else list(self._in.values()))
        n = 0
        tok = (_trace.begin("btl_sm_drain", src=src)
               if _trace.active else None)
        try:
            with self._drain_lock:
                for ring in rings:
                    rec = ring.pop()
                    while rec is not None:
                        n += 1
                        self._deliver(rec)
                        rec = ring.pop()
        finally:
            if tok is not None:
                if n:                    # empty polls would swamp the
                    _trace.end(tok, frames=n)    # ring with noise
        return n

    def _deliver(self, rec: bytes) -> None:
        try:
            magic, hlen, plen = _LEN.unpack_from(rec, 0)
            if magic != MAGIC:
                return
            hraw = rec[_LEN.size:_LEN.size + hlen]
            praw = rec[_LEN.size + hlen:_LEN.size + hlen + plen]
            self.sink(pickle.loads(hraw), praw)
        except Exception:                # noqa: BLE001
            import traceback
            traceback.print_exc()

    # -- send side -----------------------------------------------------
    def _attach(self, peer: int) -> Ring:
        with self._out_lock:
            ring = self._out.get(peer)
            if ring is not None:
                return ring
        name = self._kv_get(f"ompi_tpu/btlsm/{peer}/{self.rank}")
        if isinstance(name, bytes):
            name = name.decode()
        ring = Ring(name, self.ring_bytes)
        with self._out_lock:
            return self._out.setdefault(peer, ring)

    def try_send(self, peer: int, header: dict, payload: bytes,
                 timeout: float = 60.0) -> bool:
        """Send one frame if it fits the ring (the eager path); False
        tells the caller (bml) to route via another btl. Reader-thread
        callers pass ``timeout=0``: a full peer ring must divert the
        frame to tcp immediately, not stall inbound progress for up to
        the full producer window."""
        if _inject.active:
            # sm-plane fault hook (ft/inject): "drop" here means THIS
            # transport refuses the frame — bml's fallback carries it
            # over tcp (a full/broken ring's signature), so delivery
            # stays correct while the fallback path gets exercised.
            # Delay executes only on callers that may block (the same
            # rule the routing timeout encodes).
            act = _inject.frame_fault("sm", peer)
            if act is not None:
                if act[0] == "drop":
                    return False
                if timeout > 0:
                    _inject.delay_now(act[1])
        hraw = pickle.dumps(header)
        rec = _LEN.pack(MAGIC, len(hraw), len(payload)) + hraw + payload
        ring = self._attach(peer)
        if not ring.fits(len(rec)):
            return False
        with self._out_lock:
            lock = self._push_locks.setdefault(peer, threading.Lock())
        if timeout <= 0:
            if not lock.acquire(blocking=False):
                return False             # a busy producer IS a wait
            try:
                return ring.push(rec, timeout=0)
            finally:
                lock.release()
        with lock:
            return ring.push(rec, timeout=timeout)

    def close(self) -> None:
        self._closed = True
        with self._drain_lock:           # no drain mid-teardown
            for ring in self._out.values():
                ring.close()
            for ring in self._in.values():
                ring.close()
