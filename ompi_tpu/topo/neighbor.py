"""Device-native neighbor collectives — ppermute waves along topology
edges.

Behavioral spec: the neighborhood collectives of the base registry
(``ompi/mca/coll/base/coll_base_functions.h:185-320``) over the topo
framework (``ompi/mca/topo/``): each rank exchanges buffers with its
cart/graph neighbors; cart shifts are the halo-exchange workhorse.

TPU-native re-design (round 3 — the round-2 versions were host NumPy
round-trips, VERDICT weak #6): a neighbor exchange IS a set of
``ppermute`` patterns. Every (source → dest) topology edge is assigned
to a *wave* by greedy edge coloring (each wave touches every rank at
most once as source and once as dest — König: ≤ max-degree waves on the
bipartite edge graph); each wave is ONE ``jax.lax.ppermute`` over the
communicator's mesh axis, i.e. one XLA collective-permute riding ICI
neighbor links. A cart dimension's ± shifts color into single waves, so
a 2-D halo exchange compiles to 4 collective-permutes — exactly the
hand-written pattern. Chunk selection (alltoall's per-edge chunks) and
result assembly are local ``take_along_axis`` ops on the sharded rank
axis; nothing touches the host.
"""
from __future__ import annotations

from collections import defaultdict, deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

AXIS = "mpi_r"


class NeighborPlan:
    """Edge-colored exchange schedule for one (comm, topo)."""

    def __init__(self, comm):
        topo = comm.topo
        n = comm.size
        in_nb = topo.neighbors
        out_nb = getattr(topo, "out_neighbors", topo.neighbors)
        self.n = n
        self.in_lists = [list(in_nb(r)) for r in range(n)]
        self.out_lists = [list(out_nb(r)) for r in range(n)]
        self.max_in = max((len(l) for l in self.in_lists), default=0)
        self.max_out = max((len(l) for l in self.out_lists), default=0)
        # valid in-slot index lists (host API compresses invalid slots)
        self.valid_slots = [
            [i for i, s in enumerate(l) if 0 <= s < n]
            for l in self.in_lists]
        self.slot_valid = np.zeros((n, max(self.max_in, 1)), bool)
        for r, l in enumerate(self.in_lists):
            for i, s in enumerate(l):
                self.slot_valid[r, i] = 0 <= s < n

        # FIFO multiplicity pairing of (src,dst) out-slots with in-slots
        # (duplicate edges from periodic dims of size <= 2 / multigraphs)
        out_q: Dict[Tuple[int, int], deque] = defaultdict(deque)
        for s in range(n):
            for j, d in enumerate(self.out_lists[s]):
                if 0 <= d < n:
                    out_q[(s, d)].append(j)
        # edge = (src, dst, out_slot or None, in_slot)
        edges: List[Tuple[int, int, Optional[int], int]] = []
        for d in range(n):
            for i, s in enumerate(self.in_lists[d]):
                if not (0 <= s < n):
                    continue
                q = out_q.get((s, d))
                j = q.popleft() if q else None
                edges.append((s, d, j, i))

        # Greedy edge coloring: a wave may use each rank once as source
        # and once as destination (ppermute constraint + one chunk per
        # source per wave). König: a bipartite multigraph needs at most
        # max-degree colors, so W stays small (cart: 2 per dimension).
        waves: List[dict] = []
        # assembly maps: out[r, i] = wave_out[r, wmap[r, i]]
        self.wmap = np.zeros((n, max(self.max_in, 1)), np.int32)
        self.has_chunk = np.zeros((n, max(self.max_in, 1)), bool)
        for (s, d, j, i) in edges:
            for wi, w in enumerate(waves):
                if s not in w["srcs"] and d not in w["dsts"]:
                    break
            else:
                wi = len(waves)
                w = {"perm": [], "jsel": np.zeros(n, np.int32),
                     "srcs": set(), "dsts": set()}
                waves.append(w)
            w["perm"].append((s, d))
            w["jsel"][s] = j if j is not None else 0
            w["srcs"].add(s)
            w["dsts"].add(d)
            self.wmap[d, i] = wi
            self.has_chunk[d, i] = j is not None
        self.waves = waves
        self.n_waves = len(waves)
        self.edges = edges              # (src, dst, out_slot, in_slot)


def _plan(comm) -> NeighborPlan:
    cache = getattr(comm, "_nbr_plan", None)
    if cache is None or cache[0] is not comm.topo:
        cache = (comm.topo, NeighborPlan(comm))
        comm._nbr_plan = cache
    return cache[1]


def _fns(comm) -> Dict:
    """Compiled-exchange cache, owned by the PLAN so a topo reassignment
    invalidates both together (a stale jitted fn would exchange along
    the old topology's edges)."""
    plan = _plan(comm)
    fns = getattr(plan, "_fns", None)
    if fns is None:
        fns = plan._fns = {}
    return fns


def _wave_permute(comm, arr, perm):
    """One wave: a single XLA collective-permute over the mesh axis."""
    import jax
    from jax.sharding import PartitionSpec as P
    from ompi_tpu.coll.xla import _shard_map
    return _shard_map(
        lambda a: jax.lax.ppermute(a, AXIS, perm=perm),
        mesh=comm.mesh, in_specs=P(AXIS), out_specs=P(AXIS))(arr)


def device_neighbor_allgather(comm, x) -> List[Any]:
    """x: stacked (N, *s) device buffer; returns per-rank device arrays
    (deg_r, *s) — each rank's neighbors' buffers in neighbor order."""
    import jax
    import jax.numpy as jnp
    plan = _plan(comm)
    key = ("ag", x.shape, str(x.dtype))
    fn = _fns(comm).get(key)
    if fn is None:
        perms = [tuple(w["perm"]) for w in plan.waves]
        wmap = jnp.asarray(plan.wmap)
        mask = jnp.asarray(plan.slot_valid)

        def build(buf):
            if not perms:
                return jnp.zeros((plan.n, 1) + buf.shape[1:], buf.dtype)
            outs = [_wave_permute(comm, buf, p) for p in perms]
            stacked = jnp.stack(outs, axis=1)        # (N, W, *s)
            idx = wmap.reshape(wmap.shape + (1,) * (buf.ndim - 1))
            res = jnp.take_along_axis(stacked, idx, axis=1)
            m = mask.reshape(mask.shape + (1,) * (buf.ndim - 1))
            return jnp.where(m, res, 0)              # (N, maxD, *s)
        fn = _fns(comm)[key] = jax.jit(build)
    res = fn(x)
    return [res[r, plan.valid_slots[r]] if plan.valid_slots[r]
            else jnp.empty((0,) + x.shape[1:], x.dtype)
            for r in range(plan.n)]


def device_neighbor_alltoall(comm, x) -> List[Any]:
    """x: stacked (N, max_out_deg, *s); rank r's j-th chunk goes to its
    j-th out-neighbor; returns per-rank (deg_in_r, *s) device arrays."""
    import jax
    import jax.numpy as jnp
    plan = _plan(comm)
    key = ("a2a", x.shape, str(x.dtype))
    fn = _fns(comm).get(key)
    if fn is None:
        perms = [tuple(w["perm"]) for w in plan.waves]
        jsels = [jnp.asarray(w["jsel"]) for w in plan.waves]
        wmap = jnp.asarray(plan.wmap)
        mask = jnp.asarray(plan.slot_valid & plan.has_chunk)

        def build(buf):                              # (N, D_out, *s)
            payload = buf.shape[2:]
            if not perms:
                return jnp.zeros((plan.n, 1) + payload, buf.dtype)
            outs = []
            for p, jsel in zip(perms, jsels):
                idx = jsel.reshape((plan.n, 1) + (1,) * len(payload))
                chunk = jnp.take_along_axis(buf, idx, axis=1)[:, 0]
                outs.append(_wave_permute(comm, chunk, p))
            stacked = jnp.stack(outs, axis=1)        # (N, W, *s)
            idx = wmap.reshape(wmap.shape + (1,) * len(payload))
            res = jnp.take_along_axis(stacked, idx, axis=1)
            m = mask.reshape(mask.shape + (1,) * len(payload))
            return jnp.where(m, res, 0)              # (N, maxD_in, *s)
        fn = _fns(comm)[key] = jax.jit(build)
    res = fn(x)
    return [res[r, plan.valid_slots[r]] if plan.valid_slots[r]
            else jnp.empty((0,) + x.shape[2:], x.dtype)
            for r in range(plan.n)]
