"""Process topologies — mirrors ``ompi/mca/topo`` (base + basic;
treematch reordering becomes physical-mesh-aware rank mapping).

TPU-native meaning: a cartesian topology over a communicator *is* a
logical device mesh — ``MPI_Cart_create`` on a comm whose devices form
an ICI mesh lays ranks out so that cart neighbors are ICI neighbors
(``reorder=True`` sorts by device coords when the backend exposes them,
the role treematch's graph embedding plays in the reference).
``cart_shift`` + ``sendrecv``/``ppermute`` is then a physical ring.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

from ompi_tpu.core.errhandler import ERR_ARG, ERR_TOPOLOGY, MPIError


def dims_create(nnodes: int, ndims: int,
                dims: Optional[Sequence[int]] = None) -> List[int]:
    """MPI_Dims_create: balanced factorization of nnodes over ndims,
    honoring fixed (nonzero) entries."""
    out = list(dims) if dims is not None else [0] * ndims
    fixed = 1
    for d in out:
        if d:
            fixed *= d
    if fixed <= 0 or nnodes % fixed:
        raise MPIError(ERR_ARG, f"cannot factor {nnodes} over fixed {out}")
    rem = nnodes // fixed
    free = [i for i, d in enumerate(out) if d == 0]
    # Greedy: repeatedly assign the largest prime factor to the smallest
    # current dimension (matches the reference's balanced split).
    factors: List[int] = []
    n = rem
    p = 2
    while p * p <= n:
        while n % p == 0:
            factors.append(p)
            n //= p
        p += 1
    if n > 1:
        factors.append(n)
    if factors and not free:
        # every slot fixed but nnodes has leftover factors: silently
        # returning dims whose product != nnodes would size a cart
        # over a subset of the processes (MPI mandates an error)
        raise MPIError(ERR_ARG,
                       f"MPI_Dims_create: {nnodes} nodes are not "
                       f"consistent with fully-fixed dims {out}")
    vals = {i: 1 for i in free}
    for f in sorted(factors, reverse=True):
        i = min(free, key=lambda j: vals[j])
        vals[i] *= f
    # MPI mandates the computed dimensions appear in non-increasing
    # order across the free slots.
    for i, v in zip(free, sorted(vals.values(), reverse=True)):
        out[i] = v
    return out


class CartTopology:
    def __init__(self, dims: Sequence[int], periods: Sequence[bool]):
        self.dims = tuple(int(d) for d in dims)
        self.periods = tuple(bool(p) for p in periods)
        self.ndims = len(self.dims)
        self.size = math.prod(self.dims)

    def rank(self, coords: Sequence[int]) -> int:
        """MPI_Cart_rank (row-major, periodic wrap where allowed)."""
        r = 0
        for d, (c, n, per) in enumerate(zip(coords, self.dims,
                                            self.periods)):
            if per:
                c = c % n
            elif not (0 <= c < n):
                raise MPIError(ERR_TOPOLOGY,
                               f"coord {c} out of range in dim {d}")
            r = r * n + c
        return r

    def coords(self, rank: int) -> Tuple[int, ...]:
        out = []
        for n in reversed(self.dims):
            out.append(rank % n)
            rank //= n
        return tuple(reversed(out))

    def shift(self, rank: int, direction: int,
              disp: int) -> Tuple[int, int]:
        """MPI_Cart_shift: (source, dest) for a shift along a dim;
        -2 (MPI_PROC_NULL) at non-periodic boundaries."""
        c = list(self.coords(rank))

        def move(delta):
            cc = list(c)
            cc[direction] += delta
            n = self.dims[direction]
            if self.periods[direction]:
                cc[direction] %= n
            elif not (0 <= cc[direction] < n):
                return -2
            return self.rank(cc)
        return move(-disp), move(disp)

    def neighbors(self, rank: int) -> List[int]:
        """Cart neighborhood order per MPI: for each dim, -1 then +1."""
        out = []
        for d in range(self.ndims):
            src, dst = self.shift(rank, d, 1)
            out.extend([src, dst])
        return out

    def sub_keep(self, remain: Sequence[bool]):
        """MPI_Cart_sub helper: returns (colors, new_topology) — ranks
        sharing dropped-dim coords share a color."""
        colors = []
        for r in range(self.size):
            c = self.coords(r)
            colors.append(tuple(ci for ci, keep in zip(c, remain)
                                if not keep))
        palette = {v: i for i, v in enumerate(sorted(set(colors)))}
        new = CartTopology(
            [n for n, keep in zip(self.dims, remain) if keep],
            [p for p, keep in zip(self.periods, remain) if keep])
        return [palette[c] for c in colors], new


class GraphTopology:
    """MPI_Graph_create: index/edges CSR adjacency."""

    def __init__(self, index: Sequence[int], edges: Sequence[int]):
        self.index = tuple(index)
        self.edges = tuple(edges)
        self.size = len(self.index)

    def neighbors(self, rank: int) -> List[int]:
        lo = self.index[rank - 1] if rank > 0 else 0
        return list(self.edges[lo:self.index[rank]])

    def neighbors_count(self, rank: int) -> int:
        return len(self.neighbors(rank))


class DistGraphTopology:
    """MPI_Dist_graph_create_adjacent: explicit per-rank in/out lists."""

    def __init__(self, sources: Sequence[Sequence[int]],
                 destinations: Sequence[Sequence[int]]):
        self.sources = [list(s) for s in sources]
        self.destinations = [list(d) for d in destinations]
        self.size = len(self.sources)

    def neighbors(self, rank: int) -> List[int]:
        return self.sources[rank]

    def out_neighbors(self, rank: int) -> List[int]:
        return self.destinations[rank]
