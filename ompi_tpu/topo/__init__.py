from ompi_tpu.topo.cart import (CartTopology, GraphTopology,  # noqa: F401
                                DistGraphTopology, dims_create)
