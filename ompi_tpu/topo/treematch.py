"""topo/treematch — communication-aware rank reordering.

Behavioral spec: ``ompi/mca/topo/treematch`` (embedding the TreeMatch
library): given the application's communication graph (from
``MPI_Graph_create``/``MPI_Dist_graph_create`` with ``reorder=1``) and
the hardware topology tree (hwloc), permute ranks so heavily
communicating pairs land on close hardware.

TPU-native re-design: the hardware metric is the ICI mesh — distance
between two ranks is the Manhattan distance between their devices'
physical ``coords`` (neighbor chips = 1 hop), plus a fabric penalty when
the devices belong to different host processes (the DCN tier). The
placement heuristic is TreeMatch's constructive core: seed with the
heaviest-communicating rank, then repeatedly place the rank with the
largest traffic to already-placed ranks onto the free slot minimizing
its weighted hop count.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


def hardware_distance(devices) -> np.ndarray:
    """Pairwise hop counts between device slots. Manhattan distance on
    physical coords when exposed (the ICI mesh); |i-j| as the linear
    fallback; +8 penalty per process boundary (the DCN tier)."""
    from ompi_tpu.accelerator.framework import device_locality
    n = len(devices)
    locs = [device_locality(d) for d in devices]
    coords = [c if c else (i,) for i, (_p, c) in enumerate(locs)]
    width = max(len(c) for c in coords)
    coords = [c + (0,) * (width - len(c)) for c in coords]
    arr = np.asarray(coords, dtype=np.int64)
    dist = np.abs(arr[:, None, :] - arr[None, :, :]).sum(axis=2)
    procs = np.asarray([p for p, _c in locs])
    dist = dist + 8 * (procs[:, None] != procs[None, :])
    return dist.astype(np.float64)


def comm_matrix_from_graph(index: Sequence[int], edges: Sequence[int]
                           ) -> np.ndarray:
    """Symmetric traffic matrix from an MPI_Graph_create (index, edges)
    adjacency (unit weight per edge — the information the API carries)."""
    n = len(index)
    m = np.zeros((n, n))
    prev = 0
    for r, end in enumerate(index):
        for e in edges[prev:end]:
            m[r, e] += 1.0
            m[e, r] += 1.0
        prev = end
    return m


def treematch_permutation(comm_matrix: np.ndarray,
                          hw_dist: np.ndarray) -> List[int]:
    """Constructive placement: returns ``perm`` with ``perm[rank] =
    hardware slot``. Greedy TreeMatch core: heaviest-traffic rank
    first, then max-attached rank onto the cost-minimizing free slot."""
    n = comm_matrix.shape[0]
    if n == 0:
        return []
    cm = np.asarray(comm_matrix, np.float64)
    placed_ranks: List[int] = []
    placed_slots: List[int] = []
    free_mask = np.ones(n, bool)          # free hardware slots
    unplaced_mask = np.ones(n, bool)      # unplaced ranks
    order_seed = int(np.argmax(cm.sum(axis=1)))
    # seed on the most central slot (min total hw distance)
    seed_slot = int(np.argmin(hw_dist.sum(axis=1)))
    placed_ranks.append(order_seed)
    placed_slots.append(seed_slot)
    free_mask[seed_slot] = False
    unplaced_mask[order_seed] = False
    # traffic of every rank to the placed set, updated incrementally
    attach = cm[:, order_seed].copy()
    for _ in range(n - 1):
        # rank with max traffic to the placed set (ties: lowest rank,
        # keeping the permutation deterministic across controllers)
        cand = np.where(unplaced_mask)[0]
        best_rank = int(cand[np.argmax(attach[cand])])
        # slot minimizing weighted distance to placed peers (one
        # matvec: costs[slot] = sum_p cm[rank,p] * hw[slot, slot_of_p])
        w = cm[best_rank, placed_ranks]
        costs = hw_dist[:, placed_slots] @ w
        free = np.where(free_mask)[0]
        best_slot = int(free[np.argmin(costs[free])])
        placed_ranks.append(best_rank)
        placed_slots.append(best_slot)
        free_mask[best_slot] = False
        unplaced_mask[best_rank] = False
        attach += cm[:, best_rank]
    perm = np.empty(n, np.int64)
    perm[placed_ranks] = placed_slots
    return perm.tolist()


def placement_cost(comm_matrix: np.ndarray, hw_dist: np.ndarray,
                   perm: Optional[Sequence[int]] = None) -> float:
    """Total weighted hop count of a placement (identity when perm is
    None) — the objective treematch minimizes; exposed so tools can
    report the before/after gain."""
    n = comm_matrix.shape[0]
    if perm is None:
        perm = list(range(n))
    p = np.asarray(perm)
    return float((comm_matrix * hw_dist[np.ix_(p, p)]).sum() / 2.0)
