"""PML — point-to-point messaging layer (mirrors ``ompi/mca/pml``)."""
