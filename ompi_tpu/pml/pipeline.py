"""pml/pipeline — segment-pipelined rendezvous for large host payloads.

Behavioral spec: ob1's pipelined rendezvous protocol
(``pml_ob1_sendreq.h:389-460``) — above the rendezvous threshold a
payload leaves the single-copy eager path and moves as a train of
fragments, so pack work overlaps the wire, and the send scheduler
(``mca_pml_ob1_send_request_schedule``) round-robins fragments over
every eligible BTL.

TPU-native re-design: host-tier payloads at or above
``mpi_base_pipeline_min_bytes`` are cut into segments (size from the
``coll/decision`` pipeline rows, fed by the bml probe's per-rail
bandwidth estimate; ``mpi_base_pipeline_segment_bytes`` overrides) with
``mpi_base_pipeline_depth`` segments in flight. A small *init* frame
rides the ordered bml stream — it is what MATCHES, so MPI's
non-overtaking rule is untouched — while the segments travel unordered,
striped round-robin over ``mpi_base_btl_rails`` rails
(``btl/bml.send_segment``), each independently packed (the convertor
role), staged D2H (``btl/devxfer.SegmentStager`` double-buffering), and
compressed (``compress/wire`` per segment, whole-message gated), so all
of that work overlaps the wire. The receive side reassembles by segment
index (:class:`PipeStore`), so out-of-order rail delivery is harmless.
When ``mpi_base_shm_zerocopy`` is on, same-host offset-addressed
segments skip the ring copy entirely: the rail sender parks each one in
a shared slot (``btl/shmseg``) and ships only a descriptor, freed the
moment the PipeStore's synchronous copy-out returns.

Observability: ``pml_pipeline_segments`` / ``pml_pipeline_inits`` /
``pml_overlap_ratio`` pvars and ``pml.segment`` trace spans
(docs/LARGEMSG.md).
"""
from __future__ import annotations

import itertools
import pickle
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ompi_tpu.btl.tcp import decode_payload
from ompi_tpu.compress import wire as _cwire
from ompi_tpu.core.errhandler import ERR_PENDING, ERR_PROC_FAILED, MPIError
from ompi_tpu.mca import pvar as _pvar
from ompi_tpu.mca import var as _var
from ompi_tpu.runtime import progress as _progress
from ompi_tpu import telemetry as _tele
from ompi_tpu.trace import core as _trace

# single source of truth for the tuning defaults (the bml convention)
_DEF_MIN_BYTES = 4 << 20
_DEF_SEG_BYTES = 1 << 20
_DEF_DEPTH = 4

_uids = itertools.count(1)


def register_params() -> None:
    _var.var_register(
        "mpi", "base", "pipeline_enable", vtype="bool", default=True,
        help="Segment-pipelined rendezvous for large host-path pt2pt "
             "payloads (docs/LARGEMSG.md); off restores the serial "
             "eager path byte-for-byte")
    _var.var_register(
        "mpi", "base", "pipeline_min_bytes", vtype="int",
        default=_DEF_MIN_BYTES,
        help="Host payloads at or above this take the pipelined "
             "rendezvous (ordered init frame + unordered striped "
             "segment train)")
    _var.var_register(
        "mpi", "base", "pipeline_segment_bytes", vtype="int",
        default=_DEF_SEG_BYTES,
        help="Segment size for the pipelined rendezvous; when left at "
             "the default the effective size comes from the decision "
             "rows (coll/decision.pipeline_plan, fed by the bml "
             "probe's per-rail bandwidth)")
    _var.var_register(
        "mpi", "base", "pipeline_depth", vtype="int", default=_DEF_DEPTH,
        help="Segments in flight per pipelined send (the rendezvous "
             "scheduler window; prep of segment s+depth waits for "
             "segment s's wire slot)")


def enabled() -> bool:
    register_params()
    return bool(_var.var_get("mpi_base_pipeline_enable", True))


def min_bytes() -> int:
    register_params()
    return int(_var.var_get("mpi_base_pipeline_min_bytes",
                            _DEF_MIN_BYTES))


def depth() -> int:
    register_params()
    return max(1, int(_var.var_get("mpi_base_pipeline_depth",
                                   _DEF_DEPTH)))


def segment_bytes_for(total: int, endpoint=None) -> int:
    """Effective segment size for one ``total``-byte transfer: a
    user-set ``mpi_base_pipeline_segment_bytes`` wins; otherwise the
    decision row picks by message size and the probed per-rail
    bandwidth (``btl/bml._probe_stream``'s estimate, reused instead of
    re-probing)."""
    register_params()
    if _var.var_overridden("mpi_base_pipeline_segment_bytes"):
        return max(64 << 10, int(_var.var_get(
            "mpi_base_pipeline_segment_bytes", _DEF_SEG_BYTES)))
    from ompi_tpu.coll import decision
    basis = getattr(endpoint, "probe_basis", None) or {}
    plan = decision.pipeline_plan(
        total, rails=int(getattr(endpoint, "rails", 1) or 1),
        rail_gbps=basis.get("rail_gbps"))
    return int(plan["segment_bytes"])


# -- pvars ------------------------------------------------------------------
stats = {"segments": 0, "inits": 0}
_gauges = {"overlap_ratio": 0.0}


def _register_pvars() -> None:
    _pvar.pvar_register(
        "pml_pipeline_segments", lambda: stats["segments"],
        help="Segments sent by the pipelined rendezvous "
             "(docs/LARGEMSG.md)")
    _pvar.pvar_register(
        "pml_pipeline_inits", lambda: stats["inits"],
        help="Pipelined rendezvous trains initiated by this process")
    _pvar.pvar_register(
        "pml_overlap_ratio", lambda: _gauges["overlap_ratio"],
        unit="ratio", var_class="level",
        help="Fraction of the serial cost (segment prep + summed "
             "per-rail wire time) hidden by overlap on the most "
             "recent pipelined send")


# -- receive-side reassembly ------------------------------------------------
class _PipeBuf:
    __slots__ = ("lock", "segs", "nseg", "event", "error", "buf",
                 "have")

    def __init__(self):
        self.lock = threading.Lock()
        self.segs: Dict[int, bytes] = {}
        self.nseg: Optional[int] = None
        self.event = threading.Event()
        self.error: Optional[BaseException] = None
        # offset-addressed trains (uncompressed): ONE payload-sized
        # buffer assembled in place — no per-segment allocations, no
        # join pass, and resolve() hands the buffer to numpy zero-copy
        self.buf: Optional[bytearray] = None
        self.have = 0


class PipeStore:
    """Segment-train reassembly, keyed (source world rank, pipe id).

    Segments arrive unordered from any rail's reader thread; the
    matching init frame may land before, between, or after them (it
    rides the ordered stream, they do not), so both sides get-or-create
    the buffer. One store per :class:`~ompi_tpu.pml.perrank.Router`.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._bufs: Dict[Tuple[int, int], _PipeBuf] = {}

    def _buf(self, key: Tuple[int, int]) -> _PipeBuf:
        with self._lock:
            b = self._bufs.get(key)
            if b is None:
                b = self._bufs[key] = _PipeBuf()
        return b

    def deliver(self, header: dict, raw: bytes) -> None:
        """One segment frame, called from a btl reader thread.

        Segments carrying a byte offset (``off``/``tb``, the
        uncompressed fast path) are copied straight into ONE
        payload-sized assembly buffer — ``raw`` may be a transient
        view (the btl reader's reusable scratch, or the sender's own
        buffer on loopback), since nothing is retained past this call.
        Compressed segments have irregular wire lengths and decode
        later on the consumer thread, so they keep the classic
        per-segment stash (their ``raw`` is always an owned buffer)."""
        b = self._buf((int(header["psrc"]), int(header["pipe"])))
        off = header.get("off")
        with b.lock:
            if b.nseg is None:
                b.nseg = int(header["n"])
            if off is not None:
                if b.buf is None:
                    b.buf = bytearray(int(header["tb"]))
                b.buf[off:off + len(raw)] = raw
                b.have += 1
                done = b.have >= b.nseg
            else:
                b.segs[int(header["idx"])] = raw
                done = len(b.segs) >= b.nseg
        if done:
            _progress.wake(b.event)      # coalesced consumer wake

    def claim(self, psrc: int, uid: int, nseg: int) -> _PipeBuf:
        """The init frame's side: bind the expected train length."""
        b = self._buf((int(psrc), int(uid)))
        with b.lock:
            b.nseg = int(nseg)
            done = (b.have if b.buf is not None
                    else len(b.segs)) >= b.nseg
        if done:
            b.event.set()                # whole train raced the init
        return b

    def forget(self, psrc: int, uid: int) -> None:
        with self._lock:
            self._bufs.pop((int(psrc), int(uid)), None)

    def pending(self) -> int:
        with self._lock:
            return len(self._bufs)

    def fail_peer(self, world_rank: int) -> None:
        """ULFM: a dead sender's unfinished trains can never complete —
        fail their waiters instead of letting them ride the timeout."""
        with self._lock:
            bufs = [b for (src, _), b in self._bufs.items()
                    if src == world_rank]
        err = MPIError(ERR_PROC_FAILED,
                       f"pipelined payload source rank {world_rank} "
                       f"died mid-train")
        for b in bufs:
            b.error = err
            _progress.wake(b.event)


class PipePayload:
    """Descriptor of an in-flight segmented payload — the object that
    MATCHES (probe/status see the right counts) while segments are
    still landing. ``resolve()`` blocks until the train completes and
    assembles on the CONSUMER thread (the DevPayload contract: never
    on a btl reader thread)."""

    def __init__(self, router, desc: dict):
        self._desc = desc
        self._store: PipeStore = router.pipes
        self._buf = self._store.claim(desc["psrc"], desc["pipe"],
                                      desc["nseg"])
        self._result: Any = None
        self._done = False
        self._rlock = threading.Lock()
        inner = desc["inner"]
        self.nbytes = int(desc["nbytes"])
        if inner.get("kind") == "nd":
            self.shape = tuple(inner["shape"])
            self.dtype = np.dtype(inner["dtype"])
            self.size = int(np.prod(self.shape)) if self.shape else 1
        else:
            self.size = 1

    def resolve(self):
        with self._rlock:                # exactly-once, thread-safe
            if self._done:
                return self._result
            b = self._buf
            if not b.event.wait(600):
                raise MPIError(ERR_PENDING,
                               "pipelined payload timed out waiting "
                               "for its segment train")
            if b.error is not None:
                raise b.error
            desc = self._desc
            inner = desc["inner"]
            n = int(desc["nseg"])
            with b.lock:
                buf = b.buf
                segs = None if buf is not None \
                    else [b.segs[i] for i in range(n)]
                b.buf = None
                b.segs = {}
            if buf is not None:
                # offset-assembled train: the assembly buffer IS the
                # payload — numpy adopts it without a copy
                out = np.frombuffer(buf, dtype=self.dtype) \
                    .reshape(self.shape)
            elif inner.get("comp"):
                # per-segment codec: each segment is an independently
                # quantized slice of the flattened payload
                parts = [_cwire.decode(pickle.loads(s)) for s in segs]
                flat = parts[0] if len(parts) == 1 \
                    else np.concatenate([p.reshape(-1) for p in parts])
                out = flat.reshape(self.shape)
            else:
                out = decode_payload(inner, b"".join(segs))
            self._store.forget(desc["psrc"], desc["pipe"])
            self._result = out
            self._done = True
            return out


def maybe_resolve(data):
    """Consumer-side hook: assemble a pipelined payload; anything else
    passes through untouched (composes after devxfer's hook)."""
    if isinstance(data, PipePayload):
        return data.resolve()
    return data


# -- send side --------------------------------------------------------------
def _comp_codec(dtype_name: str, total: int) -> Optional[str]:
    """Per-segment compression gate: the codec gates of
    ``compress/wire.eligible`` applied to the WHOLE message (segments
    individually may sit under the threshold — the nbytes override
    exists for exactly this composition)."""
    from ompi_tpu import compress as _c
    if not _c.enabled():
        return None
    if dtype_name not in ("float32", "float64"):
        return None
    if total < _c.min_bytes():
        return None
    return _c.codec_name()


def maybe_send_pipelined(engine, data: Any, dest: int, tag: int,
                         synchronous: bool):
    """The pml's host-path protocol switch: returns a completed Request
    when the payload took the pipelined rendezvous, or None to fall
    through to the serial eager path. When it returns None, NOTHING
    here has touched the wire — the fallback stays byte-identical."""
    if not enabled():
        return None
    stager = None
    is_dev = False
    try:
        import jax
        is_dev = isinstance(data, jax.Array)
    except Exception:                    # noqa: BLE001
        is_dev = False
    if isinstance(data, np.ndarray) and not is_dev:
        if data.dtype.hasobject or data.ndim == 0:
            return None
        total = int(data.nbytes)
        np_dtype = data.dtype
        shape = tuple(data.shape)
    elif is_dev:
        if data.ndim == 0:
            return None
        try:                             # non-numpy dtypes (bfloat16)
            np_dtype = np.dtype(str(data.dtype))
        except TypeError:
            return None                  # keep the eager encoding
        total = int(data.nbytes)
        shape = tuple(data.shape)
    else:
        return None                     # generic objects stay eager
    if total < min_bytes():
        return None
    router = engine.router
    ep = router.endpoint
    seg_bytes = segment_bytes_for(total, ep)
    epseg = max(1, seg_bytes // max(np_dtype.itemsize, 1))
    size = int(np.prod(shape)) if shape else 1
    nseg = -(-size // epseg)
    if nseg < 2:
        return None                      # nothing to overlap
    if is_dev:
        from ompi_tpu.btl.devxfer import SegmentStager
        stager = SegmentStager(data, epseg)
        flat = None
    else:
        arr = np.ascontiguousarray(data)
        flat = arr.reshape(-1)
    codec = _comp_codec(np_dtype.name, total)
    inner: Dict[str, Any] = {"kind": "nd", "dtype": np_dtype.str,
                             "shape": shape}
    if codec:
        inner["comp"] = codec
    uid = next(_uids)
    me = engine.comm.rank()
    wdest = engine.comm.world_rank_of(dest)
    t = engine.traffic.setdefault((me, dest), [0, 0])
    t[0] += 1
    t[1] += total
    header = {"cid": engine.comm.cid, "src": me, "tag": tag,
              "desc": {"kind": "pipe", "pipe": uid, "psrc": router.rank,
                       "nseg": nseg, "nbytes": total, "inner": inner}}
    ent = aid = None
    if synchronous:
        aid, ent = router.new_ack()
        header["ack_id"] = aid
        header["wsrc"] = engine.comm.world_rank_of(me)
    # the init frame rides the ORDERED stream: it is what matches, so
    # two sends to one peer can never overtake each other even though
    # their segment trains interleave freely on the rails
    ep.send_frame(wdest, header, b"")

    window = threading.Semaphore(depth())
    lock = threading.Lock()
    state = {"pending": nseg, "wire_s": 0.0}
    done_evt = threading.Event()

    def on_done(dt: float) -> None:      # runs on a rail sender thread
        window.release()
        with lock:
            state["wire_s"] += dt
            state["pending"] -= 1
            if state["pending"] == 0:
                done_evt.set()

    t_start = time.perf_counter()
    prep_s = 0.0
    send_segment = ep.send_segment
    for i in range(nseg):
        window.acquire()                 # N segments in flight
        tok = (_trace.begin("pml.segment", idx=i, pipe=uid, dest=dest)
               if _trace.active else None)
        t0 = time.perf_counter()
        nraw = 0
        try:
            if stager is not None:
                seg = stager.get(i)      # staged D2H, next copy already
            else:                        # in flight (double buffer)
                seg = flat[i * epseg:(i + 1) * epseg]
            seg_header = {"pipeseg": 1, "pipe": uid, "psrc": router.rank,
                          "idx": i, "n": nseg}
            if codec:
                w = _cwire.encode(np.ascontiguousarray(seg))
                raw = pickle.dumps(w, protocol=pickle.HIGHEST_PROTOCOL)
            else:                        # zero-copy pack: the segment
                raw = memoryview(seg).cast("B")  # rides the source
                # buffer straight to sendall (tcp._sendmsg) —
                # tobytes() here cost one full extra pass over every
                # large message. The byte offset lets the receiver
                # assemble in place (PipeStore).
                seg_header["off"] = i * epseg * np_dtype.itemsize
                seg_header["tb"] = total
            nraw = len(raw)
        finally:
            # all exits: a staging/encode error must not leak the span
            if tok is not None:
                _trace.end(tok, bytes=nraw)
        dt = time.perf_counter() - t0
        prep_s += dt
        if _tele.active:
            # telemetry: per-segment stage+encode service time — the
            # same interval the pml.segment span covers
            hist = _tele.SEGMENT
            hist.record(dt * 1e6)
        send_segment(wdest, seg_header, raw, on_done)
    if not done_evt.wait(600):
        raise MPIError(ERR_PENDING,
                       "pipelined send timed out draining its "
                       "segment train")
    wall = time.perf_counter() - t_start
    with lock:
        serial = prep_s + state["wire_s"]
    stats["segments"] += nseg
    stats["inits"] += 1
    if serial > 1e-9:
        _gauges["overlap_ratio"] = round(
            max(0.0, min(1.0, (serial - wall) / serial)), 4)
    if ent is not None and not ent[0].wait(600):
        router.cancel_ack(aid)
        raise MPIError(ERR_PENDING,
                       "ssend timed out waiting for the receive")
    from ompi_tpu.core.request import Request
    return Request.completed()


def reset_stats() -> None:
    """Tests / a new measurement window."""
    stats["segments"] = 0
    stats["inits"] = 0
    _gauges["overlap_ratio"] = 0.0


register_params()
_register_pvars()
