"""Partitioned point-to-point (MPI-4 ``MPI_Psend_init`` family).

Behavioral spec: ``ompi/mca/part/persist`` — a persistent partitioned
send whose buffer is contributed partition-by-partition (``MPI_Pready``);
the transfer completes once every partition is marked ready. The receive
side exposes ``MPI_Parrived`` per-partition arrival.

TPU-native note: partitions map naturally onto chunked device transfers
(each partition is a shard-row slice); completion is queue-state, as with
the pml matching engine.
"""
from __future__ import annotations

from typing import Any, List, Sequence

from ompi_tpu.core.errhandler import ERR_ARG, MPIError
from ompi_tpu.core.request import Request, Status
from ompi_tpu.pml.stacked import CH_PART


class PartitionedSend(Request):
    def __init__(self, comm, parts: Sequence[Any], src: int, dest: int,
                 tag: int):
        super().__init__(arrays=[])
        self._complete = False
        self.comm = comm
        self.parts = list(parts)
        self.src, self.dest, self.tag = src, dest, tag
        self.ready: List[bool] = [False] * len(self.parts)
        self._started = False

    @property
    def partitions(self) -> int:
        return len(self.parts)

    def start(self) -> "PartitionedSend":
        self._started = True
        self._complete = False
        self.ready = [False] * len(self.parts)
        return self

    def pready(self, i: int) -> None:
        if not self._started:
            raise MPIError(ERR_ARG, "pready before start")
        if not (0 <= i < len(self.parts)):
            raise MPIError(ERR_ARG, f"partition {i} out of range")
        if not self.ready[i]:
            self.ready[i] = True
            # memchecker (opal memchecker role): per MPI-4, partition i
            # is LIBRARY-owned from pready(i) until operation
            # completion. Our engine copies eagerly, so a later user
            # write is harmless HERE — but it is non-portable MPI, and
            # catching exactly that is the memchecker's job.
            from ompi_tpu.utils import memchecker
            memchecker.inflight(self.parts[i],
                                f"partition {i} after pready")
            # Partitioned fragments ride their own matching channel with
            # structured (tag, partition) tags — no arithmetic encoding,
            # no possible collision with user int tags.
            self.comm._pml.send(self.parts[i], self.src, self.dest,
                                (self.tag, i), channel=CH_PART)
        if all(self.ready):
            # completion: verify the ownership discipline was respected
            # on EVERY partition — releasing each tracked entry even
            # when one fails (a stranded id-keyed entry could later
            # fire a spurious error on an unrelated buffer reusing the
            # address) — then complete; the violation is a diagnostic,
            # the transfer itself happened.
            from ompi_tpu.utils import memchecker
            errors = []
            for i, p in enumerate(self.parts):
                try:
                    memchecker.verify(p)
                except memchecker.MemcheckError as e:
                    errors.append(f"partition {i}: {e}")
            self._complete = True
            if errors:
                raise memchecker.MemcheckError("; ".join(errors))

    def pready_range(self, lo: int, hi: int) -> None:
        for i in range(lo, hi + 1):
            self.pready(i)

    def test(self):
        return (True, self.status) if self._complete else (False, None)

    def wait(self) -> Status:
        if not self._complete:
            raise MPIError(ERR_ARG,
                           "partitioned send incomplete: not all "
                           "partitions marked ready")
        return self.status


class PartitionedRecv(Request):
    def __init__(self, comm, source: int, tag: int, partitions: int,
                 dst: int = 0):
        super().__init__(arrays=[])
        self._complete = False
        self.comm = comm
        self.source, self.tag, self.dst = source, tag, dst
        self.partitions = partitions
        self._reqs: List[Request] = []
        self._started = False

    def start(self) -> "PartitionedRecv":
        self._started = True
        self._complete = False
        self._reqs = [
            self.comm._pml.irecv(self.dst, self.source, (self.tag, i),
                                 channel=CH_PART)
            for i in range(self.partitions)]
        return self

    def parrived(self, i: int) -> bool:
        if not self._started:
            return False
        return self._reqs[i].test()[0]

    def test(self):
        if self._started and all(r.test()[0] for r in self._reqs):
            self._result = [r.get() for r in self._reqs]
            self._complete = True
            return True, self.status
        return False, None

    def wait(self) -> Status:
        ok, _ = self.test()
        if not ok:
            raise MPIError(ERR_ARG,
                           "partitioned recv incomplete: partitions "
                           "missing (send them first)")
        return self.status
