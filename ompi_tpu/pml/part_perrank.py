"""Partitioned point-to-point for the per-rank world (MPI-4
``MPI_Psend_init`` family).

Behavioral spec: ``ompi/mca/part/persist`` — a persistent partitioned
send whose buffer is contributed partition-by-partition
(``MPI_Pready``), completing once every partition is transferred; the
receive side exposes per-partition arrival (``MPI_Parrived``).

Per-rank re-design: partitions ride the btl as independent fragments
on a HIDDEN matching channel (own CID, the _CollChannel pattern — a
user receive can never match a partition fragment), tagged
``(tag, init-order seq, k)`` flattened into one int so the matching
engine's (source, tag) lookup IS the per-partition arrival state:
``parrived`` is an iprobe, no extra bookkeeping, and two concurrently
active requests on the same (peer, tag) pair match in initialization
order (the MPI-4 channel-pairing rule) instead of cross-delivering. A partition is on the wire the moment its
``pready`` runs — genuinely incremental transfer across OS processes,
which is the entire point of the MPI-4 feature (early partitions
overlap the production of late ones).
"""
from __future__ import annotations

import threading
from typing import Any, List, Optional, Sequence

from ompi_tpu.core.errhandler import ERR_ARG, ERR_PENDING, MPIError
from ompi_tpu.core.rankcomm import hidden_engine
from ompi_tpu.core.request import Request, Status

MAX_PARTITIONS = 1 << 14
_SEQ_MOD = 1 << 20


def _part_engine(comm):
    return hidden_engine(comm, "part")


def _channel_seq(comm, side: str, peer: int, tag: int) -> int:
    """Init-order channel number for (peer, tag): MPI-4 matches
    partitioned requests in initialization order per (comm, peer,
    tag) — without this, two concurrently active requests on the same
    pair would cross-deliver partitions. Sender and receiver advance
    mirrored counters, so the i-th psend_init to (dest, tag) pairs
    with the i-th precv_init from (source, tag)."""
    with comm._lock:
        table = getattr(comm, "_part_seq", None)
        if table is None:
            table = comm._part_seq = {}
        key = (side, peer, tag)
        seq = table.get(key, 0)
        table[key] = seq + 1
    return seq % _SEQ_MOD


def _ptag(tag: int, seq: int, k: int) -> int:
    return (tag * _SEQ_MOD + seq) * MAX_PARTITIONS + k


class RankPartitionedSend(Request):
    """MPI_Psend_init: persistent; each start() opens a new round of
    pready contributions."""

    def __init__(self, comm, parts: Sequence[Any], dest: int, tag: int):
        super().__init__(arrays=[])
        if not parts or len(parts) > MAX_PARTITIONS:
            raise MPIError(ERR_ARG,
                           f"1..{MAX_PARTITIONS} partitions required")
        self.comm = comm
        self.engine = _part_engine(comm)
        self.parts = list(parts)
        self.dest, self.tag = dest, tag
        self.seq = _channel_seq(comm, "send", dest, tag)
        self.ready: List[bool] = [False] * len(parts)
        self._started = False
        self._complete = False
        self._sent = 0
        self._lock = threading.Lock()

    @property
    def partitions(self) -> int:
        return len(self.parts)

    def start(self) -> "RankPartitionedSend":
        with self._lock:
            self._started = True
            self._complete = False
            self.ready = [False] * len(self.parts)
            self._sent = 0
        return self

    def pready(self, k: int) -> None:
        """MPI_Pready: partition k's data is final — it leaves NOW."""
        with self._lock:
            if not self._started:
                raise MPIError(ERR_PENDING, "pready before start")
            if not 0 <= k < len(self.parts):
                raise MPIError(ERR_ARG, f"bad partition {k}")
            if self.ready[k]:
                raise MPIError(ERR_ARG, f"partition {k} already ready")
            self.ready[k] = True
        try:
            self.engine.send(self.parts[k], self.dest,
                             _ptag(self.tag, self.seq, k))
        except BaseException:
            # transfer failed (e.g. peer death): the partition was NOT
            # contributed — roll back so a recovery path can retry (or
            # cleanly abandon) instead of wedging on 'already ready'
            with self._lock:
                self.ready[k] = False
            raise
        # completion is counted AFTER the btl accepted the fragment —
        # with concurrent pready threads (MPI-4's intended use), an
        # all(ready) check taken before another thread's send would
        # report completion while that partition is still unsent
        with self._lock:
            self._sent += 1
            if self._sent == len(self.parts):
                self._complete = True

    def pready_range(self, lo: int, hi: int) -> None:
        for k in range(lo, hi + 1):
            self.pready(k)

    def pready_list(self, ks: Sequence[int]) -> None:
        for k in ks:
            self.pready(k)

    def test(self):
        return ((True, Status(source=self.comm.rank(), tag=self.tag))
                if self._complete else (False, None))

    def wait(self, timeout: Optional[float] = None):
        if not self._complete:
            raise MPIError(ERR_PENDING,
                           "partitioned send incomplete: partitions "
                           "not all pready (a wait here would deadlock"
                           " — the sender itself must contribute them)")
        return Status(source=self.comm.rank(), tag=self.tag)


class RankPartitionedRecv(Request):
    """MPI_Precv_init: per-partition arrival via the matching engine's
    unexpected queue (parrived == iprobe on the partition's tag)."""

    def __init__(self, comm, nparts: int, source: int, tag: int):
        super().__init__(arrays=[])
        if not 1 <= nparts <= MAX_PARTITIONS:
            raise MPIError(ERR_ARG,
                           f"1..{MAX_PARTITIONS} partitions required")
        self.comm = comm
        self.engine = _part_engine(comm)
        self.nparts = nparts
        self.source, self.tag = source, tag
        self.seq = _channel_seq(comm, "recv", source, tag)
        self._got: List[Any] = [None] * nparts
        self._have: List[bool] = [False] * nparts
        self._complete = False
        self.status = Status(source=source, tag=tag)

    def start(self) -> "RankPartitionedRecv":
        self._got = [None] * self.nparts
        self._have = [False] * self.nparts
        self._complete = False
        return self

    def parrived(self, k: int) -> bool:
        """MPI_Parrived: has partition k landed?"""
        if not 0 <= k < self.nparts:
            raise MPIError(ERR_ARG, f"bad partition {k}")
        if self._have[k]:
            return True
        ok, _ = self.engine.iprobe(self.source,
                                   _ptag(self.tag, self.seq, k))
        if ok:
            data, _ = self.engine.recv(self.source,
                                       _ptag(self.tag, self.seq, k))
            self._got[k] = data
            self._have[k] = True
        return self._have[k]

    def test(self):
        if not self._complete:
            if all(self.parrived(k) for k in range(self.nparts)):
                self._finish()
        return ((True, self.status) if self._complete else (False, None))

    def wait(self, timeout: Optional[float] = None) -> Status:
        """Blocks for real: late partitions are produced by another OS
        process. ``timeout`` bounds the WHOLE wait, not each
        partition's receive."""
        import time
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        for k in range(self.nparts):
            if not self._have[k]:
                left = (None if deadline is None
                        else max(deadline - time.monotonic(), 0.001))
                data, _ = self.engine.recv(self.source,
                                           _ptag(self.tag, self.seq,
                                                 k),
                                           timeout=left)
                self._got[k] = data
                self._have[k] = True
        self._finish()
        return self.status

    def _finish(self) -> None:
        self._result = list(self._got)
        self._complete = True

    def get(self):
        return self._result


def psend_init(comm, parts: Sequence[Any], dest: int,
               tag: int = 0) -> RankPartitionedSend:
    return RankPartitionedSend(comm, parts, dest, tag)


def precv_init(comm, nparts: int, source: int,
               tag: int = 0) -> RankPartitionedRecv:
    return RankPartitionedRecv(comm, nparts, source, tag)
