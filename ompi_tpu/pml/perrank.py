"""pml/perrank — the per-rank (multi-controller) matching engine.

Behavioral spec: ob1's receive-side matching
(``ompi/mca/pml/ob1/pml_ob1_recvfrag.c:296-330``): arriving fragments are
matched against posted receives (source/tag with wildcards); unmatched
fragments queue in arrival order; ordering is FIFO per (source, comm) —
MPI's non-overtaking rule. Unlike the single-controller stacked engine,
this one serves exactly ONE rank per process, frames arrive from btl/tcp
reader threads, and a blocking receive genuinely blocks — the matching
send is produced by another OS process, so recv-before-send is the
natural order (the reference's semantics the stacked engine cannot
express).

Synchronous send (MPI_Ssend): the sender attaches an ack id; the
receiver's match emits a control frame back; the sender's request
completes on the ack — the rendezvous-ACK handshake of
``pml_ob1_sendreq.h:389-460`` reduced to its observable semantics.

Frame routing: one process-wide :class:`Router` owns the TcpEndpoint and
demultiplexes frames by communicator CID; frames for a CID whose engine
is not yet constructed (a peer raced ahead through comm creation) wait in
a pending queue — the reference's "non-matching fragments held until the
communicator exists" behavior (comm_cid.c activation).
"""
from __future__ import annotations

import itertools
import threading
import time as _time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from ompi_tpu import telemetry as _tele
from ompi_tpu.btl.tcp import PeerDownError, decode_payload, encode_payload
from ompi_tpu.core.errhandler import ERR_PENDING, ERR_RANK, ERR_TAG, MPIError
from ompi_tpu.core.request import Request, Status
from ompi_tpu.runtime import progress as _progress
from ompi_tpu.trace import core as _trace

ANY_SOURCE = -1
ANY_TAG = -1
PROC_NULL = -2


def _ft_send(endpoint, wdest: int, header: dict, raw: bytes) -> None:
    """Send with the ULFM error mapping: a connection that dies UNDER a
    send (after the btl exhausted its reconnect retry) reports the rank
    failed and surfaces ``MPI_ERR_PROC_FAILED`` — never a raw socket
    error (satellite (a) of docs/RESILIENCE.md)."""
    try:
        endpoint.send_frame(wdest, header, raw)
    except PeerDownError as e:
        from ompi_tpu.core.errhandler import ERR_PROC_FAILED
        from ompi_tpu.runtime import ft
        ft.fail_rank(e.world_rank, "connection down during send")
        raise MPIError(
            ERR_PROC_FAILED,
            f"peer world rank {e.world_rank} failed during send") from e


class Router:
    """Process-wide frame router: CID -> engine, plus the ack table."""

    def __init__(self, rank: int, nprocs: int, kv_set, kv_get):
        self.rank = rank
        self.nprocs = nprocs
        self.kv_set = kv_set             # the modex plane (devxfer
        self.kv_get = kv_get             # publishes its address here)
        self._engines: Dict[Any, "PerRankEngine"] = {}
        self._pending: Dict[Any, List[Tuple[dict, bytes]]] = {}
        # ack id -> [Event, reply payload] (replies carry RMA get/fetch
        # results back to the origin)
        self._acks: Dict[int, list] = {}
        self._ack_ids = itertools.count(1)
        self._lock = threading.Lock()
        # wid -> handler(header, raw) for one-sided targets (the osc
        # active-message plane; handlers run on reader threads and must
        # not block)
        self._rma: Dict[Any, Any] = {}
        self._closing = False
        self._departed: set = set()      # peers that said goodbye
        # -- resilience plane (docs/RESILIENCE.md) ---------------------
        # revoked communicator CIDs + per-cid callbacks (the reliable
        # revoke broadcast's local state, coll_base_revoke_local.c) and
        # the optional heartbeat detector (ft/detector, attached by
        # runtime/init after wire_up)
        self._revoked: set = set()
        self._revoke_cbs: Dict[Any, list] = {}
        self.detector = None
        # whatever ingress learns of a death (EOF monitor, heartbeat
        # declaration, remote obituary) funnels through the registry;
        # the listener does the local cleanup AND re-broadcasts — the
        # registry's first-report dedup terminates the flood
        from ompi_tpu.runtime import ft
        ft.add_listener(self._on_rank_failed)
        # segment-train reassembly for the pipelined rendezvous
        # (pml/pipeline): keyed (source world rank, pipe id), fed by
        # rail reader threads BELOW the matching layer — created before
        # the endpoint so no reader thread can race it
        from ompi_tpu.pml.pipeline import PipeStore
        self.pipes = PipeStore()
        # the bml/r2 multiplexer: sm rings for same-host eager frames,
        # tcp for the rest (and as the failure detector's wire)
        from ompi_tpu.btl.bml import BmlEndpoint
        self.endpoint = BmlEndpoint(rank, nprocs, kv_set, kv_get,
                                    self._deliver,
                                    on_peer_lost=self._peer_lost)
        # the ctl flush-window counters ride the MPI_T pvar plumbing
        # next to the wakeup-coalescing pvars (docs/SMALLMSG.md)
        from ompi_tpu.mca import pvar
        pvar.pvar_register_dict(
            "btl_ctl", self.endpoint.tcp.ctl_stats,
            help_prefix="ctl flush window: ")

    def wire_up(self) -> None:
        """Eagerly connect to every peer (the reference's add_procs
        endpoint setup). Besides first-send latency, this is what makes
        the failure detector COMPLETE: each pair then has identified
        connections in both directions, so a process death is observed
        by every survivor — not just the peers the victim happened to
        message. (At real scale this would be lazy wire-up plus an
        obituary gossip; eager is right for the worlds one host runs.)"""
        for peer in range(self.nprocs):
            if peer != self.rank:
                try:
                    self.endpoint._connect(peer)
                except Exception:        # noqa: BLE001 — peer may be
                    pass                 # dead already; detector covers

    # -- failure detection (ULFM over real process death) --------------
    def begin_shutdown(self) -> None:
        """Called at finalize: announce graceful departure to every
        connected peer (a 'bye' obituary-suppressor — without it, a
        fast survivor's close after a failure would look like a second
        death to slower survivors), then stop treating EOFs as
        failures locally."""
        for peer in list(self.endpoint._peers):
            try:
                self.endpoint.send_frame(peer, {"ctl": "bye",
                                                "peer": self.rank})
            except Exception:            # noqa: BLE001
                pass
        self._closing = True

    def _peer_lost(self, world_rank: int) -> None:
        """An identified peer connection died: the ULFM event. Report
        it into the process default registry; the registry listener
        (:meth:`_on_rank_failed`) does the local cleanup and the
        obituary broadcast — same path whatever the ingress."""
        if self._closing or world_rank in self._departed:
            return                       # graceful exit, not death
        from ompi_tpu.runtime import ft
        ft.fail_rank(world_rank, "peer connection lost")

    def _on_rank_failed(self, world_rank: int, reason: str) -> None:
        """Registry listener (fires exactly once per failed rank):
        complete every pending operation that could have matched the
        dead rank in error (ompi/request/req_ft.c over a REAL dead
        process) and fan the obituary out as a reliable ``ftdead``
        broadcast — the PMIx event-propagation role. Receivers dedup
        through their own registries, so the flood terminates."""
        if self._closing:
            return
        # unfinished segment trains from the dead sender can never
        # complete — fail their waiters now (pml/pipeline)
        self.pipes.fail_peer(world_rank)
        # slots parked for (or attached from) the dead rank can never
        # be returned by it — reclaim/unmap them now (btl/shmseg)
        plane = getattr(getattr(self, "endpoint", None), "shm_seg",
                        None)
        if plane is not None:
            try:
                plane.peer_failed(world_rank)
            except Exception:            # noqa: BLE001
                pass
        with self._lock:
            engines = list(self._engines.values())
        for eng in engines:
            try:
                eng._peer_failed(world_rank)
            except Exception:            # noqa: BLE001
                pass
        self._broadcast_ctl({"ctl": "ftdead", "rank": world_rank,
                             "peer": self.rank})

    def _broadcast_ctl(self, header: dict) -> None:
        """Best-effort fan-out of a ctl frame to every live peer over
        the UNSEQUENCED tcp path (these frames carry no ``_sq``, so a
        lost one leaves no reorder-buffer hole; reliability comes from
        every learner re-forwarding on first receipt)."""
        from ompi_tpu.runtime import ft
        failed = ft.failed_ranks()
        for peer in range(self.nprocs):
            if peer == self.rank or peer in failed:
                continue
            try:
                self.endpoint.tcp.send_frame(peer, dict(header))
            except Exception:            # noqa: BLE001 — a dying
                pass                     # learner is its own obituary

    # -- revoke plane (MPIX_Comm_revoke over the ctl wire) -------------
    def revoke(self, rcid) -> None:
        """Locally revoke ``rcid`` and start the reliable broadcast
        (coll_base_revoke_local.c's role: first receipt re-forwards,
        the revoked-set membership test terminates the flood)."""
        self._on_revoke(rcid)

    def is_revoked(self, rcid) -> bool:
        return rcid in self._revoked

    def register_revoke_cb(self, rcid, cb) -> None:
        with self._lock:
            self._revoke_cbs.setdefault(rcid, []).append(cb)

    def unregister_revoke_cb(self, rcid) -> None:
        with self._lock:
            self._revoke_cbs.pop(rcid, None)

    def _on_revoke(self, rcid) -> None:
        with self._lock:
            if rcid in self._revoked:
                return                   # flood termination
            self._revoked.add(rcid)
            cbs = list(self._revoke_cbs.get(rcid, []))
        if _tele.active:
            # flight-recorder trigger: first receipt of a revocation is
            # incident evidence worth freezing (rate-limited inside)
            from ompi_tpu.telemetry import flightrec as _flightrec
            _flightrec.record("revoke", {"rcid": str(rcid),
                                         "rank": self.rank})
        self._broadcast_ctl({"ctl": "revoke", "rcid": rcid,
                             "peer": self.rank})
        for cb in cbs:
            try:
                cb()
            except Exception:            # noqa: BLE001
                pass

    def register(self, cid, engine: "PerRankEngine") -> None:
        with self._lock:
            self._engines[cid] = engine
            backlog = self._pending.pop(cid, [])
        for header, raw in backlog:
            engine._incoming(header, raw)

    def unregister(self, cid) -> None:
        with self._lock:
            self._engines.pop(cid, None)

    def new_ack(self) -> Tuple[int, list]:
        """Returns (ack id, entry). The entry is ``[Event, reply]``;
        _deliver pops the table slot and mutates THIS list, so waiters
        read the reply from their own reference and nothing leaks —
        one entry per ack regardless of who forgets to collect it."""
        aid = next(self._ack_ids)
        ent = [threading.Event(), None]
        with self._lock:
            self._acks[aid] = ent
        return aid, ent

    def cancel_ack(self, aid: int) -> None:
        """Drop a pending ack slot (timeout path)."""
        with self._lock:
            self._acks.pop(aid, None)

    def register_rma(self, wid, handler) -> None:
        with self._lock:
            self._rma[wid] = handler

    def unregister_rma(self, wid) -> None:
        with self._lock:
            self._rma.pop(wid, None)

    def _deliver(self, header: dict, raw: bytes) -> None:
        """Called from btl reader threads (and loopback sends)."""
        ctl = header.get("ctl")
        if ctl == "hb":
            d = self.detector
            if d is not None:
                d.on_heartbeat(header["peer"])
            # telemetry RTT echo: the sender stamped "ht" only while
            # its telemetry was on; reply in kind only while OURS is on
            # too — with the plane off neither side's frames change
            if _tele.active and "ht" in header:
                try:
                    self.endpoint.tcp.send_frame(
                        header["peer"],
                        {"ctl": "hbr", "peer": self.rank,
                         "ht": header["ht"]})
                except Exception:        # noqa: BLE001 — best-effort
                    pass
            return
        if ctl == "hbr":
            if _tele.active:
                hist = _tele.HB_RTT
                if hist is not None:
                    rtt = _time.perf_counter() - float(header["ht"])
                    hist.record(max(rtt, 0.0) * 1e6)
            return
        if ctl == "ftdead":
            # remote obituary: feed the registry (dedups); our own
            # listener re-forwards on first receipt. An obituary about
            # OURSELVES is a false accusation — the accusers will
            # exclude us either way; don't poison our own registry.
            r = header["rank"]
            if not (self._closing or r == self.rank
                    or r in self._departed):
                from ompi_tpu.runtime import ft
                ft.fail_rank(r, "obituary from rank %s"
                             % header.get("peer"))
            return
        if ctl == "revoke":
            self._on_revoke(header["rcid"])
            return
        if ctl == "segfree":
            # receiver finished with a shared slot we own (btl/shmseg
            # zero-copy plane): return it to the per-peer free pool
            plane = getattr(getattr(self, "endpoint", None),
                            "shm_seg", None)
            if plane is not None:
                plane.release(header["peer"], header["i"])
            return
        if ctl == "bye":
            with self._lock:
                self._departed.add(header["peer"])
            return
        if header.get("ctl") == "ack":
            with self._lock:
                ent = self._acks.pop(header["ack_id"], None)
            if ent is not None:
                if "desc" in header:
                    ent[1] = decode_payload(header["desc"], raw)
                _progress.wake(ent[0])   # coalesces under a drain batch
            return
        if "rma" in header:
            with self._lock:
                h = self._rma.get(header["wid"])
            if h is not None:
                h(header, raw)
            return
        if "pipeseg" in header:
            # a rail-striped segment of a pipelined rendezvous train:
            # reassembled by index below the matching layer — only the
            # train's ordered init frame participates in matching
            self.pipes.deliver(header, raw)
            return
        cid = header["cid"]
        with self._lock:
            eng = self._engines.get(cid)
            if eng is None:
                self._pending.setdefault(cid, []).append((header, raw))
                return
        eng._incoming(header, raw)

    def send_ack(self, world_rank: int, ack_id: int,
                 reply: Any = None) -> None:
        header = {"ctl": "ack", "ack_id": ack_id}
        raw = b""
        if reply is not None:
            header["desc"], raw = encode_payload(reply)
        self.endpoint.send_frame(world_rank, header, raw)

    def close(self) -> None:
        self._closing = True
        from ompi_tpu.runtime import ft
        ft.remove_listener(self._on_rank_failed)
        d, self.detector = self.detector, None
        if d is not None:
            try:
                d.stop()
            except Exception:            # noqa: BLE001
                pass
        self.endpoint.close()


class _Msg:
    __slots__ = ("src", "tag", "data", "ack")

    def __init__(self, src: int, tag: int, data: Any,
                 ack: Optional[Tuple[int, int]] = None):
        self.src = src                  # comm-local source rank
        self.tag = tag
        self.data = data
        self.ack = ack                  # (sender world rank, ack id)


class RankRequest(Request):
    """A receive (or synchronous-send) request completed by the engine
    from a btl reader thread; wait blocks on a real Event."""

    cancelled = False                    # MPI_Cancel outcome

    def __init__(self, src: int, tag: int):
        super().__init__(arrays=[])
        self._complete = False
        self._event = threading.Event()
        self._error: Optional[BaseException] = None
        self.status = Status(source=src, tag=tag)

    def cancel(self) -> None:
        """MPI_Cancel: succeeds only while the receive is still
        posted (unmatched); a matched/completed request is past the
        cancellation point and the call is a no-op (cancel.c.in
        semantics)."""
        fn = getattr(self, "_cancel_fn", None)
        if fn is not None:
            fn()

    def _deliver(self, msg: _Msg) -> None:
        self._result = msg.data
        self.status.source = msg.src
        self.status.tag = msg.tag
        self.status.count = int(getattr(msg.data, "size", 1) or 1)
        self.status.nbytes = int(getattr(msg.data, "nbytes", -1))
        self._complete = True
        # completion is a cancellation point (cancel() becomes a no-op)
        # — drop the closure NOW: it captures this request, and the
        # request → closure → cell → request cycle pins the payload
        # (up to a whole segment train) until a full gen-2 gc pass
        self._cancel_fn = None
        _progress.wake(self._event)      # coalesced under drain batches

    def _fail(self, err: BaseException) -> None:
        """ULFM (req_ft.c): complete the pending request in error —
        the matching send can never arrive from a dead peer."""
        self._error = err
        self._complete = True
        self._cancel_fn = None           # break the cancel-closure cycle
        _progress.wake(self._event)

    def test(self):
        if self._complete and self._error is not None:
            raise self._error
        return (True, self.status) if self._complete else (False, None)

    def wait(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout if timeout is not None else 600):
            raise MPIError(ERR_PENDING,
                           "recv timed out waiting for a matching send")
        if self._error is not None:
            raise self._error
        from ompi_tpu.pml.pipeline import PipePayload
        if isinstance(self._result, PipePayload):
            # MPI completion means the data is PLACED: assemble the
            # segment train now so the store's multi-MB buffer is
            # released even if the caller never calls get()
            from ompi_tpu.pml.pipeline import maybe_resolve as _pr
            self._result = _pr(self._result)
        return self.status

    def get(self):
        """Wait (raising any stored ULFM error — the base contract)
        and resolve a device-rendezvous payload on THIS (consumer)
        thread — the pull must never run on a btl reader thread."""
        self.wait()
        from ompi_tpu.btl.devxfer import maybe_resolve
        from ompi_tpu.pml.pipeline import maybe_resolve as _pipe_resolve
        self._result = _pipe_resolve(maybe_resolve(self._result))
        return self._result


def thread_request(job) -> RankRequest:
    """Run ``job`` on a daemon worker thread; the returned request
    completes with the job's result, or in error through the same
    ``_fail`` path ULFM uses. The generic request-based-operation
    primitive (request-based RMA rput/rget, ``osc.h:269-279``)."""
    req = RankRequest(ANY_SOURCE, ANY_TAG)

    def run():
        try:
            req._deliver(_Msg(ANY_SOURCE, 0, job()))
        except BaseException as e:      # noqa: BLE001 — surfaced at wait
            req._fail(e)
    threading.Thread(target=run, daemon=True).start()
    return req


class CombineSlot:
    """An inline-combining receive slot (the ``btl_sendi`` role,
    ``opal/mca/btl/btl.h`` inline-send, applied to the receive side):
    btl reader threads park small collective contributions directly
    into the slot; the LAST arrival folds them in deterministic rank
    order and wakes the consumer exactly once. Collapses the per-round
    wakeup tax that made an 8 B per-rank allreduce cost ~18 pingpongs
    on a 1-core host (VERDICT r4 weak #4)."""

    __slots__ = ("_vals", "_need", "_fold", "_event", "_lock",
                 "_error", "result")

    def __init__(self, nranks: int, need: int, fold):
        self._vals: List[Any] = [None] * nranks   # by source rank
        self._need = need
        self._fold = fold                 # fold(ordered_values) -> result
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self.result: Any = None

    def feed(self, src: int, value: Any) -> None:
        with self._lock:
            if self._vals[src] is not None or self._need <= 0:
                return                    # duplicate / already failed
            self._vals[src] = value
            self._need -= 1
            done = self._need == 0
        if done:
            # deterministic rank-ordered fold (MPI promises allreduce
            # returns the SAME value everywhere; arrival-order folding
            # of floats would not) — n tiny folds on this reader
            # thread beat one more cross-thread wakeup
            try:
                self.result = self._fold(self._vals)
            except BaseException as e:    # noqa: BLE001
                self._error = e
            _progress.wake(self._event)  # one coalesced consumer wake

    def put_own(self, rank: int, value: Any) -> None:
        """The caller's own contribution (never counted in _need)."""
        self._vals[rank] = value

    def fail(self, err: BaseException) -> None:
        with self._lock:
            self._need = -1
        self._error = err
        _progress.wake(self._event)

    def wait(self, timeout: float = 600):
        if not self._event.wait(timeout):
            raise MPIError(ERR_PENDING,
                           "combining collective timed out")
        if self._error is not None:
            raise self._error
        return self.result


class PerRankEngine:
    """Matching state for ONE rank of one communicator.

    ``comm`` provides ``cid``, ``size``, ``rank()``, and
    ``world_rank_of(local_rank)`` for endpoint addressing.
    """

    def __init__(self, comm, router: Router):
        self.comm = comm
        self.router = router
        self._lock = threading.Lock()
        self.unexpected: Dict[int, Deque[_Msg]] = {}   # src -> FIFO
        self._arrival: Deque[int] = deque()            # src arrival order
        self.posted: List[Tuple[int, int, RankRequest]] = []
        self._combine: Dict[int, CombineSlot] = {}     # tag -> slot
        # sub-eager dispatch cache: per-(dtype, shape) marshalled
        # descriptor templates for the small-message multicast path —
        # the control plane stops re-boxing the same 8 B shape on
        # every collective call (see send_small)
        self._small_desc: Dict[Tuple[str, tuple], dict] = {}
        # per-peer traffic accounting (the pml/monitoring role): THIS
        # rank's sends/receives by comm-local peer, consumed by
        # tools/profile's matrix (each rank holds its own rows in a
        # per-rank world; aggregate with comm.allgather)
        self.traffic: Dict[Tuple[int, int], List[int]] = {}
        router.register(comm.cid, self)

    # -- wire side -----------------------------------------------------
    def _incoming(self, header: dict, raw: bytes) -> None:
        d = header["desc"]
        if d.get("kind") == "devrndv":
            # descriptor-only frame: the device payload is pulled
            # lazily on the consumer thread (btl/devxfer)
            from ompi_tpu.btl.devxfer import DevPayload
            payload = DevPayload(self.router, d)
        elif d.get("kind") == "pipe":
            # pipelined-rendezvous init frame (pml/pipeline): matches
            # NOW with the right counts; the segment train assembles
            # on the consumer thread at resolve time
            from ompi_tpu.pml.pipeline import PipePayload
            payload = PipePayload(self.router, d)
        elif d.get("kind") == "shmseg":
            # zero-copy descriptor frame (btl/shmseg): adopt the
            # payload in place over the sender's shared slot; the
            # array's finalizer returns the slot when the receiver
            # drops its last reference
            from ompi_tpu.btl import shmseg as _shmseg
            payload = _shmseg.adopt(self.router.endpoint, d)
        else:
            payload = decode_payload(d, raw)
            # inline-combining fast path: a posted CombineSlot for this
            # tag absorbs the contribution right here on the reader
            # thread — no matching, no request, no per-message wakeup
            with self._lock:
                slot = self._combine.get(header["tag"])
            if slot is not None:
                slot.feed(header["src"], payload)
                return
        msg = _Msg(header["src"], header["tag"], payload,
                   ack=(header["wsrc"], header["ack_id"])
                   if header.get("ack_id") else None)
        with self._lock:
            for i, (src, tag, req) in enumerate(self.posted):
                if ((src == ANY_SOURCE or src == msg.src)
                        and (tag == ANY_TAG or tag == msg.tag)):
                    self.posted.pop(i)
                    matched = req
                    break
            else:
                self.unexpected.setdefault(msg.src, deque()).append(msg)
                self._arrival.append(msg.src)
                matched = None
        if matched is not None:
            self._ack(msg)
            matched._deliver(msg)

    def _ack(self, msg: _Msg) -> None:
        if msg.ack is not None:
            wsrc, aid = msg.ack
            self.router.send_ack(wsrc, aid)

    # -- inline-combining slots (small-message collective fast path) ---
    def post_combine(self, tag: int, nranks: int, need: int,
                     fold, own: Optional[Tuple[int, Any]] = None
                     ) -> CombineSlot:
        """Post a combining slot for one collective round. Must be
        posted before (or while) contributions arrive; ones that raced
        ahead sit in the unexpected queue and are drained here. The
        caller's own contribution goes in via ``own`` BEFORE the slot
        becomes visible — a fast peer may complete the fold before the
        caller runs another line."""
        slot = CombineSlot(nranks, need, fold)
        if own is not None:
            slot.put_own(*own)
        drained: List[_Msg] = []
        with self._lock:
            self._combine[tag] = slot
            for s, q in list(self.unexpected.items()):
                i = 0
                while i < len(q):
                    if q[i].tag == tag:
                        drained.append(q[i])
                        del q[i]
                        try:
                            self._arrival.remove(s)
                        except ValueError:
                            pass
                    else:
                        i += 1
        for m in drained:
            slot.feed(m.src, m.data)
        return slot

    def end_combine(self, tag: int) -> None:
        with self._lock:
            self._combine.pop(tag, None)

    def _take_unexpected(self, source: int, tag: int,
                         remove: bool = True) -> Optional[_Msg]:
        """Caller holds self._lock. Wildcard source scans in arrival
        order (the unexpected queue's FIFO across sources)."""
        srcs = (list(dict.fromkeys(self._arrival))
                if source == ANY_SOURCE else [source])
        for s in srcs:
            q = self.unexpected.get(s)
            if not q:
                continue
            for i, msg in enumerate(q):
                if tag == ANY_TAG or tag == msg.tag:
                    if remove:
                        del q[i]
                        try:
                            self._arrival.remove(s)
                        except ValueError:
                            pass
                    return msg
        return None

    # -- send side -----------------------------------------------------
    def send(self, data: Any, dest: int, tag: int = 0,
             synchronous: bool = False) -> Request:
        # telemetry gate: one attribute read when off; the histogram
        # times the full post-to-wire-handoff service (the degraded
        # self-health signal reads its p99)
        if _tele.active:
            hist = _tele.PML_SEND
            tok = hist.start()
            try:
                return self._send_traced(data, dest, tag, synchronous)
            finally:
                hist.observe(tok)
        return self._send_traced(data, dest, tag, synchronous)

    def _send_traced(self, data: Any, dest: int, tag: int = 0,
                     synchronous: bool = False) -> Request:
        # tracing gate: one attribute read when off (hooks event name
        # "pml_send" — the PERUSE/MPI_T stream and the trace agree);
        # cid rides in args so pt2pt spans stay out of the collective
        # sequence space the attribution layer groups on
        if _trace.active:
            tok = _trace.begin("pml_send", cid=None,
                               cc=str(self.comm.cid), dest=dest, tag=tag)
            try:
                return self._send_impl(data, dest, tag, synchronous)
            finally:
                _trace.end(tok)
        return self._send_impl(data, dest, tag, synchronous)

    def _send_impl(self, data: Any, dest: int, tag: int = 0,
                   synchronous: bool = False) -> Request:
        if dest == PROC_NULL:
            return Request.completed()
        if not (0 <= dest < self.comm.size):
            raise MPIError(ERR_RANK, f"bad destination rank {dest}")
        if not isinstance(tag, int) or tag < 0:
            raise MPIError(ERR_TAG, f"send tag must be an int >= 0, "
                                    f"got {tag!r}")
        from ompi_tpu.runtime import ft
        if ft.is_failed(self.comm.world_rank_of(dest)):
            # symmetric with the recv fail-fast: no silent buffering
            # into a dead socket, no raw OSError later
            from ompi_tpu.core.errhandler import ERR_PROC_FAILED
            raise MPIError(ERR_PROC_FAILED,
                           f"send peer rank {dest} has failed")
        # protocol switch (pml_ob1_sendreq.h:389-460): large device
        # arrays ride the PJRT transfer plane (register + descriptor-
        # only header, receiver pulls D2D); everything else goes
        # eager copy over the host byte path
        from ompi_tpu.btl import devxfer
        dev_desc = devxfer.try_register(self.router, data)
        if dev_desc is not None:
            desc, raw = dev_desc, b""
            wire_bytes = int(data.nbytes)   # moved out-of-band (D2D)
        else:
            # host byte path, fastest plane first: same-host bulk
            # payloads pack ONCE into a shared segment slot and ship a
            # descriptor (btl/shmseg) — shm beats compression for
            # pt2pt because there are no wire bytes to save. None
            # means the plane declined (off, cross-host, pool dry) and
            # nothing touched the wire.
            from ompi_tpu.btl import shmseg as _shmseg
            zreq = _shmseg.maybe_send_zerocopy(self, data, dest, tag,
                                               synchronous)
            if zreq is not None:
                return zreq
            # then the segment-pipelined rendezvous (pml/pipeline,
            # docs/LARGEMSG.md); again None means nothing touched the
            # wire — fall through to the unchanged eager path
            from ompi_tpu.pml import pipeline as _pipeline
            preq = _pipeline.maybe_send_pipelined(self, data, dest,
                                                  tag, synchronous)
            if preq is not None:
                return preq
            desc, raw = encode_payload(data)
            wire_bytes = len(raw)
        me = self.comm.rank()
        t = self.traffic.setdefault((me, dest), [0, 0])
        t[0] += 1
        t[1] += wire_bytes
        header = {"cid": self.comm.cid, "src": me,
                  "tag": tag, "desc": desc}
        ent = aid = None
        if synchronous:
            aid, ent = self.router.new_ack()
            header["ack_id"] = aid
            header["wsrc"] = self.comm.world_rank_of(self.comm.rank())
        _ft_send(self.router.endpoint, self.comm.world_rank_of(dest),
                 header, raw)
        if ent is not None and not ent[0].wait(600):
            self.router.cancel_ack(aid)
            raise MPIError(ERR_PENDING,
                           "ssend timed out waiting for the receive")
        return Request.completed()

    def send_small(self, data: Any, dests, tag: int) -> None:
        """Sub-eager multicast fast path (the combined small-message
        collectives): marshal the payload ONCE, reuse a cached
        per-(dtype, shape) descriptor, and push one frame per
        destination with none of the per-call protocol work the
        general ``send`` must do (devxfer registration, sync-ack
        plumbing, per-dest re-encoding). ``dests`` are comm-local
        ranks, validated by the collective's own construction; the
        caller's rank must not appear in ``dests`` (self-contributions
        go through ``CombineSlot.put_own``)."""
        if _tele.active:
            hist = _tele.PML_SEND
            tok = hist.start()
            try:
                return self._send_small_traced(data, dests, tag)
            finally:
                hist.observe(tok)
        return self._send_small_traced(data, dests, tag)

    def _send_small_traced(self, data: Any, dests, tag: int) -> None:
        if _trace.active:
            tok = _trace.begin("pml_send", cid=None,
                               cc=str(self.comm.cid), tag=tag,
                               ndest=(len(dests)
                                      if hasattr(dests, "__len__")
                                      else -1), small=True)
            try:
                return self._send_small_impl(data, dests, tag)
            finally:
                _trace.end(tok)
        return self._send_small_impl(data, dests, tag)

    def _send_small_impl(self, data: Any, dests, tag: int) -> None:
        if isinstance(data, np.generic):
            # numpy scalars ride the raw nd encoding as 0-d arrays —
            # a pickle round trip costs 4x the marshal of the whole
            # frame (the residual in the round-6 scalar 8 B row); the
            # collective's epilogue restores the scalar type
            data = np.asarray(data)
        if isinstance(data, np.ndarray):
            arr = data if data.flags.c_contiguous \
                else np.ascontiguousarray(data)
            key = (arr.dtype.str, arr.shape)
            desc = self._small_desc.get(key)
            if desc is None:
                desc = self._small_desc[key] = {
                    "kind": "nd", "dtype": arr.dtype.str,
                    "shape": arr.shape}
            raw = arr.tobytes()
        else:
            desc, raw = encode_payload(data)
        me = self.comm.rank()
        header = {"cid": self.comm.cid, "src": me, "tag": tag,
                  "desc": desc}
        nraw = len(raw)
        endpoint = self.router.endpoint
        world_of = self.comm.world_rank_of
        from ompi_tpu.runtime import ft
        from ompi_tpu.core.errhandler import ERR_PROC_FAILED
        for dest in dests:
            if ft.is_failed(world_of(dest)):
                raise MPIError(ERR_PROC_FAILED,
                               f"send peer rank {dest} has failed")
            t = self.traffic.setdefault((me, dest), [0, 0])
            t[0] += 1
            t[1] += nraw
            # the bml copies the header before stamping its sequence
            # number, so one template serves every destination
            _ft_send(endpoint, world_of(dest), header, raw)

    def bind_small_multicast(self, example: Any, dests) -> Any:
        """Pre-bound sub-eager multicast (the persistent-collective
        staging prebind, coll/persistent): the descriptor template,
        world-rank map and per-peer traffic rows resolve ONCE here;
        each send is the contiguous byte copy, the per-peer liveness
        check (which must stay per-call — peers die between rounds),
        and the frame pushes. The registered buffer's (dtype, shape)
        is the persistent contract; a refill that changes either
        falls back to a freshly-built descriptor."""
        arr = np.asarray(example)
        key = (arr.dtype.str, arr.shape)
        desc = self._small_desc.get(key)
        if desc is None:
            desc = self._small_desc[key] = {
                "kind": "nd", "dtype": arr.dtype.str,
                "shape": arr.shape}
        me = self.comm.rank()
        peers = [(d, self.comm.world_rank_of(d),
                  self.traffic.setdefault((me, d), [0, 0]))
                 for d in dests]
        endpoint = self.router.endpoint
        cid = self.comm.cid
        from ompi_tpu.core.errhandler import ERR_PROC_FAILED
        from ompi_tpu.runtime import ft

        def send(data: Any, tag: int) -> None:
            a = np.asarray(data)
            if not a.flags.c_contiguous:
                a = np.ascontiguousarray(a)
            d0 = desc
            if (a.dtype.str, a.shape) != key:   # contract violated:
                d0 = {"kind": "nd", "dtype": a.dtype.str,   # stay
                      "shape": a.shape}                     # correct
            raw = a.tobytes()
            header = {"cid": cid, "src": me, "tag": tag, "desc": d0}
            nraw = len(raw)
            for dest, wdest, t in peers:
                if ft.is_failed(wdest):
                    raise MPIError(ERR_PROC_FAILED,
                                   f"send peer rank {dest} has failed")
                t[0] += 1
                t[1] += nraw
                _ft_send(endpoint, wdest, header, raw)
        return send

    # -- receive side --------------------------------------------------
    def _cancel_posted(self, req: RankRequest) -> None:
        with self._lock:
            present = any(e[2] is req for e in self.posted)
            self.posted = [e for e in self.posted if e[2] is not req]
        if present:
            req.cancelled = True
            req._deliver(_Msg(ANY_SOURCE, ANY_TAG, None))

    def irecv(self, source: int = ANY_SOURCE,
              tag: int = ANY_TAG) -> RankRequest:
        req = RankRequest(source, tag)
        req._cancel_fn = lambda: self._cancel_posted(req)
        if source == PROC_NULL:
            req._deliver(_Msg(PROC_NULL, tag, None))
            return req
        with self._lock:
            msg = self._take_unexpected(source, tag)
            if msg is None:
                self.posted.append((source, tag, req))
        if msg is not None:
            self._ack(msg)
            req._deliver(msg)
            return req
        # a receive posted AFTER the peer's death can never match
        # (req_ft.c: fail fast instead of hanging); in-flight failures
        # are flushed by _peer_failed
        if source != ANY_SOURCE and 0 <= source < self.comm.size:
            from ompi_tpu.runtime import ft
            if ft.is_failed(self.comm.world_rank_of(source)):
                self._drop_posted(req)
                from ompi_tpu.core.errhandler import ERR_PROC_FAILED
                req._fail(MPIError(ERR_PROC_FAILED,
                                   f"receive peer rank {source} has "
                                   f"failed"))
        return req

    def _drop_posted(self, req: RankRequest) -> None:
        with self._lock:
            self.posted = [e for e in self.posted if e[2] is not req]

    def _peer_failed(self, world_rank: int) -> None:
        """Complete pending NAMED receives on the dead peer in error.
        Wildcard (ANY_SOURCE) receives stay posted and matchable — a
        live sender may still satisfy them (the reference's
        PROC_FAILED_PENDING keeps the request completable,
        req_ft.c; failing them outright would strand an in-flight
        message from a healthy peer). A wildcard that only the dead
        peer could have matched eventually times out."""
        if getattr(self.comm, "no_peer_map", False):
            return                   # intercomm engine: local deaths
        local = next((i for i in range(self.comm.size)
                      if self.comm.world_rank_of(i) == world_rank), None)
        if local is None:
            return
        from ompi_tpu.core.errhandler import ERR_PROC_FAILED
        with self._lock:
            hit = [e for e in self.posted if e[0] == local]
            self.posted = [e for e in self.posted if e not in hit]
            # combining slots still waiting on the dead peer's
            # contribution can never complete
            slots = [s for s in self._combine.values()
                     if 0 <= local < len(s._vals)
                     and s._vals[local] is None]
        for (_, _, req) in hit:
            req._fail(MPIError(
                ERR_PROC_FAILED,
                f"peer rank {local} died while this receive was "
                f"pending (shrink or restrict to live peers to "
                f"continue)"))
        for s in slots:
            s.fail(MPIError(
                ERR_PROC_FAILED,
                f"peer rank {local} died during a combining "
                f"collective"))

    def _flush_all(self, make_err) -> None:
        """Revocation flush (MPIX_Comm_revoke): complete EVERY pending
        operation on this engine in error — wildcards included. Unlike
        a single peer death, a revoked communicator can never match
        anything again (req_ft.c's revocation branch), so nothing may
        stay posted."""
        with self._lock:
            hit, self.posted = self.posted, []
            slots = [s for s in self._combine.values()
                     if any(v is None for v in s._vals)]
        for (_, _, req) in hit:
            req._fail(make_err())
        for s in slots:
            try:
                s.fail(make_err())
            except Exception:            # noqa: BLE001
                pass

    def recv(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
             timeout: Optional[float] = None) -> Tuple[Any, Status]:
        # telemetry: the recv histogram's duration IS blocked-waiting;
        # it doubles as the health monitor's per-peer wait ingress (the
        # matched source is only known at completion, so attribution
        # happens after the observe)
        if _tele.active:
            hist = _tele.PML_RECV
            tok = hist.start()
            try:
                data, st = self._recv_traced(source, tag, timeout)
            finally:
                hist.observe(tok)
            from ompi_tpu.telemetry import health as _health
            _health.note_wait(self.comm.world_rank_of(st.source),
                              _time.perf_counter() - tok)
            return data, st
        return self._recv_traced(source, tag, timeout)

    def _recv_traced(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
                     timeout: Optional[float] = None
                     ) -> Tuple[Any, Status]:
        # the span covers post-to-completion: its duration IS the
        # blocked-waiting time a late sender costs this rank
        if _trace.active:
            tok = _trace.begin("pml_recv", cid=None,
                               cc=str(self.comm.cid), src=source,
                               tag=tag)
            try:
                req = self.irecv(source, tag)
                st = req.wait(timeout)
                return req.get(), st
            finally:
                _trace.end(tok)
        req = self.irecv(source, tag)
        st = req.wait(timeout)
        return req.get(), st

    # -- probe ---------------------------------------------------------
    def iprobe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG
               ) -> Tuple[bool, Optional[Status]]:
        with self._lock:
            msg = self._take_unexpected(source, tag, remove=False)
        if msg is None:
            return False, None
        return True, Status(source=msg.src, tag=msg.tag,
                            count=int(getattr(msg.data, "size", 1) or 1),
                            nbytes=int(getattr(msg.data, "nbytes", -1)))

    def probe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
              timeout: float = 600, poll: float = 0.0005) -> Status:
        """Blocking probe: spin-wait (with backoff) until a matching
        message is pending — the opal_progress poll loop."""
        import time
        deadline = time.monotonic() + timeout
        while True:
            ok, st = self.iprobe(source, tag)
            if ok:
                return st
            if time.monotonic() > deadline:
                raise MPIError(ERR_PENDING, "probe timed out")
            time.sleep(poll)
            poll = min(poll * 2, 0.01)

    def mprobe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
               timeout: float = 600) -> _Msg:
        import time
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                msg = self._take_unexpected(source, tag)
            if msg is not None:
                self._ack(msg)
                return msg
            if time.monotonic() > deadline:
                raise MPIError(ERR_PENDING, "mprobe timed out")
            time.sleep(0.0005)

    @staticmethod
    def mrecv(msg: _Msg) -> Tuple[Any, Status]:
        from ompi_tpu.btl.devxfer import maybe_resolve
        from ompi_tpu.pml.pipeline import maybe_resolve as _pipe_resolve
        data = _pipe_resolve(maybe_resolve(msg.data))
        return data, Status(source=msg.src, tag=msg.tag,
                            count=int(getattr(data, "size", 1) or 1),
                            nbytes=int(getattr(data, "nbytes", -1)))

    def close(self) -> None:
        self.router.unregister(self.comm.cid)
