"""pml/stacked — the single-controller matching engine.

Behavioral spec: ob1's receive-side matching
(``ompi/mca/pml/ob1/pml_ob1_recvfrag.c:296-330``): an arriving message is
matched against the posted-receive queue (source + tag, with
MPI_ANY_SOURCE / MPI_ANY_TAG wildcards); unmatched messages go to the
unexpected queue in arrival order; a new receive first searches the
unexpected queue. Ordering is FIFO per (source, dest, comm) — MPI's
non-overtaking rule — so queues are keyed by (dest, src) and the
receiving rank is an explicit argument (in a single-controller world the
controller performs every rank's receives).

TPU-native re-design: ranks share a controller, so "the wire" is queue
state plus device-to-device shard movement. An eager send's payload is
referenced (device arrays are immutable — no copy needed, the analogue of
ob1's eager-copy without the memcpy); matching is O(queue) Python. The
protocol switch (eager vs rendezvous vs RDMA, ``pml_ob1_sendreq.h:389``)
collapses: every transfer is an HBM-resident reference handoff until a
rank actually reads it. Partitioned pt2pt rides a separate matching
*channel* so its internal fragments can never cross-match user tags.

This engine is SINGLE-CONTROLLER ONLY: in a stacked multi-controller
world a rank's shard may live on another process, so the dict handoff
would be silently wrong — ``Communicator.send/recv`` guards against it.
Genuine cross-process pt2pt lives in the per-rank execution model
(``ompi_tpu.pml.perrank`` over ``btl/tcp``), where one process == one
rank and bytes really move.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from ompi_tpu.core.errhandler import ERR_PENDING, ERR_RANK, ERR_TAG, MPIError
from ompi_tpu.core.request import Request, Status

ANY_SOURCE = -1
ANY_TAG = -1
PROC_NULL = -2

CH_P2P = 0          # ordinary sends/recvs (int tags)
CH_PART = 1         # partitioned pt2pt fragments (tuple tags)


class _Msg:
    __slots__ = ("src", "dest", "tag", "data", "synchronous", "channel")

    def __init__(self, src: int, dest: int, tag, data: Any,
                 synchronous: bool = False, channel: int = CH_P2P):
        self.src = src
        self.dest = dest
        self.tag = tag
        self.data = data
        self.synchronous = synchronous
        self.channel = channel


class _PostedRecv:
    __slots__ = ("src", "dest", "tag", "channel", "req")

    def __init__(self, src: int, dest: int, tag, channel: int,
                 req: "PtpRequest"):
        self.src = src
        self.dest = dest
        self.tag = tag
        self.channel = channel
        self.req = req

    def matches(self, msg: _Msg) -> bool:
        return (self.channel == msg.channel
                and self.dest == msg.dest
                and (self.src == ANY_SOURCE or self.src == msg.src)
                and (self.tag == ANY_TAG or self.tag == msg.tag))


class PtpRequest(Request):
    """A receive request completed by the matching engine (not by device
    readiness): ``test`` polls match state."""

    def __init__(self, engine: "MatchingEngine", src: int, tag):
        super().__init__(arrays=[])
        self._complete = False
        self._engine = engine
        self.status = Status(source=src,
                             tag=tag if isinstance(tag, int) else -1)

    def deliver(self, msg: _Msg) -> None:
        self._result = msg.data
        self.status.source = msg.src
        if isinstance(msg.tag, int):
            self.status.tag = msg.tag
        self.status.count = getattr(msg.data, "size", 1)
        self._complete = True

    def _check_ft(self) -> None:
        """Request-level fault tolerance (ompi/request/req_ft.c): a
        pending receive whose communicator was revoked, or whose (named)
        peer has failed, completes in error rather than deadlocking."""
        comm = getattr(self._engine, "comm", None)
        if comm is None or getattr(comm, "group", None) is None:
            return
        from ompi_tpu.core.errhandler import ERR_PROC_FAILED, ERR_REVOKED
        if getattr(comm, "_revoked", False):
            raise MPIError(ERR_REVOKED,
                           "pending receive on a revoked communicator")
        from ompi_tpu.runtime import ft
        src = self.status.source
        if src == ANY_SOURCE:
            unacked = [w for w in comm.group.world_ranks
                       if ft.is_failed(w)
                       and w not in comm._acked_failures]
            if unacked:
                raise MPIError(ERR_PROC_FAILED,
                               f"wildcard receive with unacknowledged "
                               f"failed world rank(s) {unacked}")
        elif 0 <= src < comm.size and ft.is_failed(
                comm.group.world_ranks[src]):
            raise MPIError(ERR_PROC_FAILED,
                           f"receive peer rank {src} has failed")

    def test(self):
        if not self._complete:
            self._check_ft()
        return (True, self.status) if self._complete else (False, None)

    def wait(self):
        if not self._complete:
            self._check_ft()
            # Single controller: no other thread can produce the matching
            # send while we block — this is the deadlock MPI semantics
            # prescribe; surface it instead of hanging.
            raise MPIError(
                ERR_PENDING,
                "recv would deadlock: no matching send has been posted "
                "(single-controller pt2pt requires the send first, or "
                "irecv + later send)")
        return self.status


class MatchingEngine:
    """Per-communicator pt2pt state: one unexpected FIFO per (dest, src)
    (non-overtaking), one posted-receive list (match order).

    Two equivalent backends: the C++ matching core (``matching.cpp``, the
    ob1-recvfrag role — integer descriptors in native queues, payloads
    held here by handle) when the native library is available, else pure
    Python. ``OMPI_TPU_DISABLE_NATIVE_MATCH=1`` forces the Python path
    (the tests run both and assert identical behavior)."""

    def __init__(self, comm):
        self.comm = comm
        self.unexpected: Dict[Tuple[int, int], Deque[_Msg]] = {}
        self.posted: List[_PostedRecv] = []
        # Per-peer traffic accounting (the pml/monitoring role): the
        # (src, dest) -> [messages, bytes] table behind
        # tools/profile.py's communication matrix.
        self.traffic: Dict[Tuple[int, int], List[int]] = {}
        self._lib = None
        self._h = -1
        import os
        if not os.environ.get("OMPI_TPU_DISABLE_NATIVE_MATCH"):
            from ompi_tpu.native import get_lib
            lib = get_lib()
            if lib is not None:
                self._lib = lib
                self._h = lib.ompi_tpu_match_create(comm.size)
                self._msgs: Dict[int, _Msg] = {}       # unexpected payloads
                self._reqs: Dict[int, PtpRequest] = {}  # posted receives
                self._next_handle = 1
                self._tag_ids: Dict[Any, int] = {}      # tuple-tag intern

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", -1)
        if lib is not None and h >= 0:
            try:
                lib.ompi_tpu_match_destroy(h)
            except Exception:
                pass

    def _tag_id(self, tag) -> int:
        """Native tags are int64; tuple tags (partitioned channel) are
        interned — equality of ids == equality of tags."""
        if isinstance(tag, int):
            return tag
        tid = self._tag_ids.get(tag)
        if tid is None:
            tid = self._tag_ids[tag] = (1 << 40) + len(self._tag_ids)
        return tid

    def _handle(self) -> int:
        h = self._next_handle
        self._next_handle += 1
        return h

    def _q(self, dest: int, src: int) -> Deque[_Msg]:
        return self.unexpected.setdefault((dest, src), deque())

    # -- send side -----------------------------------------------------
    def send(self, data: Any, src: int, dest: int, tag,
             synchronous: bool = False, channel: int = CH_P2P) -> Request:
        """Returns a completed Request; ``Request.status.count`` != -1
        indicates the message already matched a posted receive (the
        synchronous-send completion condition)."""
        if dest == PROC_NULL:
            return Request.completed()
        if not (0 <= dest < self.comm.size) or not (0 <= src < self.comm.size):
            raise MPIError(ERR_RANK, f"bad rank (src={src}, dest={dest})")
        if channel == CH_P2P and (not isinstance(tag, int) or tag < 0):
            raise MPIError(ERR_TAG, f"send tag must be an int >= 0, "
                                    f"got {tag!r}")
        import numpy as _np
        if isinstance(data, _np.ndarray):
            # MPI guarantees the send buffer is reusable the moment send
            # returns; mutable host arrays are snapshotted (the eager
            # copy). Device arrays are immutable — reference suffices.
            data = data.copy()
        if channel == CH_P2P:
            # Internal fragments (partitioned channel, vprotocol replay)
            # are not user messages; keep the profile matrix honest.
            t = self.traffic.setdefault((src, dest), [0, 0])
            t[0] += 1
            t[1] += int(getattr(data, "nbytes", 0) or 0)
        msg = _Msg(src, dest, tag, data, synchronous, channel)
        if self._lib is not None:
            mh = self._handle()
            r = self._lib.ompi_tpu_match_send(
                self._h, src, dest, self._tag_id(tag), channel, mh,
                0 if synchronous else 1)
            if r >= 0:                       # matched a posted receive
                self._reqs.pop(r).deliver(msg)
                req = Request.completed()
                req.status.count = 1
                return req
            if not synchronous:
                self._msgs[mh] = msg
        else:
            for i, pr in enumerate(self.posted):
                if pr.matches(msg):
                    self.posted.pop(i)
                    pr.req.deliver(msg)
                    req = Request.completed()
                    req.status.count = 1
                    return req
        if synchronous:
            # MPI_Ssend completes only once the receive has started; in a
            # single-controller world an unmatched synchronous send can
            # never complete — surface the deadlock. (The native core was
            # told not to enqueue it.)
            raise MPIError(
                ERR_PENDING,
                "ssend would deadlock: no matching receive posted "
                "(post irecv first)")
        if self._lib is None:
            self._q(dest, src).append(msg)
        return Request.completed()

    # -- receive side --------------------------------------------------
    def _match_unexpected(self, dest: int, source: int, tag,
                          channel: int = CH_P2P,
                          remove: bool = True) -> Optional[_Msg]:
        if self._lib is not None:
            mh = self._lib.ompi_tpu_match_take(
                self._h, dest, source, self._tag_id(tag), channel,
                1 if remove else 0)
            if mh < 0:
                return None
            return self._msgs.pop(mh) if remove else self._msgs[mh]
        srcs = (range(self.comm.size) if source == ANY_SOURCE
                else [source])
        for s in srcs:
            q = self.unexpected.get((dest, s))
            if not q:
                continue
            for i, msg in enumerate(q):
                if msg.channel == channel and (
                        tag == ANY_TAG or tag == msg.tag):
                    if remove:
                        del q[i]
                    return msg
        return None

    def irecv(self, dest: int, source: int, tag,
              channel: int = CH_P2P) -> PtpRequest:
        """Post rank ``dest``'s receive."""
        req = PtpRequest(self, source, tag)
        req.dest = dest               # receiving rank (debugger dumps)
        if source == PROC_NULL:
            req.deliver(_Msg(PROC_NULL, dest, tag, None))
            return req
        msg = self._match_unexpected(dest, source, tag, channel)
        if msg is not None:
            req.deliver(msg)
        elif self._lib is not None:
            rh = self._handle()
            self._reqs[rh] = req
            self._lib.ompi_tpu_match_post(
                self._h, dest, source, self._tag_id(tag), channel, rh)
        else:
            self.posted.append(_PostedRecv(source, dest, tag, channel, req))
        return req

    def recv(self, dest: int, source: int, tag) -> Tuple[Any, Status]:
        req = self.irecv(dest, source, tag)
        st = req.wait()
        return req.get(), st

    # -- probe ---------------------------------------------------------
    def iprobe(self, dest: int, source: int, tag
               ) -> Tuple[bool, Optional[Status]]:
        msg = self._match_unexpected(dest, source, tag, CH_P2P,
                                     remove=False)
        if msg is None:
            return False, None
        return True, Status(source=msg.src, tag=msg.tag,
                            count=getattr(msg.data, "size", 1))

    def probe(self, dest: int, source: int, tag) -> Status:
        ok, st = self.iprobe(dest, source, tag)
        if not ok:
            raise MPIError(
                ERR_PENDING,
                "probe would deadlock: no matching message pending")
        return st

    def mprobe(self, dest: int, source: int, tag):
        """Matched probe (MPI_Mprobe): removes the message from matching
        and returns it as a handle for mrecv."""
        msg = self._match_unexpected(dest, source, tag)
        if msg is None:
            raise MPIError(ERR_PENDING, "no matching message pending")
        return msg

    @staticmethod
    def mrecv(msg: _Msg) -> Tuple[Any, Status]:
        return msg.data, Status(source=msg.src,
                                tag=msg.tag if isinstance(msg.tag, int)
                                else -1,
                                count=getattr(msg.data, "size", 1))
