"""pml/stacked — the single-controller matching engine.

Behavioral spec: ob1's receive-side matching
(``ompi/mca/pml/ob1/pml_ob1_recvfrag.c:296-330``): an arriving message is
matched against the posted-receive queue (source + tag, with
MPI_ANY_SOURCE / MPI_ANY_TAG wildcards); unmatched messages go to the
unexpected queue in arrival order; a new receive first searches the
unexpected queue. Ordering is FIFO per (source, dest, comm) — MPI's
non-overtaking rule — so queues are keyed by (dest, src) and the
receiving rank is an explicit argument (in a single-controller world the
controller performs every rank's receives).

TPU-native re-design: ranks share a controller, so "the wire" is queue
state plus device-to-device shard movement. An eager send's payload is
referenced (device arrays are immutable — no copy needed, the analogue of
ob1's eager-copy without the memcpy); matching is O(queue) Python. The
protocol switch (eager vs rendezvous vs RDMA, ``pml_ob1_sendreq.h:389``)
survives with real teeth: payloads above ``pml_stacked_eager_limit``
are MOVED to the destination rank's device at send time (a PJRT D2D
transfer — bytes cross the fabric), the rendezvous/RDMA-put tier; see
the MatchingEngine class doc. Partitioned pt2pt rides a separate
matching *channel* so its internal fragments can never cross-match user
tags.

This engine is SINGLE-CONTROLLER ONLY: in a stacked multi-controller
world a rank's shard may live on another process, so the dict handoff
would be silently wrong — ``Communicator.send/recv`` guards against it.
Genuine cross-process pt2pt lives in the per-rank execution model
(``ompi_tpu.pml.perrank`` over ``btl/tcp``), where one process == one
rank and bytes really move.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from ompi_tpu.core.errhandler import ERR_PENDING, ERR_RANK, ERR_TAG, MPIError
from ompi_tpu.core.request import Request, Status

ANY_SOURCE = -1
ANY_TAG = -1
PROC_NULL = -2

CH_P2P = 0          # ordinary sends/recvs (int tags)
CH_PART = 1         # partitioned pt2pt fragments (tuple tags)


def _register_vars() -> None:
    from ompi_tpu.mca import var
    var.var_register(
        "pml", "stacked", "eager_limit", vtype="int",
        default=1 << 16,
        help="Device payloads above this many bytes are transferred "
             "to the destination rank's device at send time (the "
             "rendezvous/RDMA-put tier, a PJRT D2D move over the "
             "fabric); smaller ones are eager reference handoffs, "
             "mirroring btl_eager_limit's protocol switch")


_register_vars()


class _Msg:
    __slots__ = ("src", "dest", "tag", "data", "synchronous", "channel")

    def __init__(self, src: int, dest: int, tag, data: Any,
                 synchronous: bool = False, channel: int = CH_P2P):
        self.src = src
        self.dest = dest
        self.tag = tag
        self.data = data
        self.synchronous = synchronous
        self.channel = channel


class _PostedRecv:
    __slots__ = ("src", "dest", "tag", "channel", "req")

    def __init__(self, src: int, dest: int, tag, channel: int,
                 req: "PtpRequest"):
        self.src = src
        self.dest = dest
        self.tag = tag
        self.channel = channel
        self.req = req

    def matches(self, msg: _Msg) -> bool:
        return (self.channel == msg.channel
                and self.dest == msg.dest
                and (self.src == ANY_SOURCE or self.src == msg.src)
                and (self.tag == ANY_TAG or self.tag == msg.tag))


class PtpRequest(Request):
    """A receive request completed by the matching engine (not by device
    readiness): ``test`` polls match state."""

    def __init__(self, engine: "MatchingEngine", src: int, tag):
        super().__init__(arrays=[])
        self._complete = False
        self._engine = engine
        self.status = Status(source=src,
                             tag=tag if isinstance(tag, int) else -1)

    def deliver(self, msg: _Msg) -> None:
        self._result = msg.data
        self.status.source = msg.src
        if isinstance(msg.tag, int):
            self.status.tag = msg.tag
        self.status.count = getattr(msg.data, "size", 1)
        self._complete = True

    def _check_ft(self) -> None:
        """Request-level fault tolerance (ompi/request/req_ft.c): a
        pending receive whose communicator was revoked, or whose (named)
        peer has failed, completes in error rather than deadlocking."""
        comm = getattr(self._engine, "comm", None)
        if comm is None or getattr(comm, "group", None) is None:
            return
        from ompi_tpu.core.errhandler import ERR_PROC_FAILED, ERR_REVOKED
        if getattr(comm, "_revoked", False):
            raise MPIError(ERR_REVOKED,
                           "pending receive on a revoked communicator")
        from ompi_tpu.runtime import ft
        reg = getattr(comm, "_ft", ft)   # the comm's failure domain
        src = self.status.source
        if src == ANY_SOURCE:
            unacked = [w for w in comm.group.world_ranks
                       if reg.is_failed(w)
                       and w not in comm._acked_failures]
            if unacked:
                raise MPIError(ERR_PROC_FAILED,
                               f"wildcard receive with unacknowledged "
                               f"failed world rank(s) {unacked}")
        elif 0 <= src < comm.size and reg.is_failed(
                comm.group.world_ranks[src]):
            raise MPIError(ERR_PROC_FAILED,
                           f"receive peer rank {src} has failed")

    def test(self):
        if not self._complete:
            self._check_ft()
        return (True, self.status) if self._complete else (False, None)

    def wait(self):
        if not self._complete:
            self._check_ft()
            # Single controller: no other thread can produce the matching
            # send while we block — this is the deadlock MPI semantics
            # prescribe; surface it instead of hanging.
            raise MPIError(
                ERR_PENDING,
                "recv would deadlock: no matching send has been posted "
                "(single-controller pt2pt requires the send first, or "
                "irecv + later send)")
        return self.status


class MatchingEngine:
    """Per-communicator pt2pt state: one unexpected FIFO per (dest, src)
    (non-overtaking), one posted-receive list (match order).

    Two equivalent backends: the C++ matching core (``matching.cpp``, the
    ob1-recvfrag role — integer descriptors in native queues, payloads
    held here by handle) when the native library is available, else pure
    Python. ``OMPI_TPU_DISABLE_NATIVE_MATCH=1`` forces the Python path
    (the tests run both and assert identical behavior).

    Protocol switch (``pml_ob1_sendreq.h:389-460``): device payloads at
    or below ``pml_stacked_eager_limit`` are reference handoffs (the
    eager path — device arrays are immutable, so the reference's eager
    copy costs nothing); above it, the payload is MOVED to the
    destination rank's device at send time via a PJRT D2D transfer —
    bytes genuinely cross the fabric (ICI on TPU), the rendezvous/RDMA-
    put analogue, so the receiving rank's later reads are device-local
    instead of pulling a remote buffer at use time. Host arrays are
    always eager-copied (the snapshot below)."""

    def __init__(self, comm):
        self.comm = comm
        import threading
        # Matching is check-then-act over shared queues; the GIL makes
        # single ops atomic but not the compound sequences — a lock
        # keeps MPI_THREAD_MULTIPLE honest (the reference guards ob1's
        # match with the comm matching lock for the same reason).
        self._mlock = threading.RLock()
        self.unexpected: Dict[Tuple[int, int], Deque[_Msg]] = {}
        self.posted: List[_PostedRecv] = []
        # Per-peer traffic accounting (the pml/monitoring role): the
        # (src, dest) -> [messages, bytes] table behind
        # tools/profile.py's communication matrix.
        self.traffic: Dict[Tuple[int, int], List[int]] = {}
        self._lib = None
        self._h = -1
        import os
        if not os.environ.get("OMPI_TPU_DISABLE_NATIVE_MATCH"):
            from ompi_tpu.native import get_lib
            lib = get_lib()
            if lib is not None:
                self._lib = lib
                self._h = lib.ompi_tpu_match_create(comm.size)
                self._msgs: Dict[int, _Msg] = {}       # unexpected payloads
                self._reqs: Dict[int, PtpRequest] = {}  # posted receives
                self._next_handle = 1
                self._tag_ids: Dict[Any, int] = {}      # tuple-tag intern

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", -1)
        if lib is not None and h >= 0:
            try:
                lib.ompi_tpu_match_destroy(h)
            except Exception:
                pass

    def _tag_id(self, tag) -> int:
        """Native tags are int64; tuple tags (partitioned channel) are
        interned — equality of ids == equality of tags."""
        if isinstance(tag, int):
            return tag
        tid = self._tag_ids.get(tag)
        if tid is None:
            tid = self._tag_ids[tag] = (1 << 40) + len(self._tag_ids)
        return tid

    def _handle(self) -> int:
        h = self._next_handle
        self._next_handle += 1
        return h

    def _q(self, dest: int, src: int) -> Deque[_Msg]:
        return self.unexpected.setdefault((dest, src), deque())

    def _protocol_switch(self, data, dest: int):
        """Eager vs rendezvous for device payloads (see class doc)."""
        try:
            import jax
        except Exception:                # pragma: no cover
            return data
        if not isinstance(data, jax.Array):
            return data
        from ompi_tpu.mca import var
        from ompi_tpu.runtime import spc
        limit = var.var_get("pml_stacked_eager_limit", 1 << 16)
        nbytes = int(getattr(data, "nbytes", 0) or 0)
        devs = getattr(self.comm, "devices", None)
        if nbytes <= limit or devs is None or not (0 <= dest < len(devs)):
            spc.record("pml_eager", 1)
            return data
        target = devs[dest]
        try:
            cur = list(data.devices())
        except Exception:
            cur = []
        if cur == [target]:
            spc.record("pml_eager", 1)   # already resident at dest
            return data
        spc.record("pml_rndv", 1)
        # the fabric-touching put: PJRT moves the bytes to the
        # destination rank's device NOW (ICI on TPU hardware)
        return jax.device_put(data, target)

    # -- send side -----------------------------------------------------
    def send(self, data: Any, src: int, dest: int, tag,
             synchronous: bool = False, channel: int = CH_P2P) -> Request:
        """Returns a completed Request; ``Request.status.count`` != -1
        indicates the message already matched a posted receive (the
        synchronous-send completion condition)."""
        if dest == PROC_NULL:
            return Request.completed()
        if not (0 <= dest < self.comm.size) or not (0 <= src < self.comm.size):
            raise MPIError(ERR_RANK, f"bad rank (src={src}, dest={dest})")
        if channel == CH_P2P and (not isinstance(tag, int) or tag < 0):
            raise MPIError(ERR_TAG, f"send tag must be an int >= 0, "
                                    f"got {tag!r}")
        import numpy as _np
        if isinstance(data, _np.ndarray):
            # MPI guarantees the send buffer is reusable the moment send
            # returns; mutable host arrays are snapshotted (the eager
            # copy). Device arrays are immutable — reference suffices.
            data = data.copy()
        else:
            data = self._protocol_switch(data, dest)
        if channel == CH_P2P:
            # Internal fragments (partitioned channel, vprotocol replay)
            # are not user messages; keep the profile matrix honest.
            t = self.traffic.setdefault((src, dest), [0, 0])
            t[0] += 1
            t[1] += int(getattr(data, "nbytes", 0) or 0)
        msg = _Msg(src, dest, tag, data, synchronous, channel)
        with self._mlock:
            if self._lib is not None:
                mh = self._handle()
                r = self._lib.ompi_tpu_match_send(
                    self._h, src, dest, self._tag_id(tag), channel, mh,
                    0 if synchronous else 1)
                if r >= 0:                   # matched a posted receive
                    self._reqs.pop(r).deliver(msg)
                    req = Request.completed()
                    req.status.count = 1
                    return req
                if not synchronous:
                    self._msgs[mh] = msg
            else:
                for i, pr in enumerate(self.posted):
                    if pr.matches(msg):
                        self.posted.pop(i)
                        pr.req.deliver(msg)
                        req = Request.completed()
                        req.status.count = 1
                        return req
                if not synchronous:
                    # enqueue INSIDE the lock: a concurrent irecv that
                    # found the queue empty must not post between our
                    # scan and this append, or message and receive
                    # strand in opposite queues (the check-then-act
                    # race the matching lock exists to close)
                    self._q(dest, src).append(msg)
        if synchronous:
            # MPI_Ssend completes only once the receive has started; in a
            # single-controller world an unmatched synchronous send can
            # never complete — surface the deadlock. (Neither backend
            # enqueued it.)
            raise MPIError(
                ERR_PENDING,
                "ssend would deadlock: no matching receive posted "
                "(post irecv first)")
        return Request.completed()

    # -- receive side --------------------------------------------------
    def _match_unexpected(self, dest: int, source: int, tag,
                          channel: int = CH_P2P,
                          remove: bool = True) -> Optional[_Msg]:
        with self._mlock:
            return self._match_unexpected_locked(dest, source, tag,
                                                 channel, remove)

    def _match_unexpected_locked(self, dest: int, source: int, tag,
                                 channel: int = CH_P2P,
                                 remove: bool = True) -> Optional[_Msg]:
        if self._lib is not None:
            mh = self._lib.ompi_tpu_match_take(
                self._h, dest, source, self._tag_id(tag), channel,
                1 if remove else 0)
            if mh < 0:
                return None
            return self._msgs.pop(mh) if remove else self._msgs[mh]
        srcs = (range(self.comm.size) if source == ANY_SOURCE
                else [source])
        for s in srcs:
            q = self.unexpected.get((dest, s))
            if not q:
                continue
            for i, msg in enumerate(q):
                if msg.channel == channel and (
                        tag == ANY_TAG or tag == msg.tag):
                    if remove:
                        del q[i]
                    return msg
        return None

    def irecv(self, dest: int, source: int, tag,
              channel: int = CH_P2P) -> PtpRequest:
        """Post rank ``dest``'s receive."""
        req = PtpRequest(self, source, tag)
        req.dest = dest               # receiving rank (debugger dumps)
        if source == PROC_NULL:
            req.deliver(_Msg(PROC_NULL, dest, tag, None))
            return req
        with self._mlock:
            msg = self._match_unexpected_locked(dest, source, tag,
                                                channel)
            if msg is None:
                if self._lib is not None:
                    rh = self._handle()
                    self._reqs[rh] = req
                    self._lib.ompi_tpu_match_post(
                        self._h, dest, source, self._tag_id(tag),
                        channel, rh)
                else:
                    self.posted.append(
                        _PostedRecv(source, dest, tag, channel, req))
        if msg is not None:
            req.deliver(msg)
        return req

    def recv(self, dest: int, source: int, tag) -> Tuple[Any, Status]:
        req = self.irecv(dest, source, tag)
        st = req.wait()
        return req.get(), st

    # -- probe ---------------------------------------------------------
    def iprobe(self, dest: int, source: int, tag
               ) -> Tuple[bool, Optional[Status]]:
        msg = self._match_unexpected(dest, source, tag, CH_P2P,
                                     remove=False)
        if msg is None:
            return False, None
        return True, Status(source=msg.src, tag=msg.tag,
                            count=getattr(msg.data, "size", 1))

    def probe(self, dest: int, source: int, tag) -> Status:
        ok, st = self.iprobe(dest, source, tag)
        if not ok:
            raise MPIError(
                ERR_PENDING,
                "probe would deadlock: no matching message pending")
        return st

    def mprobe(self, dest: int, source: int, tag):
        """Matched probe (MPI_Mprobe): removes the message from matching
        and returns it as a handle for mrecv."""
        msg = self._match_unexpected(dest, source, tag)
        if msg is None:
            raise MPIError(ERR_PENDING, "no matching message pending")
        return msg

    @staticmethod
    def mrecv(msg: _Msg) -> Tuple[Any, Status]:
        return msg.data, Status(source=msg.src,
                                tag=msg.tag if isinstance(msg.tag, int)
                                else -1,
                                count=getattr(msg.data, "size", 1))
