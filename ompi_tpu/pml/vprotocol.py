"""pml/vprotocol — pessimist message-logging fault tolerance.

Behavioral spec: the reference's ``pml/v`` interposition PML with its
``vprotocol/pessimist`` component (``ompi/mca/pml/v``,
``ompi/mca/vprotocol/pessimist`` — 2,065 LoC): every *nondeterministic
event* in the message layer is logged synchronously before it is allowed
to influence execution (pessimist = no determinant may be outrun by a
message it determines), so a failed execution can be replayed to the
exact same state. The two event classes are

- **determinants** — which send matched which receive. The only true
  nondeterminism in MPI matching is wildcard receives (MPI_ANY_SOURCE /
  MPI_ANY_TAG): the per-(src,dest) non-overtaking rule fixes everything
  else.
- **sender-based payload log** — message payloads escrowed at the sender
  so a restarted process can be fed messages whose senders are not being
  rolled back (orphan redelivery).

TPU-native re-design: the matching engine is controller-resident state
(``pml/stacked.py``), so "logging before delivery" is a synchronous
append — the pessimist protocol's hard part on a real wire (holding the
message until its determinant is stable) is free here. Replay runs the
same application code against an engine constructed with the recorded
determinant log: wildcard receives are *forced* to their logged
(source, tag) resolution, which by non-overtaking reproduces the
original delivery order exactly. Payloads escrowed in the log can be
redelivered without re-executing the sender (``redeliver``).

Enabled per-communicator via the MCA var ``pml_v_protocol=pessimist``
(the reference enables pml/v the same way, by component selection).
"""
from __future__ import annotations

from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from ompi_tpu.core.errhandler import ERR_OTHER, MPIError
from ompi_tpu.pml.stacked import (ANY_SOURCE, ANY_TAG, CH_P2P,
                                  MatchingEngine, PtpRequest, _Msg)
from ompi_tpu.mca import var

var.var_register(
    "pml", "v", "protocol", vtype="str", default="none",
    enumerator=["none", "pessimist"],
    help="Message-logging fault-tolerance protocol interposed on the "
         "pt2pt matching engine (vprotocol/pessimist role): 'pessimist' "
         "logs determinants + sender payloads for deterministic replay")


class Event:
    """One logged event. ``kind`` is 'send' or 'match'.

    send:  (seq, src, dest, tag, channel, payload)   — sender-based log
    match: (seq, dest, posted_src, posted_tag, src, tag, channel)
           — the determinant: the receive posted as (posted_src,
           posted_tag) was resolved to the message (src, tag).
    """
    __slots__ = ("seq", "kind", "src", "dest", "tag", "channel",
                 "payload", "posted_src", "posted_tag")

    def __init__(self, seq: int, kind: str, *, src: int = -9,
                 dest: int = -9, tag=None, channel: int = CH_P2P,
                 payload: Any = None, posted_src: int = -9,
                 posted_tag=None):
        self.seq = seq
        self.kind = kind
        self.src = src
        self.dest = dest
        self.tag = tag
        self.channel = channel
        self.payload = payload
        self.posted_src = posted_src
        self.posted_tag = posted_tag

    def to_dict(self) -> Dict:
        d = {"seq": self.seq, "kind": self.kind, "src": self.src,
             "dest": self.dest, "tag": self.tag, "channel": self.channel,
             "posted_src": self.posted_src,
             "posted_tag": self.posted_tag}
        if self.payload is not None:
            p = self.payload
            d["payload"] = (np.asarray(p).tolist()
                            if hasattr(p, "__array__") else p)
            d["payload_dtype"] = (str(np.asarray(p).dtype)
                                  if hasattr(p, "__array__") else None)
        return d

    @classmethod
    def from_dict(cls, d: Dict) -> "Event":
        payload = d.get("payload")
        if payload is not None and d.get("payload_dtype"):
            payload = np.asarray(payload, dtype=d["payload_dtype"])
        return cls(d["seq"], d["kind"], src=d.get("src", -9),
                   dest=d.get("dest", -9), tag=d.get("tag"),
                   channel=d.get("channel", CH_P2P), payload=payload,
                   posted_src=d.get("posted_src", -9),
                   posted_tag=d.get("posted_tag"))


class PessimistEngine(MatchingEngine):
    """Matching engine with pessimist event logging (record mode) and
    determinant-forced matching (replay mode)."""

    def __init__(self, comm, replay_log: Optional[List[Event]] = None):
        super().__init__(comm)
        self.log: List[Event] = []
        self._seq = 0
        # Replay: per-dest FIFO of match determinants, consumed by
        # wildcard receives in posting order (the pessimist guarantee:
        # receive k at a rank resolves identically across executions).
        self._replay: Optional[Dict[int, Deque[Event]]] = None
        if replay_log is not None:
            self._replay = {}
            for ev in replay_log:
                # Only wildcard resolutions are nondeterministic; a
                # named receive replays itself (and consumes no
                # determinant), so enqueuing its match event would
                # shift every later wildcard onto the wrong one.
                if ev.kind == "match" and (ev.posted_src == ANY_SOURCE
                                           or ev.posted_tag == ANY_TAG):
                    self._replay.setdefault(ev.dest, deque()).append(ev)

    # -- record side ---------------------------------------------------
    def _next_seq(self) -> int:
        s = self._seq
        self._seq += 1
        return s

    def _log_send(self, data, src, dest, tag, channel) -> None:
        snap = data
        if isinstance(snap, np.ndarray):
            snap = snap.copy()
        self.log.append(Event(self._next_seq(), "send", src=src,
                              dest=dest, tag=tag, channel=channel,
                              payload=snap))

    def _log_match(self, dest: int, posted_src: int, posted_tag,
                   msg: _Msg) -> None:
        self.log.append(Event(self._next_seq(), "match", dest=dest,
                              posted_src=posted_src,
                              posted_tag=posted_tag, src=msg.src,
                              tag=msg.tag, channel=msg.channel))

    def send(self, data, src, dest, tag, synchronous=False,
             channel=CH_P2P):
        # Pessimist rule: the event is durable *before* the message can
        # match anything (log-then-send).
        self._log_send(data, src, dest, tag, channel)
        return super().send(data, src, dest, tag, synchronous, channel)

    def irecv(self, dest, source, tag, channel=CH_P2P) -> PtpRequest:
        if self._replay is not None and (source == ANY_SOURCE
                                         or tag == ANY_TAG):
            det = self._pop_determinant(dest, source, tag)
            source, tag = det.src, det.tag
        posted_src, posted_tag = source, tag
        req = super().irecv(dest, source, tag, channel)
        if req._complete:
            if req.status.source >= 0:      # not PROC_NULL
                self._log_match(dest, posted_src, posted_tag,
                                _Msg(req.status.source, dest,
                                     req.status.tag, None,
                                     channel=channel))
            return req
        # Deferred match: interpose on delivery so the determinant is
        # logged the instant the matching send arrives.
        orig_deliver = req.deliver

        def deliver(msg, _orig=orig_deliver):
            self._log_match(dest, posted_src, posted_tag, msg)
            _orig(msg)
        req.deliver = deliver               # type: ignore[method-assign]
        return req

    def mprobe(self, dest, source, tag):
        if self._replay is not None and (source == ANY_SOURCE
                                         or tag == ANY_TAG):
            det = self._pop_determinant(dest, source, tag)
            source, tag = det.src, det.tag
        msg = super().mprobe(dest, source, tag)
        self._log_match(dest, source, tag, msg)
        return msg

    # -- replay side ---------------------------------------------------
    def _pop_determinant(self, dest: int, source: int, tag) -> Event:
        q = (self._replay or {}).get(dest)
        if not q:
            raise MPIError(
                ERR_OTHER,
                f"pessimist replay: no determinant left for a wildcard "
                f"receive at rank {dest} (log and execution diverged)")
        det = q.popleft()
        if ((det.posted_src != source and det.posted_src != ANY_SOURCE
             and source != ANY_SOURCE)
                or (det.posted_tag != tag and det.posted_tag != ANY_TAG
                    and tag != ANY_TAG)):
            raise MPIError(
                ERR_OTHER,
                f"pessimist replay: determinant mismatch at rank {dest} "
                f"(logged receive ({det.posted_src}, {det.posted_tag}), "
                f"replayed ({source}, {tag}))")
        return det

    def redeliver(self, dest: int) -> int:
        """Re-inject every logged send addressed to ``dest`` from the
        sender-based payload log (orphan redelivery: the senders are
        not being re-executed). Returns the number re-injected."""
        n = 0
        # Redelivered payloads were counted when first sent; keep the
        # per-peer profile matrix at first-execution truth.
        saved = {k: list(v) for k, v in self.traffic.items()}
        for ev in self.log:
            if ev.kind == "send" and ev.dest == dest:
                super().send(ev.payload, ev.src, ev.dest, ev.tag,
                             channel=ev.channel)
                n += 1
        self.traffic = saved
        return n

    # -- persistence (checkpoint escrow) -------------------------------
    def snapshot(self) -> List[Dict]:
        return [ev.to_dict() for ev in self.log]

    @classmethod
    def restore_log(cls, dicts: List[Dict]) -> List[Event]:
        return [Event.from_dict(d) for d in dicts]
