"""Error-feedback accumulator — residual carry for iterative workloads.

Quantized collectives bias iterative sums: each step's rounding error
is lost. Error feedback (1-bit SGD / EF-SGD lineage, HiCCL §5's
compression-composition caveat) keeps the residual locally and adds it
back into the NEXT step's payload before quantization, so the error a
step drops is re-offered rather than forgotten — the accumulated
drift stays bounded instead of growing with step count.

Usage (per logical stream, e.g. one gradient buffer)::

    ef = ErrorFeedback()
    x_comp = ef.compensate(key, x)          # x + carried residual
    codes, scales = codec.encode(x_comp)
    ef.record(key, x_comp, codec.decode(codes, scales, ...))

The accumulator is deliberately NOT wired into the collective hot path
by default: residuals are only meaningful when successive calls reuse
the same logical buffer, which the transport cannot know. The wire
layer exposes it behind ``mpi_base_compress_error_feedback`` for
callers that opt a stream in (see compress/wire.py).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Hashable

import numpy as np


class ErrorFeedback:
    """Per-key residual store. Keys identify a logical stream; shapes
    must be stable per key (a shape change resets that key's residual
    — a different buffer is a different stream)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._resid: Dict[Hashable, np.ndarray] = {}

    def compensate(self, key: Hashable, x: Any) -> np.ndarray:
        """Return ``x`` plus the carried residual for ``key``."""
        x = np.asarray(x)
        with self._lock:
            r = self._resid.get(key)
        if r is None or r.shape != x.shape:
            return x.copy()
        return (x + r.astype(x.dtype)).astype(x.dtype)

    def record(self, key: Hashable, x_compensated: Any,
               dequantized: Any) -> None:
        """Store what quantization dropped: compensated input minus
        its round-trip image."""
        xc = np.asarray(x_compensated, np.float64)
        dq = np.asarray(dequantized, np.float64)
        resid = xc - dq
        # a poisoned (non-finite) block carries no meaningful residual
        resid = np.where(np.isfinite(resid), resid, 0.0)
        with self._lock:
            self._resid[key] = resid.astype(np.float32)

    def residual(self, key: Hashable):
        with self._lock:
            r = self._resid.get(key)
        return None if r is None else r.copy()

    def reset(self, key: Hashable = None) -> None:
        with self._lock:
            if key is None:
                self._resid.clear()
            else:
                self._resid.pop(key, None)


# process-default accumulator (the wire layer's opt-in store)
default = ErrorFeedback()
