"""ompi_tpu.compress — quantized & compressed collectives (EQuARX-style).

The subsystem behind the ``coll/compressed`` component: block-scaled
quantization codecs (compress/codecs), the host/per-rank wire form
(compress/wire), an error-feedback accumulator for iterative workloads
(compress/feedback), and the observability plane (compress/stats:
byte/ratio/error pvars + ``compress.*`` trace spans).

Config (MCA vars, framework ``mpi``/``base`` — the subsystem gates
collective behavior across components, like the tracer's vars):

- ``mpi_base_compress`` (bool, off): master switch. Off means every
  path is byte-identical to the uncompressed framework.
- ``mpi_base_compress_codec``: ``int8_block`` (default), ``fp8_block``,
  or ``null``.
- ``mpi_base_compress_min_bytes`` (default 4 MiB): per-rank payload
  floor below which compression never engages (quantization arithmetic
  beats wire savings only for large messages).
- ``mpi_base_compress_block`` (default 256): elements per scale block.
- ``mpi_base_compress_error_feedback`` (bool, off): opt keyed wire
  streams into the residual accumulator.

See docs/COMPRESSION.md for formats, selection rules, and accuracy
caveats.
"""
from __future__ import annotations

from ompi_tpu.mca import var as _var

from ompi_tpu.compress import stats  # noqa: F401  (registers pvars)
from ompi_tpu.compress.codecs import (Codec, DEFAULT_BLOCK,  # noqa: F401
                                      codec_names, get_codec)
from ompi_tpu.compress.feedback import ErrorFeedback  # noqa: F401

DEFAULT_MIN_BYTES = 4 << 20


def _register_vars() -> None:
    _var.var_register(
        "mpi", "base", "compress", vtype="bool", default=False,
        help="Enable block-scaled quantized collectives for large "
             "f32/f64/bf16 sum reductions and gathers "
             "(docs/COMPRESSION.md)")
    _var.var_register(
        "mpi", "base", "compress_codec", vtype="str",
        default="int8_block",
        help="Compression codec: int8_block (symmetric int8, "
             "err <= block_max/254), fp8_block (e4m3, relative err "
             "<= 2^-4), or null (identity; schedule A/B baseline)")
    _var.var_register(
        "mpi", "base", "compress_min_bytes", vtype="int",
        default=DEFAULT_MIN_BYTES,
        help="Per-rank payload floor for compressed collectives; "
             "smaller payloads take the uncompressed path unchanged")
    _var.var_register(
        "mpi", "base", "compress_block", vtype="int", default=DEFAULT_BLOCK,
        help="Elements per quantization block (one float32 scale per "
             "block rides the wire next to the 1-byte codes)")
    _var.var_register(
        "mpi", "base", "compress_error_feedback", vtype="bool",
        default=False,
        help="Carry quantization residuals per wire stream and fold "
             "them into the next payload (iterative workloads)")


def enabled() -> bool:
    _register_vars()
    return bool(_var.var_get("mpi_base_compress", False))


def codec_name() -> str:
    _register_vars()
    return str(_var.var_get("mpi_base_compress_codec", "int8_block"))


def min_bytes() -> int:
    _register_vars()
    return int(_var.var_get("mpi_base_compress_min_bytes",
                            DEFAULT_MIN_BYTES))


def block_elems() -> int:
    _register_vars()
    return max(1, int(_var.var_get("mpi_base_compress_block",
                                   DEFAULT_BLOCK)))


def error_feedback() -> bool:
    _register_vars()
    return bool(_var.var_get("mpi_base_compress_error_feedback", False))
