"""Host/per-rank wire codec — compression on the pml large-message path.

The per-rank world's host-tier collectives (rankcomm's binomial
reduce/bcast chains) move whole NumPy payloads through the pml; above
the compression threshold those hops carry a :class:`CompressedWire`
instead — codes + per-block scales — so a 4 MB fp32 hop ships ~1 MB.

Hop semantics match the device schedules: the *reduce* chain decodes,
folds, and re-encodes at every hop (dequant -> reduce -> requant, the
EQuARX reduction-hop structure); the *bcast* chain encodes once at the
root and forwards the codes losslessly (one quantization error total).

Every encode records ``compress.quant`` spans + byte pvars and feeds
the measured round-trip error into the ``compress_max_abs_error``
watermark; decode records ``compress.dequant``. Error feedback
(compress/feedback) is applied per (shape, dtype) stream when
``mpi_base_compress_error_feedback`` is on.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

from ompi_tpu.compress import codecs as _codecs
from ompi_tpu.compress import feedback as _feedback
from ompi_tpu.compress import stats as _stats
from ompi_tpu.trace import core as _trace

_NP_ELIGIBLE = ("float32", "float64")


class CompressedWire:
    """The pickled wire form: plain attributes only (rides the btl's
    generic object payload encoding)."""

    __slots__ = ("codec", "block", "codes", "scales", "shape", "dtype")

    def __init__(self, codec: str, block: int, codes: np.ndarray,
                 scales: np.ndarray, shape: Tuple[int, ...], dtype: str):
        self.codec = codec
        self.block = block
        self.codes = codes
        self.scales = scales
        self.shape = shape
        self.dtype = dtype

    # pickle via __getstate__/__setstate__ (slots have no __dict__)
    def __getstate__(self):
        return (self.codec, self.block, self.codes, self.scales,
                self.shape, self.dtype)

    def __setstate__(self, st):
        (self.codec, self.block, self.codes, self.scales,
         self.shape, self.dtype) = st

    @property
    def nbytes(self) -> int:
        return int(self.codes.nbytes + self.scales.nbytes)


def _conf():
    from ompi_tpu import compress as _c
    return _c


def eligible(data: Any, op=None, nbytes: Optional[int] = None) -> bool:
    """Host-path eligibility: compression on, NumPy float payload above
    the threshold, and (when reducing) a sum op — non-sum reduction
    semantics fall back to the uncompressed path (decision.py gates the
    device path identically)."""
    c = _conf()
    if not c.enabled():
        return False
    if not isinstance(data, np.ndarray):
        return False
    if data.dtype.name not in _NP_ELIGIBLE:
        return False
    if (data.nbytes if nbytes is None else nbytes) < c.min_bytes():
        return False
    if op is not None and getattr(op, "xla_prim", None) != "sum":
        return False
    return True


# verification sampling: the watermark-feeding round-trip costs real
# passes over multi-MB payloads, so it runs on the FIRST encode of
# each (codec, shape, dtype) and every VERIFY_EVERY-th encode after —
# the watermark stays live without taxing every hop. Error feedback
# needs the dequantized image every call regardless.
VERIFY_EVERY = 32
_seen_keys: set = set()
_encode_count = 0


def encode(arr: np.ndarray, stream_key: Any = None) -> CompressedWire:
    """Quantize ``arr`` for the wire. ``stream_key`` opts the payload
    into error feedback (only meaningful for repeated same-buffer
    calls; pass None for one-shot hops)."""
    global _encode_count
    c = _conf()
    codec = _codecs.get_codec(c.codec_name())
    block = c.block_elems()
    use_ef = stream_key is not None and c.error_feedback()
    if use_ef:
        key = (stream_key, arr.shape, arr.dtype.name)
        arr = _feedback.default.compensate(key, arr)
    tok = (_trace.begin(_stats.EV_QUANT, nbytes=int(arr.nbytes))
           if _trace.active else None)
    try:
        codes, scales = codec.encode(arr, block)
    finally:
        if tok is not None:
            _trace.end(tok)
    w = CompressedWire(codec.name, block, codes, scales,
                       tuple(arr.shape), arr.dtype.str)
    _stats.account(arr.nbytes, w.nbytes)
    _encode_count += 1
    vkey = (codec.name, tuple(arr.shape), arr.dtype.name)
    verify = use_ef or vkey not in _seen_keys \
        or _encode_count % VERIFY_EVERY == 0
    if verify:
        _seen_keys.add(vkey)
        dq = codec.decode(codes, scales, arr.shape, arr.dtype, block)
        diff = np.abs(np.asarray(arr, np.float32)
                      - np.asarray(dq, np.float32))
        finite = diff[np.isfinite(diff)]
        if finite.size:
            _stats.note_error(float(finite.max()))
        if use_ef:
            _feedback.default.record(key, arr, dq)
    return w


def decode(w: CompressedWire) -> np.ndarray:
    codec = _codecs.get_codec(w.codec)
    tok = (_trace.begin(_stats.EV_DEQUANT,
                        nbytes=int(getattr(w.codes, "nbytes", 0)))
           if _trace.active else None)
    try:
        out = codec.decode(w.codes, w.scales, w.shape,
                           np.dtype(w.dtype), w.block)
    finally:
        if tok is not None:
            _trace.end(tok)
    _stats.account_dequant()
    return out


def maybe_decode(payload: Any) -> Any:
    """Transparent receive-side hook: decode wire payloads, pass
    everything else through."""
    if isinstance(payload, CompressedWire):
        return decode(payload)
    return payload
