"""Compression observability — pvars, trace spans, hooks events.

Built in from day one (the PR-2 lesson: a subsystem without its own
counters gets diagnosed with hand-inserted timers):

- pvars: ``compress_bytes_in`` (payload bytes entering quantization,
  wire-equivalent), ``compress_bytes_out`` (bytes after quantization:
  codes + scales), ``compress_ratio`` (out/in, 1.0 before any
  traffic), and the ``compress_max_abs_error`` high-watermark (largest
  measured |x - dequant(quant(x))| — fed by the host/per-rank codec
  path and by bench/test verification passes; the fused device path's
  error rides inside the compiled program by design and is verified
  out-of-band, see docs/COMPRESSION.md).
- trace spans: ``compress.quant`` / ``compress.dequant`` in the hooks
  event namespace, so ``tools/tracedump`` and the PR-2 attribution
  reports see compression time natively.
"""
from __future__ import annotations

import threading
from typing import Dict

from ompi_tpu.mca import pvar as _pvar
from ompi_tpu.utils import hooks as _hooks

EV_QUANT = "compress.quant"
EV_DEQUANT = "compress.dequant"

_lock = threading.Lock()
_counters: Dict[str, float] = {
    "bytes_in": 0, "bytes_out": 0, "quant_calls": 0, "dequant_calls": 0,
    "max_abs_error": 0.0,
}


def account(bytes_in: int, bytes_out: int, quant_calls: int = 1) -> None:
    """Record one compression event: ``bytes_in`` wire-equivalent
    payload bytes replaced by ``bytes_out`` compressed bytes."""
    with _lock:
        _counters["bytes_in"] += int(bytes_in)
        _counters["bytes_out"] += int(bytes_out)
        _counters["quant_calls"] += int(quant_calls)


def account_dequant(calls: int = 1) -> None:
    with _lock:
        _counters["dequant_calls"] += int(calls)


def note_error(err: float) -> None:
    """Feed the max-abs-error watermark (measured round-trip error)."""
    err = float(err)
    if err != err:                       # NaN: poisoned block, not a
        return                           # quantization error magnitude
    with _lock:
        if err > _counters["max_abs_error"]:
            _counters["max_abs_error"] = err


def snapshot() -> Dict[str, float]:
    with _lock:
        return dict(_counters)


def ratio() -> float:
    with _lock:
        if not _counters["bytes_in"]:
            return 1.0
        return _counters["bytes_out"] / _counters["bytes_in"]


def reset() -> None:
    with _lock:
        for k in _counters:
            _counters[k] = 0.0 if k == "max_abs_error" else 0


def _register() -> None:
    _pvar.pvar_register(
        "compress_bytes_in", lambda: snapshot()["bytes_in"],
        unit="bytes",
        help="Payload bytes that entered collective quantization "
             "(wire-equivalent; docs/COMPRESSION.md)")
    _pvar.pvar_register(
        "compress_bytes_out", lambda: snapshot()["bytes_out"],
        unit="bytes",
        help="Bytes after quantization (codes + per-block scales) — "
             "what actually moves on the wire")
    _pvar.pvar_register(
        "compress_ratio", ratio, unit="ratio", var_class="level",
        help="compress_bytes_out / compress_bytes_in (1.0 before any "
             "compressed traffic)")
    _pvar.pvar_register(
        "compress_max_abs_error", lambda: snapshot()["max_abs_error"],
        unit="value", var_class="highwatermark",
        help="Largest measured per-element |x - dequant(quant(x))| "
             "(host codec path + verification passes)")
    # the span names are MPI_T event types too: tools can bind handlers
    _hooks.declare_event(EV_QUANT)
    _hooks.declare_event(EV_DEQUANT)


_register()
