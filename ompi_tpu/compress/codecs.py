"""Block-scaled quantization codecs — the EQuARX kernel layer.

A codec maps a float payload to (codes, scales): ``codes`` is the
1-byte-per-element wire representation, ``scales`` one float32 per
block of ``block`` elements (the max-abs of the block divided by the
code range), so dequantization is a single fused multiply. Two real
codecs plus the null codec:

- ``int8_block``: symmetric round-to-nearest int8; per-element error
  is bounded by ``scale / 2 = block_maxabs / 254``.
- ``fp8_block``: scale-to-448 then cast to float8_e4m3fn (3 mantissa
  bits); per-element error bounded by ``block_maxabs / 16`` (worst
  relative error 2^-4 on the largest element), much tighter for small
  elements — the trade EQuARX §4 describes (uniform vs logarithmic
  code spacing).
- ``null``: identity (codes are the raw bytes; for wiring tests and
  as the fallback the registry hands out for unknown names).

Non-finite policy (tested): a block containing any inf/nan gets a
non-finite scale, so the whole block dequantizes to NaN — quantization
*poisons the block* rather than silently laundering an overflow into a
finite value. MPI reduction semantics already propagate NaN through
sums, so a poisoned block behaves like the uncompressed path at block
granularity.

Both a NumPy implementation (the host/per-rank wire path — pml staging)
and a jittable jnp implementation (composed into the XLA ring/hier
schedules by coll/compressed) are provided; the property tests assert
the two round-trip within the same bound.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

try:                                     # fp8 needs ml_dtypes (jax dep)
    from ml_dtypes import float8_e4m3fn as _f8
except ImportError:                      # pragma: no cover
    _f8 = None

DEFAULT_BLOCK = 256

_INT8_RANGE = 127.0
_F8_RANGE = 448.0                        # e4m3fn max finite


def _pad_blocks(flat: np.ndarray, block: int) -> Tuple[np.ndarray, int]:
    nb = -(-flat.size // block) if flat.size else 1
    pad = nb * block - flat.size
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    return flat.reshape(nb, block), pad


class Codec:
    """Base: name, wire cost model, numpy encode/decode, jnp kernels."""

    name = "base"
    code_bytes = 1                       # wire bytes per element

    def wire_bytes(self, nelems: int, block: int) -> int:
        """Wire bytes for ``nelems`` payload elements (codes + scales)."""
        nb = -(-nelems // block) if nelems else 1
        return nelems * self.code_bytes + nb * 4

    # -- numpy (host / per-rank wire path) -----------------------------
    def encode(self, arr: np.ndarray, block: int = DEFAULT_BLOCK
               ) -> Tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError

    def decode(self, codes: np.ndarray, scales: np.ndarray,
               shape: Tuple[int, ...], dtype: Any,
               block: int = DEFAULT_BLOCK) -> np.ndarray:
        raise NotImplementedError

    # -- jnp (device path; shapes static at trace time) ----------------
    def jnp_quant(self, x, block: int):
        raise NotImplementedError

    def jnp_dequant(self, codes, scales, total: int, dtype, block: int):
        raise NotImplementedError

    def error_bound(self, block_maxabs):
        """Per-element absolute error bound given the block max-abs."""
        raise NotImplementedError


class NullCodec(Codec):
    """Identity codec: full-width wire, zero error. Exists so the
    compressed schedules can be exercised (and A/B'd) with compression
    arithmetic removed from the comparison."""

    name = "null"

    def wire_bytes(self, nelems: int, block: int) -> int:
        return nelems * 4                # payload travels full width

    def encode(self, arr, block=DEFAULT_BLOCK):
        flat = np.ascontiguousarray(arr).reshape(-1)
        return flat.copy(), np.ones(1, np.float32)

    def decode(self, codes, scales, shape, dtype, block=DEFAULT_BLOCK):
        return np.asarray(codes, dtype=dtype).reshape(shape)

    def jnp_quant(self, x, block):
        import jax.numpy as jnp
        return jnp.asarray(x), jnp.ones((1,), jnp.float32)

    def jnp_dequant(self, codes, scales, total, dtype, block):
        import jax.numpy as jnp
        return jnp.asarray(codes, dtype)[:total]

    def error_bound(self, block_maxabs):
        return np.zeros_like(np.asarray(block_maxabs, np.float64))


class Int8BlockCodec(Codec):
    """Symmetric per-block int8: scale = maxabs/127, codes = rint(x/s)."""

    name = "int8_block"

    def encode(self, arr, block=DEFAULT_BLOCK):
        # pass-lean hot path (the wire layer calls this on multi-MB
        # payloads): no-copy f32 view when possible, one abs/max pass,
        # one fused multiply into a reusable temp, in-place rint, one
        # int8 store — the naive astype/where chain cost 4x
        flat = np.ascontiguousarray(arr, dtype=np.float32).reshape(-1)
        blocks, _pad = _pad_blocks(flat, block)
        maxabs = np.abs(blocks).max(axis=1)
        scales = np.maximum(maxabs, 1e-30) * np.float32(1 / _INT8_RANGE)
        # non-finite blocks: scale -> NaN poisons the whole block on
        # dequant (the documented policy); the codes' values there are
        # irrelevant, so the payload-wide sanitize pass only runs when
        # some block actually held inf/nan (the finite check is on the
        # tiny per-block scale vector, not the payload)
        finite = np.isfinite(maxabs)
        all_finite = bool(finite.all())
        if not all_finite:
            scales[~finite] = np.nan
        scales = scales.astype(np.float32, copy=False)
        with np.errstate(invalid="ignore", over="ignore"):
            tmp = blocks * (np.float32(1.0) / scales)[:, None]
            np.rint(tmp, out=tmp)
            if not all_finite:
                np.nan_to_num(tmp, copy=False, nan=0.0,
                              posinf=_INT8_RANGE, neginf=-_INT8_RANGE)
            codes = tmp.astype(np.int8)
        return codes.reshape(-1), scales

    def decode(self, codes, scales, shape, dtype, block=DEFAULT_BLOCK):
        scales = np.asarray(scales, np.float32)
        out = codes.astype(np.float32).reshape(len(scales), block)
        out *= scales[:, None]
        total = int(np.prod(shape)) if shape else 1
        out = out.reshape(-1)[:total].reshape(shape)
        return out.astype(dtype, copy=False)

    def jnp_quant(self, x, block):
        import jax.numpy as jnp
        flat = x.reshape(-1).astype(jnp.float32)
        nb = -(-flat.shape[0] // block) if flat.shape[0] else 1
        flat = jnp.pad(flat, (0, nb * block - flat.shape[0]))
        blocks = flat.reshape(nb, block)
        maxabs = jnp.max(jnp.abs(blocks), axis=1)
        scales = jnp.where(jnp.isfinite(maxabs),
                           jnp.maximum(maxabs, 1e-30) / _INT8_RANGE,
                           jnp.nan).astype(jnp.float32)
        codes = jnp.rint(blocks / scales[:, None]).astype(jnp.int8)
        return codes.reshape(-1), scales

    def jnp_dequant(self, codes, scales, total, dtype, block):
        import jax.numpy as jnp
        blocks = codes.astype(jnp.float32).reshape(scales.shape[0], block)
        out = blocks * scales[:, None]
        return out.reshape(-1)[:total].astype(dtype)

    def error_bound(self, block_maxabs):
        m = np.asarray(block_maxabs, np.float64)
        # rint is within 0.5 code; the 1e-30 floor adds nothing at
        # these magnitudes but keeps the all-zero block exact
        return m / (2.0 * _INT8_RANGE) + 1e-30


class Fp8BlockCodec(Codec):
    """Per-block scale-to-448 + e4m3 cast: logarithmic code spacing."""

    name = "fp8_block"

    def encode(self, arr, block=DEFAULT_BLOCK):
        if _f8 is None:                  # pragma: no cover
            raise RuntimeError("fp8_block codec needs ml_dtypes")
        flat = np.ascontiguousarray(arr, dtype=np.float32).reshape(-1)
        blocks, _pad = _pad_blocks(flat, block)
        maxabs = np.abs(blocks).max(axis=1)
        scales = np.maximum(maxabs, 1e-30) * np.float32(1 / _F8_RANGE)
        finite = np.isfinite(maxabs)
        all_finite = bool(finite.all())
        if not all_finite:
            scales[~finite] = np.nan
        scales = scales.astype(np.float32, copy=False)
        with np.errstate(invalid="ignore", over="ignore"):
            scaled = blocks * (np.float32(1.0) / scales)[:, None]
            if not all_finite:
                np.nan_to_num(scaled, copy=False, nan=0.0,
                              posinf=_F8_RANGE, neginf=-_F8_RANGE)
            codes = scaled.astype(_f8)
        # int8 view for the wire: a raw byte payload transports
        # identically whatever the receiving numpy knows about fp8
        return codes.reshape(-1).view(np.int8), scales

    def decode(self, codes, scales, shape, dtype, block=DEFAULT_BLOCK):
        if _f8 is None:                  # pragma: no cover
            raise RuntimeError("fp8_block codec needs ml_dtypes")
        scales = np.asarray(scales, np.float32)
        out = np.asarray(codes, np.int8).view(_f8) \
            .astype(np.float32).reshape(len(scales), block)
        out *= scales[:, None]
        total = int(np.prod(shape)) if shape else 1
        out = out.reshape(-1)[:total].reshape(shape)
        return out.astype(dtype, copy=False)

    def jnp_quant(self, x, block):
        import jax
        import jax.numpy as jnp
        flat = x.reshape(-1).astype(jnp.float32)
        nb = -(-flat.shape[0] // block) if flat.shape[0] else 1
        flat = jnp.pad(flat, (0, nb * block - flat.shape[0]))
        blocks = flat.reshape(nb, block)
        maxabs = jnp.max(jnp.abs(blocks), axis=1)
        scales = jnp.where(jnp.isfinite(maxabs),
                           jnp.maximum(maxabs, 1e-30) / _F8_RANGE,
                           jnp.nan).astype(jnp.float32)
        codes = (blocks / scales[:, None]).astype(jnp.float8_e4m3fn)
        # bitcast to int8 so every collective primitive (ppermute,
        # all_gather, all_to_all) moves a plain byte payload
        wire = jax.lax.bitcast_convert_type(codes, jnp.int8)
        return wire.reshape(-1), scales

    def jnp_dequant(self, codes, scales, total, dtype, block):
        import jax
        import jax.numpy as jnp
        f8 = jax.lax.bitcast_convert_type(
            codes.reshape(scales.shape[0], block), jnp.float8_e4m3fn)
        out = f8.astype(jnp.float32) * scales[:, None]
        return out.reshape(-1)[:total].astype(dtype)

    def error_bound(self, block_maxabs):
        # worst relative error 2^-4 lands on the largest element:
        # 448 * 2^-4 * scale = maxabs / 16 (plus the same zero floor)
        return np.asarray(block_maxabs, np.float64) / 16.0 + 1e-30


_REGISTRY: Dict[str, Codec] = {
    "null": NullCodec(),
    "int8_block": Int8BlockCodec(),
}
if _f8 is not None:
    _REGISTRY["fp8_block"] = Fp8BlockCodec()


def get_codec(name: str) -> Codec:
    """Codec by name; unknown names get the null codec (a typo'd MCA
    var must not corrupt data — it just stops compressing)."""
    return _REGISTRY.get(name, _REGISTRY["null"])


def codec_names():
    return sorted(_REGISTRY)
