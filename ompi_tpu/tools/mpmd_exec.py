"""MPMD dispatch shim for MPI_Comm_spawn_multiple.

`mpirun --per-rank` launches one executable for every rank; the
reference's spawn_multiple builds a single child world out of
DIFFERENT binaries (dpm_dyn_init / comm_spawn_multiple.c.in). This
shim closes that gap: the spawn root writes a JSON spec
``[{command, argv, maxprocs}, ...]`` and launches ``python -m
ompi_tpu.tools.mpmd_exec spec.json`` for the whole world; each
process looks up its rank (``OMPI_TPU_MCA_mpi_base_process_id``,
set by mpirun) and execs the entry owning that rank slice — env
intact, so the child's MPI_Init still dials the parent port
(OMPI_TPU_PARENT_PORT) and the usual coordination plane.
"""
from __future__ import annotations

import json
import os
import sys


def main() -> None:
    if len(sys.argv) != 2:
        sys.stderr.write("usage: mpmd_exec spec.json\n")
        sys.exit(2)
    with open(sys.argv[1]) as f:
        spec = json.load(f)
    r = int(os.environ.get("OMPI_TPU_MCA_mpi_base_process_id", "0"))
    for ent in spec:
        n = int(ent["maxprocs"])
        if r < n:
            cmd = ent["command"]
            os.execv(cmd, [cmd] + list(ent.get("argv", [])))
        r -= n
    sys.stderr.write(f"mpmd_exec: rank beyond spec total\n")
    sys.exit(3)


if __name__ == "__main__":
    main()
