"""tracedump — merge per-rank trace dumps and render them.

The mpirun-style companion to ``ompi_tpu.trace``: each rank persists
its span ring with ``trace.dump(path, offset_s=...)`` (offset measured
against rank 0 by ``tools/mpisync``); this tool merges the dumps onto
one timebase and emits either a Perfetto-loadable JSON
(``--format perfetto``, open at https://ui.perfetto.dev), the
late-arrival attribution report (``--format report``), or the compact
summary (``--format summary``; includes per-rank ``compress.quant`` /
``compress.dequant`` time aggregation when compressed collectives ran
— docs/COMPRESSION.md — and per-rank ``ft.*`` suspicion/declaration
aggregation when the resilience plane saw action —
docs/RESILIENCE.md).

Without input files it renders the CURRENT process's ring — the
in-process escape hatch (call ``ompi_tpu.tools.tracedump.main([...])``
at the end of a traced program, or rely on ``bench.py --trace``).

Usage::

    python -m ompi_tpu.tools.tracedump [-o OUT] \
        [--format perfetto|report|summary] [DUMP.json ...]
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from ompi_tpu import trace
from ompi_tpu.trace import attribution, perfetto


def _gather(files: List[str]) -> tuple:
    """(spans, rank_offsets, live, witness_reports) merged from dump
    files, or the live ring (live=True). Lock-witness dumps
    (``lockwitness.dump()`` files, recognized by their ``lockwitness``
    key) ride the same file list and are split out for the summary's
    merged-graph section."""
    if not files:
        return trace.span_dicts(), {}, True, []
    spans: List[Dict[str, Any]] = []
    offsets: Dict[int, float] = {}
    witness: List[Dict[str, Any]] = []
    for path in files:
        with open(path) as f:
            d = json.load(f)
        if isinstance(d, dict) and "lockwitness" in d:
            witness.append(d)
            continue
        if not isinstance(d, dict) or "spans" not in d:
            raise ValueError(f"not a trace dump: {path}")
        rank = int(d.get("rank", -1))
        off = float(d.get("offset_s", 0.0))
        for s in d["spans"]:
            # a dump written before the world knew its rank (-1) keeps
            # per-span ranks; otherwise the file's rank is authoritative
            if rank >= 0 and int(s.get("rank", -1)) < 0:
                s = dict(s, rank=rank)
            spans.append(s)
        if rank >= 0:
            offsets[rank] = off
    return spans, offsets, False, witness


def render(spans, offsets, fmt: str, live: bool = False,
           witness: Optional[List[Dict[str, Any]]] = None
           ) -> Dict[str, Any]:
    if fmt == "perfetto":
        return perfetto.export(spans, offsets)
    if fmt == "report":
        return {"late_arrival": attribution.late_arrival(spans, offsets),
                "skew_watermarks": attribution.skew_watermarks()}
    # file mode: span/drop totals come from the dumps themselves, not
    # this (tool) process's empty live ring
    out = attribution.summarize(spans,
                                trace.stats() if live else None)
    if witness:
        # per-rank lockwitness dumps merged into one graph, cycle
        # detection re-run on the union (docs/ANALYSIS.md)
        from ompi_tpu.analyze import lockwitness as _lockwitness
        out["lockwitness"] = _lockwitness.merge_reports(witness)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ompi_tpu.tools.tracedump",
        description="Merge per-rank trace dumps; emit Perfetto JSON, "
                    "a late-arrival report, or a summary.")
    ap.add_argument("files", nargs="*",
                    help="trace dump files written by trace.dump(); "
                         "empty = this process's live ring")
    ap.add_argument("--format", "-f", default="perfetto",
                    choices=("perfetto", "report", "summary"))
    ap.add_argument("--out", "-o", default="-",
                    help="output path (default: stdout)")
    args = ap.parse_args(argv)

    spans, offsets, live, witness = _gather(args.files)
    obj = render(spans, offsets, args.format, live, witness)
    text = json.dumps(obj, indent=None if args.format == "perfetto"
                      else 1)
    if args.out == "-":
        sys.stdout.write(text + "\n")
    else:
        with open(args.out, "w") as f:
            f.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
