"""tracedump — merge per-rank trace dumps and render them.

The mpirun-style companion to ``ompi_tpu.trace``: each rank persists
its span ring with ``trace.dump(path, offset_s=...)`` (offset measured
against rank 0 by ``tools/mpisync``); this tool merges the dumps onto
one timebase and emits either a Perfetto-loadable JSON
(``--format perfetto``, open at https://ui.perfetto.dev), the
late-arrival attribution report (``--format report``), the compact
summary (``--format summary``; includes per-rank ``compress.quant`` /
``compress.dequant`` time aggregation when compressed collectives ran
— docs/COMPRESSION.md — per-rank ``ft.*`` suspicion/declaration
aggregation when the resilience plane saw action —
docs/RESILIENCE.md — and per-origin ``osc.*`` op/byte/epoch
aggregation when the one-sided plane ran — docs/RMA.md), or the
flight-recorder incident report
(``--format flightrec``: merges ``flightrec_<rank>.json`` snapshots
written by the telemetry plane's fault flight recorder and names the
critical rank — docs/OBSERVABILITY.md).

Unreadable or truncated dump files are SKIPPED with a warning naming
the file (a rank killed mid-write must not cost the merge the other
ranks' evidence); the summary carries a ``skipped`` count and
``--strict`` turns any skip into a nonzero exit for CI.

Without input files it renders the CURRENT process's ring — the
in-process escape hatch (call ``ompi_tpu.tools.tracedump.main([...])``
at the end of a traced program, or rely on ``bench.py --trace``).

Usage::

    python -m ompi_tpu.tools.tracedump [-o OUT] [--strict] \
        [--format perfetto|report|summary|flightrec] [DUMP.json ...]
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from ompi_tpu import trace
from ompi_tpu.trace import attribution, perfetto


def _gather(files: List[str]) -> tuple:
    """(spans, rank_offsets, live, witness_reports, flightrecs,
    skipped) merged from dump files, or the live ring (live=True).
    Lock-witness dumps (``lockwitness.dump()`` files, recognized by
    their ``lockwitness`` key) and flight-recorder snapshots
    (``flightrec`` key) ride the same file list and are split out.
    Files that don't parse or aren't any known dump shape are skipped
    and reported in ``skipped`` — never raised past the merge."""
    if not files:
        return trace.span_dicts(), {}, True, [], [], []
    spans: List[Dict[str, Any]] = []
    offsets: Dict[int, float] = {}
    witness: List[Dict[str, Any]] = []
    flightrecs: List[Dict[str, Any]] = []
    skipped: List[Dict[str, str]] = []
    for path in files:
        try:
            with open(path) as f:
                d = json.load(f)
            if isinstance(d, dict) and "lockwitness" in d:
                witness.append(d)
                continue
            if isinstance(d, dict) and "flightrec" in d:
                flightrecs.append(d)
                continue
            if not isinstance(d, dict) or "spans" not in d:
                raise ValueError("not a trace dump")
        except (OSError, json.JSONDecodeError, ValueError,
                UnicodeDecodeError) as e:
            skipped.append({"file": path, "error": str(e)})
            print(f"tracedump: warning: skipped {path}: {e}",
                  file=sys.stderr)
            continue
        rank = int(d.get("rank", -1))
        off = float(d.get("offset_s", 0.0))
        for s in d["spans"]:
            # a dump written before the world knew its rank (-1) keeps
            # per-span ranks; otherwise the file's rank is authoritative
            if rank >= 0 and int(s.get("rank", -1)) < 0:
                s = dict(s, rank=rank)
            spans.append(s)
        if rank >= 0:
            offsets[rank] = off
    return spans, offsets, False, witness, flightrecs, skipped


def render(spans, offsets, fmt: str, live: bool = False,
           witness: Optional[List[Dict[str, Any]]] = None,
           flightrecs: Optional[List[Dict[str, Any]]] = None,
           skipped: Optional[List[Dict[str, str]]] = None
           ) -> Dict[str, Any]:
    if fmt == "perfetto":
        return perfetto.export(spans, offsets)
    if fmt == "report":
        return {"late_arrival": attribution.late_arrival(spans, offsets),
                "skew_watermarks": attribution.skew_watermarks()}
    if fmt == "flightrec":
        from ompi_tpu.telemetry import flightrec as _flightrec
        out = _flightrec.merge(flightrecs or [])
        if skipped:
            out["skipped"] = len(skipped)
            out["skipped_files"] = skipped
        return out
    # file mode: span/drop totals come from the dumps themselves, not
    # this (tool) process's empty live ring
    out = attribution.summarize(spans,
                                trace.stats() if live else None)
    if witness:
        # per-rank lockwitness dumps merged into one graph, cycle
        # detection re-run on the union (docs/ANALYSIS.md)
        from ompi_tpu.analyze import lockwitness as _lockwitness
        out["lockwitness"] = _lockwitness.merge_reports(witness)
    if flightrecs:
        from ompi_tpu.telemetry import flightrec as _fr
        out["flightrec"] = _fr.merge(flightrecs)
    if skipped:
        out["skipped"] = len(skipped)
        out["skipped_files"] = skipped
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ompi_tpu.tools.tracedump",
        description="Merge per-rank trace dumps; emit Perfetto JSON, "
                    "a late-arrival report, a summary, or a "
                    "flight-recorder incident report.")
    ap.add_argument("files", nargs="*",
                    help="trace dump files written by trace.dump(); "
                         "empty = this process's live ring")
    ap.add_argument("--format", "-f", default="perfetto",
                    choices=("perfetto", "report", "summary",
                             "flightrec"))
    ap.add_argument("--out", "-o", default="-",
                    help="output path (default: stdout)")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero when any input file was "
                         "skipped as unreadable/truncated")
    args = ap.parse_args(argv)

    spans, offsets, live, witness, flightrecs, skipped = \
        _gather(args.files)
    obj = render(spans, offsets, args.format, live, witness,
                 flightrecs, skipped)
    text = json.dumps(obj, indent=None if args.format == "perfetto"
                      else 1)
    if args.out == "-":
        sys.stdout.write(text + "\n")
    else:
        with open(args.out, "w") as f:
            f.write(text)
    if skipped:
        print(f"tracedump: warning: {len(skipped)} file(s) skipped",
              file=sys.stderr)
        if args.strict:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
