"""Debugger interface — mirrors ``ompi/debuggers`` (MPIR + DLL).

Reference behavior: the MPIR specification — a debugger attaches to the
launcher, reads ``MPIR_proctable`` (one {host, executable, pid} entry
per rank) once ``MPIR_Breakpoint`` fires, and sets
``MPIR_being_debugged`` so the MPI library cooperates (holds ranks in
init until released). The message-queue DLL (``ompi_msgq_dll.c``) lets
the debugger walk posted/unexpected queues.

TPU-native re-design: ranks are mesh coordinates inside one controller
process, so the proctable maps rank -> (host, pid, device); the
"message queue dump" walks the live matching engines — the same
introspection the DLL provides, without the ptrace indirection.
"""
from __future__ import annotations

import os
import socket
import sys
from typing import Any, Callable, Dict, List, Optional

MPIR_being_debugged = False

_breakpoint_hooks: List[Callable[[], None]] = []


def proctable(comm) -> List[Dict[str, Any]]:
    """MPIR_proctable: one entry per rank."""
    host = socket.gethostname()
    exe = sys.argv[0] or "<python>"
    pid = os.getpid()
    return [{
        "rank": r,
        "host_name": host,
        "executable_name": exe,
        "pid": pid,
        "device": f"{d.platform}:{d.id}",
    } for r, d in enumerate(comm.devices)]


def set_being_debugged(flag: bool) -> None:
    global MPIR_being_debugged
    MPIR_being_debugged = flag


def on_breakpoint(fn: Callable[[], None]) -> None:
    """Debugger-side hook run when MPIR_Breakpoint fires."""
    _breakpoint_hooks.append(fn)


def MPIR_Breakpoint() -> None:
    """The rendezvous point: the launcher calls this once the job is
    wired up; an attached debugger's hooks run here."""
    for fn in list(_breakpoint_hooks):
        fn()


def message_queues(comm, *, dst: Optional[int] = None
                   ) -> Dict[str, List[Dict[str, Any]]]:
    """The message-queue DLL role: posted receives and unexpected
    messages of ``comm``'s matching engine, as the debugger would
    display them."""
    eng = comm._pml
    posted, unexpected = [], []
    if getattr(eng, "_lib", None) is not None:
        # native queues: surface the Python-side payload registries
        for rh, req in getattr(eng, "_reqs", {}).items():
            posted.append({"handle": rh,
                           "dest": getattr(req, "dest", -1),
                           "source": req.status.source,
                           "tag": req.status.tag})
        for mh, msg in getattr(eng, "_msgs", {}).items():
            unexpected.append({"handle": mh, "src": msg.src,
                               "dest": msg.dest, "tag": msg.tag})
    else:
        for pr in eng.posted:
            posted.append({"dest": pr.dest, "source": pr.src,
                           "tag": pr.tag})
        for (d, s), q in eng.unexpected.items():
            for msg in q:
                unexpected.append({"src": s, "dest": d, "tag": msg.tag})
    if dst is not None:
        posted = [p for p in posted if p.get("dest") == dst]
        unexpected = [u for u in unexpected if u["dest"] == dst]
    return {"posted": posted, "unexpected": unexpected}
