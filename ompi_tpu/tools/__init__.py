"""Tools (mirrors ``ompi/tools``): info (ompi_info), mpirun."""
