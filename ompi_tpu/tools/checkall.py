"""checkall — the single CI gate: checkparity + mpilint + MCAVARS
freshness in one run.

``python -m ompi_tpu.tools.checkall`` folds the three static contracts
every PR must hold into one exit status:

1. **checkparity** (rules 1-6): parity-test pairing for lossy/fused/
   pipelined/FT paths, slow-marker hygiene, and a fixture pair per
   analyzer rule.
2. **mpilint**: zero non-baselined findings and zero stale baseline
   entries over the whole ``ompi_tpu/`` tree (analyze/baseline.json).
3. **MCAVARS freshness**: the committed ``docs/MCAVARS.md`` matches
   what the current tree's ``var_register`` sites generate.

Prints a JSON report; exit 1 on any violation. The same three checks
run in-process in tier-1 (tests/test_lint_clean.py,
tests/test_compress_tools.py), so CI cannot drift from the local gate.
"""
from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional

from ompi_tpu.analyze import mpilint as _mpilint
from ompi_tpu.tools import checkparity as _checkparity

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def mcavars_fresh(doc_path: Optional[str] = None) -> Dict[str, Any]:
    """Is the committed docs/MCAVARS.md what the tree generates?"""
    doc_path = doc_path or os.path.join(_REPO, "docs", "MCAVARS.md")
    want = _mpilint.render_mcavars()
    try:
        with open(doc_path, encoding="utf-8") as f:
            have = f.read()
    except OSError:
        have = ""
    return {"ok": have == want, "path": doc_path,
            "hint": ("" if have == want else
                     "regenerate: python -m ompi_tpu.tools.mpilint "
                     "--emit-mcavars docs/MCAVARS.md")}


def run_all(tests_dir: Optional[str] = None) -> Dict[str, Any]:
    parity = _checkparity.audit(tests_dir)
    lint = _mpilint.run_lint()
    lint_slim = {k: v for k, v in lint.items() if k != "var_registry"}
    mcavars = mcavars_fresh()
    return {"ok": bool(parity["ok"] and lint["ok"] and mcavars["ok"]),
            "checkparity": parity,
            "mpilint": lint_slim,
            "mcavars": mcavars}


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m ompi_tpu.tools.checkall",
        description="checkparity + mpilint + MCAVARS freshness — the "
                    "one-shot CI gate (docs/ANALYSIS.md).")
    ap.add_argument("--tests", default=None,
                    help="tests directory (default: <repo>/tests)")
    args = ap.parse_args(argv)
    report = run_all(args.tests)
    print(json.dumps(report, indent=1))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
