"""PERUSE instrumentation — mirrors ``ompi/peruse/peruse.c``.

Reference behavior: the PERUSE spec's event model — a tool initializes,
queries supported events by name (``PERUSE_COMM_REQ_ACTIVATE``,
``PERUSE_COMM_MSG_ARRIVED``, ...), creates per-communicator event
handles bound to callbacks, and starts/stops them; the pml fires the
events at request state transitions.

TPU-native re-design: events ride the same hook chain as the PMPI/MPI_T
instrumentation (``utils/hooks``) — PERUSE event names are mapped onto
the framework's entry events, handles filter by communicator, and
start/stop is handle state (exactly the reference's event-handle life
cycle, ``peruse.c`` event table).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ompi_tpu.utils import hooks

PERUSE_SUCCESS = 0
PERUSE_ERR_EVENT = -1
PERUSE_ERR_COMM = -2

# PERUSE event name -> framework hook event(s)
_EVENT_MAP: Dict[str, List[str]] = {
    "PERUSE_COMM_REQ_ACTIVATE": ["pml_send", "pml_recv"],
    "PERUSE_COMM_REQ_XFER_BEGIN": ["pml_send"],
    "PERUSE_COMM_REQ_XFER_END": ["pml_recv"],
    "PERUSE_COMM_MSG_ARRIVED": ["pml_recv"],
    "PERUSE_COMM_SEARCH_POSTED_Q_BEGIN": ["pml_recv"],
    "PERUSE_COMM_COLL_BEGIN": [f"coll_{c}" for c in (
        "allreduce", "reduce", "bcast", "allgather", "gather", "scatter",
        "alltoall", "barrier")],
}

_initialized = False


def Init() -> int:
    global _initialized
    _initialized = True
    return PERUSE_SUCCESS


def Query_supported_events() -> List[str]:
    return list(_EVENT_MAP)


def Query_event(name: str) -> bool:
    return name in _EVENT_MAP


class EventHandle:
    """A per-communicator event subscription (PERUSE event handle)."""

    def __init__(self, comm, event: str,
                 callback: Callable[[str, Any, dict], None]):
        self.comm = comm
        self.event = event
        self.callback = callback
        self.active = False
        self.fired = 0
        self._hook = None

    def start(self) -> int:
        if self._hook is None:
            targets = set(_EVENT_MAP[self.event])

            def hook(ev, comm, info, _self=self, _targets=targets):
                if _self.active and ev in _targets \
                        and comm is _self.comm:
                    _self.fired += 1
                    _self.callback(_self.event, comm, info)
            self._hook = hooks.register_profiler(hook)
        self.active = True
        return PERUSE_SUCCESS

    def stop(self) -> int:
        self.active = False
        return PERUSE_SUCCESS

    def free(self) -> int:
        self.stop()
        if self._hook is not None:
            hooks.unregister_profiler(self._hook)
            self._hook = None
        return PERUSE_SUCCESS


def Event_comm_register(event: str, comm,
                        callback: Callable[[str, Any, dict], None]
                        ) -> Optional[EventHandle]:
    """PERUSE_Event_comm_register: returns a handle or None for an
    unsupported event."""
    if event not in _EVENT_MAP:
        return None
    return EventHandle(comm, event, callback)
