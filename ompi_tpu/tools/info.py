"""``ompi_info`` equivalent: dump version, devices, components,
MCA vars, pvars. Run as ``python -m ompi_tpu.tools.info [-a]``."""
from __future__ import annotations

import argparse
import json


def collect(all_vars: bool = False) -> dict:
    import jax
    import ompi_tpu as MPI
    from ompi_tpu.api import tool
    from ompi_tpu.coll.framework import _ensure_components, coll_framework
    from ompi_tpu.accelerator.framework import accel_framework
    from ompi_tpu.native import native_available

    _ensure_components()
    coll_framework.open()
    accel_framework.open()

    out = {
        "library": MPI.Get_library_version(),
        "mpi_standard": ".".join(map(str, MPI.Get_version())),
        "platform": [f"{d.platform}:{d.id}" for d in jax.devices()],
        "native_convertor": native_available(),
        "frameworks": {
            "coll": sorted(coll_framework.components),
            "accelerator": sorted(accel_framework.components),
            "pml": ["stacked"],
            "osc": ["xla_window"],
            "topo": ["cart", "graph", "dist_graph"],
        },
    }
    if all_vars:
        out["mca_vars"] = tool.cvar_list()
        out["pvars"] = tool.pvar_list()
    return out


def main() -> None:
    ap = argparse.ArgumentParser(prog="ompi_tpu_info")
    ap.add_argument("-a", "--all", action="store_true",
                    help="include every MCA var and pvar")
    ap.add_argument("--json", action="store_true", help="JSON output")
    args = ap.parse_args()
    data = collect(all_vars=args.all)
    if args.json:
        print(json.dumps(data, indent=2, default=str))
        return
    print(data["library"])
    print(f"MPI standard: {data['mpi_standard']}")
    print(f"Devices: {', '.join(data['platform'])}")
    print(f"Native convertor: {data['native_convertor']}")
    for fw, comps in data["frameworks"].items():
        print(f"MCA {fw}: {', '.join(comps)}")
    if args.all:
        for v in data["mca_vars"]:
            print(f"  cvar {v['name']} = {v['value']!r} "
                  f"(source: {v['source']}) {v['help']}")
        for p in data["pvars"]:
            print(f"  pvar {p['name']} = {p['value']} [{p['class']}]")


if __name__ == "__main__":
    main()
