"""mpitop — the fleet's `top` for an ompi_tpu job.

Merges per-rank telemetry snapshots (``telemetry.dump()`` files,
``telemetry_<rank>.json`` by convention) into one table: per rank —
collective p50/p99, pml send/recv p99, operation and byte throughput,
and the straggler score its PEERS assign it (health-monitor scores are
accusations: rank 0's snapshot scores rank 1, so a rank's column is
the worst accusation against it). ``--per-comm`` expands rows to
(rank, comm) using the histogram labels. When the one-sided plane ran,
an ``osc`` section follows the table: per-origin put/get/accumulate
counts and bytes, the ``tele_osc_*`` p99s, epoch-boundary counts, and
RMA_SYNC / torn-epoch flags (docs/RMA.md).

The ``slow_rank`` election mirrors the flight recorder's: the most
straggler-declared/accused rank wins; with no accusations, the rank
with the worst OWN-latency p99 — max(coll p99, send p99); recv waits
are deliberately excluded (blocked-waiting is the victim's symptom,
not the straggler's — the attribution layer's blocked vs in-op split).

Curses-free by design: single-shot prints one table; ``--watch N``
re-reads the files every N seconds and reprints (throughput columns
become deltas/s between reads). ``--format json`` emits the merged
machine-readable form; ``--format prom`` emits one Prometheus
exposition for ALL ranks (telemetry/prom over the merged rows).

Usage::

    python -m ompi_tpu.tools.mpitop [--watch N] [--per-comm] \
        [--format table|json|prom] telemetry_*.json
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from ompi_tpu.telemetry.hist import merge_snapshots


def load_snapshots(files: List[str]) -> Tuple[List[Dict[str, Any]],
                                              List[Dict[str, str]]]:
    """Parse telemetry.dump() files; unreadable/truncated ones are
    skipped with a warning (tracedump's contract — one dead rank must
    not cost the table the others)."""
    snaps: List[Dict[str, Any]] = []
    skipped: List[Dict[str, str]] = []
    for path in files:
        try:
            with open(path) as f:
                d = json.load(f)
            if not isinstance(d, dict) or "telemetry" not in d:
                raise ValueError("not a telemetry dump")
        except (OSError, json.JSONDecodeError, ValueError,
                UnicodeDecodeError) as e:
            skipped.append({"file": path, "error": str(e)})
            print(f"mpitop: warning: skipped {path}: {e}",
                  file=sys.stderr)
            continue
        snaps.append(d)
    return snaps, skipped


def _merge_named(hists: List[Dict[str, Any]],
                 pred) -> Dict[str, Any]:
    return merge_snapshots([h.get("snap") or {} for h in hists
                            if pred(h)])


def summarize(snaps: List[Dict[str, Any]],
              per_comm: bool = False) -> Dict[str, Any]:
    """The merged machine-readable form every output format renders
    from: one row per rank (or per (rank, comm)), plus the slow-rank
    election."""
    rows: List[Dict[str, Any]] = []
    accusations: Dict[int, float] = {}   # subject -> worst peer score
    declared: Dict[int, int] = {}        # subject -> declaring peers
    for d in snaps:
        health = d.get("health") or {}
        for peer, score in (health.get("scores") or {}).items():
            p = int(peer)
            accusations[p] = max(accusations.get(p, 0.0), float(score))
        for p in health.get("declared") or []:
            declared[int(p)] = declared.get(int(p), 0) + 1

    def is_coll(h):
        return str(h.get("name", "")).startswith("tele_coll_")

    for d in sorted(snaps, key=lambda s: int(s.get("rank", -1))):
        rank = int(d.get("rank", -1))
        hists = d.get("hists") or []
        keys: List[Optional[str]] = [None]
        if per_comm:
            keys = sorted({(h.get("labels") or {}).get("comm")
                           for h in hists if is_coll(h)} - {None}) \
                or [None]
        for comm in keys:
            if comm is None:
                coll = _merge_named(hists, is_coll)
            else:
                coll = _merge_named(
                    hists, lambda h, c=comm: is_coll(h)
                    and (h.get("labels") or {}).get("comm") == c)
            send = _merge_named(
                hists, lambda h: h.get("name") == "tele_pml_send_us")
            recv = _merge_named(
                hists, lambda h: h.get("name") == "tele_pml_recv_us")
            rail = _merge_named(
                hists, lambda h: h.get("name") == "tele_btl_rail_bytes")
            shm = _merge_named(
                hists,
                lambda h: h.get("name") == "tele_btl_shm_seg_bytes")
            row: Dict[str, Any] = {
                "rank": rank,
                "coll_ops": coll["count"],
                "coll_p50_us": coll["p50"],
                "coll_p99_us": coll["p99"],
                "send_p99_us": send["p99"],
                "recv_p99_us": recv["p99"],
                "rail_bytes": round(rail["sum"], 0),
                "shm_bytes": round(shm["sum"], 0),
                "straggler_score": accusations.get(rank, 0.0),
                "declared_by": declared.get(rank, 0),
                "time": float(d.get("time", 0.0)),
            }
            if comm is not None:
                row["comm"] = comm
            rows.append(row)

    # the one-sided plane: per-origin op/byte counters from the dump's
    # ``osc`` block, latencies from the tele_osc_* histograms — present
    # only when RMA ran at all (docs/RMA.md)
    osc_rows: List[Dict[str, Any]] = []
    for d in sorted(snaps, key=lambda s: int(s.get("rank", -1))):
        o = d.get("osc") or {}
        if not o:
            continue
        hists = d.get("hists") or []
        put = _merge_named(
            hists, lambda h: h.get("name") == "tele_osc_put_us")
        get = _merge_named(
            hists, lambda h: h.get("name") == "tele_osc_get_us")
        acc = _merge_named(
            hists, lambda h: h.get("name") == "tele_osc_acc_us")
        osc_rows.append({
            "rank": int(d.get("rank", -1)),
            "puts": int(o.get("puts", 0)),
            "gets": int(o.get("gets", 0)),
            "accs": int(o.get("accs", 0)),
            "bytes": int(o.get("put_bytes", 0))
            + int(o.get("get_bytes", 0)) + int(o.get("acc_bytes", 0)),
            "put_p99_us": put["p99"],
            "get_p99_us": get["p99"],
            "acc_p99_us": acc["p99"],
            "fences": int(o.get("fences", 0)),
            "locks": int(o.get("locks", 0)),
            "epoch_errors": int(o.get("epoch_errors", 0)),
            "ft_failed_epochs": int(o.get("ft_failed_epochs", 0)),
        })

    slow: Optional[int] = None
    if declared:
        slow = max(sorted(declared), key=lambda r: declared[r])
    elif accusations and max(accusations.values()) > 0.0:
        slow = max(sorted(accusations), key=lambda r: accusations[r])
    else:
        worst = -1.0
        for row in rows:
            own = max(float(row["coll_p99_us"]),
                      float(row["send_p99_us"]))
            if own > worst:
                worst, slow = own, int(row["rank"])
    out = {"mpitop": 1, "rows": rows, "slow_rank": slow,
           "accusations": {str(r): s
                           for r, s in sorted(accusations.items())},
           "declared": {str(r): n
                        for r, n in sorted(declared.items())}}
    if osc_rows:
        out["osc"] = osc_rows
    return out


def _fmt_us(v: float) -> str:
    if v >= 1e6:
        return f"{v / 1e6:.2f}s"
    if v >= 1e3:
        return f"{v / 1e3:.1f}ms"
    return f"{v:.0f}us"


def render_table(summary: Dict[str, Any],
                 rates: Optional[Dict[Any, Tuple[float, float]]] = None
                 ) -> str:
    per_comm = any("comm" in r for r in summary["rows"])
    hdr = ["rank"] + (["comm"] if per_comm else []) + \
        ["coll_ops", "coll_p50", "coll_p99", "send_p99", "recv_p99",
         "straggler", "flags"]
    if rates is not None:
        hdr.insert(-2, "ops/s")
    lines = []
    widths = [len(h) for h in hdr]
    table = []
    for row in summary["rows"]:
        flags = []
        if row["declared_by"]:
            flags.append(f"STRAGGLER(x{row['declared_by']})")
        if summary["slow_rank"] == row["rank"]:
            flags.append("SLOW")
        cells = [str(row["rank"])] + \
            ([str(row.get("comm", "-"))] if per_comm else []) + \
            [str(row["coll_ops"]), _fmt_us(row["coll_p50_us"]),
             _fmt_us(row["coll_p99_us"]), _fmt_us(row["send_p99_us"]),
             _fmt_us(row["recv_p99_us"])]
        if rates is not None:
            key = (row["rank"], row.get("comm"))
            ops_s, _ = rates.get(key, (0.0, 0.0))
            cells.append(f"{ops_s:.1f}")
        cells += [f"{row['straggler_score']:.3f}",
                  " ".join(flags) or "-"]
        table.append(cells)
        widths = [max(w, len(c)) for w, c in zip(widths, cells)]
    lines.append("  ".join(h.ljust(w) for h, w in zip(hdr, widths)))
    for cells in table:
        lines.append("  ".join(c.ljust(w)
                               for c, w in zip(cells, widths)))
    lines.append(f"slow_rank: {summary['slow_rank']}")
    if summary.get("osc"):
        lines.append("")
        ohdr = ["rank", "puts", "gets", "accs", "bytes", "put_p99",
                "get_p99", "acc_p99", "fences", "locks", "flags"]
        otab = []
        owid = [len(h) for h in ohdr]
        for o in summary["osc"]:
            oflags = []
            if o["epoch_errors"]:
                oflags.append(f"RMA_SYNC(x{o['epoch_errors']})")
            if o["ft_failed_epochs"]:
                oflags.append(f"FT_EPOCH(x{o['ft_failed_epochs']})")
            cells = [str(o["rank"]), str(o["puts"]), str(o["gets"]),
                     str(o["accs"]), str(o["bytes"]),
                     _fmt_us(o["put_p99_us"]), _fmt_us(o["get_p99_us"]),
                     _fmt_us(o["acc_p99_us"]), str(o["fences"]),
                     str(o["locks"]), " ".join(oflags) or "-"]
            otab.append(cells)
            owid = [max(w, len(c)) for w, c in zip(owid, cells)]
        lines.append("osc (one-sided):")
        lines.append("  ".join(h.ljust(w) for h, w in zip(ohdr, owid)))
        for cells in otab:
            lines.append("  ".join(c.ljust(w)
                                   for c, w in zip(cells, owid)))
    return "\n".join(lines)


def render_prom(snaps: List[Dict[str, Any]]) -> str:
    from ompi_tpu.telemetry import prom
    hist_rows = []
    pvars: List[Dict[str, Any]] = []
    for d in snaps:
        rank = int(d.get("rank", -1))
        for h in d.get("hists") or []:
            hist_rows.append(dict(h, rank=rank))
        health = d.get("health") or {}
        if health.get("scores"):
            pvars.append({"name": "tele_straggler_scores",
                          "value": health["scores"], "rank": rank})
    return prom.render(rank=-1, pvars=pvars, hist_rows=hist_rows)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ompi_tpu.tools.mpitop",
        description="Merge per-rank telemetry snapshots into a "
                    "per-rank/per-comm latency + straggler table.")
    ap.add_argument("files", nargs="+",
                    help="telemetry snapshot files written by "
                         "ompi_tpu.telemetry.dump()")
    ap.add_argument("--format", "-f", default="table",
                    choices=("table", "json", "prom"))
    ap.add_argument("--per-comm", action="store_true",
                    help="one row per (rank, comm) instead of per rank")
    ap.add_argument("--watch", type=float, default=0.0, metavar="N",
                    help="re-read and reprint every N seconds "
                         "(throughput becomes delta ops/s)")
    ap.add_argument("--out", "-o", default="-",
                    help="output path (default: stdout)")
    args = ap.parse_args(argv)

    prev: Dict[Any, Tuple[float, float]] = {}
    prev_t = 0.0
    while True:
        snaps, skipped = load_snapshots(args.files)
        if not snaps:
            print("mpitop: no readable telemetry snapshots",
                  file=sys.stderr)
            return 1
        summary = summarize(snaps, per_comm=args.per_comm)
        if skipped:
            summary["skipped"] = len(skipped)
        if args.format == "json":
            text = json.dumps(summary, indent=1)
        elif args.format == "prom":
            text = render_prom(snaps)
        else:
            rates = None
            if args.watch and prev_t:
                dt = max(time.monotonic() - prev_t, 1e-9)
                rates = {}
                for row in summary["rows"]:
                    key = (row["rank"], row.get("comm"))
                    p_ops, p_bytes = prev.get(
                        key, (row["coll_ops"], row["rail_bytes"]))
                    rates[key] = (
                        max(0.0, (row["coll_ops"] - p_ops) / dt),
                        max(0.0, (row["rail_bytes"] - p_bytes) / dt))
            text = render_table(summary, rates)
        if args.out == "-":
            sys.stdout.write(text + "\n")
            sys.stdout.flush()
        else:
            with open(args.out, "w") as f:
                f.write(text + ("\n" if not text.endswith("\n")
                                else ""))
        if not args.watch:
            return 0
        prev = {(r["rank"], r.get("comm")):
                (r["coll_ops"], r["rail_bytes"])
                for r in summary["rows"]}
        prev_t = time.monotonic()
        time.sleep(args.watch)


if __name__ == "__main__":
    sys.exit(main())
