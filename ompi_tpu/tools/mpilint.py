"""CLI shim: ``python -m ompi_tpu.tools.mpilint`` — the documented
entry point for the project-native static analyzer. The engine (rule
catalog, baseline handling, MCAVARS generation) lives in
:mod:`ompi_tpu.analyze.mpilint`; this wrapper exists so the tools/
namespace stays the single CLI surface (tracedump, checkparity,
mpisync precedent) and ``-m`` runs don't shadow the analyze package
module in ``sys.modules``.
"""
from __future__ import annotations

import sys

from ompi_tpu.analyze.mpilint import main

if __name__ == "__main__":
    sys.exit(main())
