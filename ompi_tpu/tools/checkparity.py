"""checkparity — CI audit for the compressed-collective test contract.

Two invariants the compression subsystem must never lose
(docs/COMPRESSION.md, docs/PARITY.md):

1. **Parity coverage**: every collective the ``coll/compressed``
   component wraps (``WRAPPED_FUNCS``) has a paired
   uncompressed-equivalence test — a test named
   ``test_compressed_<func>_matches_uncompressed`` somewhere under
   ``tests/``. A compressed schedule without its equivalence test is
   an unverified lossy path.
2. **Tier-1 budget**: compression tests that spawn real OS processes
   (``subprocess``-using test functions in ``tests/test_compress*``)
   carry the ``slow`` marker, so the multi-process jobs stay out of
   the ``-m 'not slow'`` tier-1 run and its 870 s wall budget.

Usage::

    python -m ompi_tpu.tools.checkparity [--tests DIR]

Prints a JSON report; exit status 1 on any violation (the CI entry).
The audit is also invoked in-process by tests/test_compress_tools.py,
so tier-1 itself enforces the contract.
"""
from __future__ import annotations

import ast
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _test_functions(path: str):
    """Yield (name, node) for every test function in a file (module
    level and class level)."""
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), path)
    except (OSError, SyntaxError):
        return
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name.startswith("test"):
            yield node.name, node


def _uses_subprocess(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) \
                and isinstance(sub.value, ast.Name) \
                and sub.value.id == "subprocess":
            return True
        if isinstance(sub, ast.Name) and sub.id in ("Popen", "check_call",
                                                    "check_output"):
            return True
    return False


def _has_slow_mark(node: ast.AST) -> bool:
    for dec in getattr(node, "decorator_list", []):
        for sub in ast.walk(dec):
            if isinstance(sub, ast.Attribute) and sub.attr == "slow":
                return True
    return False


def _module_slow_pytestmark(path: str) -> bool:
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), path)
    except (OSError, SyntaxError):
        return False
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "pytestmark"
                for t in node.targets):
            return "slow" in ast.dump(node.value)
    return False


def audit(tests_dir: Optional[str] = None) -> Dict[str, Any]:
    tests_dir = tests_dir or os.path.join(_REPO, "tests")
    from ompi_tpu.coll.compressed import WRAPPED_FUNCS

    wanted = {f"test_compressed_{func}_matches_uncompressed": func
              for func in WRAPPED_FUNCS}
    found: set = set()
    unmarked: List[str] = []
    for path in sorted(glob.glob(os.path.join(tests_dir, "**", "*.py"),
                                 recursive=True)):
        base = os.path.basename(path)
        mod_slow = _module_slow_pytestmark(path)
        for name, node in _test_functions(path) or ():
            if name in wanted:
                found.add(name)
            if base.startswith("test_compress") \
                    and _uses_subprocess(node) \
                    and not (mod_slow or _has_slow_mark(node)):
                unmarked.append(f"{base}::{name}")
    missing = sorted(set(wanted) - found)
    return {"ok": not missing and not unmarked,
            "wrapped_funcs": list(WRAPPED_FUNCS),
            "missing_parity": missing,
            "unmarked_slow": sorted(unmarked)}


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m ompi_tpu.tools.checkparity",
        description="Audit compressed-collective parity tests and "
                    "slow-marker hygiene.")
    ap.add_argument("--tests", default=None,
                    help="tests directory (default: <repo>/tests)")
    args = ap.parse_args(argv)
    report = audit(args.tests)
    print(json.dumps(report, indent=1))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
