"""checkparity — CI audit for the collective test-parity contracts.

Invariants the lossy/fused subsystems must never lose
(docs/COMPRESSION.md, docs/PERSISTENT.md, docs/PARITY.md):

1. **Compression parity**: every collective the ``coll/compressed``
   component wraps (``WRAPPED_FUNCS``) has a paired
   uncompressed-equivalence test — a test named
   ``test_compressed_<func>_matches_uncompressed`` somewhere under
   ``tests/``. A compressed schedule without its equivalence test is
   an unverified lossy path.
2. **Persistent/fused parity**: every collective with a pre-bound
   persistent plan (``coll/persistent.PERSISTENT_FUNCS``) has a
   ``test_persistent_<func>_matches_unfused`` pair, and every
   bucket-fused collective (``FUSED_FUNCS``) has a
   ``test_bucketed_<func>_matches_unfused`` pair — a fused wire path
   without its equivalence test is an unverified rewrite of the
   collective's result.
3. **Pipeline parity**: every collective with a segment-pipelined
   host-tier schedule (``coll/decision.PIPELINED``) has a
   ``test_pipelined_<func>_matches_unpipelined`` pair — a pipelined
   rewrite of the wire schedule without its equivalence test is an
   unverified reordering of the collective's result
   (docs/LARGEMSG.md).
3b. **Shm-fold parity**: every collective with an in-segment
   shared-memory fold schedule (``coll/decision.SHM_FOLDS``) has a
   ``test_shmfold_<func>_matches_ring`` pair — an in-place
   shared-memory rewrite of the wire schedule without its equivalence
   test is an unverified fold path (docs/LARGEMSG.md).
4. **Fault-recovery parity**: every fault class the injection plane
   can raise (``ft/inject.FAULT_CLASSES``: drop / delay / corrupt /
   sever / kill) has a paired recovery test —
   ``test_ft_<class>_recovers`` somewhere under ``tests/``. An
   injectable fault without its recovery test is an unverified
   failure mode (docs/RESILIENCE.md).
5. **Tier-1 budget**: compression/persistent/large-message/FT/osc
   tests that spawn real OS processes (``subprocess``-using test
   functions in ``tests/test_compress*`` / ``tests/test_persistent*``
   / ``tests/test_largemsg*`` / ``tests/test_btl_rails*`` /
   ``tests/test_ft*`` / ``tests/test_osc*``) carry the ``slow``
   marker, so the
   multi-process jobs stay out of the ``-m 'not slow'`` tier-1 run
   and its 870 s wall budget.
7. **One-sided parity**: every osc framework op
   (``osc.base.OSC_OPS``: put / get / accumulate) has a component
   parity pair — ``test_osc_<op>_matches_pt2pt`` somewhere under
   ``tests/``, asserting the shm component, the pt2pt emulation and a
   two-sided reference computation agree. A load/store RMA rewrite
   without its equivalence test is an unverified memory path
   (docs/RMA.md).
6. **Lint-rule fixture parity**: every static rule the analyzer ships
   (``analyze.mpilint.RULES``) has a fixture PAIR
   (``tests/fixtures/lint/bad_<rule>.py`` that must fire it and
   ``good_<rule>.py`` that must not) plus a test whose name contains
   ``lint_<rule>`` exercising them — an analyzer rule without a
   proving fixture is an unverified checker (docs/ANALYSIS.md).

Usage::

    python -m ompi_tpu.tools.checkparity [--tests DIR]

Prints a JSON report; exit status 1 on any violation (the CI entry).
The audit is also invoked in-process by tests/test_compress_tools.py,
so tier-1 itself enforces the contract.
"""
from __future__ import annotations

import ast
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _test_functions(path: str):
    """Yield (name, node) for every test function in a file (module
    level and class level)."""
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), path)
    except (OSError, SyntaxError):
        return
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name.startswith("test"):
            yield node.name, node


def _uses_subprocess(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) \
                and isinstance(sub.value, ast.Name) \
                and sub.value.id == "subprocess":
            return True
        if isinstance(sub, ast.Name) and sub.id in ("Popen", "check_call",
                                                    "check_output"):
            return True
    return False


def _has_slow_mark(node: ast.AST) -> bool:
    for dec in getattr(node, "decorator_list", []):
        for sub in ast.walk(dec):
            if isinstance(sub, ast.Attribute) and sub.attr == "slow":
                return True
    return False


def _module_slow_pytestmark(path: str) -> bool:
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), path)
    except (OSError, SyntaxError):
        return False
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "pytestmark"
                for t in node.targets):
            return "slow" in ast.dump(node.value)
    return False


def audit(tests_dir: Optional[str] = None) -> Dict[str, Any]:
    tests_dir = tests_dir or os.path.join(_REPO, "tests")
    from ompi_tpu.analyze.mpilint import RULES
    from ompi_tpu.coll.compressed import WRAPPED_FUNCS
    from ompi_tpu.coll.decision import PIPELINED, SHM_FOLDS
    from ompi_tpu.coll.persistent import FUSED_FUNCS, PERSISTENT_FUNCS
    from ompi_tpu.ft.inject import FAULT_CLASSES
    from ompi_tpu.osc.base import OSC_OPS

    wanted = {f"test_compressed_{func}_matches_uncompressed": func
              for func in WRAPPED_FUNCS}
    wanted_pers = {f"test_persistent_{func}_matches_unfused": func
                   for func in PERSISTENT_FUNCS}
    wanted_pers.update({f"test_bucketed_{func}_matches_unfused": func
                        for func in FUSED_FUNCS})
    wanted_pipe = {f"test_pipelined_{func}_matches_unpipelined": func
                   for func in PIPELINED}
    wanted_shm = {f"test_shmfold_{func}_matches_ring": func
                  for func in SHM_FOLDS}
    wanted_ft = {f"test_ft_{cls}_recovers": cls
                 for cls in FAULT_CLASSES}
    wanted_osc = {f"test_osc_{op}_matches_pt2pt": op
                  for op in OSC_OPS}
    found: set = set()
    found_osc: set = set()
    found_pers: set = set()
    found_pipe: set = set()
    found_shm: set = set()
    found_ft: set = set()
    found_lint: set = set()
    unmarked: List[str] = []
    fixtures_dir = os.path.join(tests_dir, "fixtures", "lint")
    missing_fixtures: List[str] = []
    for rule in sorted(RULES):
        for kind in ("bad", "good"):
            fx = os.path.join(fixtures_dir, f"{kind}_{rule}.py")
            if not os.path.isfile(fx):
                missing_fixtures.append(f"fixtures/lint/{kind}_{rule}.py")
    for path in sorted(glob.glob(os.path.join(tests_dir, "**", "*.py"),
                                 recursive=True)):
        base = os.path.basename(path)
        mod_slow = _module_slow_pytestmark(path)
        for name, node in _test_functions(path) or ():
            if name in wanted:
                found.add(name)
            if name in wanted_pers:
                found_pers.add(name)
            if name in wanted_pipe:
                found_pipe.add(name)
            if name in wanted_shm:
                found_shm.add(name)
            if name in wanted_ft:
                found_ft.add(name)
            if name in wanted_osc:
                found_osc.add(name)
            for rule in RULES:
                if f"lint_{rule}" in name:
                    found_lint.add(rule)
            if base.startswith(("test_compress", "test_persistent",
                                "test_largemsg", "test_btl_rails",
                                "test_ft", "test_osc")) \
                    and _uses_subprocess(node) \
                    and not (mod_slow or _has_slow_mark(node)):
                unmarked.append(f"{base}::{name}")
    missing = sorted(set(wanted) - found)
    missing_pers = sorted(set(wanted_pers) - found_pers)
    missing_pipe = sorted(set(wanted_pipe) - found_pipe)
    missing_shm = sorted(set(wanted_shm) - found_shm)
    missing_ft = sorted(set(wanted_ft) - found_ft)
    missing_osc = sorted(set(wanted_osc) - found_osc)
    missing_lint = sorted(f"test *lint_{r}* (fixture-pair test)"
                          for r in set(RULES) - found_lint)
    return {"ok": not missing and not missing_pers and not missing_pipe
            and not missing_shm and not missing_ft and not missing_osc
            and not unmarked
            and not missing_fixtures and not missing_lint,
            "wrapped_funcs": list(WRAPPED_FUNCS),
            "persistent_funcs": list(PERSISTENT_FUNCS),
            "fused_funcs": list(FUSED_FUNCS),
            "pipelined_funcs": sorted(PIPELINED),
            "shm_fold_funcs": sorted(SHM_FOLDS),
            "fault_classes": list(FAULT_CLASSES),
            "osc_ops": list(OSC_OPS),
            "lint_rules": sorted(RULES),
            "missing_parity": missing,
            "missing_persistent_parity": missing_pers,
            "missing_pipeline_parity": missing_pipe,
            "missing_shm_fold_parity": missing_shm,
            "missing_ft_recovery": missing_ft,
            "missing_osc_parity": missing_osc,
            "missing_lint_fixtures": missing_fixtures,
            "missing_lint_tests": missing_lint,
            "unmarked_slow": sorted(unmarked)}


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m ompi_tpu.tools.checkparity",
        description="Audit compressed-collective parity tests and "
                    "slow-marker hygiene.")
    ap.add_argument("--tests", default=None,
                    help="tests directory (default: <repo>/tests)")
    args = ap.parse_args(argv)
    report = audit(args.tests)
    print(json.dumps(report, indent=1))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
