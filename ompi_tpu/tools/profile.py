"""Communication-profile postprocessing (monitoring_prof + profile2mat).

Behavioral spec: the reference's monitoring stack ends in
``monitoring_prof.c`` (an LD_PRELOAD profiler dumping per-peer counts)
and ``profile2mat.pl`` (turning those dumps into a rank x rank matrix
for heat-map tools). Here the counters are already in-process: the
matching engine keeps a per-(src, dest) traffic table and
coll/monitoring keeps per-(comm, func) call/byte counts; this module
renders both as matrices / CSV.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np


def pt2pt_matrix(comm, what: str = "bytes") -> np.ndarray:
    """rank x rank matrix of pt2pt traffic on ``comm`` (row = sender,
    column = receiver). ``what`` is 'bytes' or 'messages'."""
    idx = 1 if what == "bytes" else 0
    n = comm.size
    m = np.zeros((n, n), dtype=np.int64)
    # stacked comms: the controller-local engine holds every rank's
    # rows; per-rank comms: THIS process's engine holds its own rows
    # (aggregate across ranks with comm.allgather of the matrix)
    eng = getattr(comm, "_pml_engine", None)
    if eng is None and getattr(comm, "is_per_rank", False):
        eng = comm._pml
    if eng is not None:
        for (src, dest), counts in eng.traffic.items():
            if 0 <= src < n and 0 <= dest < n:
                m[src, dest] += counts[idx]
    return m


def coll_table() -> Dict[Tuple[int, str], Tuple[int, int]]:
    """Per-(comm cid, collective) (calls, bytes) from coll/monitoring."""
    from ompi_tpu.coll import monitoring
    return monitoring.snapshot()


def to_csv(matrix: np.ndarray) -> str:
    """profile2mat output shape: one CSV row per sender."""
    return "\n".join(",".join(str(int(v)) for v in row)
                     for row in np.asarray(matrix))


def report(comm) -> str:
    lines: List[str] = []
    msgs = pt2pt_matrix(comm, "messages")
    if msgs.any():
        lines.append("# pt2pt messages (row=sender)")
        lines.append(to_csv(msgs))
        lines.append("# pt2pt bytes (row=sender)")
        lines.append(to_csv(pt2pt_matrix(comm, "bytes")))
    table = coll_table()
    if table:
        lines.append("# collectives: cid,func,calls,bytes")
        for (cid, func), (calls, nbytes) in sorted(table.items()):
            lines.append(f"{cid},{func},{calls},{nbytes}")
    return "\n".join(lines) if lines else "# no traffic recorded"


def main() -> None:
    import ompi_tpu as MPI
    if not MPI.Initialized():
        MPI.Init()
    print(report(MPI.get_comm_world()))


if __name__ == "__main__":
    main()
