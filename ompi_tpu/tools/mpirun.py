"""``mpirun`` equivalent — a thin argv-translating launcher.

Behavioral spec: the reference's mpirun is an exec shim that finds
prterun, translates argv, and execs it (``ompi/tools/mpirun/main.c:32-48,
157-180``); the runtime (PRRTE) owns process placement.

TPU-native re-design: placement is device binding.
- Single-controller (default): ``mpirun -n N prog.py`` sets
  ``OMPI_TPU_MCA_mpi_base_num_ranks=N`` and execs ``python prog.py``
  once — the controller binds N mesh devices as ranks.
- Multi-host: ``--coordinator host:port --num-hosts H --host-id I``
  populate the jax.distributed coordination-service vars (the PMIx
  stand-in); one controller per host, each contributing its local
  devices.
``--mca k v`` translates to ``OMPI_TPU_MCA_<k>`` exactly like the
reference's ``--mca`` -> ``OMPI_MCA_*`` env translation.
"""
from __future__ import annotations

import argparse
import os
import sys


def build_env(args, base_env) -> dict:
    env = dict(base_env)
    # The launched program must find the library regardless of cwd (the
    # reference's mpirun prepends its own libdir the same way).
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    if pkg_root not in parts:
        env["PYTHONPATH"] = os.pathsep.join([pkg_root] + parts)
    if args.n:
        env["OMPI_TPU_MCA_mpi_base_num_ranks"] = str(args.n)
    for k, v in args.mca or []:
        env[f"OMPI_TPU_MCA_{k}"] = v
    if args.coordinator:
        env["OMPI_TPU_MCA_mpi_base_distributed"] = "1"
        env["OMPI_TPU_MCA_mpi_base_coordinator"] = args.coordinator
        if args.num_hosts:
            env["OMPI_TPU_MCA_mpi_base_num_processes"] = str(args.num_hosts)
        if args.host_id is not None:
            env["OMPI_TPU_MCA_mpi_base_process_id"] = str(args.host_id)
    return env


def parse(argv):
    ap = argparse.ArgumentParser(prog="mpirun (ompi_tpu)")
    ap.add_argument("-n", "-np", type=int, default=0,
                    help="number of ranks (0 = all local devices)")
    ap.add_argument("--mca", nargs=2, action="append",
                    metavar=("VAR", "VALUE"),
                    help="set an MCA variable (e.g. coll_base_include xla)")
    ap.add_argument("--coordinator", default="",
                    help="host:port of the coordination service "
                         "(multi-host)")
    ap.add_argument("--num-hosts", type=int, default=0)
    ap.add_argument("--host-id", type=int, default=None)
    ap.add_argument("program", nargs=argparse.REMAINDER,
                    help="program and its args")
    return ap.parse_args(argv)


def main(argv=None) -> None:
    args = parse(argv if argv is not None else sys.argv[1:])
    if not args.program:
        sys.stderr.write("mpirun: no program given\n")
        raise SystemExit(2)
    env = build_env(args, os.environ)
    prog = args.program
    if prog[0].endswith(".py"):
        prog = [sys.executable] + prog
    os.execvpe(prog[0], prog, env)      # exec shim, like mpirun->prterun


if __name__ == "__main__":
    main()
