"""``mpirun`` equivalent — a thin argv-translating launcher.

Behavioral spec: the reference's mpirun is an exec shim that finds
prterun, translates argv, and execs it (``ompi/tools/mpirun/main.c:32-48,
157-180``); the runtime (PRRTE) owns process placement.

TPU-native re-design: placement is device binding.
- Single-controller (default): ``mpirun -n N prog.py`` sets
  ``OMPI_TPU_MCA_mpi_base_num_ranks=N`` and execs ``python prog.py``
  once — the controller binds N mesh devices as ranks.
- Multi-host: ``--coordinator host:port --num-hosts H --host-id I``
  populate the jax.distributed coordination-service vars (the PMIx
  stand-in); one controller per host, each contributing its local
  devices.
- Per-rank: ``mpirun --per-rank -n N prog.py`` takes the PRRTE DVM role
  itself — fork/exec N rank processes on this host (each one MPI rank,
  ``rank() == jax.process_index()``), wire them to a local coordination
  service, wait for all, and propagate the first failure
  (``main.c:157-180``'s process-boundary role, without the external
  daemon).
``--mca k v`` translates to ``OMPI_TPU_MCA_<k>`` exactly like the
reference's ``--mca`` -> ``OMPI_MCA_*`` env translation.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys


def build_env(args, base_env) -> dict:
    env = dict(base_env)
    # The launched program must find the library regardless of cwd (the
    # reference's mpirun prepends its own libdir the same way).
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    if pkg_root not in parts:
        env["PYTHONPATH"] = os.pathsep.join([pkg_root] + parts)
    if args.n:
        env["OMPI_TPU_MCA_mpi_base_num_ranks"] = str(args.n)
    for k, v in args.mca or []:
        env[f"OMPI_TPU_MCA_{k}"] = v
    if args.coordinator:
        env["OMPI_TPU_MCA_mpi_base_distributed"] = "1"
        env["OMPI_TPU_MCA_mpi_base_coordinator"] = args.coordinator
        if args.num_hosts:
            env["OMPI_TPU_MCA_mpi_base_num_processes"] = str(args.num_hosts)
        if args.host_id is not None:
            env["OMPI_TPU_MCA_mpi_base_process_id"] = str(args.host_id)
    return env


def parse(argv):
    ap = argparse.ArgumentParser(prog="mpirun (ompi_tpu)")
    ap.add_argument("-n", "-np", type=int, default=0,
                    help="number of ranks (0 = all local devices)")
    ap.add_argument("--mca", nargs=2, action="append",
                    metavar=("VAR", "VALUE"),
                    help="set an MCA variable (e.g. coll_base_include xla)")
    ap.add_argument("--coordinator", default="",
                    help="host:port of the coordination service "
                         "(multi-host)")
    ap.add_argument("--num-hosts", type=int, default=0)
    ap.add_argument("--host-id", type=int, default=None)
    ap.add_argument("--per-rank", action="store_true",
                    help="one OS process per MPI rank "
                         "(rank() == process_index)")
    ap.add_argument("--timeout", type=float, default=0,
                    help="per-rank mode: kill the job after this many "
                         "seconds (0 = no limit)")
    ap.add_argument("program", nargs=argparse.REMAINDER,
                    help="program and its args")
    return ap.parse_args(argv)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_per_rank(args, prog) -> int:
    """Spawn N rank processes (the PRRTE daemon's fork/exec role) and
    reap them; first nonzero exit aborts the job, as mpirun does."""
    n = args.n or 2
    coord = args.coordinator or f"127.0.0.1:{_free_port()}"
    procs = []
    for r in range(n):
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                 if p]
        if pkg_root not in parts:
            env["PYTHONPATH"] = os.pathsep.join([pkg_root] + parts)
        env["OMPI_TPU_MCA_mpi_base_distributed"] = "1"
        env["OMPI_TPU_MCA_mpi_base_per_rank"] = "1"
        env["OMPI_TPU_MCA_mpi_base_coordinator"] = coord
        env["OMPI_TPU_MCA_mpi_base_num_processes"] = str(n)
        env["OMPI_TPU_MCA_mpi_base_process_id"] = str(r)
        for k, v in args.mca or []:
            env[f"OMPI_TPU_MCA_{k}"] = v
        procs.append(subprocess.Popen(prog, env=env))
    rc = 0
    try:
        for p in procs:
            prc = p.wait(timeout=args.timeout or None)
            rc = rc or prc
    except subprocess.TimeoutExpired:
        rc = 124
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        _sweep_shm(coord)
    return rc


def _sweep_shm(coord: str) -> None:
    """Remove shared-memory files this job's ranks leaked (a killed
    rank never reaches its unlink) — the PRRTE session-cleanup role
    for the btl/sm ring files AND the btl/shmseg zero-copy segment
    pools. Tags, prefixes, and directory come from the btl modules
    themselves so the sweep can never diverge from the naming.

    Run as a script, mpirun's own process does NOT have the package
    on sys.path (script dir is tools/, and python never adds the cwd
    for scripts) — only the ranks get the PYTHONPATH injection. Put
    the package root on the path here, or the guarded import below
    silently no-ops the sweep and every crashed job leaks its files."""
    import glob
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if pkg_root not in sys.path:
        sys.path.insert(0, pkg_root)
    try:
        from ompi_tpu.btl.sm import _SHM_DIR, tag_for
    except Exception:                    # noqa: BLE001 — broken env:
        return                           # nothing we can safely sweep
    try:
        from ompi_tpu.btl.shmseg import SEG_PREFIX
    except Exception:                    # noqa: BLE001
        SEG_PREFIX = "otpuseg"
    try:
        from ompi_tpu.osc.shm import WIN_PREFIX
    except Exception:                    # noqa: BLE001
        WIN_PREFIX = "otpuwin"
    tag = tag_for(coord)
    for prefix in ("otpusm", SEG_PREFIX, WIN_PREFIX):
        for path in glob.glob(os.path.join(_SHM_DIR,
                                           f"{prefix}_{tag}_*")):
            try:
                os.unlink(path)
            except OSError:
                pass


def main(argv=None) -> None:
    args = parse(argv if argv is not None else sys.argv[1:])
    if not args.program:
        sys.stderr.write("mpirun: no program given\n")
        raise SystemExit(2)
    prog = args.program
    if prog[0].endswith(".py"):
        prog = [sys.executable] + prog
    if args.per_rank:
        raise SystemExit(run_per_rank(args, prog))
    env = build_env(args, os.environ)
    os.execvpe(prog[0], prog, env)      # exec shim, like mpirun->prterun


if __name__ == "__main__":
    main()
