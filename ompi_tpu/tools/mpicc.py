"""``mpicc`` — the compiler wrapper for the MPI C ABI.

Behavioral spec: the reference's wrapper compilers are argv shims that
splice in include/lib flags from wrapper-data text files
(``ompi/tools/wrappers``).  Here the wrapper also owns building the
bindings library itself (``native/mpi_cabi.c`` -> ``libtpumpi.so``),
on demand and mtime-cached exactly like the native component loader —
the framework never needs a separate install step.

Usage::

    python -m ompi_tpu.tools.mpicc prog.c -o prog      # compile+link
    python -m ompi_tpu.tools.mpicc --showme            # print the flags

The produced binaries embed CPython (the runtime's host language), so
the link line carries the python embed flags; ``-rpath`` entries make
the binaries runnable without LD_LIBRARY_PATH.
"""
from __future__ import annotations

import os
import subprocess
import sys
import sysconfig
from typing import List, Optional

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_DIR = os.path.dirname(_PKG_DIR)
_NATIVE_DIR = os.path.join(_REPO_DIR, "native")
_INCLUDE_DIR = os.path.join(_REPO_DIR, "include")
_SRC = os.path.join(_NATIVE_DIR, "mpi_cabi.c")
_SO = os.path.join(_NATIVE_DIR, "libtpumpi.so")


def _py_embed_flags() -> tuple:
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR") or "/usr/local/lib"
    ldver = sysconfig.get_config_var("LDVERSION") \
        or sysconfig.get_config_var("VERSION")
    return inc, libdir, f"python{ldver}"


def build_lib(cc: str = "gcc", force: bool = False) -> Optional[str]:
    """Build native/libtpumpi.so from mpi_cabi.c (content-hash-cached
    via the shared protocol in ``ompi_tpu.native.loader``: a sidecar
    ``.hash`` records the source digest, mtime is never consulted, so
    a stale binary — committed, copied, or left by an older tree — is
    always rebuilt)."""
    if not os.path.exists(_SRC):
        return None
    from ompi_tpu.native.loader import cached_native_build
    deps = [_SRC] + [p for p in
                     (os.path.join(_INCLUDE_DIR, "mpi.h"),
                      os.path.join(_INCLUDE_DIR, "mpi_pmpi.h"),
                      os.path.join(_NATIVE_DIR, "pmpi_aliases.h"))
                     if os.path.exists(p)]
    if force:
        try:
            os.remove(_SO + ".hash")
        except OSError:
            pass
    inc, libdir, pylib = _py_embed_flags()

    def make_cmd(tmp: str) -> List[str]:
        return [cc, "-O2", "-shared", "-fPIC", "-std=c11", _SRC,
                f"-I{inc}", f"-I{_INCLUDE_DIR}",
                f"-DOMPI_TPU_ROOT=\"{_REPO_DIR}\"",
                "-o", tmp,
                f"-L{libdir}", f"-l{pylib}", "-ldl", "-lm",
                f"-Wl,-rpath,{libdir}"]

    return cached_native_build(
        deps, _SO, make_cmd, timeout=180,
        on_error=lambda e: sys.stderr.write(
            e.stderr.decode(errors="replace")))


def wrapper_flags() -> List[str]:
    """The flags mpicc splices into the user's compile line."""
    _, libdir, pylib = _py_embed_flags()
    return [f"-I{_INCLUDE_DIR}",
            f"-L{_NATIVE_DIR}", "-ltpumpi",
            f"-Wl,-rpath,{_NATIVE_DIR}",
            f"-L{libdir}", f"-l{pylib}",
            f"-Wl,-rpath,{libdir}"]


def main(argv: Optional[List[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    cc = os.environ.get("OMPI_TPU_CC", "gcc")
    if args and args[0] == "--showme":
        print(" ".join([cc] + wrapper_flags()))
        return 0
    if build_lib(cc) is None:
        sys.stderr.write("mpicc: failed to build libtpumpi.so\n")
        return 1
    cmd = [cc] + args + wrapper_flags()
    try:
        return subprocess.run(cmd).returncode
    except OSError as e:
        sys.stderr.write(f"mpicc: {e}\n")
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
