"""mpisync — cross-participant clock-offset measurement.

Behavioral spec: ``ompi/tools/mpisync`` (``mpigclock.c``): measure the
clock offset of every rank against rank 0 by ping-pong round trips,
keeping the sample with the smallest RTT (the least contaminated by
network jitter), so traces from different hosts can be aligned.

TPU-native re-design: ranks on one controller share a clock (offset 0
by construction); what needs syncing is *controllers* (multi-host) and
the host <-> device timeline. The estimator is the same mpigclock
algorithm generalized over any remote-clock probe: ``measure_offset``
takes a callable returning the remote clock "now" and returns the
(offset, rtt) of the best of N round trips; ``sync_report`` applies it
to every participant of a communicator (remote controllers probed via
the coordination-service KV when distributed, the shared clock
otherwise).
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Tuple


def measure_offset(remote_now: Callable[[], float],
                   rounds: int = 10,
                   local_now: Callable[[], float] = time.perf_counter,
                   ) -> Tuple[float, float]:
    """mpigclock's kernel: ``rounds`` ping-pongs; for each, the remote
    clock is sampled between two local samples (t0, t1) and the offset
    estimate is ``remote - (t0 + t1)/2``. The sample with the smallest
    RTT wins. Returns (offset_seconds, best_rtt_seconds)."""
    best_rtt = float("inf")
    best_off = 0.0
    for _ in range(max(rounds, 1)):
        t0 = local_now()
        r = remote_now()
        t1 = local_now()
        rtt = t1 - t0
        if rtt < best_rtt:
            best_rtt = rtt
            best_off = r - (t0 + t1) / 2.0
    return best_off, best_rtt


def sync_report(comm, rounds: int = 10,
                remote_clocks: Dict[int, Callable[[], float]] | None
                = None) -> List[Dict]:
    """Offset of every rank's clock against rank 0 (the mpisync output
    table). Ranks sharing this controller share its clock: offset is 0
    by construction and reported with rtt 0. Remote controllers (ranks
    whose device belongs to another process) are probed through
    ``remote_clocks[process_index]`` — a callable returning that
    controller's "now", e.g. a coordination-service KV timestamp
    exchange. Without a probe the rank is reported ``unprobed``
    (offset None) rather than a fabricated zero."""
    rows: List[Dict] = []
    import jax
    local_proc = jax.process_index()
    devices = list(getattr(comm, "devices", []) or [])
    for rank in range(comm.size):
        proc = (getattr(devices[rank], "process_index", 0)
                if rank < len(devices) else 0)
        if proc == local_proc:
            rows.append({"rank": rank, "offset_s": 0.0, "rtt_s": 0.0,
                         "clock": "controller"})
            continue
        probe = (remote_clocks or {}).get(proc)
        if probe is None:
            rows.append({"rank": rank, "offset_s": None, "rtt_s": None,
                         "clock": f"process_{proc} (unprobed)"})
        else:
            off, rtt = measure_offset(probe, rounds)
            rows.append({"rank": rank, "offset_s": off, "rtt_s": rtt,
                         "clock": f"process_{proc}"})
    return rows


def sync_report_perrank(comm, rounds: int = 10):
    """The REAL mpisync in the per-rank world: every rank ping-pongs
    rank 0's clock over pt2pt (one client at a time, mpigclock's
    serialized measurement), keeping the smallest-RTT sample. Probe
    traffic rides a hidden matching channel (never matches user
    receives). Collective over ``comm``; every rank returns the full
    table."""
    import numpy as np

    from ompi_tpu.core.rankcomm import hidden_engine
    eng = hidden_engine(comm, "sync")
    me, n = comm.rank(), comm.size
    mine = (0.0, 0.0) if me == 0 else None
    for r in range(1, n):
        if me == r:
            def remote_now() -> float:
                eng.send(np.float64(0.0), 0, 1)
                t, _ = eng.recv(0, 2)
                return float(np.asarray(t).ravel()[0])
            mine = measure_offset(remote_now, rounds)
        elif me == 0:
            for _ in range(max(rounds, 1)):
                eng.recv(r, 1)
                eng.send(np.float64(time.perf_counter()), r, 2)
        comm.barrier()                   # one client at a time
    rows = comm.allgather(mine)
    return [{"rank": r, "offset_s": float(off), "rtt_s": float(rtt),
             "clock": "rank0" if r == 0 else f"process_{r}"}
            for r, (off, rtt) in enumerate(rows)]


def main() -> None:
    import json

    import ompi_tpu as MPI
    if not MPI.Initialized():
        MPI.Init()
    for row in sync_report(MPI.get_comm_world()):
        print(json.dumps(row))


if __name__ == "__main__":
    main()
