"""mpisync — cross-participant clock-offset measurement.

Behavioral spec: ``ompi/tools/mpisync`` (``mpigclock.c``): measure the
clock offset of every rank against rank 0 by ping-pong round trips,
keeping the sample with the smallest RTT (the least contaminated by
network jitter), so traces from different hosts can be aligned.

TPU-native re-design: ranks on one controller share a clock (offset 0
by construction); what needs syncing is *controllers* (multi-host) and
the host <-> device timeline. The estimator is the same mpigclock
algorithm generalized over any remote-clock probe: ``measure_offset``
takes a callable returning the remote clock "now" and returns the
(offset, rtt) of the best of N round trips; ``sync_report`` applies it
to every participant of a communicator (remote controllers probed via
the coordination-service KV when distributed, the shared clock
otherwise).
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Tuple


def measure_offset(remote_now: Callable[[], float],
                   rounds: int = 10,
                   local_now: Callable[[], float] = time.perf_counter,
                   ) -> Tuple[float, float]:
    """mpigclock's kernel: ``rounds`` ping-pongs; for each, the remote
    clock is sampled between two local samples (t0, t1) and the offset
    estimate is ``remote - (t0 + t1)/2``. The sample with the smallest
    RTT wins. Returns (offset_seconds, best_rtt_seconds)."""
    best_rtt = float("inf")
    best_off = 0.0
    for _ in range(max(rounds, 1)):
        t0 = local_now()
        r = remote_now()
        t1 = local_now()
        rtt = t1 - t0
        if rtt < best_rtt:
            best_rtt = rtt
            best_off = r - (t0 + t1) / 2.0
    return best_off, best_rtt


def sync_report(comm, rounds: int = 10,
                remote_clocks: Dict[int, Callable[[], float]] | None
                = None) -> List[Dict]:
    """Offset of every rank's clock against rank 0 (the mpisync output
    table). Ranks sharing this controller share its clock: offset is 0
    by construction and reported with rtt 0. Remote controllers (ranks
    whose device belongs to another process) are probed through
    ``remote_clocks[process_index]`` — a callable returning that
    controller's "now", e.g. a coordination-service KV timestamp
    exchange. Without a probe the rank is reported ``unprobed``
    (offset None) rather than a fabricated zero."""
    rows: List[Dict] = []
    import jax
    local_proc = jax.process_index()
    devices = list(getattr(comm, "devices", []) or [])
    for rank in range(comm.size):
        proc = (getattr(devices[rank], "process_index", 0)
                if rank < len(devices) else 0)
        if proc == local_proc:
            rows.append({"rank": rank, "offset_s": 0.0, "rtt_s": 0.0,
                         "clock": "controller"})
            continue
        probe = (remote_clocks or {}).get(proc)
        if probe is None:
            rows.append({"rank": rank, "offset_s": None, "rtt_s": None,
                         "clock": f"process_{proc} (unprobed)"})
        else:
            off, rtt = measure_offset(probe, rounds)
            rows.append({"rank": rank, "offset_s": off, "rtt_s": rtt,
                         "clock": f"process_{proc}"})
    return rows


def main() -> None:
    import json

    import ompi_tpu as MPI
    if not MPI.Initialized():
        MPI.Init()
    for row in sync_report(MPI.get_comm_world()):
        print(json.dumps(row))


if __name__ == "__main__":
    main()
