"""hook/comm_method — print the per-communicator selection table.

Behavioral spec: the reference's ``ompi/mca/hook/comm_method`` (1,237
LoC) prints, at init/finalize, a rank x rank matrix of which transport
(pml/btl) serves each peer pair plus which coll components were
selected, so operators can confirm the fast path is actually in use.

TPU-native re-design: there is one data plane (XLA over ICI), so the
peer-pair matrix degenerates into the communicator -> mesh binding; the
interesting selection surface is the per-function coll vtable (which
component won each collective) and the device tier each rank's shard
lives on. ``table(comm)`` returns that; the CLI prints it. Enable the
init-time print the way the reference does, via the MCA var
``hook_comm_method_display`` (reference: ``hook_comm_method_verbose``).
"""
from __future__ import annotations

from typing import Dict

from ompi_tpu.mca import var

var.var_register(
    "hook", "comm_method", "display", vtype="bool", default=False,
    help="Print the communicator selection table (coll component per "
         "function + mesh binding) when a communicator is set up")


def table(comm) -> Dict:
    """The selection table for ``comm``: per-collective winning
    component, plus the mesh/transport summary."""
    per_func = getattr(comm, "_coll_winners", None)
    priorities = getattr(comm, "_coll_priorities", None)
    if per_func is None or priorities is None:
        if getattr(comm, "devices", None):
            # Not selected yet (or a bare mock): run the shared helper.
            from ompi_tpu.coll.framework import select_winners
            winners, selected = select_winners(comm)
            per_func = {f: comp.name
                        for f, (comp, _m) in winners.items()}
            priorities = [(comp.name, prio)
                          for prio, comp, _m in selected]
        else:
            # per-rank communicator: collectives are the built-in
            # textbook/XLA algorithms, not framework-selected modules
            per_func = {"*": "rankcomm-builtin"}
            priorities = []
    devices = list(getattr(comm, "devices", []) or [])
    procs = sorted({getattr(d, "process_index", 0) for d in devices})
    out = {
        "comm": getattr(comm, "name", None) or f"cid={comm.cid}",
        "size": comm.size,
        "platform": devices[0].platform if devices else "none",
        "devices": [str(getattr(d, "id", i))
                    for i, d in enumerate(devices)],
        "hosts": len(procs),
        "data_plane": ("xla/ici" if devices and
                       devices[0].platform != "cpu" else "xla/host"),
        "coll": dict(per_func),
        "priorities": list(priorities),
    }
    # per-rank worlds: the bml's per-transport frame counts — which
    # btl actually carried this rank's pt2pt traffic (the transport
    # matrix the reference's comm_method hook prints)
    router = getattr(comm, "router", None)
    ep = getattr(router, "endpoint", None)
    if ep is not None and hasattr(ep, "stats"):
        out["pt2pt_transports"] = dict(ep.stats)
        out["btl_sm"] = getattr(ep, "sm", None) is not None
        # the MEASURED basis for the bulk-routing decision (the init
        # micro-probe): operators see why sm carries bulk — or why it
        # was demoted — instead of trusting a hard-coded default
        basis = getattr(ep, "probe_basis", None)
        if basis:
            out["btl_probe"] = dict(basis)
    # the staged device tier's measured switch point (same discipline:
    # the decision shows its data, VERDICT r4 next #3)
    from ompi_tpu.coll.tuned import probed_stage_basis
    sb = probed_stage_basis()
    if sb.get("ran"):
        out["stage_probe"] = sb
    return out


def format_table(comm) -> str:
    t = table(comm)
    lines = [
        f"comm {t['comm']}: {t['size']} rank(s) on {t['platform']} "
        f"({t['hosts']} host(s)), data plane {t['data_plane']}",
        f"  devices: {', '.join(t['devices'])}",
        f"  component priorities: "
        f"{', '.join(f'{n}={p}' for n, p in t['priorities'])}",
        "  coll selection:",
    ]
    for func, comp in sorted(t["coll"].items()):
        lines.append(f"    {func:>22}: {comp}")
    return "\n".join(lines)


def maybe_display(comm) -> None:
    """Called from communicator setup when the display var is on (the
    reference hooks mpi_init/finalize the same way)."""
    if var.var_get("hook_comm_method_display", False):
        print(format_table(comm))


def main() -> None:
    import ompi_tpu as MPI
    if not MPI.Initialized():
        MPI.Init()
    print(format_table(MPI.get_comm_world()))


if __name__ == "__main__":
    main()
