"""shmem/perrank — OpenSHMEM for the per-rank execution model.

Behavioral spec: ``oshmem/`` — PEs address each other's symmetric heap
with plain offsets (symmetry by construction: every PE allocates the
same segments in the same order, ``memheap``); ``spml`` provides
put/get with remote completion (``spml.h:229-330``); ``scoll/mpi``
delegates collectives to the MPI stack; atomics through ``atomic/*``.

TPU-native re-design: one PE == one OS process == one MPI rank. The
symmetric heap is a :class:`RankWindow` exposure region per PE (the
reference's mmap'd segment), so a "symmetric address" is an offset
valid on every PE; put/get/atomics are the window's acked active
messages over btl/tcp (target-side application on the reader thread —
genuine one-sided progress, the spml put/get contract);
``shmem_wait_until`` polls the LOCAL heap, which remote puts mutate
asynchronously — the flag-polling idiom every SHMEM program is built
on (and the structure the reference fork's switch barriers offload).
Collectives delegate to the per-rank communicator (scoll/mpi's exact
design).
"""
from __future__ import annotations

import time
from typing import Any, Optional

import numpy as np

from ompi_tpu.core import op as op_mod
from ompi_tpu.core.errhandler import ERR_ARG, ERR_PENDING, MPIError
from ompi_tpu.osc.perrank import RankWindow
from ompi_tpu.shmem.api import (CMP_EQ, CMP_GE, CMP_GT, CMP_LE, CMP_LT,
                                CMP_NE, _CMP_FNS)


class ShmemRankCtx:
    """A per-rank SHMEM context: my PE number is real, peers are other
    processes."""

    def __init__(self, comm, heap_size: int = 1 << 12,
                 dtype=np.float32):
        self.comm = comm
        self.heap_size = int(heap_size)
        self.win = RankWindow(comm, heap_size, dtype=dtype,
                              name="symheap")
        self._brk = 0

    # -- PE identity ----------------------------------------------------
    def my_pe(self) -> int:
        return self.comm.rank()

    def n_pes(self) -> int:
        return self.comm.size

    # -- symmetric allocation (shmem_malloc: collective, same offset
    # everywhere — the memheap contract) --------------------------------
    def malloc(self, count: int) -> int:
        if self._brk + count > self.heap_size:
            raise MPIError(ERR_ARG, "symmetric heap exhausted")
        off = self._brk
        self._brk += count
        return off

    # -- RMA (spml put/get) ----------------------------------------------
    def put(self, dest_off: int, data, pe: int) -> None:
        self.win.put(data, pe, dest_off)

    def get(self, src_off: int, count: int, pe: int) -> np.ndarray:
        return self.win.get(pe, src_off, count)

    def p(self, off: int, value, pe: int) -> None:
        self.win.put([value], pe, off)

    def g(self, off: int, pe: int):
        return self.win.get(pe, off, 1)[0]

    # -- atomics (oshmem/mca/atomic) ---------------------------------
    def atomic_add(self, off: int, value, pe: int) -> None:
        self.win.accumulate([value], pe, off, op="sum")

    def atomic_fetch_add(self, off: int, value, pe: int):
        return self.win.fetch_and_op(value, pe, off, op="sum")

    def atomic_fetch(self, off: int, pe: int):
        return self.win.fetch_and_op(0, pe, off, op="no_op")

    def atomic_set(self, off: int, value, pe: int) -> None:
        self.win.accumulate([value], pe, off, op="replace")

    def atomic_compare_swap(self, off: int, cond, value, pe: int):
        return self.win.compare_and_swap(cond, value, pe, off)

    # -- ordering / sync -------------------------------------------------
    def fence(self) -> None:
        """shmem_fence/quiet: every put is acked, so ordering and
        remote completion already hold."""

    quiet = fence

    def barrier_all(self) -> None:
        self.comm.barrier()

    def wait_until(self, off: int, cmp: int, value,
                   timeout: float = 60) -> None:
        """Poll the LOCAL heap until the comparison holds — the flag
        that a remote PE's put/atomic flips (shmem_wait_until)."""
        fn = _CMP_FNS[cmp]
        deadline = time.monotonic() + timeout
        poll = 0.0002
        while True:
            with self.win._lock:
                cur = self.win.local[off]
            if fn(cur, value):
                return
            if time.monotonic() > deadline:
                raise MPIError(ERR_PENDING,
                               f"shmem_wait_until timed out "
                               f"(local[{off}]={cur})")
            time.sleep(poll)
            poll = min(poll * 2, 0.005)

    def test(self, off: int, cmp: int, value) -> bool:
        with self.win._lock:
            return bool(_CMP_FNS[cmp](self.win.local[off], value))

    # -- collectives (scoll/mpi: delegate to the MPI stack) -----------
    def broadcast(self, off: int, count: int, root_pe: int) -> None:
        with self.win._lock:
            seg = self.win.local[off:off + count].copy()
        out = self.comm.bcast(seg, root=root_pe)
        with self.win._lock:
            self.win.local[off:off + count] = out

    def collect(self, src_off: int, count: int) -> np.ndarray:
        with self.win._lock:
            seg = self.win.local[src_off:src_off + count].copy()
        return np.concatenate(self.comm.allgather(seg))

    def reduce(self, off: int, count: int,
               op: op_mod.Op = op_mod.SUM) -> np.ndarray:
        with self.win._lock:
            seg = self.win.local[off:off + count].copy()
        return np.asarray(self.comm.allreduce(seg, op))

    def finalize(self) -> None:
        self.win.free()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.finalize()
        return False
