"""shmem/perrank — OpenSHMEM for the per-rank execution model.

Behavioral spec: ``oshmem/`` — PEs address each other's symmetric heap
with plain offsets (symmetry by construction: every PE allocates the
same segments in the same order, ``memheap``); ``spml`` provides
put/get with remote completion (``spml.h:229-330``); ``scoll/mpi``
delegates collectives to the MPI stack; atomics through ``atomic/*``.

TPU-native re-design: one PE == one OS process == one MPI rank. The
symmetric heap is a :class:`RankWindow` exposure region per PE (the
reference's mmap'd segment), so a "symmetric address" is an offset
valid on every PE; put/get/atomics are the window's acked active
messages over btl/tcp (target-side application on the reader thread —
genuine one-sided progress, the spml put/get contract);
``shmem_wait_until`` polls the LOCAL heap, which remote puts mutate
asynchronously — the flag-polling idiom every SHMEM program is built
on (and the structure the reference fork's switch barriers offload).
Collectives delegate to the per-rank communicator (scoll/mpi's exact
design).
"""
from __future__ import annotations

import time
from typing import Any, Optional

import numpy as np

from ompi_tpu.core import op as op_mod
from ompi_tpu.core.errhandler import ERR_ARG, ERR_PENDING, MPIError
from ompi_tpu.osc.perrank import RankWindow
from ompi_tpu.shmem.api import (CMP_EQ, CMP_GE, CMP_GT, CMP_LE, CMP_LT,
                                CMP_NE, SIGNAL_ADD, SIGNAL_SET,
                                _CMP_FNS)


class ShmemRankCtx:
    """A per-rank SHMEM context: my PE number is real, peers are other
    processes."""

    def __init__(self, comm, heap_size: int = 1 << 12,
                 dtype=np.float32):
        self.comm = comm
        self.heap_size = int(heap_size)
        self.win = RankWindow(comm, heap_size, dtype=dtype,
                              name="symheap")
        self._brk = 0

    # -- PE identity ----------------------------------------------------
    def my_pe(self) -> int:
        return self.comm.rank()

    def n_pes(self) -> int:
        return self.comm.size

    # -- symmetric allocation (shmem_malloc: collective, same offset
    # everywhere — the memheap contract) --------------------------------
    def malloc(self, count: int) -> int:
        if self._brk + count > self.heap_size:
            raise MPIError(ERR_ARG, "symmetric heap exhausted")
        off = self._brk
        self._brk += count
        return off

    # -- RMA (spml put/get) ----------------------------------------------
    def put(self, dest_off: int, data, pe: int) -> None:
        self.win.put(data, pe, dest_off)

    def get(self, src_off: int, count: int, pe: int) -> np.ndarray:
        return self.win.get(pe, src_off, count)

    def p(self, off: int, value, pe: int) -> None:
        self.win.put([value], pe, off)

    def g(self, off: int, pe: int):
        return self.win.get(pe, off, 1)[0]

    # -- atomics (oshmem/mca/atomic) ---------------------------------
    def atomic_add(self, off: int, value, pe: int) -> None:
        self.win.accumulate([value], pe, off, op="sum")

    def atomic_fetch_add(self, off: int, value, pe: int):
        return self.win.fetch_and_op(value, pe, off, op="sum")

    def atomic_fetch(self, off: int, pe: int):
        return self.win.fetch_and_op(0, pe, off, op="no_op")

    def atomic_set(self, off: int, value, pe: int) -> None:
        self.win.accumulate([value], pe, off, op="replace")

    def atomic_compare_swap(self, off: int, cond, value, pe: int):
        return self.win.compare_and_swap(cond, value, pe, off)

    def atomic_swap(self, off: int, value, pe: int):
        """shmem_swap.c: unconditional fetch-and-replace."""
        return self.win.fetch_and_op(value, pe, off, op="replace")

    def atomic_inc(self, off: int, pe: int) -> None:
        self.atomic_add(off, 1, pe)

    def atomic_fetch_inc(self, off: int, pe: int):
        return self.atomic_fetch_add(off, 1, pe)

    # bitwise AMOs (shmem_{and,or,xor}.c + shmem_f{and,or,xor}.c),
    # applied atomically on the TARGET's reader thread
    def atomic_and(self, off: int, value, pe: int) -> None:
        self.win.accumulate([value], pe, off, op="band")

    def atomic_or(self, off: int, value, pe: int) -> None:
        self.win.accumulate([value], pe, off, op="bor")

    def atomic_xor(self, off: int, value, pe: int) -> None:
        self.win.accumulate([value], pe, off, op="bxor")

    def atomic_fetch_and(self, off: int, value, pe: int):
        return self.win.fetch_and_op(value, pe, off, op="band")

    def atomic_fetch_or(self, off: int, value, pe: int):
        return self.win.fetch_and_op(value, pe, off, op="bor")

    def atomic_fetch_xor(self, off: int, value, pe: int):
        return self.win.fetch_and_op(value, pe, off, op="bxor")

    # -- signaling (shmem_put_signal.c, SHMEM 1.5) ---------------------
    def put_signal(self, dest_off: int, data, sig_off: int, signal,
                   pe: int, sig_op: int = SIGNAL_SET) -> None:
        """Deliver the payload, then flip the signal word — the acked
        put guarantees payload-before-signal ordering, so the target's
        signal_wait_until genuinely gates on delivered data."""
        self.put(dest_off, data, pe)
        if sig_op == SIGNAL_ADD:
            self.atomic_add(sig_off, signal, pe)
        else:
            self.atomic_set(sig_off, signal, pe)

    def signal_fetch(self, sig_off: int):
        with self.win._lock:
            return self.win.local[sig_off]

    def signal_wait_until(self, sig_off: int, cmp: int, value,
                          timeout: float = 60):
        self.wait_until(sig_off, cmp, value, timeout)
        return self.signal_fetch(sig_off)

    # -- distributed locks (shmem_lock.c) — per-rank these BLOCK for
    # real: the holder is another OS process that will release
    def test_lock(self, off: int) -> bool:
        """Try-acquire via CAS 0 -> my_pe+1 on the lock word at PE 0
        (the lock-owner PE of OpenSHMEM's algorithm)."""
        prev = self.atomic_compare_swap(off, 0, self.my_pe() + 1, 0)
        return int(prev) == 0

    def set_lock(self, off: int, timeout: float = 60) -> None:
        deadline = time.monotonic() + timeout
        poll = 0.0002
        while not self.test_lock(off):
            if time.monotonic() > deadline:
                raise MPIError(ERR_PENDING,
                               f"shmem_set_lock timed out at offset "
                               f"{off}")
            time.sleep(poll)
            poll = min(poll * 2, 0.005)

    def clear_lock(self, off: int) -> None:
        prev = self.atomic_compare_swap(off, self.my_pe() + 1, 0, 0)
        if int(prev) != self.my_pe() + 1:
            raise MPIError(ERR_ARG,
                           f"shmem_clear_lock: PE {self.my_pe()} does "
                           f"not hold the lock at offset {off}")

    # -- ordering / sync -------------------------------------------------
    def fence(self) -> None:
        """shmem_fence/quiet: every put is acked, so ordering and
        remote completion already hold."""

    quiet = fence

    def barrier_all(self) -> None:
        self.comm.barrier()

    def wait_until(self, off: int, cmp: int, value,
                   timeout: float = 60) -> None:
        """Poll the LOCAL heap until the comparison holds — the flag
        that a remote PE's put/atomic flips (shmem_wait_until)."""
        fn = _CMP_FNS[cmp]
        deadline = time.monotonic() + timeout
        poll = 0.0002
        while True:
            with self.win._lock:
                cur = self.win.local[off]
            if fn(cur, value):
                return
            if time.monotonic() > deadline:
                raise MPIError(ERR_PENDING,
                               f"shmem_wait_until timed out "
                               f"(local[{off}]={cur})")
            time.sleep(poll)
            poll = min(poll * 2, 0.005)

    def test(self, off: int, cmp: int, value) -> bool:
        with self.win._lock:
            return bool(_CMP_FNS[cmp](self.win.local[off], value))

    # -- multi-variable sync (shmem_{test,wait}_ivars.c, SHMEM 1.4):
    # real polling loops — remote puts mutate the local heap
    # asynchronously from the reader thread
    def _ivar_state(self, offs, cmp: int, value):
        fn = _CMP_FNS[cmp]
        with self.win._lock:
            return [bool(fn(self.win.local[o], value)) for o in offs]

    def test_all(self, offs, cmp: int, value) -> bool:
        return all(self._ivar_state(offs, cmp, value))

    def test_any(self, offs, cmp: int, value):
        st = self._ivar_state(offs, cmp, value)
        return st.index(True) if True in st else None

    def test_some(self, offs, cmp: int, value):
        return [i for i, ok in enumerate(self._ivar_state(offs, cmp,
                                                          value)) if ok]

    def _wait_ivars(self, done, timeout: float):
        deadline = time.monotonic() + timeout
        poll = 0.0002
        while True:
            got = done()
            if got is not None:
                return got
            if time.monotonic() > deadline:
                raise MPIError(ERR_PENDING, "shmem_wait_until_* timed "
                                            "out")
            time.sleep(poll)
            poll = min(poll * 2, 0.005)

    def wait_until_all(self, offs, cmp: int, value,
                       timeout: float = 60) -> None:
        self._wait_ivars(
            lambda: True if self.test_all(offs, cmp, value) else None,
            timeout)

    def wait_until_any(self, offs, cmp: int, value,
                       timeout: float = 60) -> int:
        return self._wait_ivars(
            lambda: self.test_any(offs, cmp, value), timeout)

    def wait_until_some(self, offs, cmp: int, value,
                        timeout: float = 60):
        return self._wait_ivars(
            lambda: self.test_some(offs, cmp, value) or None, timeout)

    # -- accessibility / introspection ---------------------------------
    def pe_accessible(self, pe: int) -> bool:
        return 0 <= pe < self.n_pes()

    def addr_accessible(self, off: int, pe: int) -> bool:
        return self.pe_accessible(pe) and 0 <= off < self.heap_size

    @staticmethod
    def info_get_version():
        return (1, 5)

    @staticmethod
    def info_get_name() -> str:
        return "ompi_tpu-OpenSHMEM"

    # -- collectives (scoll/mpi: delegate to the MPI stack) -----------
    def broadcast(self, off: int, count: int, root_pe: int) -> None:
        with self.win._lock:
            seg = self.win.local[off:off + count].copy()
        out = self.comm.bcast(seg, root=root_pe)
        with self.win._lock:
            self.win.local[off:off + count] = out

    def collect(self, src_off: int, count: int) -> np.ndarray:
        with self.win._lock:
            seg = self.win.local[src_off:src_off + count].copy()
        return np.concatenate(self.comm.allgather(seg))

    def reduce(self, off: int, count: int,
               op: op_mod.Op = op_mod.SUM) -> np.ndarray:
        with self.win._lock:
            seg = self.win.local[off:off + count].copy()
        return np.asarray(self.comm.allreduce(seg, op))

    def finalize(self) -> None:
        self.win.free()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.finalize()
        return False
