from ompi_tpu.shmem.api import ShmemCtx  # noqa: F401
