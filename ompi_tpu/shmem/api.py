"""OSHMEM-lite: the OpenSHMEM programming model over the framework.

Behavioral spec: ``oshmem/`` — symmetric heap (memheap), put/get with
remote completion (spml, ``oshmem/mca/spml/spml.h:229-330``), atomics,
and collectives (scoll; scoll/mpi delegates to the MPI coll stack, which
is exactly what this does).

TPU-native re-design: the symmetric heap is one RMA window per context —
every PE's heap is a shard row, so a "symmetric address" is a plain
offset valid on all PEs (symmetry by construction, no address exchange
needed). ``put``/``get``/atomics are window ops (HBM shard updates);
``barrier_all``/``broadcast``/``collect``/reductions delegate to the
coll framework like scoll/mpi.
"""
from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ompi_tpu.core import op as op_mod
from ompi_tpu.core.errhandler import ERR_ARG, MPIError
from ompi_tpu.osc.framework import Win


class ShmemCtx:
    """A SHMEM context: ``n_pes`` processing elements over a
    communicator, one symmetric heap of ``heap_size`` elements."""

    def __init__(self, comm, heap_size: int = 1 << 16, dtype=np.float32):
        self.comm = comm
        self.heap = Win(comm, heap_size, dtype=dtype, name="symheap")
        self._brk = 0
        self.heap_size = heap_size

    # -- setup (shmem_init / shmem_my_pe / shmem_n_pes) ----------------
    @property
    def n_pes(self) -> int:
        return self.comm.size

    def malloc(self, nelems: int) -> int:
        """shmem_malloc: symmetric allocation — returns the symmetric
        offset, identical on every PE (memheap buddy allocator's job;
        a bump allocator suffices for the controller)."""
        if self._brk + nelems > self.heap_size:
            raise MPIError(ERR_ARG, "symmetric heap exhausted")
        addr = self._brk
        self._brk += nelems
        return addr

    def free(self, addr: int) -> None:
        pass                        # bump allocator: no-op (like reset-free)

    # -- RMA (spml put/get) --------------------------------------------
    def put(self, dest_pe: int, addr: int, data) -> None:
        """shmem_put: deliver ``data`` into dest_pe's heap at ``addr``."""
        self.heap.put(np.asarray(data), dest_pe, addr)

    def get(self, src_pe: int, addr: int, nelems: int):
        return self.heap.get(src_pe, addr, nelems)

    def p(self, dest_pe: int, addr: int, value) -> None:
        self.put(dest_pe, addr, np.asarray([value]))

    def g(self, src_pe: int, addr: int):
        return self.get(src_pe, addr, 1)[0]

    # -- atomics (oshmem/mca/atomic) -----------------------------------
    def atomic_add(self, dest_pe: int, addr: int, value) -> None:
        self.heap.accumulate(np.asarray([value]), dest_pe, op_mod.SUM, addr)

    def atomic_fetch_add(self, dest_pe: int, addr: int, value):
        return self.heap.fetch_and_op(value, dest_pe, op_mod.SUM, addr)

    def atomic_compare_swap(self, dest_pe: int, addr: int, cond, value):
        return self.heap.compare_and_swap(value, cond, dest_pe, addr)

    # -- ordering / completion -----------------------------------------
    def fence(self) -> None:
        self.heap.flush_all()

    def quiet(self) -> None:
        self.heap.flush_all()

    # -- collectives (scoll; delegate to coll like scoll/mpi) ----------
    def barrier_all(self) -> None:
        self.comm.barrier()

    def broadcast(self, addr: int, nelems: int, root_pe: int) -> None:
        data = self.get(root_pe, addr, nelems)
        for pe in range(self.n_pes):
            if pe != root_pe:
                self.put(pe, addr, data)

    def collect(self, addr: int, nelems: int):
        """fcollect: concatenation of every PE's segment, symmetric
        result returned (host array)."""
        return np.concatenate([self.get(pe, addr, nelems)
                               for pe in range(self.n_pes)])

    def reduce(self, addr: int, nelems: int,
               op: op_mod.Op = op_mod.SUM) -> None:
        """to_all reduction over all PEs' segments; result written back
        symmetrically."""
        acc: Optional[Any] = None
        for pe in range(self.n_pes):
            seg = self.get(pe, addr, nelems)
            acc = seg if acc is None else np.asarray(op.fn(acc, seg))
        for pe in range(self.n_pes):
            self.put(pe, addr, acc)
