"""OSHMEM-lite: the OpenSHMEM programming model over the framework.

Behavioral spec: ``oshmem/`` — symmetric heap (memheap), put/get with
remote completion (spml, ``oshmem/mca/spml/spml.h:229-330``), atomics,
and collectives (scoll; scoll/mpi delegates to the MPI coll stack, which
is exactly what this does).

TPU-native re-design: the symmetric heap is one RMA window per context —
every PE's heap is a shard row, so a "symmetric address" is a plain
offset valid on all PEs (symmetry by construction, no address exchange
needed). ``put``/``get``/atomics are window ops (HBM shard updates);
``barrier_all``/``broadcast``/``collect``/reductions delegate to the
coll framework like scoll/mpi.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ompi_tpu.core import op as op_mod
from ompi_tpu.core.errhandler import ERR_ARG, ERR_PENDING, MPIError
from ompi_tpu.osc.framework import Win

# shmem_wait_until / shmem_test comparison constants
# (oshmem/include/shmem.h SHMEM_CMP_*).
CMP_EQ, CMP_NE, CMP_GT, CMP_LE, CMP_LT, CMP_GE = range(6)

_CMP_FNS = {
    CMP_EQ: lambda a, b: a == b,
    CMP_NE: lambda a, b: a != b,
    CMP_GT: lambda a, b: a > b,
    CMP_LE: lambda a, b: a <= b,
    CMP_LT: lambda a, b: a < b,
    CMP_GE: lambda a, b: a >= b,
}

# shmem_put_signal signal operations (SHMEM_SIGNAL_SET / _ADD).
SIGNAL_SET = 0
SIGNAL_ADD = 1


class ShmemCtx:
    """A SHMEM context: ``n_pes`` processing elements over a
    communicator, one symmetric heap of ``heap_size`` elements."""

    def __init__(self, comm, heap_size: int = 1 << 16, dtype=np.float32):
        self.comm = comm
        self.heap = Win(comm, heap_size, dtype=dtype, name="symheap")
        self._brk = 0
        self.heap_size = heap_size
        # Buddy allocator (C++ — the memheap/buddy component role) when
        # the native library is available; bump-allocator fallback. The
        # buddy system manages exactly 2^k elements, so it only serves
        # power-of-two heaps — any other size would either truncate the
        # window or hand out offsets beyond it.
        from ompi_tpu.native import get_lib
        self._lib = get_lib()
        self._live: dict = {}            # offset -> nelems (memheap
        #                                  allocation metadata)
        self._buddy = -1
        if (self._lib is not None and heap_size > 0
                and heap_size & (heap_size - 1) == 0):
            max_order = heap_size.bit_length() - 1
            self._buddy = self._lib.ompi_tpu_buddy_create(max_order, 0)

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_buddy", -1)
        if lib is not None and h >= 0:
            try:
                lib.ompi_tpu_buddy_destroy(h)
            except Exception:
                pass

    # -- setup (shmem_init / shmem_my_pe / shmem_n_pes) ----------------
    @property
    def n_pes(self) -> int:
        return self.comm.size

    def malloc(self, nelems: int) -> int:
        """shmem_malloc: symmetric allocation — returns the symmetric
        offset, identical on every PE. Served by the native buddy
        allocator (oshmem/mca/memheap/buddy role: power-of-two blocks,
        split/coalesce), falling back to a bump allocator. Live sizes
        are tracked host-side (the memheap metadata) so realloc/free
        know block extents on either path."""
        if self._buddy >= 0:
            addr = self._lib.ompi_tpu_buddy_alloc(self._buddy, nelems)
            if addr < 0:
                raise MPIError(ERR_ARG, "symmetric heap exhausted")
            self._live[int(addr)] = nelems
            return int(addr)
        if self._brk + nelems > self.heap_size:
            raise MPIError(ERR_ARG, "symmetric heap exhausted")
        addr = self._brk
        self._brk += nelems
        self._live[addr] = nelems
        return addr

    def free(self, addr: int) -> None:
        """shmem_free: returns the block to the buddy allocator (no-op
        on the bump fallback)."""
        if self._live.pop(addr, None) is None:
            raise MPIError(ERR_ARG,
                           f"shmem_free: invalid or double free at "
                           f"offset {addr}")
        if self._buddy >= 0:
            rc = self._lib.ompi_tpu_buddy_free(self._buddy, addr)
            if rc != 0:
                raise MPIError(ERR_ARG,
                               f"shmem_free: invalid or double free at "
                               f"offset {addr}")

    def align(self, alignment: int, nelems: int) -> int:
        """shmem_align: allocation whose symmetric offset is a multiple
        of ``alignment`` (elements). The buddy allocator's power-of-two
        blocks are naturally size-aligned; the bump path pads."""
        if alignment <= 0 or alignment & (alignment - 1):
            raise MPIError(ERR_ARG, "alignment must be a power of two")
        if self._buddy >= 0:
            # buddy blocks of 2^k elements sit at 2^k-aligned offsets:
            # request a block at least max(alignment, nelems)
            want = max(alignment, nelems)
            addr = self.malloc(want)
            return addr
        pad = (-self._brk) % alignment
        if self._brk + pad + nelems > self.heap_size:
            raise MPIError(ERR_ARG, "symmetric heap exhausted")
        self._brk += pad
        return self.malloc(nelems)

    def calloc(self, count: int) -> int:
        """shmem_calloc: zero-initialized symmetric allocation (a
        recycled block may carry stale content)."""
        addr = self.malloc(count)
        zero = np.zeros(count, dtype=self.heap.dtype)
        for pe in range(self.n_pes):
            self.put(pe, addr, zero)
        return addr

    def realloc(self, addr: int, nelems: int) -> int:
        """shmem_realloc: symmetric resize — every PE's content (up to
        the smaller extent) moves to the new block."""
        old = self._live.get(addr)
        if old is None:
            raise MPIError(ERR_ARG,
                           f"shmem_realloc: offset {addr} is not a "
                           f"live allocation")
        new = self.malloc(nelems)
        keep = min(old, nelems)
        for pe in range(self.n_pes):
            self.put(pe, new, self.get(pe, addr, keep))
        self.free(addr)
        return new

    # -- RMA (spml put/get) --------------------------------------------
    def put(self, dest_pe: int, addr: int, data) -> None:
        """shmem_put: deliver ``data`` into dest_pe's heap at ``addr``."""
        self.heap.put(np.asarray(data), dest_pe, addr)

    def get(self, src_pe: int, addr: int, nelems: int):
        return self.heap.get(src_pe, addr, nelems)

    def p(self, dest_pe: int, addr: int, value) -> None:
        self.put(dest_pe, addr, np.asarray([value]))

    def g(self, src_pe: int, addr: int):
        return self.get(src_pe, addr, 1)[0]

    # Nonblocking-implicit variants (shmem_put_nbi / shmem_get_nbi):
    # completion is deferred to quiet(). Device puts complete at XLA
    # dispatch here, so these alias the blocking calls — the contract
    # (result not guaranteed until quiet) still holds.
    def put_nbi(self, dest_pe: int, addr: int, data) -> None:
        self.put(dest_pe, addr, data)

    def get_nbi(self, src_pe: int, addr: int, nelems: int):
        return self.get(src_pe, addr, nelems)

    def iput(self, dest_pe: int, addr: int, data, tst: int = 1,
             sst: int = 1) -> None:
        """shmem_iput: strided put — element i of the (source-strided)
        ``data`` lands at ``addr + i*tst`` on the target. Assembled
        host-side (holes keep their current content) and written with
        one put, not one put per element."""
        src = np.asarray(data)[::sst]
        n = len(src)
        if n == 0:
            return
        span = (n - 1) * tst + 1
        row = np.array(self.get(dest_pe, addr, span))
        row[::tst] = src
        self.put(dest_pe, addr, row)

    def iget(self, src_pe: int, addr: int, nelems: int,
             tst: int = 1, sst: int = 1):
        """shmem_iget: strided get — reads ``nelems`` elements from
        ``addr, addr+sst, ...`` and returns them laid out as the local
        target buffer would be: element i at index ``i*tst`` (holes
        zero-filled), exactly mirroring iput's target stride."""
        vals = [self.g(src_pe, addr + i * sst) for i in range(nelems)]
        out = np.zeros((nelems - 1) * tst + 1 if nelems else 0,
                       dtype=np.asarray(vals).dtype if vals else float)
        out[::tst] = vals
        return out

    def ptr(self, pe: int):
        """shmem_ptr: direct load/store access to ``pe``'s heap segment.
        The heap row is an immutable HBM shard, so this returns a host
        snapshot (reads are direct; stores must go through put — the
        same degradation shmem_ptr has on non-shared-memory PEs, where
        it returns NULL and callers fall back to put/get)."""
        return self.get(pe, 0, self.heap_size)

    # -- pt2pt synchronization (shmem_wait_until / shmem_test) ---------
    def test(self, pe: int, addr: int, cmp: int, value) -> bool:
        """shmem_test: does PE ``pe``'s heap word at ``addr`` satisfy
        the comparison now?"""
        fn = _CMP_FNS.get(cmp)
        if fn is None:
            raise MPIError(ERR_ARG, f"bad SHMEM_CMP constant: {cmp}")
        return bool(fn(self.g(pe, addr), value))

    def wait_until(self, pe: int, addr: int, cmp: int, value) -> None:
        """shmem_wait_until. Single-controller: no other thread can
        change the heap while we block, so an unsatisfied wait is a
        deadlock — surfaced, like the matching engine does."""
        if not self.test(pe, addr, cmp, value):
            raise MPIError(
                ERR_PENDING,
                "shmem_wait_until would deadlock: condition is not "
                "satisfied and no concurrent producer exists "
                "(single-controller: perform the put first)")

    # -- signaling (shmem_put_signal, SHMEM 1.5) -----------------------
    def put_signal(self, dest_pe: int, addr: int, data, sig_addr: int,
                   signal, sig_op: int = SIGNAL_SET) -> None:
        """shmem_put_signal: deliver ``data`` then update the signal
        word at ``sig_addr`` (SET or ADD) — delivery ordering (payload
        visible before signal) is by construction here."""
        self.put(dest_pe, addr, data)
        if sig_op == SIGNAL_ADD:
            self.atomic_add(dest_pe, sig_addr, signal)
        else:
            self.atomic_set(dest_pe, sig_addr, signal)

    def signal_fetch(self, pe: int, sig_addr: int):
        """shmem_signal_fetch."""
        return self.g(pe, sig_addr)

    def signal_wait_until(self, pe: int, sig_addr: int, cmp: int,
                          value) -> None:
        self.wait_until(pe, sig_addr, cmp, value)

    # -- distributed locks (shmem_set_lock / test / clear) -------------
    def set_lock(self, addr: int, pe: int = 0) -> None:
        """shmem_set_lock: acquire the lock at symmetric ``addr`` on
        behalf of PE ``pe``. Held-lock acquisition is a deadlock in a
        single-controller world and is surfaced."""
        if not self.test_lock(addr, pe):
            raise MPIError(
                ERR_PENDING,
                f"shmem_set_lock would deadlock: lock at offset {addr} "
                f"is already held")

    def test_lock(self, addr: int, pe: int = 0) -> bool:
        """shmem_test_lock: try-acquire; True on success. Implemented
        as compare-and-swap 0 -> pe+1 on the lock word at PE 0's heap
        (the lock-owner PE in OpenSHMEM's algorithm)."""
        prev = self.atomic_compare_swap(0, addr, 0, pe + 1)
        return int(prev) == 0

    def clear_lock(self, addr: int, pe: int = 0) -> None:
        """shmem_clear_lock: release (must hold it)."""
        prev = self.atomic_compare_swap(0, addr, pe + 1, 0)
        if int(prev) != pe + 1:
            raise MPIError(ERR_ARG,
                           f"shmem_clear_lock: PE {pe} does not hold "
                           f"the lock at offset {addr}")

    # -- atomics (oshmem/mca/atomic) -----------------------------------
    def atomic_set(self, dest_pe: int, addr: int, value) -> None:
        self.p(dest_pe, addr, value)

    def atomic_fetch(self, src_pe: int, addr: int):
        return self.g(src_pe, addr)

    def atomic_swap(self, dest_pe: int, addr: int, value):
        return self.heap.fetch_and_op(value, dest_pe, op_mod.REPLACE, addr)

    def atomic_add(self, dest_pe: int, addr: int, value) -> None:
        self.heap.accumulate(np.asarray([value]), dest_pe, op_mod.SUM, addr)

    def atomic_fetch_add(self, dest_pe: int, addr: int, value):
        return self.heap.fetch_and_op(value, dest_pe, op_mod.SUM, addr)

    def atomic_compare_swap(self, dest_pe: int, addr: int, cond, value):
        return self.heap.compare_and_swap(value, cond, dest_pe, addr)

    def atomic_inc(self, dest_pe: int, addr: int) -> None:
        """shmem_atomic_inc (shmem_inc.c)."""
        self.atomic_add(dest_pe, addr, 1)

    def atomic_fetch_inc(self, dest_pe: int, addr: int):
        """shmem_atomic_fetch_inc (shmem_finc.c)."""
        return self.atomic_fetch_add(dest_pe, addr, 1)

    # bitwise AMOs (shmem_and/or/xor.c + fetching shmem_f{and,or,xor}.c)
    def atomic_and(self, dest_pe: int, addr: int, value) -> None:
        self.heap.accumulate(np.asarray([value]), dest_pe, op_mod.BAND,
                             addr)

    def atomic_or(self, dest_pe: int, addr: int, value) -> None:
        self.heap.accumulate(np.asarray([value]), dest_pe, op_mod.BOR,
                             addr)

    def atomic_xor(self, dest_pe: int, addr: int, value) -> None:
        self.heap.accumulate(np.asarray([value]), dest_pe, op_mod.BXOR,
                             addr)

    def atomic_fetch_and(self, dest_pe: int, addr: int, value):
        return self.heap.fetch_and_op(value, dest_pe, op_mod.BAND, addr)

    def atomic_fetch_or(self, dest_pe: int, addr: int, value):
        return self.heap.fetch_and_op(value, dest_pe, op_mod.BOR, addr)

    def atomic_fetch_xor(self, dest_pe: int, addr: int, value):
        return self.heap.fetch_and_op(value, dest_pe, op_mod.BXOR, addr)

    # -- accessibility / introspection ---------------------------------
    def pe_accessible(self, pe: int) -> bool:
        """shmem_pe_accessible.c: is ``pe`` a reachable PE?"""
        return 0 <= pe < self.n_pes

    def addr_accessible(self, addr: int, pe: int) -> bool:
        """shmem_addr_accessible.c: is the symmetric offset valid on
        ``pe``'s heap? (symmetry by construction: one bound check)"""
        return self.pe_accessible(pe) and 0 <= addr < self.heap_size

    @staticmethod
    def info_get_version():
        """shmem_info.c: the OpenSHMEM spec level implemented."""
        return (1, 5)

    @staticmethod
    def info_get_name() -> str:
        return "ompi_tpu-OpenSHMEM"

    @staticmethod
    def pcontrol(level: int = 1) -> None:
        """shmem_pcontrol.c: profiling control — recorded as an SPC
        event (the reference's hook point for PMPI-style tools)."""
        from ompi_tpu.runtime import spc
        spc.record("shmem_pcontrol", int(level))

    def global_exit(self, status: int = 0) -> None:
        """shmem_global_exit.c: terminate ALL PEs. Single-controller:
        every PE lives in this process — one SystemExit is the whole
        job."""
        raise SystemExit(status)

    # deprecated cache-management entry points (shmem_*cache*.c,
    # shmem_udcflush*.c): kept callable, documented no-ops — exactly
    # the reference's status for them since OpenSHMEM 1.3
    def clear_cache_inv(self) -> None:
        pass

    def set_cache_inv(self) -> None:
        pass

    def udcflush(self) -> None:
        pass

    # -- multi-variable sync (shmem_{test,wait}_ivars.c, SHMEM 1.4) ----
    def _ivar_state(self, pe: int, addrs, cmp: int, value):
        fn = _CMP_FNS.get(cmp)
        if fn is None:
            raise MPIError(ERR_ARG, f"bad SHMEM_CMP constant: {cmp}")
        return [bool(fn(self.g(pe, a), value)) for a in addrs]

    def test_all(self, pe: int, addrs, cmp: int, value) -> bool:
        return all(self._ivar_state(pe, addrs, cmp, value))

    def test_any(self, pe: int, addrs, cmp: int, value):
        """Index of ANY satisfied variable, or None."""
        st = self._ivar_state(pe, addrs, cmp, value)
        return st.index(True) if True in st else None

    def test_some(self, pe: int, addrs, cmp: int, value):
        """Indices of every satisfied variable (possibly empty)."""
        st = self._ivar_state(pe, addrs, cmp, value)
        return [i for i, ok in enumerate(st) if ok]

    def wait_until_all(self, pe: int, addrs, cmp: int, value) -> None:
        """Single-controller: like wait_until, an unsatisfied wait has
        no concurrent producer and is surfaced as the deadlock it is."""
        if not self.test_all(pe, addrs, cmp, value):
            raise MPIError(ERR_PENDING,
                           "shmem_wait_until_all would deadlock: "
                           "conditions unsatisfied with no concurrent "
                           "producer (perform the puts first)")

    def wait_until_any(self, pe: int, addrs, cmp: int, value) -> int:
        got = self.test_any(pe, addrs, cmp, value)
        if got is None:
            raise MPIError(ERR_PENDING,
                           "shmem_wait_until_any would deadlock")
        return got

    def wait_until_some(self, pe: int, addrs, cmp: int, value):
        got = self.test_some(pe, addrs, cmp, value)
        if not got:
            raise MPIError(ERR_PENDING,
                           "shmem_wait_until_some would deadlock")
        return got

    # -- ordering / completion -----------------------------------------
    def fence(self) -> None:
        self.heap.flush_all()

    def quiet(self) -> None:
        self.heap.flush_all()

    # -- collectives (scoll; delegate to coll like scoll/mpi) ----------
    def barrier_all(self) -> None:
        self.comm.barrier()

    def sync_all(self) -> None:
        """shmem_sync.c: barrier WITHOUT the implied quiet (no
        completion of pending puts) — pure arrival synchronization."""
        self.comm.barrier()

    def barrier(self, start: int, log_stride: int, size: int) -> None:
        """Active-set barrier (the pre-teams shmem_barrier.c calling
        convention): PEs {start + i*2^log_stride : i < size}. Includes
        the implied quiet, then synchronizes the strided team."""
        self.quiet()
        self.team_world().split_strided(start, 1 << log_stride,
                                        size).sync()

    def broadcast(self, addr: int, nelems: int, root_pe: int) -> None:
        self.team_world().broadcast(addr, nelems, root_pe)

    def collect(self, addr: int, nelems: int):
        """fcollect: concatenation of every PE's segment, symmetric
        result returned (host array)."""
        return np.concatenate([self.get(pe, addr, nelems)
                               for pe in range(self.n_pes)])

    def reduce(self, addr: int, nelems: int,
               op: op_mod.Op = op_mod.SUM) -> None:
        """to_all reduction over all PEs' segments; result written back
        symmetrically."""
        self.team_world().reduce(addr, nelems, op)

    def alltoall(self, addr: int, nelems: int) -> None:
        """shmem_alltoall: PE i's j-th ``nelems`` block lands in PE j's
        segment at block i (symmetric, in place in the heap)."""
        blocks = [self.get(pe, addr, nelems * self.n_pes)
                  for pe in range(self.n_pes)]
        for j in range(self.n_pes):
            out = np.concatenate([
                blocks[i][j * nelems:(j + 1) * nelems]
                for i in range(self.n_pes)])
            self.put(j, addr, out)

    def alltoalls(self, addr: int, nelems: int, dst: int = 1,
                  sst: int = 1) -> None:
        """shmem_alltoalls: strided alltoall — PE i's block j is read
        with source stride ``sst`` and written into PE j's segment with
        destination stride ``dst`` at block i."""
        n = self.n_pes
        span_src = nelems * sst * n
        blocks = [self.get(pe, addr, span_src) for pe in range(n)]
        span_dst = (n * nelems - 1) * dst + 1
        for j in range(n):
            # Assemble the whole destination row host-side (holes keep
            # their current content) and write it with ONE put — the
            # bulk pattern alltoall uses, not n*nelems single-element
            # puts.
            row = np.array(self.get(j, addr, span_dst))
            for i in range(n):
                seg = blocks[i][j * nelems * sst:
                                (j + 1) * nelems * sst:sst]
                base = i * nelems * dst
                row[base:base + (nelems - 1) * dst + 1:dst] = seg
            self.put(j, addr, row)

    def fcollect(self, addr: int, nelems: int):
        """shmem_fcollect: fixed-size concatenation (alias of collect
        with uniform block size)."""
        return self.collect(addr, nelems)

    def collect_varying(self, addr: int, nelems_per_pe: List[int]):
        """shmem_collect: concatenation with per-PE block sizes (the
        varying-nelems form the f-variant fixes)."""
        return np.concatenate([self.get(pe, addr, int(ne))
                               for pe, ne in enumerate(nelems_per_pe)])

    # Named to_all reductions (shmem_<type>_<op>_to_all surface).
    def sum_to_all(self, addr, nelems):
        self.reduce(addr, nelems, op_mod.SUM)

    def prod_to_all(self, addr, nelems):
        self.reduce(addr, nelems, op_mod.PROD)

    def max_to_all(self, addr, nelems):
        self.reduce(addr, nelems, op_mod.MAX)

    def min_to_all(self, addr, nelems):
        self.reduce(addr, nelems, op_mod.MIN)

    def and_to_all(self, addr, nelems):
        self.reduce(addr, nelems, op_mod.BAND)

    def or_to_all(self, addr, nelems):
        self.reduce(addr, nelems, op_mod.BOR)

    def xor_to_all(self, addr, nelems):
        self.reduce(addr, nelems, op_mod.BXOR)

    # -- contexts (shmem_ctx_create, SHMEM 1.4) ------------------------
    def ctx_create(self) -> "ShmemCommCtx":
        """shmem_ctx_create: an independent ordering stream over the
        same heap (its quiet orders only its own operations)."""
        return ShmemCommCtx(self)

    # -- teams (spml teams, oshmem/mca/spml/spml.h:689-784) -------------
    def team_world(self) -> "ShmemTeam":
        return ShmemTeam(self, list(range(self.n_pes)))


class ShmemCommCtx:
    """A communication context (``shmem_ctx_t``): put/get/atomics
    delegated to the parent heap, with an independent completion scope —
    ``quiet`` orders only operations issued through this context (the
    contexts framework's purpose; here each op completes at issue, so
    the scope is trivially satisfied, but the op count makes the scope
    observable/testable)."""

    def __init__(self, parent: ShmemCtx):
        self.parent = parent
        self.pending_ops = 0

    def put(self, dest_pe: int, addr: int, data) -> None:
        self.parent.put(dest_pe, addr, data)
        self.pending_ops += 1

    def get(self, src_pe: int, addr: int, nelems: int):
        self.pending_ops += 1
        return self.parent.get(src_pe, addr, nelems)

    def atomic_add(self, dest_pe: int, addr: int, value) -> None:
        self.parent.atomic_add(dest_pe, addr, value)
        self.pending_ops += 1

    def quiet(self) -> None:
        self.parent.quiet()
        self.pending_ops = 0

    def fence(self) -> None:
        self.parent.fence()

    def destroy(self) -> None:
        self.quiet()


class ShmemTeam:
    """A SHMEM team: an ordered PE subset with its own collectives —
    backed by a sub-communicator (mesh subset), the way OpenSHMEM teams
    sit over process groups (``spml.h:689-784`` team create/translate).
    """

    def __init__(self, ctx: ShmemCtx, pes: list):
        self.ctx = ctx
        self.pes = list(pes)

    @property
    def n_pes(self) -> int:
        return len(self.pes)

    def translate_pe(self, pe: int, dest: "ShmemTeam") -> int:
        """shmem_team_translate_pe: this team's ``pe`` in ``dest``'s
        numbering (-1 if absent)."""
        world_pe = self.pes[pe]
        try:
            return dest.pes.index(world_pe)
        except ValueError:
            return -1

    def split_strided(self, start: int, stride: int,
                      size: int) -> "ShmemTeam":
        """shmem_team_split_strided over this team's numbering."""
        sel = [self.pes[start + i * stride] for i in range(size)]
        return ShmemTeam(self.ctx, sel)

    def split_2d(self, xrange: int):
        """shmem_team_split_2d: (x, y) sub-teams of an xrange-wide grid."""
        xs = [ShmemTeam(self.ctx, self.pes[i:i + xrange])
              for i in range(0, self.n_pes, xrange)]
        ys = [ShmemTeam(self.ctx, self.pes[i::xrange])
              for i in range(min(xrange, self.n_pes))]
        return xs, ys

    def sync(self) -> None:
        """shmem_team_sync: order heap updates across the team."""
        self.ctx.heap.flush_all()

    def broadcast(self, addr: int, nelems: int, root_pe: int) -> None:
        """Team broadcast: ``root_pe`` in team numbering."""
        data = self.ctx.get(self.pes[root_pe], addr, nelems)
        for pe in self.pes:
            if pe != self.pes[root_pe]:
                self.ctx.put(pe, addr, data)

    def reduce(self, addr: int, nelems: int,
               op: op_mod.Op = op_mod.SUM) -> None:
        acc: Optional[Any] = None
        for pe in self.pes:
            seg = self.ctx.get(pe, addr, nelems)
            acc = seg if acc is None else np.asarray(op.fn(acc, seg))
        for pe in self.pes:
            self.ctx.put(pe, addr, acc)
