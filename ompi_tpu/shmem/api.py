"""OSHMEM-lite: the OpenSHMEM programming model over the framework.

Behavioral spec: ``oshmem/`` — symmetric heap (memheap), put/get with
remote completion (spml, ``oshmem/mca/spml/spml.h:229-330``), atomics,
and collectives (scoll; scoll/mpi delegates to the MPI coll stack, which
is exactly what this does).

TPU-native re-design: the symmetric heap is one RMA window per context —
every PE's heap is a shard row, so a "symmetric address" is a plain
offset valid on all PEs (symmetry by construction, no address exchange
needed). ``put``/``get``/atomics are window ops (HBM shard updates);
``barrier_all``/``broadcast``/``collect``/reductions delegate to the
coll framework like scoll/mpi.
"""
from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ompi_tpu.core import op as op_mod
from ompi_tpu.core.errhandler import ERR_ARG, MPIError
from ompi_tpu.osc.framework import Win


class ShmemCtx:
    """A SHMEM context: ``n_pes`` processing elements over a
    communicator, one symmetric heap of ``heap_size`` elements."""

    def __init__(self, comm, heap_size: int = 1 << 16, dtype=np.float32):
        self.comm = comm
        self.heap = Win(comm, heap_size, dtype=dtype, name="symheap")
        self._brk = 0
        self.heap_size = heap_size
        # Buddy allocator (C++ — the memheap/buddy component role) when
        # the native library is available; bump-allocator fallback. The
        # buddy system manages exactly 2^k elements, so it only serves
        # power-of-two heaps — any other size would either truncate the
        # window or hand out offsets beyond it.
        from ompi_tpu.native import get_lib
        self._lib = get_lib()
        self._buddy = -1
        if (self._lib is not None and heap_size > 0
                and heap_size & (heap_size - 1) == 0):
            max_order = heap_size.bit_length() - 1
            self._buddy = self._lib.ompi_tpu_buddy_create(max_order, 0)

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_buddy", -1)
        if lib is not None and h >= 0:
            try:
                lib.ompi_tpu_buddy_destroy(h)
            except Exception:
                pass

    # -- setup (shmem_init / shmem_my_pe / shmem_n_pes) ----------------
    @property
    def n_pes(self) -> int:
        return self.comm.size

    def malloc(self, nelems: int) -> int:
        """shmem_malloc: symmetric allocation — returns the symmetric
        offset, identical on every PE. Served by the native buddy
        allocator (oshmem/mca/memheap/buddy role: power-of-two blocks,
        split/coalesce), falling back to a bump allocator."""
        if self._buddy >= 0:
            addr = self._lib.ompi_tpu_buddy_alloc(self._buddy, nelems)
            if addr < 0:
                raise MPIError(ERR_ARG, "symmetric heap exhausted")
            return int(addr)
        if self._brk + nelems > self.heap_size:
            raise MPIError(ERR_ARG, "symmetric heap exhausted")
        addr = self._brk
        self._brk += nelems
        return addr

    def free(self, addr: int) -> None:
        """shmem_free: returns the block to the buddy allocator (no-op
        on the bump fallback)."""
        if self._buddy >= 0:
            rc = self._lib.ompi_tpu_buddy_free(self._buddy, addr)
            if rc != 0:
                raise MPIError(ERR_ARG,
                               f"shmem_free: invalid or double free at "
                               f"offset {addr}")

    # -- RMA (spml put/get) --------------------------------------------
    def put(self, dest_pe: int, addr: int, data) -> None:
        """shmem_put: deliver ``data`` into dest_pe's heap at ``addr``."""
        self.heap.put(np.asarray(data), dest_pe, addr)

    def get(self, src_pe: int, addr: int, nelems: int):
        return self.heap.get(src_pe, addr, nelems)

    def p(self, dest_pe: int, addr: int, value) -> None:
        self.put(dest_pe, addr, np.asarray([value]))

    def g(self, src_pe: int, addr: int):
        return self.get(src_pe, addr, 1)[0]

    # -- atomics (oshmem/mca/atomic) -----------------------------------
    def atomic_set(self, dest_pe: int, addr: int, value) -> None:
        self.p(dest_pe, addr, value)

    def atomic_fetch(self, src_pe: int, addr: int):
        return self.g(src_pe, addr)

    def atomic_swap(self, dest_pe: int, addr: int, value):
        return self.heap.fetch_and_op(value, dest_pe, op_mod.REPLACE, addr)

    def atomic_add(self, dest_pe: int, addr: int, value) -> None:
        self.heap.accumulate(np.asarray([value]), dest_pe, op_mod.SUM, addr)

    def atomic_fetch_add(self, dest_pe: int, addr: int, value):
        return self.heap.fetch_and_op(value, dest_pe, op_mod.SUM, addr)

    def atomic_compare_swap(self, dest_pe: int, addr: int, cond, value):
        return self.heap.compare_and_swap(value, cond, dest_pe, addr)

    # -- ordering / completion -----------------------------------------
    def fence(self) -> None:
        self.heap.flush_all()

    def quiet(self) -> None:
        self.heap.flush_all()

    # -- collectives (scoll; delegate to coll like scoll/mpi) ----------
    def barrier_all(self) -> None:
        self.comm.barrier()

    def broadcast(self, addr: int, nelems: int, root_pe: int) -> None:
        self.team_world().broadcast(addr, nelems, root_pe)

    def collect(self, addr: int, nelems: int):
        """fcollect: concatenation of every PE's segment, symmetric
        result returned (host array)."""
        return np.concatenate([self.get(pe, addr, nelems)
                               for pe in range(self.n_pes)])

    def reduce(self, addr: int, nelems: int,
               op: op_mod.Op = op_mod.SUM) -> None:
        """to_all reduction over all PEs' segments; result written back
        symmetrically."""
        self.team_world().reduce(addr, nelems, op)

    def alltoall(self, addr: int, nelems: int) -> None:
        """shmem_alltoall: PE i's j-th ``nelems`` block lands in PE j's
        segment at block i (symmetric, in place in the heap)."""
        blocks = [self.get(pe, addr, nelems * self.n_pes)
                  for pe in range(self.n_pes)]
        for j in range(self.n_pes):
            out = np.concatenate([
                blocks[i][j * nelems:(j + 1) * nelems]
                for i in range(self.n_pes)])
            self.put(j, addr, out)

    # -- teams (spml teams, oshmem/mca/spml/spml.h:689-784) -------------
    def team_world(self) -> "ShmemTeam":
        return ShmemTeam(self, list(range(self.n_pes)))


class ShmemTeam:
    """A SHMEM team: an ordered PE subset with its own collectives —
    backed by a sub-communicator (mesh subset), the way OpenSHMEM teams
    sit over process groups (``spml.h:689-784`` team create/translate).
    """

    def __init__(self, ctx: ShmemCtx, pes: list):
        self.ctx = ctx
        self.pes = list(pes)

    @property
    def n_pes(self) -> int:
        return len(self.pes)

    def translate_pe(self, pe: int, dest: "ShmemTeam") -> int:
        """shmem_team_translate_pe: this team's ``pe`` in ``dest``'s
        numbering (-1 if absent)."""
        world_pe = self.pes[pe]
        try:
            return dest.pes.index(world_pe)
        except ValueError:
            return -1

    def split_strided(self, start: int, stride: int,
                      size: int) -> "ShmemTeam":
        """shmem_team_split_strided over this team's numbering."""
        sel = [self.pes[start + i * stride] for i in range(size)]
        return ShmemTeam(self.ctx, sel)

    def split_2d(self, xrange: int):
        """shmem_team_split_2d: (x, y) sub-teams of an xrange-wide grid."""
        xs = [ShmemTeam(self.ctx, self.pes[i:i + xrange])
              for i in range(0, self.n_pes, xrange)]
        ys = [ShmemTeam(self.ctx, self.pes[i::xrange])
              for i in range(min(xrange, self.n_pes))]
        return xs, ys

    def sync(self) -> None:
        """shmem_team_sync: order heap updates across the team."""
        self.ctx.heap.flush_all()

    def broadcast(self, addr: int, nelems: int, root_pe: int) -> None:
        """Team broadcast: ``root_pe`` in team numbering."""
        data = self.ctx.get(self.pes[root_pe], addr, nelems)
        for pe in self.pes:
            if pe != self.pes[root_pe]:
                self.ctx.put(pe, addr, data)

    def reduce(self, addr: int, nelems: int,
               op: op_mod.Op = op_mod.SUM) -> None:
        acc: Optional[Any] = None
        for pe in self.pes:
            seg = self.ctx.get(pe, addr, nelems)
            acc = seg if acc is None else np.asarray(op.fn(acc, seg))
        for pe in self.pes:
            self.ctx.put(pe, addr, acc)
