"""ompi_tpu — a TPU-native communication framework with MPI semantics.

A brand-new design with the capabilities of Open MPI (reference:
``lukebest/ompi``): communicators, groups, datatypes, reduction ops,
blocking/nonblocking collectives, point-to-point and one-sided
communication — whose operations on TPU-resident (HBM) buffers lower to
XLA collective ops (``psum``, ``all_gather``, ``all_to_all``,
``ppermute``) executed over the ICI mesh, instead of being staged to host
and pushed through a byte-transport stack.

Architecture (conceptual boundaries mirrored from the reference's MCA,
re-designed TPU-first):

- ``ompi_tpu.mca``      — framework/component machinery + typed config
  ("MCA vars": env < file < CLI precedence, source tracking), mirroring
  ``opal/mca/base`` (reference ``opal/mca/base/mca_base_var.c``).
- ``ompi_tpu.core``     — communicators/groups/datatypes/ops/requests,
  mirroring ``ompi/{communicator,group,datatype,op,request}``.
- ``ompi_tpu.coll``     — collective framework with priority-selected
  components (xla-native, basic/host, tuned decision layer), mirroring
  ``ompi/mca/coll``.
- ``ompi_tpu.accelerator`` — device-memory abstraction (buffer locus,
  H2D/D2H staging, async events), mirroring ``opal/mca/accelerator``.
- ``ompi_tpu.runtime``  — init/finalize, device-mesh world binding,
  progress engine, SPC counters, mirroring ``ompi/runtime`` + ``opal/runtime``.

Execution model: single-controller SPMD. An MPI "rank" is a coordinate on
a ``jax.sharding.Mesh``; a rank's local buffer is one shard of a stacked
``jax.Array`` of shape ``(nranks, *local_shape)`` sharded along axis 0.
Collectives compile (once, cached) to one SPMD program over the
communicator's mesh — data moves over ICI, never through host.
"""

from ompi_tpu.api.mpi import (  # noqa: F401
    # constants
    IN_PLACE, UNDEFINED, ANY_SOURCE, ANY_TAG, PROC_NULL, ROOT, KEYVAL_INVALID,
    SUCCESS, ERR_COMM, ERR_TYPE, ERR_OP, ERR_ARG, ERR_COUNT, ERR_BUFFER,
    ERR_RANK, ERR_ROOT, ERR_TRUNCATE, ERR_PENDING, ERR_REVOKED, ERR_PROC_FAILED,
    ERR_WIN, ERR_BASE, ERR_LOCKTYPE, ERR_RMA_CONFLICT, ERR_RMA_SYNC,
    CONGRUENT, IDENT, SIMILAR, UNEQUAL,
    THREAD_SINGLE, THREAD_FUNNELED, THREAD_SERIALIZED, THREAD_MULTIPLE,
    COMM_TYPE_SHARED, COMM_TYPE_HWTHREAD, COMM_TYPE_NUMA,
    MAX_ERROR_STRING, MAX_PROCESSOR_NAME,
    # datatypes
    FLOAT, DOUBLE, INT, LONG, CHAR, BYTE, SHORT, UNSIGNED, UNSIGNED_LONG,
    INT8_T, INT16_T, INT32_T, INT64_T, UINT8_T, UINT16_T, UINT32_T, UINT64_T,
    C_BOOL, FLOAT16, BFLOAT16, C_FLOAT_COMPLEX, C_DOUBLE_COMPLEX,
    FLOAT_INT, DOUBLE_INT, LONG_INT, SHORT_INT, TWOINT,
    Datatype,
    # ops
    SUM, PROD, MAX, MIN, LAND, LOR, LXOR, BAND, BOR, BXOR, MAXLOC, MINLOC,
    REPLACE, NO_OP, Op,
    # objects
    Communicator, Group, Request, Status, Errhandler, Info, Win,
    ERRORS_ARE_FATAL, ERRORS_RETURN, ERRORS_ABORT,
    MPIError,
    # lifecycle
    Init, Init_thread, Finalize, Initialized, Finalized, Abort,
    Query_thread, Get_processor_name, Wtime, Wtick, Get_version,
    get_comm_world, get_comm_self, COMM_NULL,
    # request completion + persistent start
    Wait, Test, Waitall, Waitany, Waitsome, Testall, Testany, Testsome,
    Start, Startall,
    # helpers
    op_create, create_keyval, free_keyval, error_string, from_numpy_dtype,
    Grequest, INFO_ENV, INFO_NULL,
    Get_library_version,
    # local reduction + pack/external32
    reduce_local, Pack, Unpack, Pack_external, Unpack_external, Pack_size,
    # dynamic process management (ompi/dpm)
    Intercomm, Intercomm_create,
    Open_port, Close_port, Publish_name, Lookup_name, Unpublish_name,
    Comm_accept, Comm_connect, Comm_iaccept, Comm_iconnect,
    Comm_spawn, Comm_spawn_multiple, Comm_get_parent, Comm_join,
    Comm_disconnect,
    # error handlers + ULFM resilience surface (mpiext/ftmpi)
    Comm_set_errhandler, Comm_get_errhandler, Comm_call_errhandler,
    MPIX_Comm_agree, MPIX_Comm_get_failed, MPIX_Comm_is_revoked,
    MPIX_Comm_revoke, MPIX_Comm_shrink,
)

__version__ = "0.1.0"
