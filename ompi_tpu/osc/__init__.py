from ompi_tpu.osc.framework import Win  # noqa: F401
