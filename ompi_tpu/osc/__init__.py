"""ompi_tpu.osc — the one-sided communication framework.

Two execution models, one chapter of the standard:

- ``framework.Win`` — the stacked single-controller window (every
  rank's region in one process);
- ``window.RmaWindow`` + ``win_allocate``/``win_create`` — the
  per-rank framework: component selection (``decision``), the shm
  segment component (``shm``), the active-message emulation
  (``pt2pt``), epoch/FT/telemetry policy (``window``, ``base``).
"""
from ompi_tpu.osc.framework import Win  # noqa: F401
from ompi_tpu.osc.window import (RmaWindow, win_allocate,  # noqa: F401
                                 win_create)
