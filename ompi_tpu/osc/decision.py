"""osc/decision — the component-selection step at window creation.

Mirrors ``coll/decision`` for the one-sided framework: every osc
component advertises a priority-like eligibility check, and window
creation runs ONE selection (``ompi_osc_base_select`` /
``osc_sm_component_query``'s "every rank on one node" probe) whose
outcome must agree on every rank of the communicator — the inputs are
the MCA var (same config on all ranks), the storage kind (collective
call signature) and the all-pairs same-host predicate (symmetric by
construction), so no extra agreement round is needed.

Outcomes:

- ``"shm"``   — every rank of the communicator shares this host and
  the execution model is per-rank: the window is a /dev/shm segment
  peers map directly (osc/sm's load/store RMA).
- ``"pt2pt"`` — remote-host peers, user-provided storage
  (``MPI_Win_create`` memory cannot be retroactively shm-backed), or
  a stacked single-controller communicator: the window is emulated
  over the acked active-message plane (the osc/rdma-over-pml shape).
"""
from __future__ import annotations

from ompi_tpu.core.errhandler import ERR_WIN, MPIError
from ompi_tpu.mca import var

from ompi_tpu.osc import base as _base

COMPONENTS = ("shm", "pt2pt")


def same_host(comm) -> bool:
    """True when every rank of ``comm`` shares this rank's host (the
    osc/sm eligibility probe). Symmetric across ranks: if any pair
    splits hosts, every rank sees a remote peer and answers False."""
    router = getattr(comm, "router", None)
    if router is None:
        return False
    ep = getattr(router, "endpoint", None)
    if ep is None:
        return False
    try:
        return all(ep._is_same_host(comm.world_rank_of(r))
                   for r in range(comm.size))
    except Exception:                    # noqa: BLE001 — unknown peer
        return False                     # topology: be conservative


def select(comm, storage=None, force=None) -> str:
    """One selection per window creation. ``force`` (tests, drills)
    overrides the MCA var; user ``storage`` pins pt2pt regardless —
    caller-owned memory cannot be exposed through a /dev/shm segment."""
    _base.register_params()
    choice = force or str(var.var_get("mpi_base_osc", "auto"))
    if choice not in ("auto",) + COMPONENTS:
        raise MPIError(ERR_WIN, f"unknown osc component {choice!r} "
                                f"(mpi_base_osc)")
    if storage is not None:
        if choice == "shm":
            raise MPIError(ERR_WIN,
                           "osc/shm cannot expose user-provided "
                           "window memory (MPI_Win_create storage "
                           "rides osc/pt2pt)")
        return "pt2pt"
    if choice == "shm":
        if not same_host(comm):
            raise MPIError(ERR_WIN,
                           "mpi_base_osc=shm forced but the "
                           "communicator spans hosts (or is not "
                           "per-rank)")
        return "shm"
    if choice == "pt2pt":
        return "pt2pt"
    return "shm" if same_host(comm) else "pt2pt"


def selection_table() -> dict:
    """Introspection for tools (mpitop / flightrec): the var, the
    component histogram so far, and the live open-epoch state."""
    _base.register_params()
    return {
        "var": str(var.var_get("mpi_base_osc", "auto")),
        "windows_shm": _base.stats["windows_shm"],
        "windows_pt2pt": _base.stats["windows_pt2pt"],
        "open_epochs": _base.open_epoch_state(),
    }
