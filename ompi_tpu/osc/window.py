"""osc/window — the framework window: selection, epochs, instruments.

``RmaWindow`` is what ``MPI_Win_allocate`` / ``MPI_Win_create`` hand
back in the per-rank model: ONE component selection at creation
(osc/decision — shm for same-host communicators, pt2pt emulation
otherwise), then every call goes through three framework layers before
the component:

1. the epoch state machine (osc/base.EpochState) — data ops outside
   every open access epoch raise ``MPI_ERR_RMA_SYNC`` and leave a
   flight-recorder snapshot;
2. fault tolerance — an ft-registry listener marks dead peers, ops
   targeting them and epoch boundaries (``fence``) raise
   ``MPI_ERR_PROC_FAILED`` instead of hanging, and the component's
   ``peer_failed`` reclaims lock grants and segment mappings;
3. telemetry — ``tele_osc_{put,get,acc}_us`` latency histograms, the
   ``osc_*`` op/byte pvars, a per-window byte counter pvar (retired
   with the window or its communicator), and ``osc.put`` /
   ``osc.get`` / ``osc.acc`` / ``osc.epoch`` trace spans.

Anything not wrapped here (``local``, ``sizes``, ``wid``, the window
attributes the C ABI pins) delegates to the component window — the
component IS the window, this class is the framework's policy around
it.
"""
from __future__ import annotations

import time as _time
import weakref
from typing import Any, Optional, Set

import numpy as np

from ompi_tpu.core.errhandler import (ERR_PROC_FAILED, ERR_WIN,
                                      MPIError)
from ompi_tpu.mca import pvar as _pvar
from ompi_tpu.mca import var
from ompi_tpu.runtime import ft as _ft
from ompi_tpu import telemetry as _tele
from ompi_tpu.telemetry import flightrec as _flightrec
from ompi_tpu.trace import core as _trace

from ompi_tpu.osc import base as _base
from ompi_tpu.osc import decision as _decision
from ompi_tpu.osc.perrank import LOCK_EXCLUSIVE, LOCK_SHARED
from ompi_tpu.osc.pt2pt import Pt2ptWindow
from ompi_tpu.osc.shm import ShmWindow


def _ft_callback(ref):
    """The registry listener: closes over a weakref ONLY (the PR-5
    finalizer lesson — a listener must not pin a freed window)."""
    def _cb(world_rank: int, reason: str) -> None:
        w = ref()
        if w is not None:
            w._peer_dead(world_rank, reason)
    return _cb


class RmaWindow:
    """A framework window over one osc component."""

    def __init__(self, comm, size: int, dtype=np.float32,
                 name: str = "", storage: Optional[np.ndarray] = None,
                 force: Optional[str] = None):
        _base.register_params()
        _base.register_pvars()
        self.comm = comm
        self.component = _decision.select(comm, storage=storage,
                                          force=force)
        self._epoch = _base.EpochState()
        self._epoch_check = bool(
            var.var_get("mpi_base_osc_epoch_check", True))
        self._dead: Set[int] = set()
        self._bytes = 0                  # per-window traffic counter
        if self.component == "shm":
            self._w = ShmWindow(comm, size, dtype, name=name)
            _base.stats["windows_shm"] += 1
        else:
            self._w = Pt2ptWindow(comm, size, dtype, name=name,
                                  storage=storage)
            _base.stats["windows_pt2pt"] += 1
        self.name = self._w.name
        try:
            self._world = {comm.world_rank_of(r)
                           for r in range(comm.size)}
        except Exception:                # noqa: BLE001 — exotic comm:
            self._world = set()          # accept every failure event
        # peers that died BEFORE creation stay dead for this window
        for wr in (_ft.default_registry().failed_ranks() or []):
            if not self._world or wr in self._world:
                self._dead.add(wr)
        self._ft_cb = _ft_callback(weakref.ref(self))
        _ft.add_listener(self._ft_cb)
        # per-window byte-counter pvar, retired with the window (or
        # with its communicator: comm= tags it for pvar_retire_comm)
        ref = weakref.ref(self)
        self._pvar_name = (f"osc_win_{_tele._cid_token(comm.cid)}"
                           f"_{self._w.wid[-1]}_r{comm.rank()}_bytes")
        _pvar.pvar_register(
            self._pvar_name,
            lambda r=ref: (r()._bytes if r() is not None else 0),
            unit="bytes", comm=comm.cid,
            help=f"Origin-side RMA bytes moved through window "
                 f"{self.name} ({self.component})")
        _base.track_window(self)
        self._freed = False

    # -- framework guards ----------------------------------------------
    def _guard(self, fn, *args) -> None:
        """Run one epoch-machine transition/check; an RMA_SYNC refusal
        is counted and flight-recorded before it propagates."""
        if not self._epoch_check:
            return
        try:
            fn(*args)
        except MPIError as e:
            _base.stats["epoch_errors"] += 1
            _flightrec.record("rma_sync",
                              {"win": self.name, "error": str(e)})
            raise

    def _check_dead(self, what: str,
                    target: Optional[int] = None) -> None:
        if not self._dead:
            return
        if target is not None:
            wt = self.comm.world_rank_of(target)
            if wt not in self._dead:
                return
            raise MPIError(ERR_PROC_FAILED,
                           f"{what}: window peer rank {target} "
                           f"(world {wt}) has failed")
        raise MPIError(ERR_PROC_FAILED,
                       f"{what}: window peer(s) "
                       f"{sorted(self._dead)} have failed")

    def _peer_dead(self, world_rank: int, reason: str) -> None:
        if self._world and world_rank not in self._world:
            return
        self._dead.add(world_rank)
        try:
            self._w.peer_failed(world_rank)
        except Exception:                # noqa: BLE001 — reclaim is
            pass                         # best-effort on this path
        ep = self._epoch
        if (ep.fenced or ep.lock_all or ep.locked or ep.pscw_access
                or ep.pscw_exposure):
            _base.stats["ft_failed_epochs"] += 1
            _flightrec.record("rma_proc_failed",
                              {"rank": world_rank, "win": self.name,
                               "reason": reason})

    def _instrumented(self, kind: str, target: int, nbytes: int,
                      thunk):
        tok = (_trace.begin(f"osc.{kind}", target=target,
                            bytes=nbytes)
               if _trace.active else None)
        t0 = _time.perf_counter() if _tele.active else 0.0
        ok = False
        try:
            out = thunk()
            ok = True
            return out
        finally:
            if tok is not None:
                _trace.end(tok, ok=ok)
            if _tele.active:
                _base.op_hist(kind).record(
                    (_time.perf_counter() - t0) * 1e6)

    def _account(self, kind: str, nbytes: int) -> None:
        _base.stats[f"{kind}s"] += 1
        _base.stats[f"{kind}_bytes"] += int(nbytes)
        self._bytes += int(nbytes)

    # -- data ops --------------------------------------------------------
    def put(self, data, target: int, disp: int = 0) -> None:
        self._guard(self._epoch.check_access, target, "put")
        self._check_dead("RMA put", target)
        arr = np.asarray(data, dtype=self._w.dtype)
        n = int(arr.nbytes)
        self._instrumented("put", target, n,
                           lambda: self._w.put(arr, target, disp))
        self._account("put", n)

    def get(self, target: int, disp: int = 0, count: int = 1):
        self._guard(self._epoch.check_access, target, "get")
        self._check_dead("RMA get", target)
        n = int(count) * self._w.dtype.itemsize
        out = self._instrumented(
            "get", target, n,
            lambda: self._w.get(target, disp, count))
        self._account("get", n)
        return out

    def accumulate(self, data, target: int, disp: int = 0,
                   op: str = "sum") -> None:
        self._guard(self._epoch.check_access, target, "accumulate")
        self._check_dead("RMA accumulate", target)
        arr = np.asarray(data, dtype=self._w.dtype)
        n = int(arr.nbytes)
        self._instrumented(
            "acc", target, n,
            lambda: self._w.accumulate(arr, target, disp, op))
        self._account("acc", n)

    def get_accumulate(self, data, target: int, disp: int = 0,
                       op: str = "sum"):
        self._guard(self._epoch.check_access, target, "accumulate")
        self._check_dead("RMA get_accumulate", target)
        arr = np.asarray(data, dtype=self._w.dtype)
        n = int(arr.nbytes)
        out = self._instrumented(
            "acc", target, n,
            lambda: self._w.get_accumulate(arr, target, disp, op))
        self._account("acc", n)
        return out

    def fetch_and_op(self, value, target: int, disp: int = 0,
                     op: str = "sum"):
        out = self.get_accumulate(
            np.asarray([value], self._w.dtype), target, disp, op)
        return out[0]

    def compare_and_swap(self, compare, origin, target: int,
                         disp: int = 0):
        self._guard(self._epoch.check_access, target, "accumulate")
        self._check_dead("RMA compare_and_swap", target)
        n = int(self._w.dtype.itemsize)
        out = self._instrumented(
            "acc", target, n,
            lambda: self._w.compare_and_swap(compare, origin, target,
                                             disp))
        self._account("acc", n)
        return out

    # -- typed ops (byte-addressed C ABI windows) ----------------------
    def accumulate_typed(self, data, target: int, byte_disp: int,
                         op: str = "sum") -> None:
        self._guard(self._epoch.check_access, target, "accumulate")
        self._check_dead("RMA accumulate", target)
        arr = np.ascontiguousarray(np.asarray(data)).ravel()
        n = int(arr.nbytes)
        self._instrumented(
            "acc", target, n,
            lambda: self._w.accumulate_typed(arr, target, byte_disp,
                                             op))
        self._account("acc", n)

    def get_accumulate_typed(self, data, target: int, byte_disp: int,
                             op: str = "sum"):
        self._guard(self._epoch.check_access, target, "accumulate")
        self._check_dead("RMA get_accumulate", target)
        arr = np.ascontiguousarray(np.asarray(data)).ravel()
        n = int(arr.nbytes)
        out = self._instrumented(
            "acc", target, n,
            lambda: self._w.get_accumulate_typed(arr, target,
                                                 byte_disp, op))
        self._account("acc", n)
        return out

    def compare_and_swap_typed(self, compare, origin, target: int,
                               byte_disp: int):
        self._guard(self._epoch.check_access, target, "accumulate")
        self._check_dead("RMA compare_and_swap", target)
        out = self._instrumented(
            "acc", target, 0,
            lambda: self._w.compare_and_swap_typed(compare, origin,
                                                   target, byte_disp))
        self._account("acc", np.asarray(origin).ravel()[:1].nbytes)
        return out

    # -- request-based ops ---------------------------------------------
    def rput(self, data, target: int, disp: int = 0):
        self._guard(self._epoch.check_access, target, "put")
        self._check_dead("RMA rput", target)
        arr = np.asarray(data, dtype=self._w.dtype)
        self._account("put", int(arr.nbytes))
        return self._w.rput(arr, target, disp)

    def rget(self, target: int, disp: int = 0, count: int = 1):
        self._guard(self._epoch.check_access, target, "get")
        self._check_dead("RMA rget", target)
        self._account("get", int(count) * self._w.dtype.itemsize)
        return self._w.rget(target, disp, count)

    def raccumulate(self, data, target: int, disp: int = 0,
                    op: str = "sum"):
        self._guard(self._epoch.check_access, target, "accumulate")
        self._check_dead("RMA raccumulate", target)
        arr = np.asarray(data, dtype=self._w.dtype)
        self._account("acc", int(arr.nbytes))
        return self._w.raccumulate(arr, target, disp, op)

    # -- synchronization -------------------------------------------------
    def _epoch_span(self, phase: str, thunk):
        tok = (_trace.begin("osc.epoch", phase=phase,
                            win=self.name)
               if _trace.active else None)
        ok = False
        try:
            out = thunk()
            ok = True
            return out
        finally:
            if tok is not None:
                _trace.end(tok, ok=ok)

    def fence(self) -> None:
        self._guard(self._epoch.fence)
        self._check_dead("Win_fence")
        self._epoch_span("fence", self._w.fence)
        _base.stats["fences"] += 1

    def lock(self, target: int,
             lock_type: int = LOCK_EXCLUSIVE) -> None:
        self._guard(self._epoch.lock, target)
        self._check_dead("Win_lock", target)
        self._epoch_span("lock",
                         lambda: self._w.lock(target, lock_type))
        self._epoch.locked_ok(target, lock_type)
        _base.stats["locks"] += 1

    def unlock(self, target: int) -> None:
        self._guard(self._epoch.unlock, target)
        self._epoch_span("unlock", lambda: self._w.unlock(target))
        self._epoch.unlocked_ok(target)

    def lock_all(self) -> None:
        self._guard(self._epoch.lock_all_begin)
        self._check_dead("Win_lock_all")

        def _all():
            for r in range(self.comm.size):
                self._w.lock(r, LOCK_SHARED)
        self._epoch_span("lock_all", _all)
        self._epoch.lock_all_ok()
        _base.stats["locks"] += 1

    def unlock_all(self) -> None:
        self._guard(self._epoch.unlock_all)

        def _all():
            for r in range(self.comm.size):
                self._w.unlock(r)
        self._epoch_span("unlock_all", _all)

    def flush(self, target: int = -1) -> None:
        self._guard(self._epoch.flush,
                    None if target < 0 else target)
        self._w.flush(target)

    def flush_all(self) -> None:
        self.flush(-1)

    def flush_local(self, target: int = -1) -> None:
        self.flush(target)

    def flush_local_all(self) -> None:
        self.flush(-1)

    # -- PSCW ------------------------------------------------------------
    def start(self, target_ranks) -> None:
        self._check_dead("Win_start")
        self._epoch_span("start",
                         lambda: self._w.start(target_ranks))
        self._epoch.start(target_ranks)

    def complete(self) -> None:
        self._guard(self._epoch.complete)
        self._epoch_span("complete", self._w.complete)
        if not self._epoch_check:        # keep both paths consistent
            self._epoch.pscw_access = set()

    def post(self, origin_ranks) -> None:
        self._check_dead("Win_post")
        self._epoch_span("post", lambda: self._w.post(origin_ranks))
        self._epoch.post(origin_ranks)

    def wait(self) -> None:
        self._guard(self._epoch.wait)
        self._epoch_span("wait", self._w.wait)
        if not self._epoch_check:
            self._epoch.pscw_exposure = set()

    # -- lifecycle -------------------------------------------------------
    def free(self) -> None:
        if self._freed:
            return
        self._freed = True
        _base.untrack_window(self)
        try:
            _ft.remove_listener(self._ft_cb)
        except Exception:                # noqa: BLE001 — registry may
            pass                         # already be torn down
        _pvar.pvar_unregister(self._pvar_name)
        self._epoch_span("free", self._w.free)

    def __getattr__(self, name: str):
        # framework attrs live on self; everything else (local, sizes,
        # wid, dtype, size, the C-ABI pins) is the component's
        if name.startswith("__"):
            raise AttributeError(name)
        return getattr(object.__getattribute__(self, "_w"), name)


def win_allocate(comm, size: int, dtype=np.float32, name: str = "",
                 force: Optional[str] = None) -> RmaWindow:
    """MPI_Win_allocate: the framework owns the exposure memory, so
    the selection step may place it in a /dev/shm segment."""
    if getattr(comm, "router", None) is None:
        raise MPIError(ERR_WIN,
                       "framework windows require the per-rank "
                       "execution model (the stacked world keeps "
                       "MPI.Win)")
    return RmaWindow(comm, size, dtype, name=name, force=force)


def win_create(comm, storage: np.ndarray, name: str = "",
               force: Optional[str] = None) -> RmaWindow:
    """MPI_Win_create: caller-owned memory — pinned to osc/pt2pt by
    selection (user memory cannot be retroactively shm-backed)."""
    if getattr(comm, "router", None) is None:
        raise MPIError(ERR_WIN,
                       "framework windows require the per-rank "
                       "execution model (the stacked world keeps "
                       "MPI.Win)")
    return RmaWindow(comm, int(storage.size), storage.dtype,
                     name=name, storage=storage, force=force)
