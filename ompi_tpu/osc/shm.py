"""osc/shm — same-host windows over /dev/shm segments (load/store RMA).

Behavioral spec: ``ompi/mca/osc/sm`` — when every rank of the
communicator shares the host, each rank's exposure region lives in a
raw mmap'd /dev/shm file (the PR-9 segment-pool discipline:
``btl/shmseg._PoolFile``, creator owns and unlinks, attachers never
unlink, POSIX keeps mapped views valid past the unlink). Every peer
maps every other peer's segment lazily on first access, and the data
ops become memory ops instead of messages:

- ``put``          — ONE copy, straight into the target's window slice;
- ``get``          — ZERO copies: an ``np.frombuffer`` view adopted in
  place (valid for the window's lifetime; callers that need a
  snapshot ``.copy()`` — docs/RMA.md has the copy-count table);
- ``accumulate`` / ``get_accumulate`` / ``compare_and_swap`` — an
  in-segment typed fold under the target file's ``flock`` (the
  cross-process atomicity domain MPI_Accumulate requires; all ranks
  are same-host by selection, so one file lock covers every origin).

After a remote put/accumulate the origin sends the target a
descriptor-only NOTE frame over the ctl plane (no payload, no ack) so
the target's pvars account bytes landed in its window — the
"completion descriptors" of the reference's osc/sm, reduced to their
accounting role since shared memory already made the data visible.

Synchronization is inherited from ``RankWindow`` unchanged: the
passive-lock FIFO grant queue, PSCW tokens and the barrier fence all
operate on wid-addressed ctl frames, and since ``self.local`` IS the
shared mapping, both the RPC path and direct loads observe the same
bytes.

Segment files are named ``otpuwin_<tag>_<wrank>_<suffix>`` —
``WIN_PREFIX`` is imported by the launcher's post-reap orphan sweep
(tools/mpirun.py), same never-diverge contract as ``otpuseg``.
"""
from __future__ import annotations

import fcntl
import itertools
import os
import threading
from contextlib import contextmanager
from typing import Dict, Optional, Tuple

import numpy as np

from ompi_tpu.btl.shmseg import _PoolFile, coll_token
from ompi_tpu.btl.sm import job_tag
from ompi_tpu.core.errhandler import ERR_ARG, ERR_WIN, MPIError
from ompi_tpu.mca import var

from ompi_tpu.osc import base as _base
from ompi_tpu.osc.perrank import _ACC_OPS, RankWindow

# the launcher's post-reap sweep globs on this prefix
# (tools/mpirun.py imports it) — prefix and glob must never diverge
WIN_PREFIX = "otpuwin"


def _win_name(world_rank: int, suffix: str) -> str:
    tag = job_tag()
    if tag:
        return f"{WIN_PREFIX}_{tag}_{world_rank}_{suffix}"
    return (f"{WIN_PREFIX}_{os.getpid():x}_{world_rank}_{suffix}_"
            f"{os.urandom(4).hex()}")


class ShmWindow(RankWindow):
    """A window whose exposure region is a mapped /dev/shm segment."""

    component = "shm"

    def __init__(self, comm, size: int, dtype=np.float32,
                 name: str = ""):
        dt = np.dtype(dtype)
        nbytes = int(size) * dt.itemsize
        # window ids must agree across ranks and the segment must be
        # published BEFORE the creation barrier (RankWindow's sizes
        # allgather) so any peer's first op finds the name in the KV —
        # a dedicated collective-order counter keys both
        if not hasattr(comm, "_osc_shm_seq"):
            comm._osc_shm_seq = itertools.count(0)
        self._shm_seq = next(comm._osc_shm_seq)
        tok = coll_token(comm.cid)
        me = comm.rank()
        wrank = comm.world_rank_of(me)
        try:
            pf = _PoolFile(_win_name(wrank, f"w{tok}{self._shm_seq}"),
                           max(nbytes, 1), max(nbytes, 1), create=True)
        except OSError as e:
            raise MPIError(ERR_WIN,
                           f"cannot allocate window segment: {e}")
        self._pf = pf
        self._kv_key = f"ompi_tpu/oscwin/{tok}/{self._shm_seq}"
        comm.router.kv_set(f"{self._kv_key}/{me}", pf.name)
        storage = np.frombuffer(pf.buf, dtype=dt, count=int(size))
        self._maps_lock = threading.Lock()
        self._peer_maps: Dict[int, Tuple[_PoolFile, np.ndarray]] = {}
        super().__init__(comm, size, dtype, name=name, storage=storage)

    # -- peer mappings -------------------------------------------------
    def _peer_entry(self, target: int) -> Tuple[_PoolFile, np.ndarray]:
        if target == self.comm.rank():
            return self._pf, self.local
        with self._maps_lock:
            ent = self._peer_maps.get(target)
        if ent is not None:
            return ent
        val = self.comm.router.kv_get(f"{self._kv_key}/{target}")
        if isinstance(val, bytes):
            val = val.decode()
        if not val:
            raise MPIError(ERR_WIN,
                           f"no window segment published by rank "
                           f"{target}")
        peer_bytes = self.sizes[target] * self.dtype.itemsize
        pf = _PoolFile(str(val), max(peer_bytes, 1),
                       max(peer_bytes, 1), create=False)
        arr = np.frombuffer(pf.buf, dtype=self.dtype,
                            count=self.sizes[target])
        with self._maps_lock:
            cur = self._peer_maps.setdefault(target, (pf, arr))
        if cur[0] is not pf:
            pf.close()                   # lost the attach race (never
        return cur                       # unlinks: not the creator)

    @contextmanager
    def _atomic(self, pf: _PoolFile):
        """The accumulate atomicity domain: the target file's flock
        excludes every other same-host origin; the window lock
        excludes this process's own reader thread."""
        with self._lock:
            fcntl.flock(pf._fd, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(pf._fd, fcntl.LOCK_UN)

    def _note(self, target: int, kind: str, nbytes: int) -> None:
        """Descriptor-only completion note to the target (accounting
        plane; best-effort, gated, never carries data)."""
        if target == self.comm.rank():
            return
        _base.register_params()
        if not var.var_get("mpi_base_osc_shm_notes", True):
            return
        router = self.comm.router
        header = {"rma": True, "wid": self.wid, "op": "note",
                  "origin": router.rank, "kind": kind,
                  "nb": int(nbytes)}
        try:
            router.endpoint.send_frame(
                self.comm.world_rank_of(target), header, b"")
        except Exception:                # noqa: BLE001 — accounting
            pass                         # must never fail the op

    # -- data ops: direct load/store -----------------------------------
    def put(self, data, target: int, disp: int = 0) -> None:
        arr = np.asarray(data, dtype=self.dtype).ravel()
        self._bounds(disp, arr.size, target)
        _pf, dst = self._peer_entry(target)
        dst[disp:disp + arr.size] = arr
        self._note(target, "put", arr.nbytes)

    def get(self, target: int, disp: int = 0, count: int = 1):
        self._bounds(disp, count, target)
        _pf, src = self._peer_entry(target)
        return src[disp:disp + count]    # zero-copy in-place adoption

    def accumulate(self, data, target: int, disp: int = 0,
                   op: str = "sum") -> None:
        if op not in _ACC_OPS or _ACC_OPS[op] is False:
            raise MPIError(ERR_ARG, f"bad accumulate op {op!r}")
        arr = np.asarray(data, dtype=self.dtype).ravel()
        self._bounds(disp, arr.size, target)
        pf, dst = self._peer_entry(target)
        fn = _ACC_OPS[op]
        with self._atomic(pf):
            seg = dst[disp:disp + arr.size]
            dst[disp:disp + arr.size] = (arr if fn is None
                                         else fn(seg, arr))
        self._note(target, "acc", arr.nbytes)

    def get_accumulate(self, data, target: int, disp: int = 0,
                       op: str = "sum"):
        if op not in _ACC_OPS:           # no_op is legal here (fetch)
            raise MPIError(ERR_ARG, f"bad accumulate op {op!r}")
        arr = np.asarray(data, dtype=self.dtype).ravel()
        self._bounds(disp, arr.size, target)
        pf, dst = self._peer_entry(target)
        fn = _ACC_OPS[op]
        with self._atomic(pf):
            seg = dst[disp:disp + arr.size]
            prior = seg.copy()
            if fn is not False:          # MPI_NO_OP fetches only
                dst[disp:disp + arr.size] = (arr if fn is None
                                             else fn(prior, arr))
        self._note(target, "acc", arr.nbytes)
        return prior

    def compare_and_swap(self, compare, origin, target: int,
                         disp: int = 0):
        self._bounds(disp, 1, target)
        pf, dst = self._peer_entry(target)
        cmp_v = np.asarray(compare, self.dtype).ravel()[0]
        org_v = np.asarray(origin, self.dtype).ravel()[0]
        with self._atomic(pf):
            prior = dst[disp].copy()
            if prior == cmp_v:
                dst[disp] = org_v
        self._note(target, "acc", int(self.dtype.itemsize))
        return prior

    # -- typed ops against byte-addressed (C ABI) windows --------------
    def accumulate_typed(self, data, target: int, byte_disp: int,
                         op: str = "sum") -> None:
        if self.dtype != np.dtype(np.uint8):
            raise MPIError(ERR_ARG,
                           "accumulate_typed requires a byte window")
        if op not in _ACC_OPS or _ACC_OPS[op] is False:
            raise MPIError(ERR_ARG, f"bad accumulate op {op!r}")
        arr = np.ascontiguousarray(np.asarray(data)).ravel()
        self._bounds(byte_disp, arr.nbytes, target)
        pf, dst = self._peer_entry(target)
        fn = _ACC_OPS[op]
        nb = arr.nbytes
        with self._atomic(pf):
            seg = dst[byte_disp:byte_disp + nb].view(arr.dtype)
            out = arr if fn is None else fn(seg, arr)
            dst[byte_disp:byte_disp + nb] = \
                np.ascontiguousarray(out).view(np.uint8)
        self._note(target, "acc", nb)

    def get_accumulate_typed(self, data, target: int, byte_disp: int,
                             op: str = "sum"):
        if self.dtype != np.dtype(np.uint8):
            raise MPIError(ERR_ARG, "typed RMA requires a byte window")
        if op not in _ACC_OPS:
            raise MPIError(ERR_ARG, f"bad accumulate op {op!r}")
        arr = np.ascontiguousarray(np.asarray(data)).ravel()
        self._bounds(byte_disp, arr.nbytes, target)
        pf, dst = self._peer_entry(target)
        fn = _ACC_OPS[op]
        nb = arr.nbytes
        with self._atomic(pf):
            seg = dst[byte_disp:byte_disp + nb].view(arr.dtype)
            prior = seg.copy()
            if fn is not False:
                out = arr if fn is None else fn(prior, arr)
                dst[byte_disp:byte_disp + nb] = \
                    np.ascontiguousarray(out).view(np.uint8)
        self._note(target, "acc", nb)
        return prior

    def compare_and_swap_typed(self, compare, origin, target: int,
                               byte_disp: int):
        if self.dtype != np.dtype(np.uint8):
            raise MPIError(ERR_ARG, "typed RMA requires a byte window")
        org = np.ascontiguousarray(np.asarray(origin).ravel()[:1])
        cmp_v = np.asarray(compare, org.dtype).ravel()[0]
        esz = org.dtype.itemsize
        self._bounds(byte_disp, esz, target)
        pf, dst = self._peer_entry(target)
        with self._atomic(pf):
            seg = dst[byte_disp:byte_disp + esz].view(org.dtype)
            prior = seg.copy()[0]
            if prior == cmp_v:
                dst[byte_disp:byte_disp + esz] = org.view(np.uint8)
        self._note(target, "acc", esz)
        return prior

    # -- note frames (target side) -------------------------------------
    def _handle_inner(self, header: dict, raw: bytes) -> None:
        if header.get("op") == "note":
            _base.stats["notes"] += 1
            return                       # descriptor-only: no ack
        super()._handle_inner(header, raw)

    # -- FT / lifecycle ------------------------------------------------
    def peer_failed(self, world_rank: int) -> None:
        super().peer_failed(world_rank)  # passive-lock queue purge
        # reclaim the dead peer's mapping: the segment file itself is
        # the dead creator's to unlink (the launcher sweep's job after
        # a SIGKILL); dropping our view releases the memory here
        dead = []
        with self._maps_lock:
            for r, (pf, _arr) in list(self._peer_maps.items()):
                try:
                    if self.comm.world_rank_of(r) == world_rank:
                        dead.append(pf)
                        del self._peer_maps[r]
                except Exception:        # noqa: BLE001 — shrinking
                    pass                 # comm: rank may be gone
        for pf in dead:
            pf.close()

    def free(self) -> None:
        # reclaim the segments even when the completion barrier raises
        # over a dead peer (the FT drill's survivor-side free)
        try:
            super().free()
        finally:
            with self._maps_lock:
                maps = [pf for pf, _ in self._peer_maps.values()]
                self._peer_maps.clear()
            for pf in maps:
                pf.close()
            self._pf.close()             # creator: unlinks the file
