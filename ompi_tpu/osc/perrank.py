"""osc/perrank — one-sided RMA windows for the per-rank execution model.

Behavioral spec: ``ompi/mca/osc/rdma`` — put/get/accumulate against a
remote exposure region (``osc_rdma_comm.c`` fragments the transfer and
targets the peer's registered memory), active-target ``fence`` epochs,
and passive-target ``lock/unlock`` built on remote atomics
(``osc_rdma_lock.h``); ``osc/sm`` services the same interface over
shared memory.

TPU-native re-design (round 3): in the per-rank model every rank is an
OS process, so a window is a LOCAL exposure region (numpy buffer) plus
an active-message handler registered with the process Router: an
origin's put/get/accumulate/fetch_op/compare_and_swap is one framed
message over btl/tcp, applied to the target's region ON THE TARGET'S
READER THREAD under the window lock (true one-sided progress: the
target's application thread never participates — the property the
reference gets from hardware RDMA and agents). Every operation is
acked, so origin-side completion == remote completion; ``fence`` is
then simply a comm barrier. Passive-target ``lock/unlock`` run a
FIFO grant queue at the target (exclusive vs shared), with grants
delivered as acks — the osc/rdma lock protocol reduced to its
observable semantics.
"""
from __future__ import annotations

# env-gated RMA handler tracing (operator debugging; reads once)
import os as _os_mod
_RMA_DEBUG = bool(_os_mod.environ.get("OMPI_TPU_RMA_DEBUG"))

import itertools
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ompi_tpu.core.errhandler import ERR_ARG, ERR_RANK, MPIError

LOCK_EXCLUSIVE = 1
LOCK_SHARED = 2

# dtype-preserving numpy combiners (shared host fold table) plus the
# two accumulate-only pseudo-ops
from ompi_tpu.core.op import NP_COMBINERS as _NP_COMBINERS

_ACC_OPS = {
    **_NP_COMBINERS,
    "replace": None,                    # MPI_REPLACE
    "no_op": False,                     # MPI_NO_OP (fetch only)
}


class RankWindow:
    """An RMA window whose caller is one rank (collective creation)."""

    # osc framework component name (osc/pt2pt is the emulation over
    # the acked active-message plane — this class IS that component;
    # osc/shm subclasses it and overrides the data ops)
    component = "pt2pt"

    def __init__(self, comm, size: int, dtype=np.float32,
                 name: str = "", storage: Optional[np.ndarray] = None):
        """``storage``: use the CALLER's memory as the exposure region
        (MPI_Win_create over user-allocated memory,
        win_create.c.in:79): remote puts applied by the reader thread
        land directly in it, so the owner's plain loads observe them —
        the osc/sm shared-window model."""
        self.comm = comm
        self.size = int(size)
        self.dtype = np.dtype(dtype)
        if storage is not None:
            if (storage.dtype != self.dtype or storage.ndim != 1
                    or storage.size != self.size
                    or not storage.flags.writeable):
                raise MPIError(ERR_ARG, "bad window storage array")
        # window id must agree across ranks: creation is collective ON
        # THIS communicator, so the sequence lives on the comm — a
        # process-global counter would diverge when ranks have created
        # different numbers of windows on OTHER comms
        if not hasattr(comm, "_win_seq"):
            comm._win_seq = itertools.count(0)
        seq = next(comm._win_seq)
        self.wid = ("win", comm.cid, seq)
        self.name = name or f"win#{seq}"
        self.local = (storage if storage is not None
                      else np.zeros(self.size, self.dtype))
        self._lock = threading.Lock()
        # passive-target lock state (target side)
        self._holders: List[Tuple[int, int]] = []   # (origin, type)
        self._waiters: List[Tuple[int, int, int]] = []  # (+ack id)
        self.comm.router.register_rma(self.wid, self._handle)
        # per-process window sizes may legitimately differ (MPI_Win):
        # exchange them so origin-side bounds checks use the TARGET's
        # exposure size (the osc_rdma region-table role); doubles as
        # the expose-epoch barrier
        self.sizes = [int(x) for x in self.comm.allgather(self.size)]

    # ------------------------------------------------------------------
    def _check_target(self, rank: int) -> int:
        if not (0 <= rank < self.comm.size):
            raise MPIError(ERR_RANK, f"bad target rank {rank}")
        return self.comm.world_rank_of(rank)

    def _rpc(self, target: int, header: dict, payload: Any = None,
             timeout: float = 120):
        """One acked active message to ``target``'s window handler."""
        from ompi_tpu.btl.tcp import encode_payload
        router = self.comm.router
        aid, ent = router.new_ack()
        header.update(rma=True, wid=self.wid, ack_id=aid,
                      origin=router.rank)
        raw = b""
        if payload is not None:
            header["desc"], raw = encode_payload(payload)
        router.endpoint.send_frame(self._check_target(target), header,
                                   raw)
        if not ent[0].wait(timeout):
            router.cancel_ack(aid)
            raise MPIError(ERR_ARG, f"RMA {header.get('op')} to rank "
                                    f"{target} timed out")
        reply = ent[1]
        if isinstance(reply, dict) and "rma_error" in reply:
            # target-side failure travels back as an error reply, so
            # the origin raises promptly instead of timing out
            raise MPIError(ERR_ARG,
                           f"RMA {header.get('op')} failed at rank "
                           f"{target}: {reply['rma_error']}")
        return reply

    # -- origin-side API -------------------------------------------------
    def put(self, data, target: int, disp: int = 0) -> None:
        arr = np.asarray(data, dtype=self.dtype).ravel()
        self._bounds(disp, arr.size, target)
        self._rpc(target, {"op": "put", "disp": int(disp)}, arr)

    def get(self, target: int, disp: int = 0, count: int = 1):
        self._bounds(disp, count, target)
        return self._rpc(target, {"op": "get", "disp": int(disp),
                                  "count": int(count)})

    def accumulate(self, data, target: int, disp: int = 0,
                   op: str = "sum") -> None:
        if op not in _ACC_OPS or _ACC_OPS[op] is False:
            raise MPIError(ERR_ARG, f"bad accumulate op {op!r}")
        arr = np.asarray(data, dtype=self.dtype).ravel()
        self._bounds(disp, arr.size, target)
        self._rpc(target, {"op": "acc", "disp": int(disp), "acc": op},
                  arr)

    def get_accumulate(self, data, target: int, disp: int = 0,
                       op: str = "sum"):
        if op not in _ACC_OPS:           # no_op is legal here (fetch)
            raise MPIError(ERR_ARG, f"bad accumulate op {op!r}")
        arr = np.asarray(data, dtype=self.dtype).ravel()
        self._bounds(disp, arr.size, target)
        return self._rpc(target, {"op": "getacc", "disp": int(disp),
                                  "acc": op}, arr)

    def accumulate_typed(self, data, target: int, byte_disp: int,
                         op: str = "sum") -> None:
        """Typed accumulate into a BYTE-addressed (uint8) window: the
        value keeps its own dtype and the target combines through a
        typed view of its byte storage — the C ABI's MPI_Accumulate
        path, where the window is raw allocated memory and each call
        brings its own datatype."""
        if self.dtype != np.dtype(np.uint8):
            raise MPIError(ERR_ARG,
                           "accumulate_typed requires a byte window")
        if op not in _ACC_OPS or _ACC_OPS[op] is False:
            raise MPIError(ERR_ARG, f"bad accumulate op {op!r}")
        arr = np.ascontiguousarray(np.asarray(data)).ravel()
        self._bounds(byte_disp, arr.nbytes, target)
        self._rpc(target, {"op": "acc", "disp": int(byte_disp),
                           "acc": op}, arr)

    def fetch_and_op(self, value, target: int, disp: int = 0,
                     op: str = "sum"):
        out = self.get_accumulate(np.asarray([value], self.dtype),
                                  target, disp, op)
        return out[0]

    # -- typed origin entry points for byte-addressed (C ABI) windows --
    def get_accumulate_typed(self, data, target: int, byte_disp: int,
                             op: str = "sum"):
        """Fetch-and-accumulate with the VALUE's dtype against a uint8
        window (MPI_Get_accumulate from C: raw window memory, each
        call brings its own datatype). Returns the prior typed
        contents."""
        if self.dtype != np.dtype(np.uint8):
            raise MPIError(ERR_ARG, "typed RMA requires a byte window")
        if op not in _ACC_OPS:
            raise MPIError(ERR_ARG, f"bad accumulate op {op!r}")
        arr = np.ascontiguousarray(np.asarray(data)).ravel()
        self._bounds(byte_disp, arr.nbytes, target)
        return self._rpc(target, {"op": "getacc",
                                  "disp": int(byte_disp), "acc": op},
                         arr)

    def compare_and_swap_typed(self, compare, origin, target: int,
                               byte_disp: int):
        if self.dtype != np.dtype(np.uint8):
            raise MPIError(ERR_ARG, "typed RMA requires a byte window")
        pair = np.ascontiguousarray(
            np.stack([np.asarray(origin).ravel()[0],
                      np.asarray(compare).ravel()[0]]))
        self._bounds(byte_disp, pair.dtype.itemsize, target)
        return self._rpc(target, {"op": "cas", "disp": int(byte_disp)},
                         pair)[0]

    # -- request-based operations (osc.h:269-279 rput/rget) ------------
    def rput(self, data, target: int, disp: int = 0):
        """MPI_Rput: returns a request; completion == remote completion
        (every op here is target-acked)."""
        from ompi_tpu.pml.perrank import thread_request
        return thread_request(lambda: self.put(data, target, disp))

    def rget(self, target: int, disp: int = 0, count: int = 1):
        """MPI_Rget: the request's payload is the fetched array."""
        from ompi_tpu.pml.perrank import thread_request
        return thread_request(lambda: self.get(target, disp, count))

    def raccumulate(self, data, target: int, disp: int = 0,
                    op: str = "sum"):
        from ompi_tpu.pml.perrank import thread_request
        return thread_request(
            lambda: self.accumulate(data, target, disp, op))

    def compare_and_swap(self, compare, origin, target: int,
                         disp: int = 0):
        self._bounds(disp, 1, target)
        # compare travels IN the typed payload next to the origin value
        # (a float() round-trip would corrupt int64 values > 2**53)
        return self._rpc(target, {"op": "cas", "disp": int(disp)},
                         np.asarray([origin, compare], self.dtype))[0]

    # -- synchronization ---------------------------------------------
    def fence(self) -> None:
        """Active target: all ops are remotely complete when acked, so
        the epoch boundary is the comm barrier."""
        self.comm.barrier()

    def lock(self, target: int, lock_type: int = LOCK_EXCLUSIVE) -> None:
        self._rpc(target, {"op": "lock", "lt": int(lock_type)})

    def unlock(self, target: int) -> None:
        self._rpc(target, {"op": "unlock"})

    def flush(self, target: int = -1) -> None:
        pass                            # every op is acked: always flushed

    # -- PSCW active-target epochs (MPI_Win_post/start/complete/wait,
    # osc_rdma_active_target.c semantics): every RMA op here is
    # target-acked before returning, so origin completion already
    # implies remote completion — the epochs reduce to their token
    # exchanges over a hidden pt2pt channel, which is exactly the
    # synchronization contract the standard requires.
    def _pscw_engine(self):
        from ompi_tpu.core.rankcomm import hidden_engine
        return hidden_engine(self.comm, "pscw")

    def _pscw_tag(self, phase: int) -> int:
        # per-window tags: seq * 2 + phase (0 = post, 1 = complete)
        return int(self.wid[-1]) * 2 + phase

    def post(self, origin_ranks) -> None:
        """Target side: expose the window to ``origin_ranks``."""
        eng = self._pscw_engine()
        self._pscw_origins = list(origin_ranks)
        for o in self._pscw_origins:
            eng.send(None, o, self._pscw_tag(0))

    def start(self, target_ranks) -> None:
        """Origin side: wait for each target's post token."""
        eng = self._pscw_engine()
        self._pscw_targets = list(target_ranks)
        for t in self._pscw_targets:
            eng.recv(t, self._pscw_tag(0))

    def complete(self) -> None:
        """Origin side: epoch ends — ops are already target-acked, so
        one token per target carries the completion."""
        eng = self._pscw_engine()
        for t in getattr(self, "_pscw_targets", []):
            eng.send(None, t, self._pscw_tag(1))
        self._pscw_targets = []

    def wait(self) -> None:
        """Target side: block until every origin completed."""
        eng = self._pscw_engine()
        for o in getattr(self, "_pscw_origins", []):
            eng.recv(o, self._pscw_tag(1))
        self._pscw_origins = []

    def free(self) -> None:
        # the completion barrier can raise over a dead/revoked peer
        # (ULFM free); the handler must unregister regardless or the
        # router keeps dispatching frames into a freed window
        try:
            self.comm.barrier()
        finally:
            self.comm.router.unregister_rma(self.wid)

    def peer_failed(self, world_rank: int) -> None:
        """FT reclaim hook (osc/window wires it to the ft registry):
        a dead origin can never send its unlock, so purge it from the
        passive-lock queue and hand its grant to the next waiter —
        otherwise one SIGKILL wedges every survivor's Win_lock."""
        grants = []
        with self._lock:
            self._holders = [(o, t) for (o, t) in self._holders
                             if o != world_rank]
            self._waiters = [(o, t, a) for (o, t, a) in self._waiters
                             if o != world_rank]
            while self._waiters:
                o, t, a = self._waiters[0]
                ok = (not self._holders if t == LOCK_EXCLUSIVE
                      else all(ht == LOCK_SHARED
                               for _, ht in self._holders))
                if not ok:
                    break
                self._waiters.pop(0)
                self._holders.append((o, t))
                grants.append((o, a))
                if t == LOCK_EXCLUSIVE:
                    break
        for o, a in grants:
            try:
                self.comm.router.send_ack(o, a)
            except Exception:            # noqa: BLE001 — a grant to a
                pass                     # failing peer is best-effort

    def _bounds(self, disp: int, count: int,
                target: Optional[int] = None) -> None:
        limit = (self.sizes[target] if target is not None
                 else self.size)
        if disp < 0 or disp + count > limit:
            raise MPIError(ERR_ARG,
                           f"window access [{disp}, {disp + count}) "
                           f"outside [0, {limit}) at rank "
                           f"{target if target is not None else 'self'}")

    # -- target-side handler (runs on btl reader threads) --------------
    def _handle(self, header: dict, raw: bytes) -> None:
        # runs on a btl reader thread: NOTHING may escape (an uncaught
        # exception would kill the reader and silently drop every later
        # frame from that peer) — failures reply as rma_error
        try:
            self._handle_inner(header, raw)
        except Exception as e:          # noqa: BLE001
            self.comm.router.send_ack(
                header["origin"], header["ack_id"],
                {"rma_error": f"{type(e).__name__}: {e}"})

    def _handle_inner(self, header: dict, raw: bytes) -> None:
        from ompi_tpu.btl.tcp import decode_payload
        router = self.comm.router
        origin_world = header["origin"]          # world rank of origin
        op = header["op"]
        if _RMA_DEBUG:
            import sys as _sys
            _sys.stderr.write(
                f"RMADBG r{router.rank} handle {op} wid={self.wid} "
                f"name={self.name} origin={origin_world}\n")
            _sys.stderr.flush()
        aid = header["ack_id"]
        data = (decode_payload(header["desc"], raw)
                if "desc" in header else None)
        if op == "lock":
            self._lock_request(origin_world, header["lt"], aid)
            return
        reply = None
        with self._lock:
            if op == "put":
                d = header["disp"]
                if d + data.size > self.size:
                    raise MPIError(ERR_ARG, "put past exposure region")
                self.local[d:d + data.size] = data
            elif op == "get":
                d, c = header["disp"], header["count"]
                if d + c > self.size:
                    raise MPIError(ERR_ARG, "get past exposure region")
                reply = self.local[d:d + c].copy()
            elif op == "acc":
                d = header["disp"]
                fn = _ACC_OPS[header["acc"]]
                if self.dtype == np.uint8 and data.dtype != np.uint8:
                    # typed accumulate into a BYTE-addressed window
                    # (the C ABI's Win_allocate windows): combine
                    # through a typed view of the byte storage, still
                    # atomically on this reader thread
                    nb = data.nbytes
                    seg = self.local[d:d + nb].view(data.dtype)
                    out = data if fn is None else fn(seg, data)
                    self.local[d:d + nb] = \
                        np.ascontiguousarray(out).view(np.uint8)
                else:
                    seg = self.local[d:d + data.size]
                    self.local[d:d + data.size] = (
                        data if fn is None else fn(seg, data))
            elif op == "getacc":
                d = header["disp"]
                fn = _ACC_OPS.get(header["acc"])
                if self.dtype == np.uint8 and data.dtype != np.uint8:
                    # typed fetch-accumulate into a byte-addressed
                    # window (C ABI Get_accumulate/Fetch_and_op)
                    nb = data.nbytes
                    seg = self.local[d:d + nb].view(data.dtype)
                    reply = seg.copy()
                    if fn is not False:  # MPI_NO_OP fetches only
                        out = data if fn is None else fn(seg, data)
                        self.local[d:d + nb] = \
                            np.ascontiguousarray(out).view(np.uint8)
                else:
                    seg = self.local[d:d + data.size]
                    reply = seg.copy()
                    if fn is not False:  # MPI_NO_OP fetches only
                        self.local[d:d + data.size] = (
                            data if fn is None else fn(seg, data))
            elif op == "cas":
                d = header["disp"]
                if self.dtype == np.uint8 and data.dtype != np.uint8:
                    # typed CAS against a byte-addressed window
                    esz = data.dtype.itemsize
                    seg = self.local[d:d + esz].view(data.dtype)
                    reply = seg.copy()
                    if seg[0] == data[1]:
                        self.local[d:d + esz] = np.ascontiguousarray(
                            data[0:1]).view(np.uint8)
                else:
                    reply = np.array([self.local[d]], self.dtype)
                    if self.local[d] == data[1]:  # typed compare
                        self.local[d] = data[0]
            elif op == "unlock":
                self._unlock(origin_world, aid)
                return
        router.send_ack(origin_world, aid, reply)

    # -- passive-target lock queue (target side, non-blocking) --------
    def _lock_request(self, origin: int, lt: int, aid: int) -> None:
        with self._lock:
            grant = (not self._holders if lt == LOCK_EXCLUSIVE
                     else all(t == LOCK_SHARED
                              for _, t in self._holders))
            if grant and not self._waiters:
                self._holders.append((origin, lt))
            else:
                self._waiters.append((origin, lt, aid))
                return
        self.comm.router.send_ack(origin, aid)   # grant

    def _unlock(self, origin: int, aid: int) -> None:
        # caller holds self._lock
        self._holders = [(o, t) for (o, t) in self._holders
                         if o != origin]
        grants = []
        while self._waiters:
            o, t, a = self._waiters[0]
            ok = (not self._holders if t == LOCK_EXCLUSIVE
                  else all(ht == LOCK_SHARED
                           for _, ht in self._holders))
            if not ok:
                break
            self._waiters.pop(0)
            self._holders.append((o, t))
            grants.append((o, a))
            if t == LOCK_EXCLUSIVE:
                break
        router = self.comm.router
        router.send_ack(origin, aid)             # unlock complete
        for o, a in grants:
            router.send_ack(o, a)                # deferred lock grants
