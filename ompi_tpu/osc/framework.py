"""OSC — one-sided communication (RMA windows).

Behavioral spec: ``ompi/mca/osc/osc.h:373`` (module interface; put :210,
get :220, request-based rput/rget :269/:279), osc/rdma's active-target
(``osc_rdma_active_target.c``) and passive-target (lock/unlock via btl
atomics, ``osc_rdma_lock.h``) synchronization.

TPU-native re-design (single-controller SPMD): a window is a stacked
device buffer ``(nranks, win_size)`` sharded one shard per rank over the
communicator's mesh. ``put``/``get``/``accumulate`` become functional
shard updates (XLA dynamic-update-slice on the target's shard — data
moves over ICI, never through host); epochs map to JAX's async dispatch:
``fence`` drains outstanding updates (the analogue of the btl-atomic
fence), passive-target ``lock/unlock`` serialize controller-side access.
Accumulate honors MPI_REPLACE / MPI_NO_OP / predefined ops
(``ompi/op/op.c`` accumulate semantics).
"""
from __future__ import annotations

import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ompi_tpu.accelerator import LOCUS_DEVICE, check_addr
from ompi_tpu.core import op as op_mod
from ompi_tpu.core.errhandler import ERR_ARG, ERR_RANK, MPIError
from ompi_tpu.core.request import Request

LOCK_EXCLUSIVE = 1
LOCK_SHARED = 2


class Win:
    """An RMA window over per-rank buffers of ``comm``.

    ``win = Win(comm, size)`` or ``Win.create(comm, stacked_buffer)``.
    All offsets/counts are in elements of the window's dtype.
    """

    def __init__(self, comm, size: int, dtype=np.float32,
                 buffer: Optional[Any] = None, name: str = ""):
        self.comm = comm
        if getattr(comm, "is_multiprocess", False):
            # Window state is controller-local; in a multi-controller
            # world remote shards are not addressable and put/get would
            # be silently wrong — the same clean guard the collectives
            # path raises (coll/xla._to_mesh). Spec for the real thing:
            # osc_rdma_comm.c remote-region tables.
            from ompi_tpu.core.errhandler import ERR_INTERN
            raise MPIError(
                ERR_INTERN,
                "stacked RMA windows are single-controller only: this "
                "communicator spans processes. For cross-process RMA "
                "use the per-rank execution model's RankWindow "
                "(ompi_tpu.osc.perrank, under mpirun --per-rank).")
        if buffer is not None:
            if buffer.ndim < 2 or buffer.shape[0] != comm.size:
                raise MPIError(ERR_ARG,
                               "window buffer must be stacked (nranks, n)")
            self._buf = buffer
            self.size = int(buffer.shape[-1])
            self.dtype = buffer.dtype
        else:
            self._buf = comm.alloc((size,), dtype)
            self.size = size
            self.dtype = np.dtype(dtype)
        self.name = name or f"win#{comm.cid}"
        self._lock = threading.RLock()
        self._lock_state = {}           # rank -> lock type
        self.attributes = {}
        self._freed = False

    @classmethod
    def create(cls, comm, buffer, name: str = "") -> "Win":
        return cls(comm, 0, buffer=buffer, name=name)

    @classmethod
    def allocate(cls, comm, size: int, dtype=np.float32) -> "Win":
        return cls(comm, size, dtype=dtype)

    # -- access ---------------------------------------------------------
    def _check_rank(self, rank: int):
        if not (0 <= rank < self.comm.size):
            raise MPIError(ERR_RANK, f"target rank {rank} out of range")

    def _update(self, target_rank: int, target_disp: int, data,
                combine=None):
        self._check_rank(target_rank)
        data = jnp.asarray(data) if check_addr(self._buf) == LOCUS_DEVICE \
            else np.asarray(data)
        n = data.shape[-1]
        if target_disp + n > self.size:
            raise MPIError(ERR_ARG, "RMA access beyond window bounds")
        with self._lock:
            if check_addr(self._buf) == LOCUS_DEVICE:
                cur = jax.lax.dynamic_slice(
                    self._buf, (target_rank, target_disp), (1, n))[0]
                new = combine(cur, data) if combine else data
                self._buf = jax.lax.dynamic_update_slice(
                    self._buf, new[None].astype(self._buf.dtype),
                    (target_rank, target_disp))
            else:
                cur = self._buf[target_rank, target_disp:target_disp + n]
                new = combine(cur, data) if combine else data
                self._buf[target_rank, target_disp:target_disp + n] = new

    def put(self, origin_data, target_rank: int, target_disp: int = 0):
        """MPI_Put (osc.h:210)."""
        self._update(target_rank, target_disp, origin_data)

    def get(self, target_rank: int, target_disp: int = 0,
            count: Optional[int] = None):
        """MPI_Get (osc.h:220): returns a host copy of the target region
        (functional API: recvbuf is the return value)."""
        self._check_rank(target_rank)
        count = count if count is not None else self.size - target_disp
        with self._lock:
            return np.asarray(
                self._buf[target_rank, target_disp:target_disp + count])

    def accumulate(self, origin_data, target_rank: int,
                   op: op_mod.Op = op_mod.SUM, target_disp: int = 0):
        """MPI_Accumulate: REPLACE overwrites, NO_OP leaves target."""
        if op is op_mod.NO_OP:
            return
        comb = (None if op is op_mod.REPLACE
                else (lambda cur, d: op.fn(cur, d.astype(cur.dtype))))
        self._update(target_rank, target_disp, origin_data, combine=comb)

    def get_accumulate(self, origin_data, target_rank: int,
                       op: op_mod.Op = op_mod.SUM, target_disp: int = 0):
        """MPI_Get_accumulate: fetch-then-accumulate, atomic under the
        window lock."""
        with self._lock:
            n = np.asarray(origin_data).shape[-1]
            old = self.get(target_rank, target_disp, n)
            self.accumulate(origin_data, target_rank, op, target_disp)
        return old

    def fetch_and_op(self, value, target_rank: int,
                     op: op_mod.Op = op_mod.SUM, target_disp: int = 0):
        return self.get_accumulate(np.asarray([value]), target_rank, op,
                                   target_disp)[0]

    def compare_and_swap(self, value, compare, target_rank: int,
                         target_disp: int = 0):
        with self._lock:
            old = self.get(target_rank, target_disp, 1)[0]
            if old == compare:
                self.put(np.asarray([value]), target_rank, target_disp)
        return old

    def rput(self, origin_data, target_rank: int,
             target_disp: int = 0) -> Request:
        self.put(origin_data, target_rank, target_disp)
        arrays = [self._buf] if isinstance(self._buf, jax.Array) else None
        return Request(arrays=arrays)

    def rget(self, target_rank: int, target_disp: int = 0,
             count: Optional[int] = None) -> Request:
        return Request.completed(self.get(target_rank, target_disp, count))

    def raccumulate(self, origin_data, target_rank: int,
                    op: op_mod.Op = op_mod.SUM,
                    target_disp: int = 0) -> Request:
        """MPI_Raccumulate (osc.h request-based variants)."""
        self.accumulate(origin_data, target_rank, op, target_disp)
        arrays = [self._buf] if isinstance(self._buf, jax.Array) else None
        return Request(arrays=arrays)

    def rget_accumulate(self, origin_data, target_rank: int,
                        op: op_mod.Op = op_mod.SUM,
                        target_disp: int = 0) -> Request:
        return Request.completed(
            self.get_accumulate(origin_data, target_rank, op, target_disp))

    # -- synchronization ------------------------------------------------
    def fence(self) -> None:
        """MPI_Win_fence: drain outstanding device updates (active
        target epoch boundary)."""
        if isinstance(self._buf, jax.Array):
            jax.block_until_ready(self._buf)
        self.comm.barrier()

    def lock(self, target_rank: int, lock_type: int = LOCK_EXCLUSIVE):
        self._lock.acquire()
        self._lock_state[target_rank] = lock_type

    def unlock(self, target_rank: int):
        self._lock_state.pop(target_rank, None)
        self._lock.release()

    def lock_all(self):
        self.lock(-1)

    def unlock_all(self):
        self.unlock(-1)

    def flush(self, target_rank: int = -1) -> None:
        if isinstance(self._buf, jax.Array):
            jax.block_until_ready(self._buf)

    def flush_all(self) -> None:
        self.flush()

    def sync(self) -> None:
        self.flush()

    # -- PSCW active-target (MPI_Win_post/start/complete/wait;
    #    osc_rdma_active_target.c generalized-sync semantics) -----------
    def post(self, group) -> None:
        """Expose this window to an access epoch by ``group``'s ranks."""
        self._exposure = tuple(group.world_ranks)

    def start(self, group) -> None:
        """Begin an access epoch targeting ``group``'s ranks; must pair
        with a matching ``post`` (checked at ``complete``)."""
        self._access = tuple(group.world_ranks)

    def complete(self) -> None:
        """End the access epoch: drain origin-side updates."""
        if getattr(self, "_access", None) is None:
            raise MPIError(ERR_ARG, "Win.complete without Win.start")
        self.flush()
        self._access = None

    def wait(self) -> None:
        """End the exposure epoch (blocks until accesses drained — in
        dispatch order that is a flush here)."""
        if getattr(self, "_exposure", None) is None:
            raise MPIError(ERR_ARG, "Win.wait without Win.post")
        self.flush()
        self._exposure = None

    def test(self) -> bool:
        """MPI_Win_test: nonblocking ``wait`` — exposure always drains
        in one flush here, so report completion and end the epoch."""
        if getattr(self, "_exposure", None) is None:
            return True
        self.wait()
        return True

    # -- dynamic windows (MPI_Win_create_dynamic / attach / detach) ----
    @classmethod
    def create_dynamic(cls, comm, dtype=np.float32) -> "Win":
        """A zero-size window that memory is attached to later."""
        w = cls(comm, 0, dtype=dtype, name=f"win_dyn#{comm.cid}")
        w._dynamic = True
        return w

    def attach(self, size: int) -> int:
        """Attach ``size`` elements (symmetrically, every rank) and
        return the base displacement of the new region — the analogue of
        the address the reference exchanges out-of-band after
        MPI_Win_attach."""
        if not getattr(self, "_dynamic", False):
            raise MPIError(ERR_ARG, "attach on a non-dynamic window")
        base = self.size
        grown_shape = (self.comm.size, base + size)
        if check_addr(self._buf) == LOCUS_DEVICE:
            pad = jnp.zeros((self.comm.size, size), dtype=self.dtype)
            self._buf = jax.device_put(
                jnp.concatenate([self._buf, pad], axis=1),
                self.comm.sharding)
        else:
            buf = np.zeros(grown_shape, dtype=self.dtype)
            if base:
                buf[:, :base] = self._buf
            self._buf = buf
        self.size = base + size
        return base

    def detach(self, base: int) -> None:
        """Detach a region; the displacement range becomes invalid (the
        storage is kept — displacement validity is the MPI contract)."""
        if not getattr(self, "_dynamic", False):
            raise MPIError(ERR_ARG, "detach on a non-dynamic window")

    def get_group(self):
        """MPI_Win_get_group: the group of the window's communicator."""
        return self.comm.group

    # -- introspection ---------------------------------------------------
    @property
    def buffer(self):
        """The stacked window contents (rank-major)."""
        return self._buf

    def free(self) -> None:
        self._freed = True
        self._buf = None

    def __repr__(self):
        return f"Win({self.name}, size={self.size}, dtype={self.dtype})"
