"""osc/pt2pt — window emulation over the acked active-message plane.

Behavioral spec: ``ompi/mca/osc/rdma`` running over the pml when no
RDMA-capable btl reaches the peer (``osc_rdma_component.c``'s
alternate path): every Put/Get/Accumulate is one framed request to the
target's window handler, applied on the target's reader thread and
acked — origin completion is remote completion, which is what makes
``fence`` a plain barrier and ``flush`` a no-op.

The engine is ``osc/perrank.RankWindow`` unchanged — this module is
the component's *selection identity*: ``osc/decision`` names it for
remote-host communicators, user-provided ``MPI_Win_create`` storage
(caller memory cannot be retroactively shm-backed), and any topology
``osc/shm`` refuses. It must therefore stay correct everywhere the
framework runs; ``osc/shm`` is the same-host fast path on top.
"""
from __future__ import annotations

from ompi_tpu.osc.perrank import RankWindow


class Pt2ptWindow(RankWindow):
    """The pt2pt osc component — RankWindow under its framework name."""

    component = "pt2pt"
