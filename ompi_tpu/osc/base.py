"""osc/base — the one-sided framework's shared plane.

Mirrors ``ompi/mca/osc/base``: the component-independent state every
osc component shares — MCA parameters, MPI_T pvars, telemetry
histograms, and the epoch state machine (``osc_base_frame.c`` +
the synchronization legality table of MPI-3 ch. 11.5).

The epoch machine is ORIGIN-side bookkeeping: each window tracks which
access epochs are plausibly open (fence / per-target passive locks /
lock_all / PSCW start set) and refuses data ops outside all of them
with ``MPI_ERR_RMA_SYNC``.  One deliberate looseness, shared with the
reference: a fence with no assert info both ends an epoch and may
start the next, so once any fence has run the window stays
fence-accessible until freed — the machine catches the real bug
classes (op before any sync, unlock without lock, flush outside a
passive epoch, fence inside a passive epoch, complete without start)
without false-positives on legal fence-then-lock programs.
"""
from __future__ import annotations

import threading
import weakref
from typing import Any, Dict, List, Optional, Set

from ompi_tpu.core.errhandler import ERR_RMA_SYNC, MPIError
from ompi_tpu.mca import pvar as _pvar
from ompi_tpu.mca import var

# the osc ops every component must serve and checkparity rule 7
# enforces parity tests for (tools/checkparity.py imports this)
OSC_OPS = ("put", "get", "accumulate")

_registered = False


def register_params() -> None:
    global _registered
    if _registered:
        return
    _registered = True
    var.var_register(
        "mpi", "base", "osc", vtype="str", default="auto",
        help="One-sided component selection at window creation: "
             "'shm' backs same-host windows with /dev/shm segments "
             "(direct load/store RMA), 'pt2pt' emulates the window "
             "over the acked active-message plane, 'auto' picks shm "
             "when every rank of the communicator shares the host "
             "(docs/RMA.md)")
    var.var_register(
        "mpi", "base", "osc_epoch_check", vtype="bool", default=True,
        help="Enforce the MPI-3 epoch discipline on window ops: data "
             "ops outside every open access epoch, unlock without "
             "lock, flush outside a passive epoch and fence inside "
             "one raise MPI_ERR_RMA_SYNC instead of corrupting "
             "memory silently")
    var.var_register(
        "mpi", "base", "osc_shm_notes", vtype="bool", default=True,
        help="osc/shm: after a direct remote put/accumulate, send the "
             "target a descriptor-only note frame so its pvars "
             "account bytes landed in its window by peers (the "
             "completion/accounting ctl plane; off drops the frames, "
             "never the data)")


# -- pvars ------------------------------------------------------------------
stats: Dict[str, int] = {
    "puts": 0, "gets": 0, "accs": 0,
    "put_bytes": 0, "get_bytes": 0, "acc_bytes": 0,
    "fences": 0, "locks": 0, "epoch_errors": 0,
    "windows_shm": 0, "windows_pt2pt": 0, "notes": 0,
    "ft_failed_epochs": 0,
}

_pvars_registered = False


def register_pvars() -> None:
    global _pvars_registered
    if _pvars_registered:
        return
    _pvars_registered = True
    _pvar.pvar_register(
        "osc_puts", lambda: stats["puts"],
        help="One-sided Put operations issued by this process "
             "(both osc components; docs/RMA.md)")
    _pvar.pvar_register(
        "osc_gets", lambda: stats["gets"],
        help="One-sided Get operations issued by this process")
    _pvar.pvar_register(
        "osc_accs", lambda: stats["accs"],
        help="One-sided Accumulate-class operations issued by this "
             "process (accumulate/get_accumulate/fetch_and_op/CAS)")
    _pvar.pvar_register(
        "osc_put_bytes", lambda: stats["put_bytes"], unit="bytes",
        help="Bytes written into remote windows by this process's "
             "Put operations")
    _pvar.pvar_register(
        "osc_get_bytes", lambda: stats["get_bytes"], unit="bytes",
        help="Bytes read from remote windows by this process's Get "
             "operations")
    _pvar.pvar_register(
        "osc_acc_bytes", lambda: stats["acc_bytes"], unit="bytes",
        help="Bytes combined into remote windows by this process's "
             "accumulate-class operations")
    _pvar.pvar_register(
        "osc_fences", lambda: stats["fences"],
        help="Win_fence epoch boundaries this process crossed")
    _pvar.pvar_register(
        "osc_locks", lambda: stats["locks"],
        help="Passive-target locks this process acquired (Win_lock "
             "grants, exclusive and shared)")
    _pvar.pvar_register(
        "osc_epoch_errors", lambda: stats["epoch_errors"],
        help="RMA calls refused with MPI_ERR_RMA_SYNC by the epoch "
             "state machine (op outside every open epoch)")
    _pvar.pvar_register(
        "osc_windows_shm", lambda: stats["windows_shm"],
        help="Windows this process created on the osc/shm component "
             "(same-host /dev/shm segment windows)")
    _pvar.pvar_register(
        "osc_windows_pt2pt", lambda: stats["windows_pt2pt"],
        help="Windows this process created on the osc/pt2pt "
             "component (active-message emulation)")
    _pvar.pvar_register(
        "osc_notes", lambda: stats["notes"],
        help="Descriptor-only completion notes received from peers "
             "that wrote this process's shm windows directly")
    _pvar.pvar_register(
        "osc_ft_failed_epochs", lambda: stats["ft_failed_epochs"],
        help="Open window epochs failed with MPI_ERR_PROC_FAILED "
             "because a peer of the window died")


# -- telemetry histograms ----------------------------------------------------
def op_hist(kind: str):
    """The per-op-kind latency histogram (``tele_osc_put_us`` /
    ``tele_osc_get_us`` / ``tele_osc_acc_us``), created lazily so a
    telemetry-off process never allocates them. Callers gate on
    ``telemetry.active`` themselves (the hot-path discipline)."""
    from ompi_tpu import telemetry as _tele
    return _tele.get_hist(
        f"tele_osc_{kind}_us", unit="us",
        help=f"One-sided {kind} origin-side completion latency "
             f"(docs/RMA.md)")


# -- live-window registry (flight recorder) ---------------------------------
_live_lock = threading.Lock()
_live: "weakref.WeakSet" = weakref.WeakSet()


def track_window(win) -> None:
    with _live_lock:
        _live.add(win)


def untrack_window(win) -> None:
    with _live_lock:
        _live.discard(win)


def open_epoch_state() -> List[Dict[str, Any]]:
    """Every live window's epoch state — the flight recorder's
    ``osc_epochs`` section (what was open when the incident fired)."""
    with _live_lock:
        wins = list(_live)
    out = []
    for w in wins:
        try:
            ep = w._epoch
            st = {"win": w.name, "component": w.component,
                  "fenced": ep.fenced, "lock_all": ep.lock_all,
                  "locked": sorted(ep.locked),
                  "pscw_access": sorted(ep.pscw_access),
                  "pscw_exposure": sorted(ep.pscw_exposure),
                  "dead_peers": sorted(getattr(w, "_dead", ()))}
            if (ep.fenced or ep.lock_all or ep.locked
                    or ep.pscw_access or ep.pscw_exposure
                    or st["dead_peers"]):
                out.append(st)
        except Exception:                # noqa: BLE001 — advisory only
            pass
    return out


# -- epoch state machine -----------------------------------------------------
class EpochState:
    """Origin-side access-epoch legality (MPI-3 ch. 11.5).

    States tracked: ``fenced`` (a Win_fence has run — active-target
    access plausibly open until the window dies), per-target passive
    ``locked`` map, ``lock_all``, and the PSCW ``start`` target set
    (access) / ``post`` origin set (exposure)."""

    def __init__(self) -> None:
        self.fenced = False
        self.lock_all = False
        self.locked: Dict[int, int] = {}      # target -> lock type
        self.pscw_access: Set[int] = set()
        self.pscw_exposure: Set[int] = set()

    # -- data-op legality ----------------------------------------------
    def check_access(self, target: int, op: str) -> None:
        if (self.fenced or self.lock_all or target in self.locked
                or target in self.pscw_access):
            return
        raise MPIError(
            ERR_RMA_SYNC,
            f"RMA {op} to rank {target} outside every access epoch "
            f"(no fence has run, target not locked, no lock_all, "
            f"not in the Win_start group)")

    # -- synchronization transitions -----------------------------------
    def fence(self) -> None:
        if self.locked or self.lock_all:
            raise MPIError(ERR_RMA_SYNC,
                           "Win_fence inside a passive-target epoch "
                           "(unlock first)")
        self.fenced = True

    def lock(self, target: int) -> None:
        if target in self.locked:
            raise MPIError(ERR_RMA_SYNC,
                           f"Win_lock: rank {target} already locked "
                           f"by this origin")
        if self.lock_all:
            raise MPIError(ERR_RMA_SYNC,
                           "Win_lock inside a lock_all epoch")

    def locked_ok(self, target: int, lock_type: int) -> None:
        self.locked[target] = lock_type

    def unlock(self, target: int) -> None:
        if target not in self.locked:
            raise MPIError(ERR_RMA_SYNC,
                           f"Win_unlock: rank {target} is not locked")

    def unlocked_ok(self, target: int) -> None:
        self.locked.pop(target, None)

    def lock_all_begin(self) -> None:
        if self.lock_all:
            raise MPIError(ERR_RMA_SYNC, "Win_lock_all twice")

    def lock_all_ok(self) -> None:
        self.lock_all = True

    def unlock_all(self) -> None:
        if not self.lock_all:
            raise MPIError(ERR_RMA_SYNC,
                           "Win_unlock_all without Win_lock_all")
        self.lock_all = False

    def flush(self, target: Optional[int] = None) -> None:
        if self.lock_all:
            return
        if target is not None and target in self.locked:
            return
        if target is None and self.locked:
            return
        raise MPIError(ERR_RMA_SYNC,
                       "Win_flush outside a passive-target epoch")

    def start(self, targets) -> None:
        self.pscw_access = set(int(t) for t in targets)

    def complete(self) -> None:
        if not self.pscw_access:
            raise MPIError(ERR_RMA_SYNC,
                           "Win_complete without Win_start")
        self.pscw_access = set()

    def post(self, origins) -> None:
        self.pscw_exposure = set(int(o) for o in origins)

    def wait(self) -> None:
        if not self.pscw_exposure:
            raise MPIError(ERR_RMA_SYNC, "Win_wait without Win_post")
        self.pscw_exposure = set()


def _reset_for_tests() -> None:
    for k in stats:
        stats[k] = 0
    with _live_lock:
        _live.clear()


register_params()
register_pvars()
