"""Host locality synthesis — the hwloc-depth role (VERDICT r4 next
#10).

Behavioral spec: the reference feeds NUMA/socket/L3 levels from hwloc
to its hierarchical components (``opal/mca/hwloc/base/``; xhc builds
its ladder from hwloc levels per ``ompi/mca/coll/xhc/README.md``).
PJRT exposes almost no host topology, so this module reads it from the
OS directly (/sys cpu/cache/node trees) and, where the hardware ladder
is trivial (single-package CI hosts, virtual CPU meshes), synthesizes
a balanced factorization of the rank count so hierarchical algorithms
still exercise their multi-level paths — with the basis labeled, per
the decision-provenance discipline (every tuned default says where it
came from).
"""
from __future__ import annotations

import glob
import os
from typing import List, Optional, Tuple


def _read_int(path: str) -> Optional[int]:
    try:
        with open(path) as f:
            return int(f.read().strip().split("-")[0].split(",")[0])
    except (OSError, ValueError):
        return None


def host_topology() -> dict:
    """(packages, numa nodes, L3 domains, cpus) from /sys — the hwloc
    discovery collapsed to the levels the ladder builders consume."""
    cpus = sorted(glob.glob("/sys/devices/system/cpu/cpu[0-9]*"))
    ncpu = len(cpus) or (os.cpu_count() or 1)
    pkgs = set()
    l3s = set()
    for c in cpus:
        p = _read_int(os.path.join(c, "topology/physical_package_id"))
        if p is not None:
            pkgs.add(p)
        # L3 is index3 on every mainstream layout; shared_cpu_list
        # identifies the domain
        try:
            with open(os.path.join(c, "cache/index3",
                                   "shared_cpu_list")) as f:
                l3s.add(f.read().strip())
        except OSError:
            pass
    numa = len(glob.glob("/sys/devices/system/node/node[0-9]*"))
    return {"cpus": ncpu,
            "packages": len(pkgs) or 1,
            "numa": numa or 1,
            "l3_domains": len(l3s) or 1}


def _balanced_factor(n: int) -> Optional[int]:
    """Largest factor of n not above sqrt(n) (>= 2), for the synthetic
    two-level ladder."""
    best = None
    f = 2
    while f * f <= n:
        if n % f == 0:
            best = f
        f += 1
    return best


def ladder_sizes(nranks: int,
                 devices=None) -> Tuple[Optional[List[int]], str]:
    """(group sizes innermost-first, basis) for an n-rank hierarchical
    ladder. Preference order mirrors the reference's hwloc walk:

    1. device locality (ranks per process — the ICI/DCN boundary);
    2. OS topology (cpus per L3, L3s per NUMA, NUMA per package —
       mapped proportionally onto the rank count);
    3. a synthesized balanced factorization when both are trivial (a
       virtual mesh on a small host) — labeled so nobody mistakes it
       for measured hardware structure.
    """
    if nranks <= 3:
        return None, "trivial"
    if devices is not None:
        procs: dict = {}
        for d in devices:
            k = int(getattr(d, "process_index", 0) or 0)
            procs[k] = procs.get(k, 0) + 1
        if len(procs) > 1 and max(procs.values()) > 1:
            return [max(procs.values())], "device-locality"
    topo = host_topology()
    sizes: List[int] = []
    remaining = nranks
    # ranks per L3 domain, then L3 domains per NUMA, then NUMA count —
    # each level only materializes when it actually divides the ranks
    # into >1 groups of >1
    for domains in (topo["l3_domains"] * topo["numa"], topo["numa"],
                    topo["packages"]):
        if domains > 1 and remaining % domains == 0 \
                and remaining // domains > 1:
            sizes.append(remaining // domains)
            remaining = domains
    if sizes:
        return sizes, "os-topology"
    f = _balanced_factor(nranks)
    if f is not None:
        return [f], "synthetic-mesh"
    return None, "trivial"
