"""Memchecker — user-buffer state tracking around communication calls.

Behavioral spec: ``opal/mca/memchecker/valgrind``
(``memchecker_valgrind_module.c``): the reference wraps
``VALGRIND_MAKE_MEM_*`` to mark user buffers *undefined* while a pending
operation owns them (a nonblocking send's buffer must not be modified, a
nonblocking receive's buffer must not be read) and *defined* again at
completion, so valgrind flags the misuse at the exact racing access.

TPU-native re-design: there is no valgrind to delegate to, and device
arrays are immutable — the entire class of "modified a buffer the
library still owns" races only exists for HOST (numpy) buffers. The
checker therefore tracks host buffers by id with content fingerprints:

- ``inflight(buf, why)``   — the library holds a read obligation
  (partitioned send between ``pready`` and completion, a pending ssend):
  a fingerprint is taken; ``verify(buf)`` at completion raises
  ``MemcheckError`` if the user mutated the buffer meanwhile.
- ``undefined(buf, why)``  — the library holds a write obligation (a
  posted receive's target): ``check_readable(buf)`` raises until
  ``defined(buf)``.

Enabled with the MCA var ``mpi_memchecker_enable`` (off by default —
fingerprinting is a full buffer read, exactly like the reference's
memchecker being a debug-build feature). All entry points are no-ops
when disabled, so hot paths stay clean.
"""
from __future__ import annotations

import threading
import zlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ompi_tpu.mca import var


class MemcheckError(RuntimeError):
    """A tracked buffer was used while the library owned it."""


def _register() -> None:
    var.var_register("mpi", "memchecker", "enable", vtype="bool",
                     default=False,
                     help="Track host buffer ownership around pt2pt "
                          "calls: detect user modification of in-flight "
                          "send buffers and reads of not-yet-delivered "
                          "receive buffers (the opal memchecker role; "
                          "debug feature, costs a buffer read per mark)")


_register()

_lock = threading.Lock()
# id(buf) -> ("inflight", fingerprint, why) | ("undefined", None, why)
_tracked: Dict[int, Tuple[str, Optional[int], str]] = {}
_violations = 0


def enabled() -> bool:
    return bool(var.var_get("mpi_memchecker_enable", False))


def _fp(buf: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(buf).tobytes())


def _host(buf: Any) -> Optional[np.ndarray]:
    return buf if isinstance(buf, np.ndarray) else None


def inflight(buf: Any, why: str = "pending send") -> None:
    """Library takes a read obligation on ``buf``."""
    if not enabled():
        return
    a = _host(buf)
    if a is None:
        return                       # device arrays are immutable
    with _lock:
        _tracked[id(a)] = ("inflight", _fp(a), why)


def undefined(buf: Any, why: str = "pending receive") -> None:
    """Library takes a write obligation on ``buf``: contents are
    undefined for the user until ``defined``."""
    if not enabled():
        return
    a = _host(buf)
    if a is None:
        return
    with _lock:
        _tracked[id(a)] = ("undefined", None, why)


def verify(buf: Any) -> None:
    """Completion of a read obligation: raise if the user mutated the
    buffer while the library owned it (the race valgrind would flag at
    the mutating store)."""
    if not enabled():
        return
    a = _host(buf)
    if a is None:
        return
    with _lock:
        ent = _tracked.pop(id(a), None)
    if ent is None or ent[0] != "inflight":
        return
    if _fp(a) != ent[1]:
        global _violations
        with _lock:
            _violations += 1
        raise MemcheckError(
            f"send buffer modified while in flight ({ent[2]}): MPI "
            f"forbids touching a buffer the library still owns")


def defined(buf: Any) -> None:
    """Completion of a write obligation: the buffer is the user's
    again."""
    if not enabled():
        return
    a = _host(buf)
    if a is not None:
        with _lock:
            _tracked.pop(id(a), None)


def check_readable(buf: Any) -> None:
    """Raise if ``buf`` is currently undefined (a posted receive's
    target that has not completed)."""
    if not enabled():
        return
    a = _host(buf)
    if a is None:
        return
    with _lock:
        ent = _tracked.get(id(a))
    if ent is not None and ent[0] == "undefined":
        raise MemcheckError(
            f"read of an undefined buffer ({ent[2]}): contents are "
            f"unspecified until the operation completes")


def violations() -> int:
    return _violations


def _reset_for_tests() -> None:
    global _violations
    with _lock:
        _tracked.clear()
        _violations = 0
