"""opal_output — verbosity-gated diagnostic streams.

Behavioral spec: ``opal/util/output.h:32-58`` — components open named
output streams; each stream has a verbosity level controlled by a
per-framework MCA var (``<framework>_base_verbose``); ``opal_output(id,
fmt, ...)`` writes unconditionally, ``opal_output_verbose(level, id,
...)`` only when the stream's verbosity is at least ``level``.

TPU-native: same shape over Python logging-free file objects (stderr by
default; capturable for tests). Stream verbosity reads the live MCA var
at call time, so ``--mca coll_base_verbose 10`` style overrides work
mid-run — matching the reference's var-backed stream levels.
"""
from __future__ import annotations

import sys
import threading
from typing import Dict, Optional, TextIO

from ompi_tpu.mca import var

_lock = threading.Lock()
_streams: Dict[int, "Stream"] = {}
_next_id = 1


class Stream:
    def __init__(self, sid: int, prefix: str, framework: str,
                 file: Optional[TextIO]):
        self.id = sid
        self.prefix = prefix
        self.framework = framework
        self.file = file

    def verbosity(self) -> int:
        if not self.framework:
            return 0
        return int(var.var_get(f"{self.framework}_base_verbose", 0) or 0)


def open_stream(prefix: str = "", framework: str = "",
                file: Optional[TextIO] = None) -> int:
    """Returns a stream id (opal_output_open). ``framework`` binds the
    stream's verbosity to ``<framework>_base_verbose`` (registered here
    when the framework hasn't opened yet — registration is idempotent)."""
    global _next_id
    if framework:
        var.var_register(framework, "base", "verbose", vtype="int",
                         default=0,
                         help=f"Verbosity for the {framework} framework")
    with _lock:
        sid = _next_id
        _next_id += 1
        _streams[sid] = Stream(sid, prefix, framework, file)
    return sid


def close_stream(sid: int) -> None:
    with _lock:
        _streams.pop(sid, None)


def output(sid: int, message: str) -> None:
    """Unconditional write (opal_output)."""
    s = _streams.get(sid)
    if s is None:
        return
    f = s.file or sys.stderr
    f.write(f"[{s.prefix}] {message}\n" if s.prefix else message + "\n")


def output_verbose(level: int, sid: int, message: str) -> None:
    """Write only when the stream's verbosity >= level
    (opal_output_verbose)."""
    s = _streams.get(sid)
    if s is None or s.verbosity() < level:
        return
    output(sid, message)


def _reset_for_tests() -> None:
    global _next_id
    with _lock:
        _streams.clear()
        _next_id = 1
