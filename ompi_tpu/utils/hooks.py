"""Profiling interposition — the PMPI re-design.

The reference generates every binding twice (``MPI_*`` weak-aliased over
``PMPI_*``, ``ompi/mpi/c/Makefile.am:43,522-533``) so tools interpose by
defining ``MPI_*``. In Python the same capability is an explicit hook
chain: ``register_profiler(fn)`` installs ``fn(event, comm, info)``
callbacks fired at every collective/pt2pt entry — the MPI_T events /
PERUSE instrumentation point (``ompi/peruse``)."""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List

_lock = threading.Lock()
_hooks: List[Callable[[str, Any, Dict[str, Any]], None]] = []

# Event-name registry backing the MPI_T events API (``MPI_T_event_*``,
# ``ompi/mpi/tool/events.c`` semantics): the event types a tool can bind
# handlers to. Components pre-declare theirs; names are also learned
# dynamically the first time they fire. Registration order is the index
# space — MPI_T requires an event-type index to stay valid once handed
# out, so this is an append-only list (never sorted, never compacted).
_known_events: List[str] = [
    "coll_allreduce", "coll_reduce", "coll_bcast", "coll_allgather",
    "coll_gather", "coll_scatter", "coll_alltoall",
    "coll_reduce_scatter_block", "coll_scan", "coll_exscan",
    "coll_barrier", "pml_send", "pml_recv",
]
_known_event_set = set(_known_events)


def declare_event(name: str) -> None:
    with _lock:
        if name not in _known_event_set:
            _known_event_set.add(name)
            _known_events.append(name)


def known_events() -> List[str]:
    with _lock:
        return list(_known_events)


def register_profiler(fn: Callable[[str, Any, Dict[str, Any]], None]):
    """Install a profiling hook; returns a handle for unregister."""
    with _lock:
        _hooks.append(fn)
    return fn


def unregister_profiler(handle) -> None:
    with _lock:
        try:
            _hooks.remove(handle)
        except ValueError:
            pass


def fire(event: str, comm, info: Dict[str, Any]) -> None:
    # Hot path (every collective and pt2pt entry): stay lock-free when
    # there is nothing to do — membership reads on builtins are safe.
    if event not in _known_event_set:
        declare_event(event)
    if not _hooks:
        return
    with _lock:
        hooks = list(_hooks)
    for h in hooks:
        try:
            h(event, comm, info)
        except Exception:
            pass          # profiler bugs must not break communication
