"""Profiling interposition — the PMPI re-design.

The reference generates every binding twice (``MPI_*`` weak-aliased over
``PMPI_*``, ``ompi/mpi/c/Makefile.am:43,522-533``) so tools interpose by
defining ``MPI_*``. In Python the same capability is an explicit hook
chain: ``register_profiler(fn)`` installs ``fn(event, comm, info)``
callbacks fired at every collective/pt2pt entry — the MPI_T events /
PERUSE instrumentation point (``ompi/peruse``)."""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List

_lock = threading.Lock()
_hooks: List[Callable[[str, Any, Dict[str, Any]], None]] = []

# Event-name registry backing the MPI_T events API (``MPI_T_event_*``,
# ``ompi/mpi/tool/events.c`` semantics): the event types a tool can bind
# handlers to. Components pre-declare theirs; names are also learned
# dynamically the first time they fire. Registration order is the index
# space — MPI_T requires an event-type index to stay valid once handed
# out, so this is an append-only list (never sorted, never compacted).
_known_events: List[str] = [
    "coll_allreduce", "coll_reduce", "coll_bcast", "coll_allgather",
    "coll_gather", "coll_scatter", "coll_alltoall",
    "coll_reduce_scatter_block", "coll_scan", "coll_exscan",
    "coll_barrier", "pml_send", "pml_recv",
]
_known_event_set = set(_known_events)


def declare_event(name: str) -> None:
    with _lock:
        if name not in _known_event_set:
            _known_event_set.add(name)
            _known_events.append(name)


def known_events() -> List[str]:
    with _lock:
        return list(_known_events)


def register_profiler(fn: Callable[[str, Any, Dict[str, Any]], None]):
    """Install a profiling hook; returns a handle for unregister."""
    with _lock:
        _hooks.append(fn)
    return fn


def unregister_profiler(handle) -> None:
    with _lock:
        try:
            _hooks.remove(handle)
        except ValueError:
            pass


# Dropped-callback accounting: a raising profiler must not break
# communication, but silently eating its exceptions made tool bugs
# undiagnosable (and MPI_T's event-handle ``dropped`` count was never
# incremented). Every swallowed exception is now counted globally
# (pvar ``hooks_dropped``) and the FIRST failure of each hook logs its
# traceback once — later failures of the same hook stay silent.
_drop_lock = threading.Lock()
_dropped_total = 0
_logged_hooks: set = set()               # id(hook) already tracebacked


def _count_drop(h, event: str) -> None:
    global _dropped_total
    with _drop_lock:
        _dropped_total += 1
        first = id(h) not in _logged_hooks
        if first:
            _logged_hooks.add(id(h))
    if first:
        import sys
        import traceback
        sys.stderr.write(
            f"ompi_tpu: profiler hook "
            f"{getattr(h, '__name__', repr(h))} raised on event "
            f"{event!r}; dropping (counted in the hooks_dropped pvar; "
            f"further failures of this hook are silent):\n")
        traceback.print_exc(file=sys.stderr)


def dropped() -> int:
    with _drop_lock:
        return _dropped_total


def _reset_drops_for_tests() -> None:
    global _dropped_total
    with _drop_lock:
        _dropped_total = 0
        _logged_hooks.clear()


def fire(event: str, comm, info: Dict[str, Any]) -> None:
    # Hot path (every collective and pt2pt entry): stay lock-free when
    # there is nothing to do — membership reads on builtins are safe.
    if event not in _known_event_set:
        declare_event(event)
    if not _hooks:
        return
    with _lock:
        hooks = list(_hooks)
    for h in hooks:
        try:
            h(event, comm, info)
        except Exception:
            _count_drop(h, event)


def _register_pvar() -> None:
    from ompi_tpu.mca import pvar
    pvar.pvar_register(
        "hooks_dropped", dropped,
        help="Profiler-hook exceptions swallowed by utils.hooks.fire "
             "(first failure per hook logged with traceback)")


_register_pvar()
