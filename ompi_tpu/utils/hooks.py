"""Profiling interposition — the PMPI re-design.

The reference generates every binding twice (``MPI_*`` weak-aliased over
``PMPI_*``, ``ompi/mpi/c/Makefile.am:43,522-533``) so tools interpose by
defining ``MPI_*``. In Python the same capability is an explicit hook
chain: ``register_profiler(fn)`` installs ``fn(event, comm, info)``
callbacks fired at every collective/pt2pt entry — the MPI_T events /
PERUSE instrumentation point (``ompi/peruse``)."""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List

_lock = threading.Lock()
_hooks: List[Callable[[str, Any, Dict[str, Any]], None]] = []


def register_profiler(fn: Callable[[str, Any, Dict[str, Any]], None]):
    """Install a profiling hook; returns a handle for unregister."""
    with _lock:
        _hooks.append(fn)
    return fn


def unregister_profiler(handle) -> None:
    with _lock:
        try:
            _hooks.remove(handle)
        except ValueError:
            pass


def fire(event: str, comm, info: Dict[str, Any]) -> None:
    if not _hooks:
        return
    with _lock:
        hooks = list(_hooks)
    for h in hooks:
        try:
            h(event, comm, info)
        except Exception:
            pass          # profiler bugs must not break communication
