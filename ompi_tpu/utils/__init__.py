"""Utility layer (mirrors ``opal/util``): output streams, help
catalogs, profiling hooks."""
