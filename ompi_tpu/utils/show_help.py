"""show_help — user-facing diagnostic catalogs with de-duplication.

Behavioral spec: ``opal/util/show_help.h`` — components ship
``help-*.txt`` message catalogs (INI-style ``[topic]`` sections with
``%s``-style substitution); ``opal_show_help(file, topic, ...)`` renders
the catalog text, and repeated emissions of the same (file, topic) are
aggregated ("N more processes sent help message ...") instead of
spamming every rank's copy.

TPU-native: catalogs are the in-package ``help/*.txt`` files (same
INI-section format); de-dup is per (catalog, topic) with a count,
flushed on demand — the single-controller analogue of the reference's
cross-rank aggregation window.
"""
from __future__ import annotations

import os
import re
import sys
import threading
from typing import Dict, List, Optional, TextIO, Tuple

_lock = threading.Lock()
_catalog_cache: Dict[str, Dict[str, str]] = {}
_seen: Dict[Tuple[str, str], int] = {}

_HELP_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "help")


def _load_catalog(name: str) -> Dict[str, str]:
    cat = _catalog_cache.get(name)
    if cat is not None:
        return cat
    cat = {}
    path = os.path.join(_HELP_DIR, name)
    try:
        with open(path) as f:
            topic, lines = None, []
            for raw in f:
                m = re.match(r"^\[(.+)\]\s*$", raw)
                if m:
                    if topic is not None:
                        cat[topic] = "".join(lines).rstrip("\n")
                    topic, lines = m.group(1), []
                elif topic is not None and not raw.startswith("#"):
                    lines.append(raw)
            if topic is not None:
                cat[topic] = "".join(lines).rstrip("\n")
    except OSError:
        pass
    _catalog_cache[name] = cat
    return cat


def render(filename: str, topic: str, *args) -> str:
    """Catalog text with %s substitution; a self-describing fallback
    when the catalog/topic is missing (the reference prints a 'sorry,
    no help' banner rather than failing)."""
    text = _load_catalog(filename).get(topic)
    if text is None:
        return (f"Help message {filename!r} / topic {topic!r} "
                f"unavailable (args: {args})")
    try:
        return text % args if args else text
    except (TypeError, ValueError):
        return text


def show_help(filename: str, topic: str, *args,
              want_error_header: bool = True,
              file: Optional[TextIO] = None) -> str:
    """Render + emit with de-duplication: the first emission prints the
    full message; repeats are counted and summarized by flush()."""
    key = (filename, topic)
    out = file or sys.stderr
    with _lock:
        n = _seen.get(key, 0)
        _seen[key] = n + 1
        first = (n == 0)
    msg = render(filename, topic, *args)
    if first:
        if want_error_header:
            bar = "-" * 60
            out.write(f"{bar}\n{msg}\n{bar}\n")
        else:
            out.write(msg + "\n")
    return msg


def flush(file: Optional[TextIO] = None) -> List[str]:
    """Emit the aggregation summary ('N more ... sent help message X')
    and reset counts — the reference's periodic aggregation output."""
    out = file or sys.stderr
    lines = []
    with _lock:
        for (fname, topic), n in _seen.items():
            if n > 1:
                line = (f"{n - 1} more occurrence(s) of help message "
                        f"[{fname} / {topic}] suppressed")
                lines.append(line)
                out.write(line + "\n")
        _seen.clear()
    return lines


def _reset_for_tests() -> None:
    with _lock:
        _seen.clear()
        _catalog_cache.clear()
