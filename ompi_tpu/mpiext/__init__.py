"""MPI extensions — mirrors ``ompi/mpiext`` (the MPIX_* namespace).

The reference ships extensions as self-contained sub-trees with their own
C bindings (ftmpi/ULFM, cuda/rocm support queries, affinity, shortfloat);
here each is a module exporting MPIX-style functions over the core.
"""
from ompi_tpu.mpiext import accel, affinity, ftmpi, shortfloat  # noqa: F401
