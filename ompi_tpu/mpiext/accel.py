"""mpiext/accel — accelerator support queries.

Behavioral spec: ``ompi/mpiext/cuda`` / ``ompi/mpiext/rocm`` —
``MPIX_Query_cuda_support()`` / ``MPIX_Query_rocm_support()`` return
whether the library was built with, and is currently running against,
device-buffer support (``ompi/mpiext/cuda/c/mpiext_cuda.c``).

TPU-native re-design: the question is whether HBM-resident jax arrays
ride the native XLA collective path (they always do when a TPU/device
platform is up; on CPU-only hosts the "device" is the host platform and
staging is the identity). The extension also exposes the device
inventory the reference leaves to ``MPIX_Query_*`` callers to discover
themselves.
"""
from __future__ import annotations

from typing import Dict, List


def Query_tpu_support() -> bool:
    """True when device (HBM) buffers dispatch to XLA collectives
    without host staging — the MPIX_Query_cuda_support analogue."""
    import jax
    try:
        return len(jax.devices()) > 0
    except RuntimeError:
        return False


def Query_cuda_support() -> bool:
    """Always False: this framework's device plane is XLA/TPU, not CUDA
    (provided so reference-portable apps can probe both)."""
    return False


def Query_rocm_support() -> bool:
    return False


def Device_inventory() -> List[Dict]:
    """One record per visible device (platform, id, process, coords)."""
    import jax
    from ompi_tpu.accelerator.framework import device_attrs
    return [device_attrs(d) for d in jax.devices()]
