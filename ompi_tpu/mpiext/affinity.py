"""mpiext/affinity — locality strings.

Behavioral spec: ``ompi/mpiext/affinity`` — ``OMPI_Affinity_str()``
returns three strings per calling rank describing requested binding,
actual binding, and the map of the whole job (hwloc-derived).

TPU-native re-design: "binding" is the rank -> device pinning on the
mesh; the locality string names the device platform/id/process and its
physical coordinates (the ICI-topology analogue of a socket/core map).
"""
from __future__ import annotations

from typing import List, Tuple


def _one(rank: int, device) -> str:
    from ompi_tpu.accelerator.framework import device_locality
    proc, coords = device_locality(device)
    where = f" coords={coords}" if coords else ""
    return (f"rank {rank} bound to {device.platform}:{device.id} "
            f"(process {proc}{where})")


def Affinity_str(comm, rank: int = 0) -> Tuple[str, str, str]:
    """(requested, actual, full-map) binding strings for ``rank`` —
    OMPI_Affinity_str shape. Requested == actual in this runtime: the
    communicator constructor is the binding."""
    actual = _one(rank, comm.devices[rank])
    full = "; ".join(_one(r, d) for r, d in enumerate(comm.devices))
    return actual, actual, full


def Affinity_map(comm) -> List[str]:
    return [_one(r, d) for r, d in enumerate(comm.devices)]
