"""mpiext/ftmpi — the ULFM MPIX_* API surface.

Behavioral spec: ``ompi/mpiext/ftmpi`` (the user-level ULFM interface
documented in ``docs/features/ulfm.rst:1-31``): revoke, shrink, agree,
failure acknowledgment, plus the MPI-5 FT additions (get_failed /
ack_failed). The heavy lifting lives in ``Communicator`` (state machine),
``coll/ftagree`` (agreement algorithm) and ``runtime/ft`` (detector).
"""
from __future__ import annotations

from typing import Optional, Sequence

from ompi_tpu.runtime import ft as _ft


def Comm_revoke(comm) -> None:
    comm.revoke()


def Comm_is_revoked(comm) -> bool:
    return comm.is_revoked()


def Comm_shrink(comm):
    return comm.shrink()


def Comm_ishrink(comm):
    return comm.ishrink()


def Comm_agree(comm, flags: Sequence[int]) -> int:
    return comm.agree(flags)


def Comm_iagree(comm, flags: Sequence[int]):
    return comm.iagree(flags)


def Comm_failure_ack(comm) -> None:
    comm.failure_ack()


def Comm_failure_get_acked(comm):
    return comm.failure_get_acked()


def Comm_get_failed(comm):
    return comm.get_failed()


def Comm_ack_failed(comm, num_to_ack: Optional[int] = None):
    return comm.ack_failed(num_to_ack)


# -- detector / injection surface (the PMIx-event-plane equivalent) -------
fail_rank = _ft.fail_rank
probe_devices = _ft.probe_devices
failed_ranks = _ft.failed_ranks
failure_epoch = _ft.epoch
failure_events = _ft.events
add_failure_listener = _ft.add_listener
remove_failure_listener = _ft.remove_listener

# the resilience plane's two halves, re-exported so FT tooling needs one
# import: deterministic fault injection (ft/inject, MCA-gated, zero-cost
# when off) and the ring heartbeat detector (ft/detector) — see
# docs/RESILIENCE.md
from ompi_tpu.ft import detector, inject  # noqa: E402,F401
