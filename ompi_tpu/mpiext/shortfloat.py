"""mpiext/shortfloat — half-precision datatypes.

Behavioral spec: ``ompi/mpiext/shortfloat`` — exposes
``MPIX_SHORT_FLOAT`` / ``MPIX_C_SHORT_FLOAT`` (and, where the compiler
supports it, bfloat16) as predefined datatypes usable in reductions.

TPU-native: half precision is not an extension here — bfloat16 is the
MXU's native format — so these are aliases into the core datatype
registry, provided for source parity with reference-portable apps.
"""
from ompi_tpu.core.datatype import BFLOAT16, FLOAT16

SHORT_FLOAT = FLOAT16          # MPIX_SHORT_FLOAT
C_SHORT_FLOAT = FLOAT16        # MPIX_C_SHORT_FLOAT
C_BF16 = BFLOAT16              # MPIX_C_BF16 (the MXU-native format)
