"""A tensor-parallel-aware causal transformer LM in pure JAX.

This model exists to exercise the framework the way real users exercise
the reference: a data-parallel + tensor-parallel training step whose
every cross-device byte moves through ``ompi_tpu.parallel.InGraphComm``
collectives (psum over the tp axis after row-parallel matmuls; gradient
allreduce over the dp axis) — the §2.6 strategy table made concrete.

Layout: attention heads and MLP hidden are sharded over the ``tp`` mesh
axis (Megatron-style column/row parallel pairs); embeddings and norms
are replicated; the batch is sharded over ``dp``. bfloat16 activations,
float32 params — MXU-friendly.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ompi_tpu.parallel import InGraphComm
from ompi_tpu.parallel.ring_attention import ring_attention


@dataclass(frozen=True)
class Config:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 8
    n_layers: int = 2
    d_ff: int = 512
    seq: int = 64
    dtype: Any = jnp.bfloat16

    @property
    def d_head(self) -> int:
        return self.d_model // self.n_heads


def init_params(key, cfg: Config, tp: int = 1) -> Dict:
    """Initialize params. ``tp`` > 1 returns the *local* shard for one tp
    rank-size (heads and d_ff divided by tp); with shard_map the same
    code initializes per-shard params inside the mesh.

    Pytree layout separates replicated from tp-sharded leaves so the
    gradient-sync rule (psum over dp for all; also over tp for
    replicated) is explicit.
    """
    assert cfg.n_heads % tp == 0 and cfg.d_ff % tp == 0
    hl, fl = cfg.n_heads // tp, cfg.d_ff // tp
    d, dh = cfg.d_model, cfg.d_head
    ks = jax.random.split(key, 2 + 4 * cfg.n_layers)
    scale = lambda fan_in: 1.0 / jnp.sqrt(fan_in)
    rep = {
        "emb": jax.random.normal(ks[0], (cfg.vocab, d), jnp.float32) * 0.02,
        "ln_f": jnp.ones((d,), jnp.float32),
    }
    rep["layers"] = [{"ln1": jnp.ones((d,), jnp.float32),
                      "ln2": jnp.ones((d,), jnp.float32)}
                     for _ in range(cfg.n_layers)]
    tp_layers = []
    for i in range(cfg.n_layers):
        k1, k2, k3, k4 = ks[2 + 4 * i: 6 + 4 * i]
        tp_layers.append({
            "wqkv": jax.random.normal(k1, (d, 3, hl, dh), jnp.float32)
            * scale(d),
            "wo": jax.random.normal(k2, (hl, dh, d), jnp.float32)
            * scale(cfg.n_heads * dh),
            "w1": jax.random.normal(k3, (d, fl), jnp.float32) * scale(d),
            "w2": jax.random.normal(k4, (fl, d), jnp.float32)
            * scale(cfg.d_ff),
        })
    return {"rep": rep, "tp": {"layers": tp_layers}}


def _rmsnorm(x, g):
    x32 = x.astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return (x32 * r * g).astype(x.dtype)


def forward(params: Dict, tokens, cfg: Config,
            tp_comm: Optional[InGraphComm] = None,
            sp_comm: Optional[InGraphComm] = None):
    """Causal LM forward. ``tp_comm`` set => heads/d_ff leaves are local
    tp shards and row-parallel outputs are psum'ed over the tp axis.
    ``sp_comm`` set => ``tokens`` is this rank's sequence block and
    attention runs as ring attention over the sp axis (K/V circulate by
    ppermute) — long-context via sequence parallelism."""
    rep, tpp = params["rep"], params["tp"]
    x = rep["emb"][tokens].astype(cfg.dtype)          # (B, S, D)
    B, S, D = x.shape
    causal = jnp.tril(jnp.ones((S, S), jnp.bool_))
    for li in range(cfg.n_layers):
        lr, lt = rep["layers"][li], tpp["layers"][li]
        h = _rmsnorm(x, lr["ln1"])
        if tp_comm is not None:
            h = tp_comm.copy_in(h)
        qkv = jnp.einsum("bsd,dchk->bcshk", h,
                         lt["wqkv"].astype(cfg.dtype))  # (B,3,S,hl,dh)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        if sp_comm is not None:
            o = ring_attention(q, k, v, sp_comm, causal=True)
        else:
            att = jnp.einsum("bshk,bthk->bhst", q, k) / jnp.sqrt(
                jnp.asarray(cfg.d_head, cfg.dtype))
            att = jnp.where(causal[None, None], att, -1e9)
            att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(
                cfg.dtype)
            o = jnp.einsum("bhst,bthk->bshk", att, v)  # (B,S,hl,dh)
        o = jnp.einsum("bshk,hkd->bsd", o, lt["wo"].astype(cfg.dtype))
        if tp_comm is not None:
            o = tp_comm.reduce_out(o)                  # row-parallel sum
        x = x + o
        h = _rmsnorm(x, lr["ln2"])
        if tp_comm is not None:
            h = tp_comm.copy_in(h)
        m = jax.nn.gelu(jnp.einsum("bsd,df->bsf", h,
                                   lt["w1"].astype(cfg.dtype)))
        m = jnp.einsum("bsf,fd->bsd", m, lt["w2"].astype(cfg.dtype))
        if tp_comm is not None:
            m = tp_comm.reduce_out(m)                  # row-parallel sum
        x = x + m
    x = _rmsnorm(x, rep["ln_f"])
    logits = jnp.einsum("bsd,vd->bsv", x.astype(jnp.float32), rep["emb"])
    return logits


def loss_fn(params, inputs, targets, cfg: Config,
            tp_comm: Optional[InGraphComm] = None,
            sp_comm: Optional[InGraphComm] = None):
    """Next-token cross-entropy (mean over the local batch/sequence
    shard). Callers pre-shift: inputs = tokens[:, :-1], targets =
    tokens[:, 1:] — pre-shifting keeps sequence-parallel blocks aligned
    (each sp rank's targets are its own block of the shifted stream)."""
    logits = forward(params, inputs, cfg, tp_comm, sp_comm)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def sgd_train_step(params, batch, cfg: Config, lr: float,
                   dp_comm: Optional[InGraphComm] = None,
                   tp_comm: Optional[InGraphComm] = None,
                   sp_comm: Optional[InGraphComm] = None):
    """One DP x TP x SP training step. Gradient synchronization follows
    the strategy table (SURVEY.md §2.6): grads allreduced (mean) over dp
    and over sp (each sp rank saw 1/n of the sequence); tp correctness
    comes from the Megatron f/g operators inside ``forward``.
    ``batch`` = (inputs, targets), pre-shifted."""
    inputs, targets = batch
    loss, grads = jax.value_and_grad(loss_fn)(params, inputs, targets,
                                              cfg, tp_comm, sp_comm)
    for comm in (sp_comm, dp_comm):
        if comm is not None:
            grads = jax.tree_util.tree_map(lambda g: comm.pmean(g), grads)
            loss = comm.pmean(loss)
    params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    return params, loss
