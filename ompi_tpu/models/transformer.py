"""The flagship causal transformer LM — every parallelism strategy in
ONE model.

This model exists to exercise the framework the way real users exercise
the reference: a training step whose every cross-device byte moves
through ``ompi_tpu.parallel.InGraphComm`` collectives — the §2.6
strategy table made concrete in a single composed program:

- **tp**: attention heads / MLP hidden sharded Megatron-style
  (column/row pairs; psum after row-parallel matmuls).
- **sp**: ring attention over the sequence axis (K/V circulate by
  ppermute, flash-style online softmax).
- **dp**: gradient allreduce (pmean) over the batch axis.
- **pp**: GPipe microbatch pipelining over layer stages
  (``pipeline_apply``: activations ring-shift between stages inside a
  ``lax.scan``; backward is AD through the shifts).
- **ep**: Switch-style MoE MLPs with one expert per rank of the
  expert axis (``moe_apply``: two alltoalls dispatch/combine).
- local attention lowers through ``ops/flash_attention``'s
  differentiable online-softmax fold when ``cfg.use_flash`` (the
  pallas kernel serves forward-only uses until a custom VJP lands).

Layout: bfloat16 activations, float32 params — MXU-friendly.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ompi_tpu.parallel import InGraphComm
from ompi_tpu.parallel.moe import moe_apply
from ompi_tpu.parallel.pipeline import pipeline_apply
from ompi_tpu.parallel.ring_attention import ring_attention


@dataclass(frozen=True)
class Config:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 8
    n_layers: int = 2
    d_ff: int = 512
    seq: int = 64
    dtype: Any = jnp.bfloat16
    moe: bool = False            # MLPs become Switch MoE blocks
    moe_experts: int = 0         # expert count (0: the tp arg/axis)
    moe_capacity: int = 0        # per-(src, expert) slots; 0 = auto
    use_flash: bool = False      # local attention via ops/flash

    @property
    def d_head(self) -> int:
        return self.d_model // self.n_heads


def init_params(key, cfg: Config, tp: int = 1) -> Dict:
    """Initialize params. ``tp`` > 1 returns the *local* shard for one tp
    rank-size (heads and d_ff divided by tp); with shard_map the same
    code initializes per-shard params inside the mesh.

    Pytree layout separates replicated from tp-sharded leaves so the
    gradient-sync rule (psum over dp for all; also over tp for
    replicated) is explicit.
    """
    assert cfg.n_heads % tp == 0 and cfg.d_ff % tp == 0
    hl, fl = cfg.n_heads // tp, cfg.d_ff // tp
    d, dh = cfg.d_model, cfg.d_head
    ks = jax.random.split(key, 2 + 4 * cfg.n_layers)
    scale = lambda fan_in: 1.0 / jnp.sqrt(fan_in)
    rep = {
        "emb": jax.random.normal(ks[0], (cfg.vocab, d), jnp.float32) * 0.02,
        "ln_f": jnp.ones((d,), jnp.float32),
    }
    rep["layers"] = [{"ln1": jnp.ones((d,), jnp.float32),
                      "ln2": jnp.ones((d,), jnp.float32)}
                     for _ in range(cfg.n_layers)]
    tp_layers = []
    for i in range(cfg.n_layers):
        k1, k2, k3, k4 = ks[2 + 4 * i: 6 + 4 * i]
        lay = {
            "wqkv": jax.random.normal(k1, (d, 3, hl, dh), jnp.float32)
            * scale(d),
            "wo": jax.random.normal(k2, (hl, dh, d), jnp.float32)
            * scale(cfg.n_heads * dh),
        }
        if cfg.moe:
            # Switch MoE: gate replicated; w1/w2 hold ALL experts on a
            # leading expert axis (sharded over the expert-axis ranks
            # by the caller; the expert axis reuses tp, so `tp` here
            # is n_experts and each rank's shard is its one expert)
            k5 = jax.random.fold_in(k4, 7)
            n_exp = cfg.moe_experts or max(tp, 1)
            lay["gate"] = jax.random.normal(
                k5, (d, n_exp), jnp.float32) * 0.02
            lay["w1"] = jax.random.normal(
                k3, (n_exp, d, cfg.d_ff), jnp.float32) * scale(d)
            lay["w2"] = jax.random.normal(
                k4, (n_exp, cfg.d_ff, d), jnp.float32) * scale(cfg.d_ff)
        else:
            lay["w1"] = jax.random.normal(
                k3, (d, fl), jnp.float32) * scale(d)
            lay["w2"] = jax.random.normal(
                k4, (fl, d), jnp.float32) * scale(cfg.d_ff)
        tp_layers.append(lay)
    return {"rep": rep, "tp": {"layers": tp_layers}}


def _rmsnorm(x, g):
    x32 = x.astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return (x32 * r * g).astype(x.dtype)


def _flash_causal(q, k, v, cfg: Config):
    """Single-block causal attention through the flash kernel
    (ops/flash_attention): mode 1 is exactly the causal diagonal
    block. Pallas on TPU, the same-math jnp fold elsewhere."""
    from ompi_tpu.ops.flash_attention import flash_block_update
    B, S, H, D = q.shape
    scale = jnp.asarray(cfg.d_head, jnp.float32) ** -0.5
    qf = (jnp.transpose(q, (0, 2, 1, 3)).reshape(B * H, S, D)
          .astype(jnp.float32) * scale)
    kf = jnp.transpose(k, (0, 2, 1, 3)).reshape(B * H, S, D) \
        .astype(jnp.float32)
    vf = jnp.transpose(v, (0, 2, 1, 3)).reshape(B * H, S, D) \
        .astype(jnp.float32)
    o = jnp.zeros_like(qf)
    m = jnp.full((B * H, S), -1e30, jnp.float32)
    l = jnp.zeros((B * H, S), jnp.float32)
    # the TRAINING path needs AD: the jnp online-softmax fold is the
    # same flash math, differentiable and XLA-fused; the pallas kernel
    # (no VJP yet) serves forward-only uses
    o, m, l = flash_block_update(qf, kf, vf, o, m, l, 1,
                                 use_pallas=False)
    o = o / jnp.where(l == 0.0, 1.0, l)[..., None]
    return jnp.transpose(o.reshape(B, H, S, D),
                         (0, 2, 1, 3)).astype(q.dtype)


def _attend(q, k, v, causal, cfg: Config,
            sp_comm: Optional[InGraphComm]):
    """The attention dispatch: ring attention over sp when sequence-
    parallel, flash kernel or dense softmax locally otherwise."""
    if sp_comm is not None:
        return ring_attention(q, k, v, sp_comm, causal=True)
    if cfg.use_flash:
        return _flash_causal(q, k, v, cfg)
    att = jnp.einsum("bshk,bthk->bhst", q, k) / jnp.sqrt(
        jnp.asarray(cfg.d_head, cfg.dtype))
    att = jnp.where(causal[None, None], att, -1e9)
    att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(
        cfg.dtype)
    return jnp.einsum("bhst,bthk->bshk", att, v)


def _mlp(x, lt: Dict, cfg: Config, tp_comm: Optional[InGraphComm],
         ep_comm: Optional[InGraphComm]):
    """The feed-forward dispatch: Switch MoE over the expert axis when
    configured, Megatron column/row pair otherwise. ``x`` is the
    ln2-normalized input (already copy_in'd for tp)."""
    if cfg.moe and ep_comm is not None:
        # the Megatron f operator over the EXPERT axis — identity
        # forward, psum backward. Each expert rank consumes only its
        # token shard (dynamic_slice below); without the backward psum
        # every upstream cotangent (ln/wqkv/wo/emb) would be a
        # per-rank partial and "replicated" params would silently
        # diverge — regardless of whether ep rides the tp axis
        x = ep_comm.copy_in(x)
        B, S, D = x.shape
        E = ep_comm._size
        assert cfg.moe_experts in (0, E), (
            f"moe_experts={cfg.moe_experts} != expert axis size {E}: "
            f"extra experts would be silently dead weights")
        T = B * S
        assert T % E == 0, "tokens must divide the expert axis"
        Tl = T // E
        r = ep_comm.rank()
        flat = x.reshape(T, D)
        # The expert axis rides the tp axis, where activations are
        # REPLICATED: each expert rank takes its own token shard
        # (token parallelism), runs the alltoall dispatch/combine, and
        # the shards reassemble with one psum — so the output is
        # replicated again for the row-parallel world downstream.
        shard = jax.lax.dynamic_slice_in_dim(flat, r * Tl, Tl, 0)
        # w1/w2 carry a leading expert axis sharded over the expert
        # ranks: inside shard_map the local shard is (1, D, F)
        w1, w2 = lt["w1"], lt["w2"]
        if w1.ndim == 3:
            w1, w2 = w1[0], w2[0]
        cap = cfg.moe_capacity or max(1, 2 * Tl // E)
        moe_params = {"gate": lt["gate"].astype(x.dtype),
                      "w1": w1.astype(x.dtype),
                      "w2": w2.astype(x.dtype)}
        out_shard = moe_apply(shard, moe_params, ep_comm,
                              capacity=cap)              # (Tl, D)
        full = jnp.zeros((T, D), out_shard.dtype)
        full = jax.lax.dynamic_update_slice_in_dim(
            full, out_shard, r * Tl, 0)
        return ep_comm.reduce_out(full).reshape(B, S, D)
    m = jax.nn.gelu(jnp.einsum("bsd,df->bsf", x,
                               lt["w1"].astype(cfg.dtype)))
    m = jnp.einsum("bsf,fd->bsd", m, lt["w2"].astype(cfg.dtype))
    if tp_comm is not None:
        m = tp_comm.reduce_out(m)                      # row-parallel sum
    return m


def _layer(x, lr: Dict, lt: Dict, causal, cfg: Config,
           tp_comm: Optional[InGraphComm],
           sp_comm: Optional[InGraphComm],
           ep_comm: Optional[InGraphComm] = None):
    """One transformer block (attention + MLP/MoE with residuals)."""
    h = _rmsnorm(x, lr["ln1"])
    if tp_comm is not None:
        h = tp_comm.copy_in(h)
    qkv = jnp.einsum("bsd,dchk->bcshk", h,
                     lt["wqkv"].astype(cfg.dtype))      # (B,3,S,hl,dh)
    o = _attend(qkv[:, 0], qkv[:, 1], qkv[:, 2], causal, cfg, sp_comm)
    o = jnp.einsum("bshk,hkd->bsd", o, lt["wo"].astype(cfg.dtype))
    if tp_comm is not None:
        o = tp_comm.reduce_out(o)                      # row-parallel sum
    x = x + o
    h = _rmsnorm(x, lr["ln2"])
    if tp_comm is not None and not (cfg.moe and ep_comm is not None):
        # dense Megatron pair: f operator here, g (reduce_out) in _mlp.
        # The MoE branch applies its own f over the EP axis instead —
        # applying both on the same axis would double the backward psum
        h = tp_comm.copy_in(h)
    return x + _mlp(h, lt, cfg, tp_comm, ep_comm)


def forward(params: Dict, tokens, cfg: Config,
            tp_comm: Optional[InGraphComm] = None,
            sp_comm: Optional[InGraphComm] = None,
            ep_comm: Optional[InGraphComm] = None):
    """Causal LM forward. ``tp_comm`` set => heads/d_ff leaves are local
    tp shards and row-parallel outputs are psum'ed over the tp axis.
    ``sp_comm`` set => ``tokens`` is this rank's sequence block and
    attention runs as ring attention over the sp axis (K/V circulate by
    ppermute) — long-context via sequence parallelism. ``ep_comm`` set
    (with ``cfg.moe``) => MLPs are Switch MoE blocks with one expert
    per expert-axis rank."""
    rep, tpp = params["rep"], params["tp"]
    x = rep["emb"][tokens].astype(cfg.dtype)          # (B, S, D)
    B, S, D = x.shape
    causal = jnp.tril(jnp.ones((S, S), jnp.bool_))
    for li in range(cfg.n_layers):
        x = _layer(x, rep["layers"][li], tpp["layers"][li], causal,
                   cfg, tp_comm, sp_comm, ep_comm)
    x = _rmsnorm(x, rep["ln_f"])
    logits = jnp.einsum("bsd,vd->bsv", x.astype(jnp.float32), rep["emb"])
    return logits


def loss_fn(params, inputs, targets, cfg: Config,
            tp_comm: Optional[InGraphComm] = None,
            sp_comm: Optional[InGraphComm] = None):
    """Next-token cross-entropy (mean over the local batch/sequence
    shard). Callers pre-shift: inputs = tokens[:, :-1], targets =
    tokens[:, 1:] — pre-shifting keeps sequence-parallel blocks aligned
    (each sp rank's targets are its own block of the shifted stream)."""
    logits = forward(params, inputs, cfg, tp_comm, sp_comm)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def init_pp_params(key, cfg: Config, pp: int) -> Dict:
    """Flagship (pipelined) parameter layout: ``rep`` = {emb, ln_f}
    replicated everywhere; ``stage`` = a list of layers-per-stage
    slots, each leaf stacked on a LEADING pp axis (slot j's row s is
    global layer s*(L/pp)+j — stage s's j-th layer). Leaves are
    GLOBAL (full heads/d_ff/experts); shard stage leaves
    P("pp", <tp axis where applicable>) so each pipeline rank holds
    its stage and each tp rank its head/expert shard."""
    assert cfg.n_layers % pp == 0
    per = cfg.n_layers // pp
    base = init_params(key, cfg, tp=1)
    rep, tpl = base["rep"], base["tp"]["layers"]
    stage = []
    for j in range(per):
        rows = [dict(tpl[s * per + j],
                     ln1=rep["layers"][s * per + j]["ln1"],
                     ln2=rep["layers"][s * per + j]["ln2"])
                for s in range(pp)]
        stage.append({k: jnp.stack([r[k] for r in rows])
                      for k in rows[0]})
    return {"rep": {"emb": rep["emb"], "ln_f": rep["ln_f"]},
            "stage": stage}


def pp_train_step(params, batch, cfg: Config, lr: float, *,
                  pp_comm: InGraphComm, n_micro: int,
                  dp_comm: Optional[InGraphComm] = None,
                  tp_comm: Optional[InGraphComm] = None,
                  sp_comm: Optional[InGraphComm] = None,
                  ep_comm: Optional[InGraphComm] = None):
    """ONE combined dp x tp x sp x pp (x ep) training step — the
    flagship program. Runs inside shard_map on a 4-axis mesh.

    Params layout: ``rep`` (emb/ln_f) replicated across pp; ``stage``
    leaves carry a leading pp axis (this rank's slice arrives as
    (1, ...) — its stage's layers). The batch is microbatched and
    pipelined: activations ring-shift between stages inside a scan
    (pipeline_apply); backward is AD through the shifts, so each pp
    rank's stage gradients land on that rank.

    Gradient sync: stage grads pmean over dp+sp only (stage params
    live on one pp rank); rep grads additionally SUM over pp — each
    stage contributes a different piece (stage 0 the input embedding,
    the last stage ln_f and the logits weights)."""
    inputs, targets = batch
    n_pp = pp_comm._size
    r_pp = pp_comm.rank()
    B, S = inputs.shape
    assert B % n_micro == 0
    Bm = B // n_micro
    causal = jnp.tril(jnp.ones((S, S), jnp.bool_))

    def stage_fn(stage_params, a):
        for lay in stage_params:
            lr_ = {"ln1": lay["ln1"][0], "ln2": lay["ln2"][0]}
            lt_ = {k: v[0] for k, v in lay.items()
                   if k not in ("ln1", "ln2")}
            a = _layer(a, lr_, lt_, causal, cfg, tp_comm, sp_comm,
                       ep_comm)
        return a

    def compute_loss(p):
        x = p["rep"]["emb"][inputs].astype(cfg.dtype)  # (B, S, D)
        micro = x.reshape(n_micro, Bm, S, -1)
        y = pipeline_apply(stage_fn, p["stage"], micro, pp_comm)
        y = y.reshape(B, S, -1)
        h = _rmsnorm(y, p["rep"]["ln_f"])
        logits = jnp.einsum("bsd,vd->bsv", h.astype(jnp.float32),
                            p["rep"]["emb"])
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
        local = jnp.mean(nll)
        # only the LAST stage's outputs are real: its loss is the
        # job's loss; psum the masked value so every pp rank agrees
        return pp_comm.reduce_out(
            jnp.where(r_pp == n_pp - 1, local, 0.0))

    loss, grads = jax.value_and_grad(compute_loss)(params)
    for comm in (sp_comm, dp_comm):
        if comm is not None:
            grads = jax.tree_util.tree_map(comm.pmean, grads)
            loss = comm.pmean(loss)
    # rep params are replicated across pp but each stage contributes a
    # DIFFERENT gradient piece: sum them
    grads["rep"] = jax.tree_util.tree_map(pp_comm.reduce_out,
                                          grads["rep"])
    if tp_comm is not None:              # rep grads identical across
        grads["rep"] = jax.tree_util.tree_map(   # tp; mean is a no-op
            tp_comm.pmean, grads["rep"])         # that keeps them tied
    if cfg.moe and ep_comm is not None:
        # the gate is replicated across the expert axis but each rank
        # routed a DIFFERENT token shard: sum its gradient pieces
        for lay in grads["stage"]:
            lay["gate"] = ep_comm.reduce_out(lay["gate"])
    params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params,
                                    grads)
    return params, loss


def sgd_train_step(params, batch, cfg: Config, lr: float,
                   dp_comm: Optional[InGraphComm] = None,
                   tp_comm: Optional[InGraphComm] = None,
                   sp_comm: Optional[InGraphComm] = None,
                   grad_sync: Optional["BucketedGradSync"] = None):
    """One DP x TP x SP training step. Gradient synchronization follows
    the strategy table (SURVEY.md §2.6): grads allreduced (mean) over dp
    and over sp (each sp rank saw 1/n of the sequence); tp correctness
    comes from the Megatron f/g operators inside ``forward``.
    ``batch`` = (inputs, targets), pre-shifted.

    ``grad_sync`` replaces the in-graph dp pmean with DDP-style
    bucketed persistent allreduces over the framework's communicator
    tier (one fused wire collective per gradient bucket instead of one
    collective per tensor — docs/PERSISTENT.md)."""
    inputs, targets = batch
    loss, grads = jax.value_and_grad(loss_fn)(params, inputs, targets,
                                              cfg, tp_comm, sp_comm)
    for comm in (sp_comm, dp_comm if grad_sync is None else None):
        if comm is not None:
            grads = jax.tree_util.tree_map(lambda g: comm.pmean(g), grads)
            loss = comm.pmean(loss)
    if grad_sync is not None:
        grads = grad_sync(grads)
        loss = grad_sync.mean_scalar(loss)
    params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    return params, loss


class BucketedGradSync:
    """DDP-style gradient synchronization over bucketed persistent
    allreduces (coll/persistent, docs/PERSISTENT.md).

    Built once per (comm, gradient tree shape): each leaf gets a
    pinned numpy staging buffer and a persistent allreduce plan
    (``comm.allreduce_init``), so every step is copy-in -> one
    ``Startall`` (buckets fuse into ceil(total/bucket_bytes) wire
    collectives when ``mpi_base_bucket`` is on; byte-identical
    per-leaf collectives when off) -> copy-out. Works on both
    communicator tiers: on a per-rank comm each leaf is this rank's
    local gradient; on the stacked single-controller comm each leaf
    carries the leading rank axis."""

    def __init__(self, comm, grads_example):
        import numpy as np
        from ompi_tpu.core import op as _op
        self.comm = comm
        self.n = comm.size
        leaves, self._treedef = jax.tree_util.tree_flatten(grads_example)
        self._stages = [np.zeros(tuple(g.shape),
                                 np.dtype(jnp.asarray(g).dtype))
                        for g in leaves]
        self._reqs = [comm.allreduce_init(s, _op.SUM)
                      for s in self._stages]
        self._scalar_req = None

    def __call__(self, grads):
        import numpy as np
        from ompi_tpu.core.request import startall
        leaves = jax.tree_util.tree_leaves(grads)
        for stage, g in zip(self._stages, leaves):
            np.copyto(stage, np.asarray(g))
        startall(self._reqs)
        out = [np.asarray(r.get()) / self.n for r in self._reqs]
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def shrink(self, comm=None) -> "BucketedGradSync":
        """Elastic continuation (docs/RESILIENCE.md): after a data-
        parallel peer dies mid-training, rebind this synchronizer to
        the survivor communicator and keep stepping. ``comm`` is the
        already-shrunk comm (``MPIX_Comm_shrink``'s result); None
        shrinks ``self.comm`` here. The staging buffers and tree
        layout carry over unchanged — only the persistent plans
        rebind (they are comm-bound) and the mean divisor RESCALES to
        the survivor count, so the surviving ranks' gradients still
        average to an unbiased estimate (smaller effective batch, not
        a corrupted one). Returns self."""
        from ompi_tpu.core import op as _op
        if comm is None:
            comm = self.comm.shrink()
        self.comm = comm
        self.n = comm.size
        self._reqs = [comm.allreduce_init(s, _op.SUM)
                      for s in self._stages]
        self._scalar_req = None          # lazily rebuilt on new comm
        return self

    def mean_scalar(self, value):
        """Mean one scalar (the loss) over the comm — rides the same
        persistent machinery through a lazily-built 1-elem plan."""
        import numpy as np
        from ompi_tpu.core import op as _op
        if self._scalar_req is None:
            shape = tuple(np.shape(value)) or ()
            self._scalar_stage = np.zeros(
                (self.n,) + shape if not getattr(
                    self.comm, "is_per_rank", False) else shape,
                np.float64)
            self._scalar_req = self.comm.allreduce_init(
                self._scalar_stage, _op.SUM)
        np.copyto(self._scalar_stage, np.asarray(value, np.float64))
        self._scalar_req.start()
        return np.asarray(self._scalar_req.get()) / self.n
