"""Flagship demo models built ON the framework — the workload proof that
the communication stack supports real DP/TP training (SURVEY.md §2.6)."""
