"""Reduction-op framework: MPI_Op -> XLA reduction computation.

Behavioral spec from the reference: predefined ops declared at
``ompi/op/op.c:73-80``; the (op x type) kernel table in
``ompi/mca/op/base/op_base_functions.c`` (2,418 LoC of scalar loops) with
SIMD components (``ompi/mca/op/avx``) selected per (op x type) by
``ompi/mca/op/base/op_base_op_select.c``.

TPU-native re-design: there is no kernel table. An op is (a) a JAX binary
combiner usable in device-side folds, and (b) where XLA has a fused
collective primitive for it (psum/pmax/pmin), a tag the coll component
uses to pick that primitive instead of an allgather+fold. MINLOC/MAXLOC
operate on (value, index) pair types carried as a trailing axis of size 2.
User-defined ops (MPI_Op_create) supply a JAX-traceable combiner; the
``commute`` flag gates algorithm choice exactly as the reference documents
(``coll_base_allreduce.c:291-294``).
"""
from __future__ import annotations

import itertools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


_op_counter = itertools.count()


class Op:
    """An MPI reduction operator.

    ``fn(a, b)`` must be a JAX-traceable elementwise combiner.
    ``xla_prim`` in {"sum", "max", "min", None}: when set, collectives may
    lower to the corresponding fused XLA collective (psum/pmax/pmin).
    """

    def __init__(self, fn: Callable, *, commute: bool = True,
                 name: str = "user_op", xla_prim: Optional[str] = None,
                 is_loc: bool = False, predefined: bool = False):
        self.fn = fn
        self.commute = commute
        self.name = name
        # Cache identity: distinct user ops share the default name, so
        # executable caches keyed on the name alone would collide.
        self.uid = name if predefined else f"{name}#{next(_op_counter)}"
        self.xla_prim = xla_prim
        self.is_loc = is_loc         # MINLOC/MAXLOC pair semantics
        self.predefined = predefined
        self._frozen = predefined

    def __call__(self, a, b):
        return self.fn(a, b)

    def __repr__(self):
        return f"Op({self.name})"

    def is_commute(self) -> bool:
        return self.commute

    def free(self) -> None:
        if self.predefined:
            raise ValueError("cannot free a predefined op")
        self.fn = None

    def reduce_tree(self, stacked, axis: int = 0):
        """Fold ``stacked`` along ``axis`` with this op.

        For predefined arithmetic ops this is a single jnp reduction (XLA
        emits a tree); for user ops an associative fold via binary
        splitting, preserving rank order for non-commutative ops (the
        reference documents the same ordering constraint at
        ``coll_base_allreduce.c:291-294``).
        """
        n = stacked.shape[axis]
        if n == 1:
            return jax.lax.index_in_dim(stacked, 0, axis, keepdims=False)
        if self.name in _JNP_REDUCERS:
            return _JNP_REDUCERS[self.name](stacked, axis)
        # Ordered binary-splitting fold: combines (0..k) with (k..n) so the
        # result equals left-to-right application for associative ops.
        def fold(lo, hi):
            if hi - lo == 1:
                return jax.lax.index_in_dim(stacked, lo, axis, keepdims=False)
            mid = (lo + hi) // 2
            return self.fn(fold(lo, mid), fold(mid, hi))
        return fold(0, n)


def _land(a, b):
    return jnp.logical_and(a != 0, b != 0).astype(a.dtype)


def _lor(a, b):
    return jnp.logical_or(a != 0, b != 0).astype(a.dtype)


def _lxor(a, b):
    return jnp.logical_xor(a != 0, b != 0).astype(a.dtype)


def _minloc(a, b):
    """Pair reduce on trailing axis [..., 2] = (value, index); ties pick
    the lower index — MPI MINLOC semantics (op_base_functions.c pair ops)."""
    av, ai = a[..., 0], a[..., 1]
    bv, bi = b[..., 0], b[..., 1]
    take_a = (av < bv) | ((av == bv) & (ai <= bi))
    return jnp.stack([jnp.where(take_a, av, bv),
                      jnp.where(take_a, ai, bi)], axis=-1)


def _maxloc(a, b):
    av, ai = a[..., 0], a[..., 1]
    bv, bi = b[..., 0], b[..., 1]
    take_a = (av > bv) | ((av == bv) & (ai <= bi))
    return jnp.stack([jnp.where(take_a, av, bv),
                      jnp.where(take_a, ai, bi)], axis=-1)


_JNP_REDUCERS = {
    "sum": lambda x, ax: jnp.sum(x, axis=ax),
    "prod": lambda x, ax: jnp.prod(x, axis=ax),
    "max": lambda x, ax: jnp.max(x, axis=ax),
    "min": lambda x, ax: jnp.min(x, axis=ax),
    "band": lambda x, ax: jax.lax.reduce(x, jnp.bitwise_not(jnp.zeros((), x.dtype)),
                                         jax.lax.bitwise_and, (ax,)),
    "bor": lambda x, ax: jax.lax.reduce(x, jnp.array(0, x.dtype),
                                        jax.lax.bitwise_or, (ax,)),
    "bxor": lambda x, ax: jax.lax.reduce(x, jnp.array(0, x.dtype),
                                         jax.lax.bitwise_xor, (ax,)),
}

def _np_logical(npfn):
    """MPI logical ops yield 0/1 IN THE OPERAND TYPE (a bool result
    would change the element size under typed byte-window views)."""
    def fn(a, b):
        return npfn(a, b).astype(np.asarray(b).dtype)
    return fn


def _np_minloc(a, b):
    a, b = np.asarray(a), np.asarray(b)
    av, ai = a[..., 0], a[..., 1]
    bv, bi = b[..., 0], b[..., 1]
    take_a = (av < bv) | ((av == bv) & (ai <= bi))
    return np.stack([np.where(take_a, av, bv),
                     np.where(take_a, ai, bi)], axis=-1)


def _np_maxloc(a, b):
    a, b = np.asarray(a), np.asarray(b)
    av, ai = a[..., 0], a[..., 1]
    bv, bi = b[..., 0], b[..., 1]
    take_a = (av > bv) | ((av == bv) & (ai <= bi))
    return np.stack([np.where(take_a, av, bv),
                     np.where(take_a, ai, bi)], axis=-1)


# Dtype-preserving numpy combiners for the predefined ops — the HOST
# fold table (the op/base scalar-loop role). Host tiers must never use
# the jnp combiners on numpy operands: without x64 enabled jax would
# silently downcast 64-bit operands to 32-bit. Shared by the per-rank
# host collectives and the RMA accumulate path.
NP_COMBINERS = {
    "sum": np.add,
    "prod": np.multiply,
    "max": np.maximum,
    "min": np.minimum,
    "band": np.bitwise_and,
    "bor": np.bitwise_or,
    "bxor": np.bitwise_xor,
    "land": _np_logical(np.logical_and),
    "lor": _np_logical(np.logical_or),
    "lxor": _np_logical(np.logical_xor),
    "minloc": _np_minloc,
    "maxloc": _np_maxloc,
}

SUM = Op(jnp.add, name="sum", xla_prim="sum", predefined=True)
PROD = Op(jnp.multiply, name="prod", predefined=True)
MAX = Op(jnp.maximum, name="max", xla_prim="max", predefined=True)
MIN = Op(jnp.minimum, name="min", xla_prim="min", predefined=True)
LAND = Op(_land, name="land", predefined=True)
LOR = Op(_lor, name="lor", predefined=True)
LXOR = Op(_lxor, name="lxor", predefined=True)
BAND = Op(jnp.bitwise_and, name="band", predefined=True)
BOR = Op(jnp.bitwise_or, name="bor", predefined=True)
BXOR = Op(jnp.bitwise_xor, name="bxor", predefined=True)
MINLOC = Op(_minloc, name="minloc", is_loc=True, predefined=True)
MAXLOC = Op(_maxloc, name="maxloc", is_loc=True, predefined=True)
# RMA accumulate ops (MPI-3): REPLACE takes the incoming value, NO_OP keeps
# the target value (osc accumulate semantics, ompi/op/op.c).
REPLACE = Op(lambda a, b: b, name="replace", commute=False, predefined=True)
NO_OP = Op(lambda a, b: a, name="no_op", commute=False, predefined=True)


def op_create(fn: Callable, commute: bool = True, name: str = "user_op") -> Op:
    """MPI_Op_create equivalent: ``fn`` is a JAX-traceable binary combiner."""
    return Op(fn, commute=commute, name=name)


def reduce_local(inbuf, inoutbuf, op: Op):
    """MPI_Reduce_local: combine ``inbuf`` into ``inoutbuf`` with ``op``
    (no communication — the entry point the reference's
    ``test/datatype/check_op.sh`` matrix drives to validate the SIMD
    reduction kernels; here it exercises the same combiner the
    collectives use). Functional: returns the combined array."""
    if not isinstance(op, Op) or op.fn is None:
        raise TypeError("invalid reduction op")
    if op.predefined and not op.is_loc:
        from ompi_tpu.native import native_reduce_local
        out = native_reduce_local(op.name, inbuf, inoutbuf)
        if out is not None:           # C++ kernel table (op/avx role)
            return out
    return op.fn(inbuf, inoutbuf)      # inoutbuf = inbuf op inoutbuf
