"""Core MPI objects: ops, datatypes, groups, communicators, requests,
buffers — mirroring ``ompi/{op,datatype,group,communicator,request}``."""
