"""dpm/perrank — dynamic process management across SEPARATE jobs.

Behavioral spec: ``ompi/dpm`` — ``MPI_Open_port`` publishes a network
address, ``MPI_Comm_accept``/``MPI_Comm_connect`` rendezvous two
independent MPI jobs into an intercommunicator, over which ordinary
point-to-point addresses the REMOTE group (``dpm_dpm.c`` connect/accept
over PMIx; the reference wires full cross-job connectivity through the
modex).

TPU-native re-design: two per-rank jobs own two separate coordination
services (two PMIx universes), so the bridge is its own TCP link
between the accept root and the connect root. Cross-job traffic is
root-relayed: a non-root sender ships an envelope to its root's Router
(handled on a READER thread, like the RMA plane — the root's
application thread never participates), the root forwards it over the
bridge, and the remote root re-injects it into its job's engine
registry, where it matches like any local frame. Root-relay is the
honest first tier (the reference's fully-wired equivalent would open
per-pair sockets from the modex); the relay is documented, not hidden
— ``BridgeInterComm`` reports it in ``repr``.

Surface: ``open_port() -> "host:port"``; ``comm_accept(port, comm)`` /
``comm_connect(port, comm)`` (collective over the local comm) return a
:class:`BridgeInterComm` with ``remote_size``, ``send``/``recv``/
``irecv``/``probe`` addressing REMOTE ranks, and ``disconnect``.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Any, Optional, Tuple

from ompi_tpu.btl.tcp import MAGIC, _LEN, encode_payload
from ompi_tpu.core.errhandler import ERR_ARG, ERR_PORT, MPIError
from ompi_tpu.pml.perrank import ANY_SOURCE, ANY_TAG, PerRankEngine


class _Port:
    """An open MPI port: a listening socket bound to an ephemeral
    address (MPI_Open_port)."""

    def __init__(self):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        host, port = self.sock.getsockname()
        self.name = f"{host}:{port}"

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


_ports = {}


def open_port() -> str:
    p = _Port()
    _ports[p.name] = p
    return p.name


def close_port(name: str) -> None:
    p = _ports.pop(name, None)
    if p is not None:
        p.close()


class _ICView:
    """Engine-comm shim for the intercomm's receive side: frames carry
    REMOTE-group source ranks; delivery happens into the local rank's
    private engine registered under the intercomm cid. ``no_peer_map``
    tells the failure detector that LOCAL peer deaths have no rank
    mapping here (the remote group's liveness is the bridge's story)."""

    no_peer_map = True

    def __init__(self, icid, local_comm, remote_size: int):
        self.cid = ("ic", icid, local_comm.rank())
        self._comm = local_comm
        self.size = remote_size      # source-rank bound (remote group)

    def rank(self):
        return self._comm.rank()

    def world_rank_of(self, local):
        return self._comm.world_rank_of(self._comm.rank())


class BridgeInterComm:
    """An intercommunicator spanning two independently-launched jobs."""

    def __init__(self, local_comm, icid: str, remote_size: int,
                 bridge: Optional[socket.socket], root: int):
        self.local_comm = local_comm
        self.icid = icid
        self.remote_size = remote_size
        self.root = root
        self._bridge = bridge                     # root only
        self._blk = threading.Lock()
        self._disconnected = False
        router = local_comm.router
        self._router = router
        # my receive engine: remote frames land here
        self._engine = PerRankEngine(
            _ICView(icid, local_comm, remote_size), router)
        if bridge is not None:
            # the root registers (a) the outbound relay handler other
            # local ranks target and (b) the bridge reader that fans
            # inbound remote frames out to local ranks — both run on
            # reader threads (one-sided progress)
            router.register_rma(("icrelay", icid), self._relay_out)
            t = threading.Thread(target=self._bridge_reader,
                                 daemon=True,
                                 name=f"ic-bridge-{icid}")
            t.start()

    # -- send path -----------------------------------------------------
    def send(self, data: Any, remote_rank: int, tag: int = 0) -> None:
        if self._disconnected:
            raise MPIError(ERR_ARG, "intercomm is disconnected")
        if not (0 <= remote_rank < self.remote_size):
            raise MPIError(ERR_ARG, f"bad remote rank {remote_rank}")
        desc, raw = encode_payload(data)
        env = {"dest": remote_rank, "src": self.local_comm.rank(),
               "tag": tag, "desc": desc}
        if self._bridge is not None:
            self._bridge_write(env, raw)
        else:
            # relay through my root's Router (reader-thread handler)
            header = {"rma": True, "wid": ("icrelay", self.icid),
                      "env": env, "origin": self._router.rank,
                      "ack_id": 0}
            self._router.endpoint.send_frame(
                self.local_comm.world_rank_of(self.root), header, raw)

    # -- receive path (remote-group sources) ---------------------------
    def recv(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
             timeout: Optional[float] = None):
        return self._engine.recv(source, tag, timeout)

    def irecv(self, source: int = ANY_SOURCE, tag: int = ANY_TAG):
        return self._engine.irecv(source, tag)

    def iprobe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG):
        return self._engine.iprobe(source, tag)

    # -- plumbing ------------------------------------------------------
    def _bridge_write(self, env: dict, raw: bytes) -> None:
        hraw = pickle.dumps(env)
        with self._blk:
            self._bridge.sendall(
                _LEN.pack(MAGIC, len(hraw), len(raw)) + hraw + raw)

    def _relay_out(self, header: dict, raw: bytes) -> None:
        """Root handler for local non-root senders (reader thread)."""
        self._bridge_write(header["env"], raw)

    def _bridge_reader(self) -> None:
        """Root: fan inbound remote frames out to the addressed local
        rank's intercomm engine (re-wrapped as a local frame)."""
        conn = self._bridge
        from ompi_tpu.btl.tcp import TcpEndpoint

        def read_exact(n: int) -> Optional[bytes]:
            return TcpEndpoint._read_exact(conn, n)

        while not self._disconnected:
            try:
                head = read_exact(_LEN.size)
                if head is None:
                    return
                magic, hlen, plen = _LEN.unpack(head)
                if magic != MAGIC:
                    return
                env = pickle.loads(read_exact(hlen))
                raw = read_exact(plen) if plen else b""
                dest = env["dest"]
                local_header = {
                    "cid": ("ic", self.icid, dest),
                    "src": env["src"], "tag": env["tag"],
                    "desc": env["desc"],
                }
                self._router.endpoint.send_frame(
                    self.local_comm.world_rank_of(dest),
                    local_header, raw)
            except OSError:
                return

    def disconnect(self) -> None:
        """MPI_Comm_disconnect: collective over the local comm."""
        self.local_comm.barrier()
        self._disconnected = True
        if self._bridge is not None:
            self._router.unregister_rma(("icrelay", self.icid))
            try:
                self._bridge.close()
            except OSError:
                pass
        self._engine.close()

    def __repr__(self):
        return (f"BridgeInterComm(local={self.local_comm.size}, "
                f"remote={self.remote_size}, root-relayed)")


def _handshake(sock: socket.socket, my_size: int) -> int:
    sock.sendall(struct.pack("!I", my_size))
    raw = b""
    while len(raw) < 4:
        chunk = sock.recv(4 - len(raw))
        if not chunk:
            raise MPIError(ERR_PORT, "bridge handshake failed")
        raw += chunk
    return struct.unpack("!I", raw)[0]


def comm_accept(port_name: str, comm, root: int = 0,
                timeout: Optional[float] = None) -> BridgeInterComm:
    """MPI_Comm_accept: collective over ``comm``; the root accepts one
    connection on its open port and the jobs exchange group sizes.
    ``timeout`` bounds the root's accept wait (None = block)."""
    icid = port_name
    if comm.rank() == root:
        p = _ports.get(port_name)
        if p is None:
            raise MPIError(ERR_PORT, f"port {port_name!r} is not open "
                                     f"in this process")
        if timeout is not None:
            p.sock.settimeout(timeout)
        try:
            conn, _ = p.sock.accept()
        except socket.timeout:
            # the accept is COLLECTIVE: non-roots are blocked in the
            # bcast below — broadcast the failure sentinel so every
            # rank raises instead of only unblocking the root
            comm.bcast(-1, root=root)
            raise MPIError(ERR_PORT,
                           f"no connection arrived on {port_name!r} "
                           f"within {timeout}s") from None
        finally:
            # the listener persists in _ports for later accepts, which
            # must see their own timeout (or the blocking default) —
            # not this call's
            if timeout is not None:
                p.sock.settimeout(None)
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            remote = _handshake(conn, comm.size)
        except BaseException:
            # same collective-hang class as the accept timeout: a
            # connector that dies mid-handshake must not leave the
            # non-roots parked in the bcast below — and the accepted
            # socket must not leak a descriptor per failed attempt
            try:
                conn.close()
            except OSError:
                pass
            comm.bcast(-1, root=root)
            raise
        comm.bcast(remote, root=root)
        return BridgeInterComm(comm, icid, remote, conn, root)
    remote = comm.bcast(None, root=root)
    if remote == -1:                     # root's accept/handshake failed
        raise MPIError(ERR_PORT,
                       "comm_accept failed at the root (timeout or "
                       "handshake error)")
    return BridgeInterComm(comm, icid, remote, None, root)


def comm_connect(port_name: str, comm, root: int = 0,
                 timeout: float = 60) -> BridgeInterComm:
    """MPI_Comm_connect: collective over ``comm``; the root dials the
    advertised port."""
    icid = port_name
    if comm.rank() == root:
        host, port = port_name.rsplit(":", 1)
        conn = socket.create_connection((host, int(port)),
                                        timeout=timeout)
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        remote = _handshake(conn, comm.size)
        comm.bcast(remote, root=root)
        return BridgeInterComm(comm, icid, remote, conn, root)
    remote = comm.bcast(None, root=root)
    return BridgeInterComm(comm, icid, remote, None, root)
