"""MPI_Info — string key/value hints (mirrors ``ompi/info``)."""
from __future__ import annotations

from typing import Dict, Optional


class Info:
    def __init__(self, initial: Optional[Dict[str, str]] = None):
        self._kv: Dict[str, str] = dict(initial or {})

    def set(self, key: str, value: str) -> None:
        self._kv[str(key)] = str(value)

    def get(self, key: str) -> Optional[str]:
        return self._kv.get(key)

    def delete(self, key: str) -> None:
        self._kv.pop(key, None)

    def get_nkeys(self) -> int:
        return len(self._kv)

    def get_nthkey(self, n: int) -> str:
        return list(self._kv.keys())[n]

    def dup(self) -> "Info":
        return Info(self._kv)

    def free(self) -> None:
        self._kv.clear()


INFO_NULL = Info()
INFO_ENV = Info()   # populated at Init with environment facts
