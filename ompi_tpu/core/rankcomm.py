"""RankCommunicator — the per-rank (multi-controller) execution model.

Behavioral spec: the textbook MPI model every reference binding serves —
``MPI_Comm_rank`` returns THIS process's rank
(``ompi/mpi/c/comm_rank.c.in``), point-to-point moves bytes between
processes (``ompi/mca/pml/ob1/pml_ob1_recvfrag.c:296-330`` matching),
collectives are called by every member and return each caller its local
result, and ``mpirun -n N`` launches N such processes
(``ompi/tools/mpirun/main.c:157-180``).

TPU-native re-design: one OS process == one MPI rank, bound 1:1 to the
JAX coordination service (``rank() == jax.process_index()``). Two data
planes, mirroring the reference's split between byte transports and
(here) the ICI fabric:

- **Host tier (btl/tcp)**: pt2pt and generic-object collectives run
  textbook algorithms (binomial bcast/reduce, dissemination barrier,
  pairwise alltoall — the coll/base registry,
  ``coll_base_functions.h:185-320``) over the framed TCP transport, with
  addresses modex'd through the coordination-service KV (the PMIx role).
- **Device tier (XLA/ICI)**: collectives on ``jax.Array`` buffers
  assemble a global array over the communicator's device mesh
  (one shard per rank via ``make_array_from_single_device_arrays``) and
  dispatch ONE compiled SPMD program using XLA collectives
  (psum/all_gather/all_to_all/psum_scatter under ``shard_map``) — every
  member calls the collective, which is exactly the multi-controller
  contract jit requires. No bytes touch the host tier.

Internal collective traffic rides a separate CID channel (``("c", cid)``)
so it can never cross-match user point-to-point tags — MPI's hidden
collective context id, re-created literally.

CID agreement: communicator creation is collective, so a deterministic
derivation (parent cid + per-parent creation sequence + color) gives
every member the same child CID with zero extra traffic — the property
the reference's iterative CID allreduce establishes
(``comm_cid.c:61-109``).
"""
from __future__ import annotations

import functools
import itertools
import queue
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ompi_tpu.compress import wire as _cwire
from ompi_tpu.core import op as op_mod
from ompi_tpu.core.errhandler import (ERR_ARG, ERR_COMM, ERR_COUNT, ERR_OP,
                                      ERR_RANK, ERR_REVOKED, ERR_ROOT,
                                      ERRORS_ARE_FATAL, Errhandler, MPIError)
from ompi_tpu.ft import inject as _inject
from ompi_tpu.core.group import Group, UNDEFINED
from ompi_tpu.core.info import Info
from ompi_tpu.core.request import Request, Status
from ompi_tpu.pml.perrank import (ANY_SOURCE, ANY_TAG, PROC_NULL,
                                  PerRankEngine, RankRequest, Router)
from ompi_tpu.runtime import spc
from ompi_tpu.utils import hooks as _hooks_mod

AXIS = "mpi_r"

# Compressed host-tier allreduce: worlds at or below this size use the
# direct code-exchange schedule (one parallel round, single quant
# error); larger worlds use the binomial reduce + code-forwarding
# bcast, whose per-rank wire bytes stay O(1) (docs/COMPRESSION.md).
_WIRE_DIRECT_MAX_RANKS = 4


class _HiddenChannel:
    """A hidden matching-channel view of a communicator: same ranks,
    separate CID, so internal/tool messages never match user receives.
    Channels: "c" collectives, "part" partitioned pt2pt, "sync"
    clock probes."""

    def __init__(self, comm: "RankCommunicator", prefix: str):
        self._comm = comm
        self.cid = (prefix, comm.cid)

    @property
    def size(self) -> int:
        return self._comm.size

    def rank(self) -> int:
        return self._comm.rank()

    def world_rank_of(self, local: int) -> int:
        return self._comm.world_rank_of(local)


class _CollChannel(_HiddenChannel):
    def __init__(self, comm: "RankCommunicator"):
        super().__init__(comm, "c")


def hidden_engine(comm: "RankCommunicator", prefix: str):
    """The lazily-created matching engine for one hidden channel of
    ``comm`` — created once (two engines on one CID would split
    matching state), closed with the communicator."""
    with comm._lock:
        eng = comm._aux_pmls.get(prefix)
        if eng is None:
            eng = PerRankEngine(_HiddenChannel(comm, prefix),
                                comm.router)
            comm._aux_pmls[prefix] = eng
    return eng


# thread-local CALL CONTEXT that must travel with a funneled body:
# layers above (the C ABI sets a reduction-datatype context on the
# caller thread before invoking blocking reductions) register a
# capture hook; _coll_serial snapshots every registered context at
# funnel time and applies/resets it around the body on the worker.
_TLS_PROPAGATORS: List[Callable[[], Tuple[Callable, Callable]]] = []


def register_tls_propagator(
        capture: Callable[[], Tuple[Callable, Callable]]) -> None:
    """``capture()`` runs on the funneling caller and returns
    ``(apply, reset)`` closures run on the worker around the body."""
    _TLS_PROPAGATORS.append(capture)


class _SlotRequest(Request):
    """A request completed by a posted CombineSlot (the persistent
    small-allreduce's Start residue): wait blocks on the slot's event,
    collects the rank-ordered fold, and retires the slot's tag."""

    __slots__ = ("_eng", "_tag_", "_slot", "_epilogue")

    def __init__(self, eng, tag: int, slot, epilogue):
        super().__init__(arrays=[])
        self._complete = False
        self._eng = eng
        self._tag_ = tag
        self._slot = slot
        self._epilogue = epilogue

    def _collect(self) -> None:
        try:
            out = self._slot.wait()      # set already: returns/raises
        finally:
            self._eng.end_combine(self._tag_)
            self._complete = True
        self._result = self._epilogue(out)

    def test(self):
        if not self._complete:
            if not self._slot._event.is_set():
                return False, None
            self._collect()
        return True, self.status

    def wait(self, timeout: Optional[float] = None):
        if not self._complete:
            try:
                out = self._slot.wait(
                    timeout if timeout is not None else 600)
            finally:
                self._eng.end_combine(self._tag_)
                self._complete = True
            self._result = self._epilogue(out)
        return self.status

    def get(self):
        self.wait()
        return self._result


def _serialized(fn):
    """Collective-execution serializer — applied to every public
    collective entry that (transitively) draws the comm's sequence
    tag. ``_tag()`` draws at EXECUTION time and its cross-rank
    agreement rests on one invariant: each rank executes the comm's
    collectives in issue order on a single thread at a time.
    Deferred i-collectives run on the comm's serial worker, so a
    blocking collective issued while any are pending must queue
    BEHIND them (two concurrent draws would order differently on
    different ranks and cross-match payloads — e.g. a barrier's
    round messages consumed as a scan's partial). With an idle
    worker the call runs inline: no thread hop on the latency path.
    This is the chokepoint the C ABI, the Python API, and internal
    collective users (window creation, file IO, dpm) all share."""
    @functools.wraps(fn)
    def entry(self, *a, **kw):
        return self._coll_serial(fn, self, *a, **kw)
    return entry


class RankCommunicator:
    """A communicator whose caller is exactly one rank."""

    is_per_rank = True

    def __init__(self, group: Group, my_world_rank: int, router: Router, *,
                 cid: Any = "w", name: str = "",
                 parent: Optional["RankCommunicator"] = None,
                 errhandler: Optional[Errhandler] = None,
                 info: Optional[Info] = None):
        self.group = group
        self.router = router
        self.cid = cid
        self.name = name or f"comm#{cid}"
        self.info = info.dup() if info else Info()
        self.errhandler = errhandler or (
            parent.errhandler if parent else ERRORS_ARE_FATAL)
        self.attributes: Dict[int, Any] = {}
        self.topo = None
        self._freed = False
        self._rank = group.rank_of(my_world_rank)
        if self._rank == UNDEFINED:
            raise MPIError(ERR_RANK,
                           f"process world rank {my_world_rank} is not a "
                           f"member of {self.name}")
        self._my_world = my_world_rank
        self._pml = PerRankEngine(self, router)
        self._coll_pml = PerRankEngine(_CollChannel(self), router)
        self._aux_pmls: Dict[str, PerRankEngine] = {}   # hidden_engine
        # ownership list (MPI-4 Sessions): a session-created comm
        # carries the session's comm list so DERIVED comms
        # (dup/split/cart/shrink) register too — finalize must quiesce
        # the whole family, not just the direct creations
        owners = getattr(parent, "_owner_list", None)
        if owners is not None:
            self._owner_list = owners
            owners.append(self)
        # the interposition tier of the coll framework (sync /
        # monitoring) applies to per-rank comms too — same MCA vars,
        # same boundary, wrapping the bound collective methods
        from ompi_tpu.coll.interpose_perrank import interpose
        interpose(self)
        self._seq = itertools.count(1)          # collective sequence
        self._create_seq = itertools.count(1)   # comm-creation sequence
        self._dev_fns: Dict[Any, Callable] = {}
        self._small_fold: Dict[Any, Callable] = {}  # op.uid -> combiner
        self._mesh_cache = None
        self._lock = threading.Lock()
        self._cq: Optional["queue.Queue"] = None   # serial collective
        self._cworker: Optional[threading.Thread] = None  # executor
        self._cclosed = False            # set by _coll_drain: no new
        # jobs may spawn a worker after teardown began
        # revoke plane (MPIX_Comm_revoke, docs/RESILIENCE.md): when the
        # router's reliable broadcast revokes this cid, every pending
        # operation on the comm completes with ERR_REVOKED
        router.register_revoke_cb(self.cid, self._on_revoked)

    # ------------------------------------------------------------------
    @property
    def size(self) -> int:
        return self.group.size

    def rank(self) -> int:
        """MPI_Comm_rank: this process's rank (comm_rank.c.in) — the
        round-2 gap closed: per-rank worlds no longer report 0
        everywhere."""
        return self._rank

    @property
    def is_multiprocess(self) -> bool:
        return True

    def world_rank_of(self, local: int) -> int:
        return self.group.world_ranks[local]

    def _err(self, error_class: int, msg: str = ""):
        return self.errhandler.invoke(self, error_class, msg)

    def _check(self) -> None:
        if self._freed:
            raise MPIError(ERR_COMM, "communicator has been freed")
        if self.router.is_revoked(self.cid):
            # ULFM: every operation on a revoked comm (except the
            # recovery surface — shrink/agree/get_failed/free, which
            # bypass _check) raises ERR_REVOKED (comm_revoke.c)
            raise MPIError(ERR_REVOKED,
                           f"{self.name} has been revoked")

    def _validate_root(self, root: int) -> int:
        if not (0 <= root < self.size):
            self._err(ERR_ROOT, f"root {root} out of range")
        return root

    def _validate_op(self, op) -> op_mod.Op:
        if not isinstance(op, op_mod.Op) or op.fn is None:
            self._err(ERR_OP, "invalid reduction op")
        return op

    # ==================================================================
    # Point-to-point (textbook signatures: caller IS the rank)
    # ==================================================================
    def send(self, data: Any, dest: int, tag: int = 0) -> None:
        self._check()
        spc.record("pml_send", 1)
        self._pml.send(data, dest, tag)

    def isend(self, data: Any, dest: int, tag: int = 0) -> Request:
        self._check()
        spc.record("pml_send", 1)
        return self._pml.send(data, dest, tag)

    def ssend(self, data: Any, dest: int, tag: int = 0) -> None:
        self._check()
        spc.record("pml_send", 1)
        self._pml.send(data, dest, tag, synchronous=True)

    def bsend(self, data: Any, dest: int, tag: int = 0) -> None:
        self.send(data, dest, tag)        # sends are always buffered

    def recv(self, source: int = ANY_SOURCE, tag: int = ANY_TAG
             ) -> Tuple[Any, Status]:
        self._check()
        spc.record("pml_recv", 1)
        return self._pml.recv(source, tag)

    def irecv(self, source: int = ANY_SOURCE,
              tag: int = ANY_TAG) -> RankRequest:
        self._check()
        spc.record("pml_recv", 1)
        return self._pml.irecv(source, tag)

    def sendrecv(self, senddata: Any, dest: int, source: int = ANY_SOURCE,
                 sendtag: int = 0, recvtag: int = ANY_TAG
                 ) -> Tuple[Any, Status]:
        """Deadlock-free by construction: the receive is posted before
        the (eager, buffered) send."""
        self._check()
        req = self._pml.irecv(source, recvtag)
        self._pml.send(senddata, dest, sendtag)
        st = req.wait()
        return req.get(), st

    def probe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG) -> Status:
        self._check()
        return self._pml.probe(source, tag)

    def iprobe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG):
        self._check()
        return self._pml.iprobe(source, tag)

    def mprobe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG):
        self._check()
        return self._pml.mprobe(source, tag)

    def improbe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG):
        self._check()
        flag, status = self._pml.iprobe(source, tag)
        if not flag:
            return False, None, None
        return True, self._pml.mprobe(source, tag), status

    def mrecv(self, message) -> Tuple[Any, Status]:
        return self._pml.mrecv(message)

    def send_init(self, data: Any, dest: int, tag: int = 0) -> Request:
        self._check()
        return Request(persistent_start=lambda: self._pml.send(
            data, dest, tag))

    def recv_init(self, source: int = ANY_SOURCE,
                  tag: int = ANY_TAG) -> Request:
        self._check()
        return Request(persistent_start=lambda: self._pml.irecv(
            source, tag))

    # ==================================================================
    # Collectives — host tier (textbook algorithms over btl/tcp)
    # ==================================================================
    def _tag(self) -> int:
        """Per-collective sequence tag: calls are collective, so every
        member draws the same value; successive collectives can never
        cross-match even under wildcard-free FIFO reordering."""
        return next(self._seq)

    def _csend(self, dest: int, tag: int, data: Any) -> None:
        self._coll_pml.send(data, dest, tag)

    def _crecv(self, src: int, tag: int) -> Any:
        data, _ = self._coll_pml.recv(src, tag)
        return data

    # -- staged device tier (the coll/accelerator bracket, inverted) ---
    # The reference stages device buffers OUT to run host algorithms
    # (coll_accelerator_allreduce.c:55-80); here host/C buffers above
    # coll_tuned_stage_min_bytes stage IN — one device shard per rank —
    # so the collective rides the fabric as one compiled XLA program
    # and the result copies back. This is the path that puts textbook
    # C programs (numpy buffers via api/cabi.py) on the TPU.
    def _stage_min(self, func: str) -> int:
        # one decision plane with the single-controller tier: the flat
        # MCA var plus the per-collective dynamic-rules override
        from ompi_tpu.coll.tuned import stage_min_for
        return stage_min_for(func)

    def _stageable(self, data: Any, op: Optional[op_mod.Op] = None,
                   nbytes: Optional[int] = None,
                   func: str = "allreduce") -> bool:
        """Local staging decision. Only called with arguments whose
        relevant properties (shape, dtype, size) are identical on every
        member by MPI semantics, so all ranks decide alike — the device
        dispatch below is collective and a split decision would hang
        the job. Asymmetric-argument collectives (bcast) must propagate
        one rank's decision instead. ``nbytes`` overrides the payload
        size for collectives whose full payload spans several chunks."""
        if not isinstance(data, np.ndarray):
            return False
        if data.dtype.kind not in "fiub":
            return False
        if (data.nbytes if nbytes is None else nbytes) \
                < self._stage_min(func):
            return False
        if data.dtype.itemsize == 8:
            import jax
            if not jax.config.jax_enable_x64:
                return False             # silent downcast would corrupt
        if op is not None:
            if op.is_loc or op.fn is None:
                return False             # pair ops stay on the host fold
            if getattr(op, "_c_callback", None) is not None:
                return False             # C fn pointers cannot trace
        return self._mesh() is not None

    @_serialized
    def barrier(self) -> None:
        """Dissemination barrier: ceil(log2 n) rounds
        (coll_base_barrier.c bruck/dissemination)."""
        self._check()
        spc.record("coll_barrier", 1)
        n, r, t = self.size, self._rank, self._tag()
        k = 1
        while k < n:
            self._csend((r + k) % n, t, None)
            self._crecv((r - k) % n, t)
            k <<= 1

    @_serialized
    def bcast(self, data: Any = None, root: int = 0) -> Any:
        """Binomial-tree bcast (coll_base_bcast.c binomial): non-root
        callers pass nothing and receive the root's value.

        Staged device tier (the coll/accelerator bracket inverted,
        ``coll_accelerator_allreduce.c:55-80``): bcast's args are
        asymmetric — non-root callers may hold nothing — so the root's
        staging decision travels first as a small host-tier metadata
        bcast, then every rank joins the one compiled device bcast
        with a right-shaped local buffer. Cost: log(n) tiny messages
        before a >=stage_min_bytes payload rides the fabric once."""
        self._check()
        self._validate_root(root)
        spc.record("coll_bcast", 1)
        if isinstance(data, _dev_array_type()) and self._mesh() is not None:
            return self._device_bcast(data, root)
        if self._mesh() is not None:
            # ONE binomial round carries (staging decision, payload):
            # staged -> (meta, None), the payload rides the device op;
            # not staged -> (None, data), the payload already arrived.
            if self._rank == root:
                if self._stageable(data, func="bcast"):
                    msg = (("stage", tuple(data.shape), data.dtype.str),
                           None)
                elif self._pipeline_bcast_ok(data):
                    msg = (("chain",), None)
                elif _cwire.eligible(data):
                    # quantize ONCE at the root; the binomial tree
                    # forwards the codes losslessly (one quantization
                    # error total, ~1/4 the bytes per hop)
                    msg = (None, _cwire.encode(data))
                else:
                    msg = (None, data)
            else:
                msg = None
            meta, payload = self._host_bcast(msg, root)
            if meta is not None and meta[0] == "stage":
                shape, dtstr = meta[1], meta[2]
                local = (np.ascontiguousarray(data) if self._rank == root
                         else np.empty(shape, np.dtype(dtstr)))
                spc.record("coll_staged_device", 1)
                res = self._device_bcast(local, root)
                # the root already holds the payload: participate in
                # the collective but skip the redundant D2H copy
                return data if self._rank == root else np.asarray(res)
            if meta is not None and meta[0] == "chain":
                return self._pipelined_chain_bcast(data, root)
            return data if self._rank == root \
                else _cwire.maybe_decode(payload)
        if self._rank == root and _cwire.eligible(data):
            self._host_bcast(_cwire.encode(data), root)
            return data
        return _cwire.maybe_decode(self._host_bcast(data, root))

    def _host_bcast(self, data: Any, root: int) -> Any:
        n, t = self.size, self._tag()
        vr = (self._rank - root) % n
        mask = 1
        while mask < n:                  # climb to my parent
            if vr & mask:
                data = self._crecv(((vr - mask) + root) % n, t)
                break
            mask <<= 1
        mask >>= 1
        while mask:                      # feed my subtree
            if vr + mask < n:
                self._csend(((vr + mask) + root) % n, t, data)
            mask >>= 1
        return data

    @_serialized
    def reduce(self, data: Any, op: op_mod.Op = op_mod.SUM,
               root: int = 0) -> Any:
        """Binomial reduce for commutative ops; linear ordered fold at
        root otherwise (the ordering constraint of
        coll_base_allreduce.c:291-294)."""
        self._check()
        self._validate_op(op)
        self._validate_root(root)
        spc.record("coll_reduce", 1)
        n, t = self.size, self._tag()
        if n == 1:
            return data
        if not op.commute:
            rows = self.gather(data, root)
            if self._rank != root:
                return None
            acc = rows[0]
            for x in rows[1:]:
                acc = _apply(op, acc, x)
            return acc
        if self._stageable(data, op, func="reduce"):
            spc.record("coll_staged_device", 1)
            y = self._device_allreduce(np.ascontiguousarray(data), op)
            # only the root pays the D2H copy; others just participate
            return np.asarray(y) if self._rank == root else None
        # compressed wire hops (docs/COMPRESSION.md): large float sum
        # payloads quantize per hop — decode, fold, re-encode at every
        # tree level (the EQuARX reduction-hop structure on the host
        # tier). The decision is a pure function of (shape, dtype,
        # nbytes, op), identical on every member by MPI semantics.
        use_wire = _cwire.eligible(data, op)
        vr = (self._rank - root) % n
        acc = data
        k = 1
        while k < n:
            if vr & k:
                self._csend(((vr - k) + root) % n, t,
                            _cwire.encode(acc) if use_wire else acc)
                return None
            if vr + k < n:
                acc = _apply(op, acc, _cwire.maybe_decode(
                    self._crecv(((vr + k) + root) % n, t)))
            k <<= 1
        return acc if self._rank == root else None

    def _small_allreduce(self, data: Any, op: op_mod.Op) -> Any:
        """Combined small-message allreduce (VERDICT r4 next #4): every
        rank eagerly sends its contribution to every peer ONCE; btl
        reader threads park arrivals straight into a combining slot
        (``btl_sendi`` role — no matching, no per-message request); the
        last arrival folds in deterministic rank order and wakes the
        caller exactly once. One message latency + one wakeup replaces
        the reduce-then-bcast chain's log(n) serialized round trips —
        the path that held 8 B latency at ~2.2 ms for two rounds.
        Rank-ordered folding keeps non-commutative ops and float
        reproducibility exact (same canonical order on every rank).

        Sub-eager dispatch cache (round 6): the fold combiner resolves
        ONCE per op to the dtype-preserving numpy kernel — the generic
        ``_apply`` boxed scalar contributions through the jnp combiner
        on the reader thread, a per-fold JAX dispatch that made the
        scalar 8 B row 8x the ndarray row on the round-5 record — and
        the outbound side multicasts one marshalled frame through the
        engine's cached header templates (``send_small``)."""
        n, r, t = self.size, self._rank, self._tag()
        eng = self._coll_pml
        fold = self._small_fold_for(op)
        slot = eng.post_combine(t, n, n - 1, fold, own=(r, data))
        try:
            eng.send_small(data, [(r + off) % n for off in range(1, n)],
                           t)
            out = slot.wait()
        finally:
            eng.end_combine(t)
        if not isinstance(data, np.ndarray) and (
                isinstance(out, np.generic)
                or (isinstance(out, np.ndarray) and out.ndim == 0)):
            out = out.item()             # scalar in, python scalar out
        return out

    def _small_fold_for(self, op: op_mod.Op) -> Callable:
        """The memoized deterministic rank-order fold for ``op`` (the
        sub-eager dispatch cache's combiner leg, shared by the one-shot
        small path and the persistent plan prebinding)."""
        fold = self._small_fold.get(op.uid)
        if fold is None:
            npfn = (op_mod.NP_COMBINERS.get(op.name)
                    if op.predefined and not op.is_loc else None)
            if npfn is not None:
                def fold(vals, _fn=npfn):
                    acc = vals[0]
                    for v in vals[1:]:
                        acc = _fn(acc, v)
                    return acc
            else:
                def fold(vals):
                    acc = vals[0]
                    for v in vals[1:]:
                        acc = _apply(op, acc, v)
                    return acc
            self._small_fold[op.uid] = fold
        return fold

    def bind_small_allreduce(self, data: Any, op: op_mod.Op) -> Callable:
        """Pre-bound persistent small-allreduce launcher
        (coll/persistent): the fold combiner, destination ring, and the
        engine's multicast template resolve ONCE here. The returned
        launcher is Start-only — it draws the sequence tag (through
        the serialized chokepoint so tag order can never race deferred
        i-collectives), posts the combining slot, and multicasts this
        rank's contribution; completion rides the slot through the
        returned request. N outstanding starts therefore PIPELINE:
        every contribution is on the wire before the first wait, and
        reader threads feed all N slots concurrently. ``data`` (the
        registered buffer, refilled by the app between rounds) is
        re-read at every Start."""
        n, r = self.size, self._rank
        fold = self._small_fold_for(op)
        dests = [(r + off) % n for off in range(1, n)]
        eng = self._coll_pml
        send = eng.bind_small_multicast(data, dests)
        scalar_in = not isinstance(data, np.ndarray)

        def epilogue(out):
            if scalar_in and (isinstance(out, np.generic)
                              or (isinstance(out, np.ndarray)
                                  and out.ndim == 0)):
                out = out.item()
            return out

        def post():
            spc.record("coll_allreduce", 1)
            spc.record("coll_small_combine", 1)
            t = self._tag()
            slot = eng.post_combine(t, n, n - 1, fold, own=(r, data))
            send(data, t)
            return t, slot

        def launch() -> Request:
            t, slot = self._coll_serial(post)
            return _SlotRequest(eng, t, slot, epilogue)
        return launch

    def _small_allreduce_ok(self, data: Any, op: op_mod.Op) -> bool:
        from ompi_tpu.coll.tuned import small_allreduce_limits
        max_bytes, max_ranks = small_allreduce_limits()
        if not (1 < self.size <= max_ranks):
            return False
        if isinstance(data, np.ndarray):
            return data.nbytes <= max_bytes
        return isinstance(data, (int, float, complex, np.generic))

    @_serialized
    def allreduce(self, data: Any, op: op_mod.Op = op_mod.SUM) -> Any:
        self._check()
        self._validate_op(op)
        if _inject.active:               # named kill site for the FT
            _inject.point("coll.allreduce")   # drill (ft/inject)
        spc.record("coll_allreduce", 1)
        if _hooks_mod._hooks:            # tool bound: fire the event
            _hooks_mod.fire("coll_allreduce", self,
                            {"value": int(getattr(data, "nbytes", 0)
                                          or 0)})
        if isinstance(data, _dev_array_type()) and self._mesh() is not None:
            return self._device_allreduce(data, op)
        if self._stageable(data, op):
            spc.record("coll_staged_device", 1)
            return np.asarray(self._device_allreduce(
                np.ascontiguousarray(data), op))
        if self._small_allreduce_ok(data, op):
            spc.record("coll_small_combine", 1)
            return self._small_allreduce(data, op)
        if _cwire.eligible(data, op) \
                and 1 < self.size <= _WIRE_DIRECT_MAX_RANKS:
            return self._wire_allreduce_direct(data, op)
        if self._shm_fold_ok(data, op):
            return self._shm_fold_allreduce(data, op)
        if self._pipeline_ring_ok(data, op):
            return self._pipelined_ring_allreduce(data, op)
        r = self.reduce(data, op, 0)
        if _cwire.eligible(data, op):
            # allreduce must return the SAME value on every rank: the
            # root broadcasts the wire form as an opaque payload and
            # every member (root included) decodes the same image —
            # root keeping its exact fold would diverge from the
            # quantized copies the peers receive.
            w = _cwire.encode(r) if self._rank == 0 else None
            return _cwire.maybe_decode(self.bcast(w, 0))
        return self.bcast(r, 0)

    def _wire_allreduce_direct(self, data, op):
        """Direct-exchange compressed allreduce (small worlds): every
        rank quantizes its contribution ONCE and multicasts the codes;
        every rank decodes all n images and folds them in rank order —
        one fully parallel round (no serialized tree levels), exactly
        one quantization error per contribution (lossless code
        forwarding), and bitwise-identical results everywhere (all
        ranks fold the same images in the same order). Wire cost is
        (n-1)*qbytes per rank vs the tree's ~2*qbytes, the winning
        trade while n is small — the tree path above takes over past
        _WIRE_DIRECT_MAX_RANKS."""
        n, r, t = self.size, self._rank, self._tag()
        spc.record("coll_compress_direct", 1)
        w = _cwire.encode(data)
        for off in range(1, n):
            self._csend((r + off) % n, t, w)
        parts: Dict[int, Any] = {r: w}
        for _ in range(n - 1):
            d, st = self._coll_pml.recv(ANY_SOURCE, t)
            parts[st.source] = d
        out = None
        for i in range(n):
            img = _cwire.maybe_decode(parts[i])
            out = img if out is None else _apply(op, out, img)
        return out

    # -- in-segment shared-memory fold (btl/shmseg, docs/LARGEMSG.md) --
    def _shm_fold_ok(self, data: Any, op: op_mod.Op) -> bool:
        """Rank-symmetric gate for the in-segment fold: every member
        must sit on this host (the fold IS the shared mapping), the
        payload must fit one fold workspace, the op must have a numpy
        kernel, and the coll/decision shm rows must select it.
        Commutativity is NOT required — each slice is folded once, in
        rank order, by exactly one rank."""
        if self.size < 2 or not isinstance(data, np.ndarray):
            return False
        if data.dtype.kind not in "fiu" or data.ndim == 0:
            return False
        if op.is_loc or not op.predefined:
            return False
        if op_mod.NP_COMBINERS.get(op.name) is None:
            return False
        plane = getattr(self.router.endpoint, "shm_seg", None)
        if plane is None or int(data.nbytes) > plane.slot_bytes:
            return False
        from ompi_tpu.coll import decision
        rules = decision.shm_rules().get("allreduce")
        if not rules:
            return False
        if decision._match(rules, self.size,
                           int(data.nbytes)) != "shm_fold":
            return False
        ep = self.router.endpoint
        return all(ep._is_same_host(self.world_rank_of(i))
                   for i in range(self.size) if i != self._rank)

    def _fold_barrier(self, t: int) -> None:
        """Dissemination barrier on a private tag — the fold's two
        phase fences (the public ``barrier`` is @_serialized and may
        not be re-entered from inside a collective)."""
        n, r = self.size, self._rank
        k = 1
        while k < n:
            self._csend((r + k) % n, t, None)
            self._crecv((r - k) % n, t)
            k <<= 1

    def _shm_fold_allreduce(self, data: np.ndarray,
                            op: op_mod.Op) -> np.ndarray:
        """In-segment node-local allreduce (btl/shmseg fold
        workspaces): every rank writes its contribution into its own
        per-comm shared segment ONCE, then — after a fence — folds its
        slice of the element range across ALL members' segments in
        rank order and writes the folded slice back into every
        segment (disjoint slices, so writers never race). After the
        second fence each rank reads the complete result out of its
        OWN segment. ~4 byte-touches per rank vs the ring schedule's
        ~2·P, and bitwise-identical results everywhere (each slice is
        folded exactly once, in rank order, and every rank reads the
        same bytes). No third fence is needed: a rank's next phase-0
        write to its own segment is self-serialized behind its own
        read-out, and partners touch it again only after the next
        collective's first fence — which requires this rank to have
        moved on already."""
        from ompi_tpu.btl import shmseg as _shmseg
        n, r = self.size, self._rank
        spc.record("coll_shm_fold", 1)
        plane = self.router.endpoint.shm_seg
        token = _shmseg.coll_token(self.cid)
        arr = np.ascontiguousarray(data)
        shape, dtype = arr.shape, arr.dtype
        flat = arr.reshape(-1)
        nbytes = int(arr.nbytes)
        ws = plane.coll_segment(token)
        ws.buf[0:nbytes] = memoryview(flat).cast("B")
        self._fold_barrier(self._tag())  # contributions visible
        views = [np.frombuffer(
            plane.coll_attach(token, self.world_rank_of(i)).buf,
            dtype=dtype, count=flat.size) for i in range(n)]
        bounds = [(flat.size * i) // n for i in range(n + 1)]
        lo, hi = bounds[r], bounds[r + 1]
        npfn = op_mod.NP_COMBINERS[op.name]
        if hi > lo:
            acc = views[0][lo:hi].copy()
            for k in range(1, n):
                acc = npfn(acc, views[k][lo:hi])
            for v in views:
                v[lo:hi] = acc
        self._fold_barrier(self._tag())  # folded slices visible
        out = views[r].copy()
        _shmseg.stats["folds"] += 1
        from ompi_tpu import telemetry as _telemetry_mod
        if _telemetry_mod.active:
            hist = _telemetry_mod.SHMSEG
            if hist is not None:
                hist.record(nbytes)
        return out.reshape(shape)

    # -- segment-pipelined host tier (docs/LARGEMSG.md) ----------------
    def _pipeline_ring_ok(self, data: Any, op: op_mod.Op) -> bool:
        """Rank-symmetric gate for the pipelined ring: the decision
        rows (coll/decision.pipeline_rules) select by size and bytes,
        and the fold must be a commutative predefined op with a numpy
        kernel — the ring reassociates chunk folds exactly like the
        other REORDERING schedules."""
        if self.size < 2 or not isinstance(data, np.ndarray):
            return False
        if data.dtype.kind not in "fiu" or data.ndim == 0:
            return False
        if not op.commute or op.is_loc or not op.predefined:
            return False
        if op_mod.NP_COMBINERS.get(op.name) is None:
            return False
        from ompi_tpu.coll import decision
        rules = decision.pipeline_rules().get("allreduce")
        if not rules:
            return False
        return decision._match(rules, self.size,
                               int(data.nbytes)) == "pipelined_ring"

    def _pipelined_ring_allreduce(self, data: np.ndarray,
                                  op: op_mod.Op) -> np.ndarray:
        """Segment-pipelined ring allreduce for the host tier — the
        device ``_ring_segmented_allreduce_inner``'s analogue over the
        byte transport (coll_base_allreduce.c ring: reduce-scatter
        ring then allgather ring). Each rank ends up computing ONE
        chunk's full fold and circulating it, so results are bitwise
        identical everywhere; every chunk hop is a large pt2pt send
        that rides the pml's segment-pipelined rendezvous (striped
        over mpi_base_btl_rails rails), and since all ranks send and
        receive concurrently the wire time per step is one chunk, not
        two. Wire bytes per rank: 2(n-1)/n payloads with overlap — vs
        the serial reduce-then-bcast fallback's 2 payloads with none."""
        n, r, t = self.size, self._rank, self._tag()
        spc.record("coll_pipelined_ring", 1)
        arr = np.ascontiguousarray(data)
        shape, flat = arr.shape, arr.reshape(-1)
        bounds = [(flat.size * i) // n for i in range(n + 1)]
        # views, not copies: sends pack straight from the source buffer
        # (pml/pipeline's zero-copy segments); the fold below replaces
        # each entry with a fresh array, so the input is never mutated
        chunks = [flat[bounds[i]:bounds[i + 1]] for i in range(n)]
        right, left = (r + 1) % n, (r - 1) % n
        npfn = op_mod.NP_COMBINERS[op.name]
        # reduce-scatter ring: at step s, send chunk (r-s), fold the
        # incoming chunk (r-s-1); after n-1 steps this rank holds the
        # complete fold of chunk (r+1) % n
        for s in range(n - 1):
            si = (r - s) % n
            ri = (r - s - 1) % n
            req = self._coll_pml.irecv(left, t)
            self._csend(right, t, chunks[si])
            req.wait()
            inc = req.get()
            chunks[ri] = npfn(chunks[ri],
                              np.asarray(inc).reshape(chunks[ri].shape))
        # allgather ring: circulate the n fully-folded chunks
        own = (r + 1) % n
        cur = chunks[own]
        for s in range(n - 1):
            req = self._coll_pml.irecv(left, t)
            self._csend(right, t, cur)
            req.wait()
            cur = np.asarray(req.get())
            idx = (own - 1 - s) % n
            chunks[idx] = cur.reshape(chunks[idx].shape)
        out = chunks[0] if n == 1 else np.concatenate(
            [np.asarray(c).reshape(-1) for c in chunks])
        return out.reshape(shape).astype(arr.dtype, copy=False)

    def _pipeline_bcast_ok(self, data: Any) -> bool:
        """Root-side gate for the pipelined chain bcast; the decision
        travels to the other ranks in the metadata round (bcast's args
        are asymmetric, so only the root can decide)."""
        if self.size < 2 or not isinstance(data, np.ndarray):
            return False
        if data.dtype.kind not in "fiub" or data.ndim == 0:
            return False
        from ompi_tpu.coll import decision
        rules = decision.pipeline_rules().get("bcast")
        if not rules:
            return False
        return decision._match(rules, self.size,
                               int(data.nbytes)) == "pipelined_chain"

    def _pipelined_chain_bcast(self, data: Any, root: int) -> Any:
        """Segment-pipelined chain bcast (coll_base_bcast.c
        pipeline/chain): ranks form a chain from the root; the payload
        moves as a train of chunks, and every intermediate rank
        forwards chunk c while its predecessor is already sending
        chunk c+1 — after the chain fills, every link streams
        concurrently, so wall time approaches one payload's wire time
        plus chain-depth chunk latencies instead of depth full
        payloads. Chunks large enough also ride the pml's segmented
        rendezvous inside each hop."""
        n, t = self.size, self._tag()
        vr = (self._rank - root) % n
        succ = ((vr + 1) + root) % n if vr + 1 < n else None
        pred = ((vr - 1) + root) % n
        spc.record("coll_pipelined_chain", 1)
        if vr == 0:
            arr = np.ascontiguousarray(data)
            flat = arr.reshape(-1)
            from ompi_tpu.pml import pipeline as _pl
            seg = _pl.segment_bytes_for(int(arr.nbytes),
                                        self.router.endpoint)
            # chunk = a few segments: big enough to pipeline inside
            # the hop, small enough that the chain fills quickly
            per = max(1, (seg * 4) // max(arr.dtype.itemsize, 1))
            k = max(1, -(-flat.size // per))
            if succ is not None:
                self._csend(succ, t, (k, tuple(arr.shape),
                                      arr.dtype.str))
                for c in range(k):
                    self._csend(succ, t, flat[c * per:(c + 1) * per])
            return data
        k, shape, dtstr = self._crecv(pred, t)
        if succ is not None:
            self._csend(succ, t, (k, shape, dtstr))
        parts: List[Any] = []
        for c in range(k):
            part = self._crecv(pred, t)
            if succ is not None:
                self._csend(succ, t, part)   # forward c while pred
            parts.append(part)               # streams c+1 behind it
        flat = np.asarray(parts[0]).reshape(-1) if k == 1 \
            else np.concatenate([np.asarray(p).reshape(-1)
                                 for p in parts])
        return flat.reshape(shape).astype(np.dtype(dtstr), copy=False)

    @_serialized
    def gather(self, data: Any, root: int = 0) -> Optional[List[Any]]:
        """Linear gather (coll/basic): returns the rank-ordered list at
        root, None elsewhere."""
        self._check()
        self._validate_root(root)
        spc.record("coll_gather", 1)
        n, t = self.size, self._tag()
        if self._rank != root:
            self._csend(root, t, data)
            return None
        out: List[Any] = [None] * n
        out[root] = data
        for s in range(n):
            if s != root:
                out[s] = self._crecv(s, t)
        return out

    @_serialized
    def scatter(self, chunks: Optional[Sequence[Any]] = None,
                root: int = 0) -> Any:
        """Linear scatter: root passes one chunk per rank; every caller
        gets its chunk."""
        self._check()
        self._validate_root(root)
        spc.record("coll_scatter", 1)
        n, t = self.size, self._tag()
        if self._rank == root:
            if chunks is None or len(chunks) != n:
                self._err(ERR_COUNT, "root must pass one chunk per rank")
            for d in range(n):
                if d != root:
                    self._csend(d, t, chunks[d])
            return chunks[root]
        return self._crecv(root, t)

    @_serialized
    def allgather(self, data: Any, *, uniform: bool = False) -> List[Any]:
        """Ring allgather (coll_base_allgather ring): n-1 rounds, each
        forwarding the chunk received last round.

        ``uniform=True`` asserts every caller passes one (shape, dtype)
        — the C `MPI_Allgather` signature guarantee — unlocking the
        staged device tier for large host buffers (see ``alltoall``:
        the staging decision must be rank-symmetric, and the generic
        host path legally carries ragged objects)."""
        self._check()
        spc.record("coll_allgather", 1)
        if isinstance(data, _dev_array_type()) and self._mesh() is not None:
            return self._device_allgather(data)
        if uniform and self._stageable(data, func="allgather"):
            spc.record("coll_staged_device", 1)
            return [np.asarray(g) for g in self._device_allgather(
                np.ascontiguousarray(data))]
        n, r, t = self.size, self._rank, self._tag()
        out: List[Any] = [None] * n
        out[r] = data
        cur = data
        right, left = (r + 1) % n, (r - 1) % n
        for s in range(n - 1):
            req = self._coll_pml.irecv(left, t)
            self._csend(right, t, cur)
            req.wait()
            cur = req.get()
            out[(r - 1 - s) % n] = cur
        return out

    @_serialized
    def alltoall(self, chunks: Sequence[Any], *,
                 uniform: bool = False) -> List[Any]:
        """Pairwise-exchange alltoall (coll_base_alltoall pairwise).

        ``uniform=True`` asserts that every CALLER passes chunks of one
        (shape, dtype) — the property the C `MPI_Alltoall` signature
        (one sendcount/sendtype) guarantees globally. Only then may
        large host chunks take the staged device tier: the staging
        decision must be identical on every rank (the device dispatch
        is collective), and chunk uniformity checked locally cannot
        prove anything about other ranks' generic-object chunks."""
        self._check()
        spc.record("coll_alltoall", 1)
        n, r, t = self.size, self._rank, self._tag()
        if len(chunks) != n:
            self._err(ERR_COUNT, "alltoall needs one chunk per peer")
        if all(isinstance(c, _dev_array_type()) for c in chunks) \
                and self._mesh() is not None and n > 1:
            return self._device_alltoall(chunks)
        if (uniform and n > 1 and chunks
                and all(isinstance(c, np.ndarray) for c in chunks)
                and len({(c.shape, c.dtype.str) for c in chunks}) == 1
                and self._stageable(chunks[0], nbytes=chunks[0].nbytes * n,
                                    func="alltoall")):
            spc.record("coll_staged_device", 1)
            return [np.asarray(g) for g in self._device_alltoall(
                [np.ascontiguousarray(c) for c in chunks])]
        out: List[Any] = [None] * n
        out[r] = chunks[r]
        for s in range(1, n):
            dest, src = (r + s) % n, (r - s) % n
            req = self._coll_pml.irecv(src, t)
            self._csend(dest, t, chunks[dest])
            req.wait()
            out[src] = req.get()
        return out

    @_serialized
    def scan(self, data: Any, op: op_mod.Op = op_mod.SUM) -> Any:
        """Linear scan: inclusive prefix over ranks 0..r."""
        self._check()
        self._validate_op(op)
        spc.record("coll_scan", 1)
        n, r, t = self.size, self._rank, self._tag()
        acc = data
        if r > 0:
            acc = _apply(op, self._crecv(r - 1, t), data)
        if r + 1 < n:
            self._csend(r + 1, t, acc)
        return acc

    @_serialized
    def exscan(self, data: Any, op: op_mod.Op = op_mod.SUM) -> Any:
        """Exclusive prefix: rank 0 gets None."""
        self._check()
        self._validate_op(op)
        spc.record("coll_exscan", 1)
        n, r, t = self.size, self._rank, self._tag()
        prev = None if r == 0 else self._crecv(r - 1, t)
        if r + 1 < n:
            nxt = data if prev is None else _apply(op, prev, data)
            self._csend(r + 1, t, nxt)
        return prev

    def reduce_scatter_block(self, chunks: Sequence[Any],
                             op: op_mod.Op = op_mod.SUM) -> Any:
        """chunks[j] is this rank's contribution for rank j; returns the
        reduction of everyone's chunk for me."""
        self._check()
        self._validate_op(op)
        spc.record("coll_reduce_scatter_block", 1)
        if len(chunks) != self.size:
            self._err(ERR_COUNT, "need one chunk per rank")
        mine = self.alltoall(list(chunks))
        acc = mine[0]
        for x in mine[1:]:
            acc = _apply(op, acc, x)
        return acc

    # -- nonblocking collectives (async over a worker thread) ----------
    def _coll_worker_loop(self, q: "queue.Queue") -> None:
        # ONE worker per comm runs every deferred collective and any
        # funneled blocking body. It must never fire the coll
        # interposition hooks: blocking entries fire them on the
        # CALLER thread before funneling, i-slots are interposition-
        # exempt by contract (like the stacked coll/sync component),
        # and a fresh thread-local depth would let sync's op counter
        # race across threads and desynchronize injected barriers
        # between ranks.
        from ompi_tpu.coll.interpose_perrank import _tls as _itls
        _itls.sync_depth = 1
        _itls.mon_depth = 1
        while True:
            item = q.get()
            if item is None:
                q.task_done()
                return
            try:
                item()
            except BaseException:        # noqa: BLE001
                # runners report their own errors through their
                # completion boxes; anything escaping here (a broken
                # propagator, an OOM in the plumbing) must not kill
                # the worker — that would wedge every later collective
                # on this comm behind a queue nobody drains
                import traceback
                traceback.print_exc()
            finally:
                q.task_done()            # unfinished_tasks is the
                # _coll_serial busy signal: queued + in-flight jobs

    def _coll_submit(self, runner: Callable) -> None:
        with self._lock:
            if self._cclosed:
                raise MPIError(ERR_COMM,
                               "communicator has been freed")
            q = self._cq
            if q is None:
                q = self._cq = queue.Queue()
                self._cworker = threading.Thread(
                    target=self._coll_worker_loop, args=(q,),
                    daemon=True, name=f"coll-worker-{self.name}")
                self._cworker.start()
            # enqueue under the lock: a concurrent drain's sentinel
            # must not overtake this job
            q.put(runner)

    def _coll_serial(self, fn: Callable, *a, **kw):
        """Execute a collective body on the comm's single collective-
        execution context (see _serialized). Reentrant: a body already
        on the worker runs directly."""
        w = self._cworker
        if w is not None and threading.current_thread() is w:
            return fn(*a, **kw)
        box: Dict[str, Any] = {}
        ev: Optional[threading.Event] = None
        with self._lock:
            q = self._cq
            if q is not None and q.unfinished_tasks > 0:
                ev = threading.Event()
                # a funneled body must see the caller's interposition
                # depths (a collective entry arrives with its hook
                # already fired and depth incremented — nested calls
                # stay uncounted; a file/window op arrives at depth 0
                # — its nested collectives count as app ops), exactly
                # as an inline run would: a rank whose worker happens
                # to be idle runs inline, and hook counts must not
                # depend on that race or coll/sync's injected
                # barriers desync across ranks
                from ompi_tpu.coll.interpose_perrank import \
                    _tls as _itls
                sd = getattr(_itls, "sync_depth", 0)
                md = getattr(_itls, "mon_depth", 0)
                props = [cap() for cap in _TLS_PROPAGATORS]

                def runner():
                    _itls.sync_depth = sd
                    _itls.mon_depth = md
                    applied = []
                    # apply() runs INSIDE the try: a raising propagator
                    # must surface at the caller's wait like any body
                    # error — not escape the runner, leave ev unset,
                    # and hang the funneling caller forever
                    try:
                        for apply, reset in props:
                            apply()
                            applied.append(reset)
                        box["res"] = fn(*a, **kw)
                    except BaseException as e:  # noqa: BLE001
                        box["err"] = e
                    finally:
                        for reset in applied:
                            try:
                                reset()
                            except BaseException:  # noqa: BLE001
                                pass
                        _itls.sync_depth = 1    # the worker default:
                        _itls.mon_depth = 1     # i-jobs are exempt
                        ev.set()
                q.put(runner)
        if ev is None:                   # worker idle: inline
            return fn(*a, **kw)
        ev.wait()
        if "err" in box:
            raise box["err"]
        return box["res"]

    def _coll_drain(self) -> None:
        """Retire the comm's worker, draining pending jobs first
        (MPI-3.1 6.4.3: deallocation only after pending operations
        complete). _cclosed is set under the same lock hold as the
        sentinel, so no concurrent submit can spawn a SECOND worker
        while the old one still runs queued jobs (two executors would
        break the single-tag-draw-thread invariant); late submits get
        a clean freed-comm error instead."""
        with self._lock:
            q, t = self._cq, self._cworker
            self._cq = self._cworker = None
            self._cclosed = True
            if q is not None:
                q.put(None)              # queues behind pending jobs
        if t is not None:
            t.join()

    def _nb(self, fn: Callable, *args) -> Request:
        req = RankRequest(ANY_SOURCE, ANY_TAG)
        req._error: Optional[BaseException] = None
        orig_wait = req.wait

        def wait(timeout=None):
            st = orig_wait(timeout)
            if req._error is not None:           # surfaced at wait()
                raise req._error
            return st
        req.wait = wait

        def run():
            from ompi_tpu.pml.perrank import _Msg
            try:
                req._deliver(_Msg(self._rank, 0, fn(*args)))
            except BaseException as e:
                req._error = e
                req._complete = True
                req._event.set()
        self._coll_submit(run)
        return req

    # The i-variants run the CLASS-level implementations, bypassing any
    # interposition rebindings (coll/interpose_perrank): the stacked
    # coll/sync component excludes i-slots for the same reason — the
    # worker thread's fresh thread-local depth would race the sync op
    # counter across ranks and desynchronize injected barriers.
    def ibarrier(self) -> Request:
        return self._nb(RankCommunicator.barrier, self)

    def ibcast(self, data: Any = None, root: int = 0) -> Request:
        return self._nb(RankCommunicator.bcast, self, data, root)

    def iallreduce(self, data: Any, op: op_mod.Op = op_mod.SUM) -> Request:
        from ompi_tpu.coll import persistent as _pcoll
        if _pcoll.bucket_enabled():
            # DDP-style bucket fusion (docs/PERSISTENT.md): concurrent
            # small iallreduces on one (op, dtype) ride a single fused
            # wire collective; flush points are deterministic program
            # points so every rank fuses the identical bucket
            r = _pcoll.maybe_bucket_iallreduce(self, data, op)
            if r is not None:
                return r
        return self._nb(RankCommunicator.allreduce, self, data, op)

    def iallgather(self, data: Any) -> Request:
        return self._nb(RankCommunicator.allgather, self, data)

    def ireduce(self, data: Any, op: op_mod.Op = op_mod.SUM,
                root: int = 0) -> Request:
        return self._nb(RankCommunicator.reduce, self, data, op, root)

    # -- persistent collectives (MPI-4 *_init; coll/persistent) --------
    # The plan — route decision, fold combiner, multicast template,
    # staged-device executable, codec gates — binds once at init;
    # Start is launch-only and bucketable starts fuse (Startall).
    def allreduce_init(self, data: Any,
                       op: op_mod.Op = op_mod.SUM) -> Request:
        self._check()
        from ompi_tpu.coll import persistent as _pcoll
        return _pcoll.coll_init(self, "allreduce", data, op)

    def bcast_init(self, data: Any = None, root: int = 0) -> Request:
        self._check()
        from ompi_tpu.coll import persistent as _pcoll
        return _pcoll.coll_init(self, "bcast", data, root)

    def allgather_init(self, data: Any) -> Request:
        self._check()
        from ompi_tpu.coll import persistent as _pcoll
        return _pcoll.coll_init(self, "allgather", data)

    def reduce_scatter_block_init(self, chunks: Sequence[Any],
                                  op: op_mod.Op = op_mod.SUM) -> Request:
        self._check()
        from ompi_tpu.coll import persistent as _pcoll
        return _pcoll.coll_init(self, "reduce_scatter_block", chunks, op)

    def barrier_init(self) -> Request:
        self._check()
        from ompi_tpu.coll import persistent as _pcoll
        return _pcoll.coll_init(self, "barrier")

    # ==================================================================
    # Collectives — device tier (XLA over the global mesh)
    # ==================================================================
    def _mesh(self):
        """Mesh over one device per member rank (rank -> the first
        device of that rank's process). None when some member has no
        visible device (host tier handles it)."""
        if self._mesh_cache is not None:
            return self._mesh_cache or None
        import jax
        from jax.sharding import Mesh
        by_proc: Dict[int, Any] = {}
        for d in jax.devices():
            by_proc.setdefault(getattr(d, "process_index", 0), d)
        devs = []
        for w in self.group.world_ranks:
            d = by_proc.get(w)
            if d is None:
                self._mesh_cache = False
                return None
            devs.append(d)
        self._mesh_cache = Mesh(np.array(devs, dtype=object), (AXIS,))
        return self._mesh_cache

    def _global(self, x):
        """Assemble the (n, *local) global array from my local shard."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = self._mesh()
        sh = NamedSharding(mesh, P(AXIS))
        local = jax.device_put(x, mesh.devices[self._rank])
        return jax.make_array_from_single_device_arrays(
            (self.size,) + tuple(x.shape), sh,
            [local.reshape((1,) + tuple(x.shape))])

    def _local(self, garr):
        """My shard of a mesh-sharded result, squeezed."""
        shard = garr.addressable_shards[0].data
        return shard[0]

    def _dev_fn(self, key, builder):
        fn = self._dev_fns.get(key)
        if fn is None:
            fn = self._dev_fns[key] = builder()
        return fn

    def _device_allreduce(self, x, op: op_mod.Op):
        import jax
        from jax.sharding import PartitionSpec as P
        mesh = self._mesh()

        def build():
            def inner(s):
                if op.xla_prim == "sum":
                    return jax.lax.psum(s, AXIS)
                if op.xla_prim == "max":
                    return jax.lax.pmax(s, AXIS)
                if op.xla_prim == "min":
                    return jax.lax.pmin(s, AXIS)
                g = jax.lax.all_gather(s, AXIS, axis=0, tiled=True)
                return op.reduce_tree(g, axis=0)[None]
            return jax.jit(_shard_map()(
                inner, mesh=mesh, in_specs=P(AXIS), out_specs=P(AXIS)))
        fn = self._dev_fn(("ar", op.uid), build)
        return self._local(fn(self._global(x)))

    def _device_bcast(self, x, root: int):
        import jax
        from jax.sharding import PartitionSpec as P
        mesh = self._mesh()

        def build():
            def inner(s):
                g = jax.lax.all_gather(s, AXIS, axis=0, tiled=True)
                return jax.lax.dynamic_slice_in_dim(g, root, 1, 0)
            return jax.jit(_shard_map()(
                inner, mesh=mesh, in_specs=P(AXIS), out_specs=P(AXIS)))
        fn = self._dev_fn(("bc", root), build)
        return self._local(fn(self._global(x)))

    def _device_allgather(self, x) -> List[Any]:
        import jax
        from jax.sharding import PartitionSpec as P
        mesh = self._mesh()

        def build():
            def inner(s):
                return jax.lax.all_gather(s, AXIS, axis=0, tiled=True)[None]
            return jax.jit(_shard_map()(
                inner, mesh=mesh, in_specs=P(AXIS), out_specs=P(AXIS)))
        fn = self._dev_fn(("ag",), build)
        g = self._local(fn(self._global(x)))           # (n, *local)
        return [g[i] for i in range(self.size)]

    def _device_alltoall(self, chunks: Sequence[Any]) -> List[Any]:
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        mesh = self._mesh()

        def build():
            def inner(s):                  # s: (1, n, *c)
                # split the peer axis, land chunk-from-rank-i at row i,
                # then restore the (1, n, *c) local block layout
                return jnp.moveaxis(
                    jax.lax.all_to_all(s, AXIS, split_axis=1,
                                       concat_axis=0), 0, 1)
            return jax.jit(_shard_map()(
                inner, mesh=mesh, in_specs=P(AXIS), out_specs=P(AXIS)))
        fn = self._dev_fn(("a2a",), build)
        x = jnp.stack(list(chunks))                    # (n, *c)
        g = self._local(fn(self._global(x)))           # (n, *c) received
        return [g[i] for i in range(self.size)]

    # ==================================================================
    # Communicator algebra (collective; deterministic CIDs)
    # ==================================================================
    def split(self, color: int, key: int = 0
              ) -> Optional["RankCommunicator"]:
        """MPI_Comm_split (comm.c:749), textbook signature: each caller
        passes ITS color/key and receives its child (or None)."""
        self._check()
        seq = next(self._create_seq)
        rows = self.allgather((color, key))
        if color == UNDEFINED:
            return None
        members = sorted((r for r in range(self.size)
                          if rows[r][0] == color),
                         key=lambda r: (rows[r][1], r))
        g = Group([self.group.world_ranks[r] for r in members])
        return RankCommunicator(
            g, self._my_world, self.router,
            cid=("s", self.cid, seq, color),
            name=f"{self.name}.split({color})", parent=self,
            errhandler=self.errhandler)

    def split_type(self, split_type: int, key: int = 0):
        if split_type == UNDEFINED:
            return None
        if split_type == 2:                 # COMM_TYPE_HWTHREAD
            color = self._rank
        elif split_type in (1, 3):          # SHARED / NUMA: same host
            import socket
            names = self.allgather(socket.gethostname())
            color = names.index(names[self._rank])
        else:                               # match Communicator's
            self._err(ERR_ARG,              # validation, not a silent
                      f"unknown split_type {split_type}")  # SHARED
            return None
        return self.split(color, key)

    def dup(self, info: Optional[Info] = None) -> "RankCommunicator":
        self._check()
        seq = next(self._create_seq)
        self.barrier()                      # dup is collective
        c = RankCommunicator(
            Group(self.group.world_ranks), self._my_world, self.router,
            cid=("d", self.cid, seq), name=f"{self.name}.dup",
            parent=self, errhandler=self.errhandler,
            info=info or self.info)
        from ompi_tpu.core.communicator import propagate_attrs
        try:
            propagate_attrs(self, c)
        except BaseException:
            c.free()                     # no half-built comm leaks
            raise
        return c

    # -- process topologies (textbook cart surface) --------------------
    def create_cart(self, dims: Sequence[int],
                    periods: Optional[Sequence[bool]] = None,
                    reorder: bool = False
                    ) -> Optional["RankCommunicator"]:
        """MPI_Cart_create, textbook signature: callers beyond the cart
        size get None (MPI_COMM_NULL)."""
        import math
        from ompi_tpu.topo import CartTopology
        dims = list(dims)
        n = math.prod(dims)
        if n > self.size:
            self._err(ERR_ARG, f"cart size {n} exceeds comm size")
        sub = self.split(0 if self._rank < n else UNDEFINED)
        if sub is None:
            return None
        sub.topo = CartTopology(dims, list(periods) if periods
                                else [False] * len(dims))
        sub.name = f"{self.name}.cart"
        return sub

    def create_graph(self, index: Sequence[int], edges: Sequence[int],
                     reorder: bool = False
                     ) -> Optional["RankCommunicator"]:
        """MPI_Graph_create, textbook signature: callers beyond the
        graph size get None. ``reorder`` is accepted but placement is
        identity in the per-rank world — process binding is fixed at
        launch (the single-controller path runs the treematch
        permutation instead)."""
        from ompi_tpu.topo import GraphTopology
        topo = GraphTopology(index, edges)
        if topo.size > self.size:
            self._err(ERR_ARG, "graph larger than communicator")
        sub = self.split(0 if self._rank < topo.size else UNDEFINED)
        if sub is None:
            return None
        sub.topo = topo
        sub.name = f"{self.name}.graph"
        return sub

    def create_dist_graph_adjacent(self, sources: Sequence[int],
                                   destinations: Sequence[int]
                                   ) -> "RankCommunicator":
        """MPI_Dist_graph_create_adjacent, textbook signature: THIS
        rank's in/out neighbor lists; the full per-rank table is
        assembled collectively (the modex the reference does through
        its topo machinery)."""
        from ompi_tpu.topo import DistGraphTopology
        rows = self.allgather(([int(s) for s in sources],
                               [int(d) for d in destinations]))
        c = self.dup()
        c.topo = DistGraphTopology([r[0] for r in rows],
                                   [r[1] for r in rows])
        c.name = f"{self.name}.dist_graph"
        return c

    def _cart(self):
        from ompi_tpu.topo import CartTopology
        if not isinstance(self.topo, CartTopology):
            from ompi_tpu.core.errhandler import ERR_TOPOLOGY
            self._err(ERR_TOPOLOGY,
                      "communicator has no cartesian topology")
        return self.topo

    def cart_coords(self, rank: Optional[int] = None):
        return self._cart().coords(self._rank if rank is None else rank)

    def cart_rank(self, coords: Sequence[int]) -> int:
        return self._cart().rank(coords)

    def cart_shift(self, direction: int, disp: int = 1):
        """MPI_Cart_shift for THIS rank: (source, dest)."""
        return self._cart().shift(self._rank, direction, disp)

    @_serialized
    def neighbor_allgather(self, data: Any) -> List[Any]:
        """MPI_Neighbor_allgather, textbook: exchange ``data`` with each
        topology neighbor; returns received buffers in neighbor order
        (None at invalid slots — alignment is never shifted). Balanced
        eager sendrecv per slot: every edge endpoint sends once and
        receives once per slot pair, FIFO keeps duplicate edges
        ordered."""
        self._check()
        if self.topo is None:
            from ompi_tpu.core.errhandler import ERR_TOPOLOGY
            self._err(ERR_TOPOLOGY, "no topology attached")
        # post ALL receives, then send ALL, then wait — a sequential
        # per-slot wait deadlocks on periodic rings of size >= 3 (each
        # rank's slot-0 wait needs a frame its neighbor only sends
        # after ITS slot-0 wait: a cycle)
        # directed topologies (dist_graph): receive from IN-neighbors,
        # send to OUT-neighbors (MPI_Neighbor_* on a dist graph)
        nbrs = list(self.topo.neighbors(self._rank))
        outs = (list(self.topo.out_neighbors(self._rank))
                if hasattr(self.topo, "out_neighbors") else nbrs)
        t = self._tag()
        reqs = [self._coll_pml.irecv(nb, t)
                if 0 <= nb < self.size else None for nb in nbrs]
        for nb in outs:
            if 0 <= nb < self.size:
                self._coll_pml.send(data, nb, t)
        out: List[Any] = []
        for q in reqs:
            if q is None:
                out.append(None)
            else:
                q.wait()
                out.append(q.get())
        return out

    @_serialized
    def neighbor_alltoall(self, chunks: Sequence[Any]) -> List[Any]:
        """MPI_Neighbor_alltoall, textbook: chunk j goes to my j-th
        neighbor; returns one buffer per neighbor slot (None at invalid
        slots)."""
        self._check()
        if self.topo is None:
            from ompi_tpu.core.errhandler import ERR_TOPOLOGY
            self._err(ERR_TOPOLOGY, "no topology attached")
        nbrs = list(self.topo.neighbors(self._rank))
        outs = (list(self.topo.out_neighbors(self._rank))
                if hasattr(self.topo, "out_neighbors") else nbrs)
        if len(chunks) != len(outs):
            self._err(ERR_COUNT, "need one chunk per neighbor slot")
        t = self._tag()
        reqs: List[Optional[RankRequest]] = []
        for nb in nbrs:
            reqs.append(self._coll_pml.irecv(nb, t)
                        if 0 <= nb < self.size else None)
        for nb, c in zip(outs, chunks):
            if 0 <= nb < self.size:
                self._coll_pml.send(c, nb, t)
        out: List[Any] = []
        for q in reqs:
            if q is None:
                out.append(None)
            else:
                q.wait()
                out.append(q.get())
        return out

    def create(self, group: Group) -> Optional["RankCommunicator"]:
        self._check()
        seq = next(self._create_seq)
        self.barrier()
        if group.rank_of(self._my_world) == UNDEFINED:
            return None
        return RankCommunicator(
            group, self._my_world, self.router,
            cid=("g", self.cid, seq, tuple(group.world_ranks)),
            name=f"{self.name}.create", parent=self,
            errhandler=self.errhandler)

    # -- ULFM over real process death (mpiext/ftmpi semantics) ---------
    # The failure detector is the btl/tcp connection monitor (an
    # identified peer's EOF == PMIx failure event); these methods are
    # the MPIX_Comm_* recovery surface for the per-rank world.
    def get_failed(self) -> List[int]:
        """MPIX_Comm_get_failed: comm-local ranks known dead."""
        from ompi_tpu.runtime import ft
        return [r for r in range(self.size)
                if ft.is_failed(self.group.world_ranks[r])]

    def revoke(self) -> None:
        """MPIX_Comm_revoke: non-collective — ONE caller poisons the
        communicator everywhere. The router floods a reliable
        ``revoke`` ctl broadcast (every first receipt re-forwards, the
        revoked-set test terminates it — coll_base_revoke_local.c);
        locally and on every receiver the pending operations complete
        with ERR_REVOKED and new ones refuse in ``_check``. The
        recovery surface (shrink/agree/get_failed/free) keeps
        working."""
        self.router.revoke(self.cid)

    def is_revoked(self) -> bool:
        """MPIX_Comm_is_revoked (local, non-collective)."""
        return self.router.is_revoked(self.cid)

    def _on_revoked(self) -> None:
        """Router revoke callback: flush every pending operation —
        wildcards included (unlike a single peer death, a revoked comm
        can never match ANYTHING again, req_ft.c's revocation
        branch)."""
        def err():
            return MPIError(ERR_REVOKED,
                            f"{self.name} has been revoked")
        for eng in (self._pml, self._coll_pml,
                    *list(self._aux_pmls.values())):
            try:
                eng._flush_all(err)
            except Exception:            # noqa: BLE001
                pass

    def agree(self, flag: int = 1, timeout: float = 20) -> int:
        """MPIX_Comm_agree: fault-tolerant agreement — AND-folds the
        integer ``flag`` over the SURVIVING members and returns the
        agreed value on all of them, completing even with failed (or
        failing) participants. Runs on a revoked comm — it is the
        recovery path. The early-returning protocol lives in
        coll/ftagree (known-dead ranks are excluded up front, only a
        rank dying DURING the agreement costs a timeout)."""
        from ompi_tpu.coll import ftagree
        value, _failed = ftagree.perrank_agree(self, int(flag),
                                               timeout=timeout)
        return value

    def shrink(self, timeout: float = 20) -> "RankCommunicator":
        """MPIX_Comm_shrink: survivors agree on the failed set through
        coll/ftagree's early-returning agreement (a silent rank is
        itself suspected into the set — the ftagree suspicion rule)
        and build the survivor communicator through the NORMAL
        RankCommunicator construction, i.e. normal coll selection.
        Collective among survivors; works on a revoked comm. Retried
        when a survivor's stale failure view elected a dead leader
        (detection is asynchronous; the failed first exchange itself
        surfaces the death, and the retry settles)."""
        last: Optional[BaseException] = None
        for _ in range(3):
            try:
                return self._shrink_once(timeout)
            except (MPIError, OSError) as e:
                # OSError: a send raced the detector onto a just-dead
                # leader's broken socket (EPIPE beats the EOF callback)
                last = e
                import time
                time.sleep(0.2)          # let the detector settle
        raise last

    def _shrink_once(self, timeout: float) -> "RankCommunicator":
        # NO draw from _create_seq here: ranks may take different
        # numbers of retry attempts, and divergent draws would desync
        # every later dup/split cid. The child cid derives from the
        # AGREED failed set instead (same on every survivor, distinct
        # per failure epoch).
        from ompi_tpu.coll import ftagree
        _value, final = ftagree.perrank_agree(self, 1, timeout=timeout)
        survivors = [r for r in range(self.size) if r not in final]
        g = Group([self.group.world_ranks[r] for r in survivors])
        child = RankCommunicator(
            g, self._my_world, self.router,
            cid=("shrink", self.cid, tuple(final)),
            name=f"{self.name}.shrink", parent=self,
            errhandler=self.errhandler)
        # parent stays alive after a shrink, but its per-comm
        # instruments describe the dead-rank era — retire them so later
        # reads (trace_skew_c<cid>, tele_coll_*) can't report keys from
        # before the failure epoch
        from ompi_tpu import telemetry as _telemetry
        _telemetry.retire_comm(self.cid)
        return child

    def free(self) -> None:
        # delete callbacks fire FIRST (attribute.c free path): a
        # failing callback aborts the free with the comm fully intact
        # — worker alive, engines open — so the caller's "free did
        # not happen, comm stays valid" contract holds (MPI-3.1
        # 6.7.2)
        from ompi_tpu.core.communicator import fire_delete_attrs
        fire_delete_attrs(self)
        self.router.unregister_revoke_cb(self.cid)
        self._coll_drain()               # pending deferred collectives
        # complete against the live comm before teardown (MPI-3.1
        # 6.4.3)
        self._pml.close()
        self._coll_pml.close()
        for eng in self._aux_pmls.values():   # hidden channels too —
            eng.close()                       # a leaked registration
        self._aux_pmls.clear()                # would outlive the comm
        self._freed = True
        # pvar session semantics: per-comm instruments (telemetry
        # histograms, trace_skew_c<cid>) retire with the comm
        from ompi_tpu import telemetry as _telemetry
        _telemetry.retire_comm(self.cid)

    # -- attributes / naming -------------------------------------------
    def set_attr(self, keyval: int, value: Any) -> None:
        self.attributes[keyval] = value

    def get_attr(self, keyval: int) -> Tuple[bool, Any]:
        if keyval in self.attributes:
            return True, self.attributes[keyval]
        return False, None

    def delete_attr(self, keyval: int) -> None:
        from ompi_tpu.core import communicator as core_comm
        val = self.attributes.pop(keyval, None)
        cb = core_comm._keyvals.get(keyval)
        if cb and cb[1] and val is not None:
            cb[1](self, keyval, val)

    def set_errhandler(self, errh: Errhandler) -> None:
        self.errhandler = errh

    def get_errhandler(self) -> Errhandler:
        return self.errhandler

    def set_name(self, name: str) -> None:
        self.name = name

    def get_name(self) -> str:
        return self.name

    def abort(self, errorcode: int = 1):
        import os
        import sys
        sys.stderr.write(f"MPI_Abort on {self.name} "
                         f"errorcode={errorcode}\n")
        sys.stderr.flush()
        os._exit(errorcode)

    def __repr__(self):
        return (f"RankCommunicator({self.name}, rank={self._rank}/"
                f"{self.size}, cid={self.cid!r})")


def _apply(op: op_mod.Op, a: Any, b: Any) -> Any:
    """Apply a reduction combiner on the host tier: numpy in, numpy out.
    Predefined ops take the C++ SIMD kernel table (the op/avx role) or
    a dtype-preserving numpy ufunc — never the jnp combiner, which
    would silently downcast 64-bit numpy operands to 32-bit whenever
    jax runs without x64 (the per-rank default)."""
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        if op.predefined:
            an, bn = np.asarray(a), np.asarray(b)
            from ompi_tpu.native import native_reduce_local
            out = native_reduce_local(op.name, an, bn)
            if out is not None:
                return np.asarray(out)
            npfn = op_mod.NP_COMBINERS.get(op.name)
            if npfn is not None:
                return np.asarray(npfn(an, bn))
        return np.asarray(op.fn(a, b))
    if (op.predefined and not op.is_loc
            and isinstance(a, np.generic) and isinstance(b, np.generic)):
        # scalar fast path: the numpy kernel both preserves 64-bit
        # dtypes (the jnp combiner below silently downcasts without
        # x64) and skips a per-call JAX dispatch — this fold runs on
        # btl reader threads inside the sub-eager collective path
        npfn = op_mod.NP_COMBINERS.get(op.name)
        if npfn is not None:
            return npfn(a, b).item()
    try:
        import jax
        if isinstance(a, jax.Array):
            return op.fn(a, b)
    except Exception:
        pass
    r = op.fn(np.asarray(a), np.asarray(b))
    r = np.asarray(r)
    return r.item() if r.ndim == 0 else r


def _dev_array_type():
    import jax
    return jax.Array


def _shard_map():
    """The shard_map entry point across jax versions (jax >= 0.4.35
    exposes it at top level; older releases keep it experimental) —
    the same shim coll/xla.py carries."""
    import jax
    try:
        return jax.shard_map
    except AttributeError:              # pragma: no cover
        from jax.experimental.shard_map import shard_map
        return shard_map
