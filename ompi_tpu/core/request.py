"""Request lifecycle — test/wait{,any,all,some}, persistent and
generalized requests.

Behavioral spec: ``ompi/request/request.h`` (:311-430 wait/test family,
:451-470 completion sync). TPU-native re-design: there is no progress
engine to spin. JAX dispatch is asynchronous — a collective/pt2pt call
returns immediately with output arrays whose values materialize when the
device stream reaches them. A Request therefore wraps those arrays:
``wait`` is ``jax.block_until_ready``; ``test`` polls readiness without
blocking. Host-side components complete synchronously (requests are born
complete), which matches the reference's self/sm fast path.
"""
from __future__ import annotations

import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax

from ompi_tpu.core.errhandler import ERR_REQUEST, MPIError


class Status:
    """MPI_Status: source, tag, error, element count."""

    __slots__ = ("source", "tag", "error", "count", "cancelled",
                 "nbytes")

    ANY_SOURCE = -1
    ANY_TAG = -1

    def __init__(self, source: int = -1, tag: int = -1, error: int = 0,
                 count: int = 0, nbytes: int = -1):
        self.source = source
        self.tag = tag
        self.error = error
        self.count = count
        self.cancelled = False
        # payload size in bytes (-1 = unknown): what the reference
        # stores in status->_ucount so MPI_Get_count can convert into
        # any caller datatype's units; the C ABI relies on it
        self.nbytes = nbytes

    def get_count(self, datatype=None) -> int:
        if datatype is None or datatype.count == 0:
            return self.count
        return self.count // datatype.count

    def is_cancelled(self) -> bool:
        return self.cancelled


def _is_ready(arr) -> bool:
    f = getattr(arr, "is_ready", None)
    if callable(f):
        try:
            return bool(f())
        except Exception:
            return True
    return True                      # host values are always ready


class Request:
    """A pending operation. ``result`` is the operation's output (stacked
    arrays); ``on_complete`` runs exactly once at completion."""

    def __init__(self, result: Any = None,
                 arrays: Optional[Sequence[Any]] = None,
                 on_complete: Optional[Callable[[Any], Any]] = None,
                 status: Optional[Status] = None,
                 persistent_start: Optional[Callable[[], "Request"]] = None):
        self._result = result
        self._arrays = list(arrays) if arrays is not None else None
        self._on_complete = on_complete
        self._complete = arrays is None
        self._freed = False
        self._free_pending = False
        self.status = status or Status()
        self._persistent_start = persistent_start
        self._active = persistent_start is None
        self._inner_req: Optional["Request"] = None
        self._error: Optional[BaseException] = None

    # -- ULFM completion-in-error (ompi/request/req_ft.c) ------------------
    def fail(self, err: BaseException) -> None:
        """Complete the request NOW, carrying ``err``: the operation can
        never finish (its peer died, or its communicator was revoked).
        wait/test/get raise the stored error; ``status.error`` reports
        its class for the status-based readers."""
        self._error = err
        self.status.error = int(getattr(err, "error_class", 0) or 0)
        self._arrays = None
        self._on_complete = None
        self._inner_req = None
        self._complete = True

    # -- completion --------------------------------------------------------
    def _finish(self):
        if self._on_complete is not None:
            cb, self._on_complete = self._on_complete, None
            self._result = cb(self._result)
        self._complete = True
        if self._free_pending:
            # MPI_Request_free was called while the operation was in
            # flight: the deallocation completes with the operation
            # (request_free.c.in deferred-free semantics)
            self._free_pending = False
            self._freed = True

    def test(self) -> Tuple[bool, Optional[Status]]:
        """MPI_Test: non-blocking completion check."""
        if self._complete:
            if self._error is not None:
                raise self._error
            return True, self.status
        if self._inner_req is not None:
            # started persistent request: delegate to this iteration's
            # operation (which may itself be schedule-backed)
            ok, _st = self._inner_req.test()
            if ok:
                self._result = self._inner_req._result
                self._finish()
                return True, self.status
            return False, None
        if self._arrays is None or all(_is_ready(a) for a in self._arrays):
            self._finish()
            return True, self.status
        return False, None

    def wait(self) -> Status:
        """MPI_Wait: block until complete; returns the Status."""
        if not self._complete:
            if self._inner_req is not None:
                self._inner_req.wait()
                self._result = self._inner_req._result
            elif self._arrays is not None:
                jax.block_until_ready(self._arrays)
            self._finish()
        if self._error is not None:
            raise self._error
        return self.status

    def get(self) -> Any:
        """Wait and return the operation's result value (framework
        extension — the functional-API analogue of reading recvbuf).
        Device-rendezvous payloads resolve here, on the consumer
        thread (covers persistent receives, whose completion copies
        the inner request's raw result)."""
        self.wait()
        from ompi_tpu.btl.devxfer import maybe_resolve
        self._result = maybe_resolve(self._result)
        return self._result

    def cancel(self) -> None:
        # XLA execution cannot be cancelled post-dispatch; mirror the
        # reference's behavior for already-started requests: no-op.
        if not self._complete:
            self.status.cancelled = False

    def free(self) -> None:
        """MPI_Request_free. On an ACTIVE request (started, not yet
        completed) the free is DEFERRED: the operation runs to
        completion and the handle is released then — but it is
        unusable (un-startable) from this call on, exactly the
        standard's contract."""
        if self._active and not self._complete:
            self._free_pending = True
            return
        self._freed = True

    # -- persistent requests (MPI_Send_init / MPI_Start) -------------------
    def _check_startable(self) -> None:
        """MPI_Start argument checks (start.c.in:56-70): the request
        must be persistent, not freed (or free-pending), and INACTIVE —
        starting an already-active persistent request is
        MPI_ERR_REQUEST, not a silent second dispatch."""
        if self._persistent_start is None:
            raise MPIError(ERR_REQUEST,
                           "MPI_Start on a non-persistent request")
        if self._freed or self._free_pending:
            raise MPIError(ERR_REQUEST,
                           "MPI_Start on a freed request")
        if self._active and not self._complete:
            raise MPIError(ERR_REQUEST,
                           "MPI_Start on an active persistent request "
                           "(complete it with MPI_Wait/MPI_Test first)")

    def start(self) -> "Request":
        self._check_startable()
        self._error = None
        self.status.error = 0
        self._complete = False
        self._active = True
        try:
            self._inner_req = self._persistent_start()
        except MPIError as e:
            # the plan's peer died between rounds (the per-start
            # liveness check fired): the START is what failed, but the
            # REQUEST completes carrying the error (req_ft.c) — a
            # waitall over a mixed batch surfaces MPI_ERR_PROC_FAILED
            # instead of deadlocking on a request that never started
            self.fail(e)
        return self

    @staticmethod
    def completed(result: Any = None, status: Optional[Status] = None):
        return Request(result=result, status=status)


# -- generalized requests (MPI_Grequest_start) -----------------------------
class Grequest(Request):
    def __init__(self, query_fn=None, free_fn=None, cancel_fn=None):
        super().__init__(arrays=[])
        self._complete = False
        self._q, self._f, self._c = query_fn, free_fn, cancel_fn

    def complete(self, result: Any = None) -> None:     # MPI_Grequest_complete
        self._result = result
        self._complete = True
        if self._q:
            self._q(self.status)

    def test(self):
        return (True, self.status) if self._complete else (False, None)

    def wait(self):
        while not self._complete:
            time.sleep(0)            # yield; completion is external
        return self.status

    def cancel(self):
        if self._c:
            self._c(self._complete)


# -- wait/test families (request.h:311-430) --------------------------------
def waitall(requests: Sequence[Request]) -> List[Status]:
    return [r.wait() for r in requests]


def startall(requests: Sequence[Request]) -> Sequence[Request]:
    """MPI_Startall. Persistent COLLECTIVES on the same communicator
    coalesce: bucketable ones enqueue into the comm's BucketFuser and
    flush once at the startall boundary — K small allreduces ride
    ceil(K*bytes/bucket_bytes) wire collectives instead of K
    (coll/persistent, docs/PERSISTENT.md). Everything else starts
    singly, in order."""
    from ompi_tpu.coll import persistent as _pcoll
    return _pcoll.startall(requests)


UNDEFINED = -32766


def waitany(requests: Sequence[Request]) -> Tuple[int, Optional[Status]]:
    if not requests:
        return UNDEFINED, None       # MPI: empty list returns immediately
    while True:
        for i, r in enumerate(requests):
            ok, st = r.test()
            if ok:
                return i, st
        time.sleep(0)


def waitsome(requests: Sequence[Request]) -> Tuple[List[int], List[Status]]:
    if not requests:
        return [], []
    while True:
        idx = [i for i, r in enumerate(requests) if r.test()[0]]
        if idx:
            return idx, [requests[i].status for i in idx]
        time.sleep(0)


def testall(requests: Sequence[Request]) -> Tuple[bool, Optional[List[Status]]]:
    if all(r.test()[0] for r in requests):
        return True, [r.status for r in requests]
    return False, None


def testany(requests: Sequence[Request]) -> Tuple[bool, int, Optional[Status]]:
    if not requests:
        return True, UNDEFINED, None
    for i, r in enumerate(requests):
        ok, st = r.test()
        if ok:
            return True, i, st
    return False, -1, None


def testsome(requests: Sequence[Request]) -> Tuple[List[int], List[Status]]:
    idx = [i for i, r in enumerate(requests) if r.test()[0]]
    return idx, [requests[i].status for i in idx]
