"""Dynamic process management — mirrors ``ompi/dpm`` (2,313 LoC).

Reference behavior: ``MPI_Comm_spawn`` launches a child job through PRRTE
and wires an intercommunicator to it over PMIx; ``MPI_Open_port`` /
``MPI_Comm_accept`` / ``MPI_Comm_connect`` rendezvous two independent
jobs through a PMIx-published port string; ``MPI_Publish_name`` /
``MPI_Lookup_name`` are the naming service over the same KV;
``MPI_Comm_join`` bootstraps an intercomm across an existing socket.

TPU-native re-design (single controller): a "job" is a communicator bound
to a device subset of the controller's mesh — spawning allocates a child
world over requested devices (same ICI fabric, the analogue of PRRTE
placing children on the same hosts) and returns the parent⇄child
intercommunicator. Ports and names live in a controller-scope registry
(the PMIx KV role). Rendezvous follows the same discipline as the pt2pt
matching engine: the first side *posts*, the second side *completes* —
a blocking call that would deadlock raises instead (single-controller
semantics), while the i-variants return pollable requests.
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ompi_tpu.core.communicator import Communicator
from ompi_tpu.core.errhandler import (ERR_ARG, ERR_NAME, ERR_PENDING,
                                      ERR_PORT, ERR_SERVICE, ERR_SPAWN,
                                      MPIError)
from ompi_tpu.core.group import Group
from ompi_tpu.core.intercomm import Intercomm
from ompi_tpu.core.request import Request

_port_counter = itertools.count(0)
_ports: Dict[str, dict] = {}           # open ports: port -> rendezvous slot
_names: Dict[str, str] = {}            # published names: service -> port
_joins: Dict[Any, dict] = {}           # Comm_join rendezvous by fd token


class _PendingIntercomm(Request):
    """Request returned by iaccept/iconnect before the peer arrives."""

    def __init__(self):
        super().__init__(arrays=[])
        self._done = False

    def deliver(self, inter: Intercomm) -> None:
        self._result = inter
        self._done = True

    def test(self):
        return (True, None) if self._done else (False, None)

    def wait(self):
        if not self._done:
            raise MPIError(
                ERR_PENDING,
                "accept/connect would deadlock: the peer side has not "
                "been posted (single-controller requires one side to use "
                "the i-variant)")
        return None


def open_port(info=None) -> str:
    """MPI_Open_port: returns a port string usable by accept/connect."""
    port = f"tpu://port/{next(_port_counter)}"
    _ports[port] = {"accept": [], "connect": []}
    return port


def close_port(port: str) -> None:
    _ports.pop(port, None)


def publish_name(service: str, port: str, info=None) -> None:
    """MPI_Publish_name (the PMIx naming-service role)."""
    if service in _names:
        raise MPIError(ERR_SERVICE,
                       f"service {service!r} already published")
    _names[service] = port


def lookup_name(service: str, info=None) -> str:
    port = _names.get(service)
    if port is None:
        raise MPIError(ERR_NAME, f"service {service!r} not published")
    return port


def unpublish_name(service: str, info=None) -> None:
    _names.pop(service, None)


def _slot(port: str) -> dict:
    slot = _ports.get(port)
    if slot is None:
        raise MPIError(ERR_PORT, f"port {port!r} is not open")
    return slot


def _rendezvous(slot: dict, side: str, comm: Communicator,
                req: _PendingIntercomm) -> Optional[Intercomm]:
    """One side arrives; if the other is already posted, both complete.
    Each side is a FIFO so repeated posts pair in order (a port may
    serve several clients, as the reference's accept loop does).
    accept's group is the intercomm's *local* group on the accept side."""
    other = "connect" if side == "accept" else "accept"
    if slot[other]:
        peer_comm, peer_req = slot[other].pop(0)
        mine = Intercomm(comm, peer_comm)
        theirs = Intercomm(peer_comm, comm)
        peer_req.deliver(theirs)
        req.deliver(mine)
        return mine
    slot[side].append((comm, req))
    return None


def iaccept(port: str, comm: Communicator) -> _PendingIntercomm:
    """MPI_Comm_accept, nonblocking posting side."""
    req = _PendingIntercomm()
    _rendezvous(_slot(port), "accept", comm, req)
    return req


def iconnect(port: str, comm: Communicator) -> _PendingIntercomm:
    req = _PendingIntercomm()
    _rendezvous(_slot(port), "connect", comm, req)
    return req


def _blocking(port: str, side: str, comm: Communicator) -> Intercomm:
    req = _PendingIntercomm()
    slot = _slot(port)
    if _rendezvous(slot, side, comm, req) is None:
        # A blocking call that cannot complete must not stay posted
        # (it raises, it does not wait — single-controller semantics).
        slot[side].remove((comm, req))
        req.wait()                       # raises the deadlock error
    return req.get()


def accept(port: str, comm: Communicator) -> Intercomm:
    """MPI_Comm_accept (blocking): completes only if a connect is
    already posted on the port; raises the deadlock otherwise."""
    return _blocking(port, "accept", comm)


def connect(port: str, comm: Communicator) -> Intercomm:
    return _blocking(port, "connect", comm)


def join(fd: Any, comm: Communicator) -> "Intercomm | _PendingIntercomm":
    """MPI_Comm_join: rendezvous over an existing channel token (the
    reference exchanges port names over a connected socket ``fd``).
    First caller posts and receives a pending request; second caller
    completes both sides."""
    slot = _joins.setdefault(fd, {"accept": [], "connect": []})
    req = _PendingIntercomm()
    side = "accept" if not slot["accept"] and not slot["connect"] \
        else "connect"
    inter = _rendezvous(slot, side, comm, req)
    if inter is not None:
        _joins.pop(fd, None)
        return inter
    return req


def spawn(fn: Optional[Callable], maxprocs: int, comm: Communicator,
          *, devices: Optional[Sequence[Any]] = None, root: int = 0,
          info=None, appnum: int = 0, soft: bool = False) -> Intercomm:
    """MPI_Comm_spawn: create a child world of ``maxprocs`` ranks and
    return the parent⇄child intercommunicator (the child side is
    ``intercomm.remote_comm``; ``get_parent(child_world)`` recovers the
    reverse view, as MPI_Comm_get_parent does in the child).

    Child placement: ``devices`` when given (the ``host`` info key
    role), else the parent's devices — spawning onto the same fabric, as
    the reference does on a single node. One rank = one device (a mesh
    cannot hold a device twice), so ``maxprocs`` beyond the distinct
    devices available raises MPI_ERR_SPAWN unless ``soft=True`` (the
    MPI ``soft`` info key: spawn as many as possible). ``fn``, when
    given, is the child program's main: called as ``fn(child_world)``
    (the command/argv of the reference collapses to a callable in a
    single-controller world)."""
    if maxprocs < 1:
        raise MPIError(ERR_ARG, f"maxprocs must be >= 1, got {maxprocs}")
    comm._validate_root(root)
    pool = list(devices) if devices is not None else list(comm.devices)
    # de-dup preserving order (an explicit list may repeat devices)
    seen, devs = set(), []
    for d in pool:
        if id(d) not in seen:
            seen.add(id(d))
            devs.append(d)
    if not devs:
        raise MPIError(ERR_ARG, "spawn needs at least one device")
    if len(devs) < maxprocs:
        if not soft:
            raise MPIError(
                ERR_SPAWN,
                f"cannot spawn {maxprocs} ranks on {len(devs)} distinct "
                f"device(s) (one rank = one device); pass soft=True to "
                f"spawn fewer")
        maxprocs = len(devs)
    devs = devs[:maxprocs]
    # Child world ranks live in a fresh world-rank namespace slice so
    # parent and child groups stay disjoint (separate PMIx nspace).
    base = _next_world_base(comm)
    g = Group(list(range(base, base + maxprocs)))
    child = Communicator(g, devs, name=f"spawn#{appnum}",
                         errhandler=comm.errhandler)
    inter = Intercomm(comm, child)
    child._spawn_parent = Intercomm(child, comm)
    child._spawn_appnum = appnum
    if fn is not None:
        fn(child)
    return inter


def spawn_multiple(apps: List[Tuple[Optional[Callable], int]],
                   comm: Communicator, *, root: int = 0,
                   info=None) -> Intercomm:
    """MPI_Comm_spawn_multiple: one child world running several apps;
    ranks are ordered by app, each app's main sees the whole child
    world (MPI semantics: a single MPI_COMM_WORLD for all apps, appnum
    distinguishes them)."""
    total = sum(n for _f, n in apps)
    inter = spawn(None, total, comm, root=root)
    child = inter.remote_comm
    child._spawn_appnums = []
    for appnum, (_fn, n) in enumerate(apps):
        child._spawn_appnums += [appnum] * n
    for appnum, (fn, _n) in enumerate(apps):
        if fn is not None:
            fn(child, appnum)
    return inter


def get_parent(comm: Communicator) -> Optional[Intercomm]:
    """MPI_Comm_get_parent: the child-side intercomm, or None
    (MPI_COMM_NULL) for worlds that were not spawned."""
    return getattr(comm, "_spawn_parent", None)


def disconnect(comm) -> None:
    """MPI_Comm_disconnect: collective teardown of a connected comm.
    With no pending-operation queue to drain (requests complete at
    creation or raise), this is free() plus dropping the parent link."""
    if isinstance(comm, Intercomm):
        comm.free()
        return
    if getattr(comm, "_spawn_parent", None) is not None:
        comm._spawn_parent = None
    comm.free()


_world_hwm = 0          # high-water mark of handed-out world-rank blocks


def _next_world_base(comm: Communicator) -> int:
    """A world-rank namespace slice disjoint from every group allocated
    so far — including nested spawns — via a single global high-water
    mark (the PMIx nspace-uniqueness property). Deterministic
    (the CID-agreement property): allocation order is program order."""
    global _world_hwm
    step = 1 << 20
    floor = max(_world_hwm, max(comm.group.world_ranks, default=0) + 1)
    base = ((floor + step - 1) // step) * step
    _world_hwm = base + step
    return base


def _reset_for_tests() -> None:
    global _port_counter, _world_hwm
    _ports.clear()
    _names.clear()
    _joins.clear()
    _port_counter = itertools.count(0)
    _world_hwm = 0
