"""Datatype engine: predefined + derived datatypes with a device-lowerable
layout description.

Behavioral spec from the reference: ``ompi/datatype`` (MPI layer,
constructors incl. vector/indexed/struct/subarray/resized) over the OPAL
convertor (``opal/datatype/opal_convertor.c`` — iovec-walking pack/unpack
with resumable positioning).

TPU-native re-design: there is no byte-walking convertor on the critical
path. A datatype over a single base element type is described by a *flat
element-index map*: ``indices`` (positions of the datatype's ``count``
base elements within one ``extent``-element window). Pack/unpack then
lower to XLA ``take``/``scatter`` on device (HBM-resident, fused by XLA)
or to NumPy fancy indexing on host (with an optional C++ fast path in
``ompi_tpu.native``). Heterogeneous struct types (mixed base types) are
host-only byte layouts, as device arrays are homogeneous.
"""
from __future__ import annotations

import itertools
from typing import List, Optional, Sequence, Tuple

import numpy as np


def coalesce_runs(idx: np.ndarray):
    """Coalesce a sorted-or-not element-index array into (offsets,
    lengths) of runs of consecutive indices, preserving order."""
    idx = np.asarray(idx, dtype=np.int64)
    if idx.size == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    breaks = np.where(np.diff(idx) != 1)[0]
    starts = np.concatenate(([0], breaks + 1))
    ends = np.concatenate((breaks, [idx.size - 1]))
    return idx[starts], (ends - starts + 1).astype(np.int64)


class Datatype:
    """An MPI datatype.

    Attributes:
      base:     numpy dtype of the underlying elements (None => raw bytes).
      indices:  int64 array of element offsets (in base elements) selected
                by one instance of this type, in *serialization order*.
      extent:   extent in base elements (stride between consecutive
                instances, MPI_Type_get_extent semantics).
      count:    len(indices) — number of base elements per instance.
    """

    _uid_counter = itertools.count(1)

    def __init__(self, base: Optional[np.dtype], indices: np.ndarray,
                 extent: int, *, name: str = "", predefined: bool = False,
                 pair: bool = False, lb: int = 0):
        self.base = np.dtype(base) if base is not None else None
        self.indices = np.asarray(indices, dtype=np.int64)
        self.extent = int(extent)
        self.lb = int(lb)
        self.name = name
        self.predefined = predefined
        self.pair = pair               # MINLOC/MAXLOC pair type
        self._committed = predefined
        # identity for compiled-program caches (datatypes are immutable
        # once committed; names are not unique)
        self.uid = next(Datatype._uid_counter)
        self._flat_cache: dict = {}    # count -> flat index array

    # -- introspection (MPI_Type_get_extent / MPI_Type_size) ---------------
    @property
    def count(self) -> int:
        return int(self.indices.size)

    def get_size(self) -> int:
        """Size in bytes of the data content (MPI_Type_size)."""
        return self.count * (self.base.itemsize if self.base else 1)

    def get_extent(self) -> Tuple[int, int]:
        """(lb, extent) in base-element units (byte-free redesign: the
        framework addresses typed elements, not raw memory)."""
        return (self.lb, self.extent)

    def get_true_extent(self) -> Tuple[int, int]:
        if self.count == 0:
            return (0, 0)
        lo = int(self.indices.min())
        hi = int(self.indices.max()) + 1
        return (lo, hi - lo)

    @property
    def is_contiguous(self) -> bool:
        n = self.count
        return (n == self.extent
                and bool(np.array_equal(self.indices, np.arange(n))))

    def commit(self) -> "Datatype":
        """MPI_Type_commit: finalize and precompute the flat index map."""
        self.indices = np.ascontiguousarray(self.indices, dtype=np.int64)
        self._committed = True
        return self

    def free(self) -> None:
        if self.predefined:
            raise ValueError("cannot free a predefined datatype")
        self._committed = False

    # -- constructors (MPI_Type_*) -----------------------------------------
    def create_contiguous(self, count: int) -> "Datatype":
        idx = (np.arange(count)[:, None] * self.extent
               + self.indices[None, :]).ravel()
        return Datatype(self.base, idx, count * self.extent,
                        name=f"contig({count},{self.name})")

    def create_vector(self, count: int, blocklength: int,
                      stride: int) -> "Datatype":
        """count blocks of blocklength instances, stride instances apart."""
        block = (np.arange(blocklength)[:, None] * self.extent
                 + self.indices[None, :]).ravel()
        idx = (np.arange(count)[:, None] * (stride * self.extent)
               + block[None, :]).ravel()
        extent = ((count - 1) * stride + blocklength) * self.extent
        return Datatype(self.base, idx, extent,
                        name=f"vector({count},{blocklength},{stride})")

    def create_indexed(self, blocklengths: Sequence[int],
                       displacements: Sequence[int]) -> "Datatype":
        parts: List[np.ndarray] = []
        for bl, disp in zip(blocklengths, displacements):
            block = (np.arange(bl)[:, None] * self.extent
                     + self.indices[None, :]).ravel()
            parts.append(disp * self.extent + block)
        idx = np.concatenate(parts) if parts else np.empty(0, np.int64)
        extent = max((d + b for d, b in zip(displacements, blocklengths)),
                     default=0) * self.extent
        return Datatype(self.base, idx, extent, name="indexed")

    def create_indexed_block(self, blocklength: int,
                             displacements: Sequence[int]) -> "Datatype":
        return self.create_indexed([blocklength] * len(displacements),
                                   displacements)

    def create_subarray(self, sizes: Sequence[int], subsizes: Sequence[int],
                        starts: Sequence[int], order: str = "C") -> "Datatype":
        """MPI_Type_create_subarray over a C- or F-ordered array."""
        sizes = list(sizes)
        subsizes = list(subsizes)
        starts = list(starts)
        if order.upper() == "F":
            sizes, subsizes, starts = sizes[::-1], subsizes[::-1], starts[::-1]
        grids = np.meshgrid(*[np.arange(st, st + ss)
                              for st, ss in zip(starts, subsizes)],
                            indexing="ij")
        flat = np.ravel_multi_index([g.ravel() for g in grids], sizes)
        idx = (flat[:, None] * self.extent + self.indices[None, :]).ravel()
        extent = int(np.prod(sizes)) * self.extent
        return Datatype(self.base, idx, extent, name="subarray")

    def create_resized(self, lb: int, extent: int) -> "Datatype":
        return Datatype(self.base, self.indices.copy(), extent,
                        name=f"resized({self.name})", lb=lb)

    @staticmethod
    def create_struct(blocklengths: Sequence[int],
                      displacements: Sequence[int],
                      types: Sequence["Datatype"]) -> "Datatype":
        """Homogeneous struct (all fields share one base dtype) lowers to
        an indexed layout; heterogeneous structs are not representable on
        device (jax arrays are homogeneous) and raise — stage per-field or
        use a pair type instead."""
        bases = {t.base for t in types}
        if len(bases) != 1:
            raise TypeError(
                "heterogeneous MPI_Type_create_struct is host-only; "
                "decompose into per-field messages for device transfer")
        base_t = types[0]
        parts: List[np.ndarray] = []
        for bl, disp, t in zip(blocklengths, displacements, types):
            block = (np.arange(bl)[:, None] * t.extent
                     + t.indices[None, :]).ravel()
            parts.append(disp + block)
        idx = np.concatenate(parts) if parts else np.empty(0, np.int64)
        extent = max((d + bl * t.extent for d, bl, t in
                      zip(displacements, blocklengths, types)), default=0)
        return Datatype(base_t.base, idx, extent, name="struct")

    def runs(self):
        """Coalesce the element-index map into contiguous runs
        (offset, length) — the native convertor's unit of work (the
        re-design of the reference convertor's contiguous-with-gaps
        fast path). Cached after first call."""
        r = getattr(self, "_runs", None)
        if r is None:
            r = self._runs = coalesce_runs(self.indices)
        return r

    def flat_indices(self, count: int) -> np.ndarray:
        """Flat element indices for ``count`` consecutive instances —
        cached per instance (rebuilt index maps were a measured tax on
        the derived-datatype hot path, VERDICT r4 weak #6)."""
        got = self._flat_cache.get(count)
        if got is None:
            got = (np.arange(count)[:, None] * self.extent
                   + self.indices[None, :]).ravel()
            if len(self._flat_cache) < 64:
                self._flat_cache[count] = got
        return got

    def __repr__(self):
        return f"Datatype({self.name or self.base}, count={self.count})"


def _predef(np_dtype, name: str, pair: bool = False) -> Datatype:
    return Datatype(np_dtype, np.array([0]), 1, name=name, predefined=True,
                    pair=pair)


# Predefined datatypes (ompi/datatype predefined set; names mirror MPI).
FLOAT = _predef(np.float32, "float")
DOUBLE = _predef(np.float64, "double")
FLOAT16 = _predef(np.float16, "float16")
try:
    import ml_dtypes
    BFLOAT16 = _predef(ml_dtypes.bfloat16, "bfloat16")
except ImportError:                                    # pragma: no cover
    BFLOAT16 = _predef(np.float16, "bfloat16")
INT = _predef(np.int32, "int")
LONG = _predef(np.int64, "long")
SHORT = _predef(np.int16, "short")
CHAR = _predef(np.int8, "char")
BYTE = _predef(np.uint8, "byte")
UNSIGNED = _predef(np.uint32, "unsigned")
UNSIGNED_LONG = _predef(np.uint64, "unsigned_long")
INT8_T = _predef(np.int8, "int8_t")
INT16_T = _predef(np.int16, "int16_t")
INT32_T = _predef(np.int32, "int32_t")
INT64_T = _predef(np.int64, "int64_t")
UINT8_T = _predef(np.uint8, "uint8_t")
UINT16_T = _predef(np.uint16, "uint16_t")
UINT32_T = _predef(np.uint32, "uint32_t")
UINT64_T = _predef(np.uint64, "uint64_t")
C_BOOL = _predef(np.bool_, "c_bool")
C_FLOAT_COMPLEX = _predef(np.complex64, "c_float_complex")
C_DOUBLE_COMPLEX = _predef(np.complex128, "c_double_complex")
# Pair types for MINLOC/MAXLOC: value/index pairs carried as a trailing
# axis of size 2 in the value dtype (redesign of struct{float;int} pairs).
FLOAT_INT = _predef(np.float32, "float_int", pair=True)
DOUBLE_INT = _predef(np.float64, "double_int", pair=True)
LONG_INT = _predef(np.int64, "long_int", pair=True)
SHORT_INT = _predef(np.int16, "short_int", pair=True)
TWOINT = _predef(np.int32, "2int", pair=True)

_BY_NP: dict = {}
for _t in (FLOAT, DOUBLE, FLOAT16, BFLOAT16, INT, LONG, SHORT, CHAR, BYTE,
           UNSIGNED, UNSIGNED_LONG, C_BOOL, C_FLOAT_COMPLEX,
           C_DOUBLE_COMPLEX):
    _BY_NP.setdefault(np.dtype(_t.base), _t)


def from_numpy_dtype(dt) -> Datatype:
    """Map a numpy dtype to the matching predefined Datatype."""
    dt = np.dtype(dt)
    try:
        return _BY_NP[dt]
    except KeyError:
        raise TypeError(f"no predefined MPI datatype for {dt}") from None
