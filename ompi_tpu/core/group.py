"""Process groups — mirrors ``ompi/group`` (dense storage variant).

A Group is an ordered tuple of world ranks. All MPI-3 group set algebra is
provided; comparison constants follow MPI semantics.
"""
from __future__ import annotations

from typing import Sequence, Tuple

IDENT = 0
CONGRUENT = 1
SIMILAR = 2
UNEQUAL = 3
UNDEFINED = -32766


class Group:
    def __init__(self, world_ranks: Sequence[int]):
        self.world_ranks: Tuple[int, ...] = tuple(int(r) for r in world_ranks)

    @property
    def size(self) -> int:
        return len(self.world_ranks)

    def rank_of(self, world_rank: int) -> int:
        """Local rank of a world rank, or UNDEFINED."""
        try:
            return self.world_ranks.index(world_rank)
        except ValueError:
            return UNDEFINED

    def translate_ranks(self, ranks: Sequence[int],
                        other: "Group") -> Tuple[int, ...]:
        out = []
        for r in ranks:
            out.append(other.rank_of(self.world_ranks[r]))
        return tuple(out)

    def compare(self, other: "Group") -> int:
        if self.world_ranks == other.world_ranks:
            return IDENT
        if set(self.world_ranks) == set(other.world_ranks):
            return SIMILAR
        return UNEQUAL

    def incl(self, ranks: Sequence[int]) -> "Group":
        return Group([self.world_ranks[r] for r in ranks])

    def excl(self, ranks: Sequence[int]) -> "Group":
        drop = set(ranks)
        return Group([wr for i, wr in enumerate(self.world_ranks)
                      if i not in drop])

    def range_incl(self, ranges: Sequence[Tuple[int, int, int]]) -> "Group":
        ranks = []
        for first, last, stride in ranges:
            stop = last + (1 if stride > 0 else -1)
            ranks.extend(range(first, stop, stride))
        return self.incl(ranks)

    def range_excl(self, ranges: Sequence[Tuple[int, int, int]]) -> "Group":
        drop = []
        for first, last, stride in ranges:
            stop = last + (1 if stride > 0 else -1)
            drop.extend(range(first, stop, stride))
        return self.excl(drop)

    def union(self, other: "Group") -> "Group":
        seen = list(self.world_ranks)
        have = set(seen)
        for wr in other.world_ranks:
            if wr not in have:
                seen.append(wr)
                have.add(wr)
        return Group(seen)

    def intersection(self, other: "Group") -> "Group":
        have = set(other.world_ranks)
        return Group([wr for wr in self.world_ranks if wr in have])

    def difference(self, other: "Group") -> "Group":
        have = set(other.world_ranks)
        return Group([wr for wr in self.world_ranks if wr not in have])

    def __repr__(self):
        return f"Group(size={self.size})"
