"""Convertor — pack/unpack between user datatype layouts and the wire
(contiguous) representation.

Behavioral spec: ``opal/datatype/opal_convertor.c`` (pack/unpack engines,
resumable positioning). TPU-native re-design: on device the convertor is
not a byte-walker — a derived layout lowers to ``jnp.take`` (pack) and a
scatter (unpack) that XLA fuses with the surrounding collective, so
non-contiguous data never round-trips through host. On host it is NumPy
fancy indexing, with a C++ fast path (``ompi_tpu.native.convertor``) for
the strided hot loops, mirroring the role of the reference's optimized
contiguous-with-gaps paths.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ompi_tpu.accelerator import LOCUS_DEVICE, check_addr
from ompi_tpu.core.datatype import Datatype


@partial(jax.jit, static_argnums=(2,), donate_argnums=())
def _take_last(buf, idx, _tag):
    return jnp.take(buf, idx, axis=-1)


@partial(jax.jit, static_argnums=(3,))
def _scatter_last(buf, idx, packed, _tag):
    return buf.at[..., idx].set(packed)


def _native_args(buf, datatype: Datatype, count: int):
    """Common byte-unit geometry for the native run-copy loops; None when
    the native path doesn't apply (no lib, exotic dtype, bad layout)."""
    from ompi_tpu.native import get_lib
    lib = get_lib()
    if lib is None or buf.dtype.hasobject:
        return None
    if buf.shape[-1] < count * datatype.extent:
        # Undersized strided buffer: fall back to the NumPy path, whose
        # fancy indexing raises a proper IndexError instead of letting
        # the native memcpy loops run out of bounds.
        return None
    offs, lens = datatype.runs()
    if offs.size == 0:
        return None
    item = buf.dtype.itemsize
    lead = int(np.prod(buf.shape[:-1])) if buf.ndim > 1 else 1
    return (lib, (offs * item).astype(np.int64),
            (lens * item).astype(np.int64), int(offs.size), count,
            datatype.extent * item, datatype.count * item, lead,
            buf.shape[-1] * item, count * datatype.count * item)


def _native_pack(buf, datatype: Datatype, count: int):
    geo = _native_args(buf, datatype, count)
    if geo is None:
        return None
    (lib, offb, lenb, nruns, cnt, extent_b, packed_b, lead,
     src_row_b, dst_row_b) = geo
    src = np.ascontiguousarray(buf)
    out = np.empty(buf.shape[:-1] + (count * datatype.count,), buf.dtype)
    lib.ompi_tpu_pack_runs_rows(
        out.ctypes.data, src.ctypes.data, offb.ctypes.data,
        lenb.ctypes.data, nruns, cnt, extent_b, packed_b, lead,
        src_row_b, dst_row_b)
    return out


def _native_unpack(out_buf, packed, datatype: Datatype, count: int) -> bool:
    if not (isinstance(out_buf, np.ndarray)
            and out_buf.flags["C_CONTIGUOUS"]):
        return False
    if (getattr(packed, "shape", (0,))[-1] != count * datatype.count
            or packed.shape[:-1] != out_buf.shape[:-1]):
        return False            # let the NumPy path raise the shape error
    geo = _native_args(out_buf, datatype, count)
    if geo is None:
        return False
    (lib, offb, lenb, nruns, cnt, extent_b, packed_b, lead,
     dst_row_b, src_row_b) = geo
    src = np.ascontiguousarray(packed, dtype=out_buf.dtype)
    lib.ompi_tpu_unpack_runs_rows(
        out_buf.ctypes.data, src.ctypes.data, offb.ctypes.data,
        lenb.ctypes.data, nruns, cnt, extent_b, packed_b, lead,
        dst_row_b, src_row_b)
    return True


def pack(buf, datatype: Optional[Datatype], count: int):
    """Pack ``count`` instances of ``datatype`` from ``buf`` (…, extent*count
    flat elements on the last axis) into a contiguous (…, count*dt.count)
    array. Contiguous types return views/slices — no copy is forced."""
    if datatype is None or datatype.is_contiguous:
        need = count * (datatype.count if datatype is not None else 1)
        if buf.shape[-1] == need:
            return buf
        return buf[..., :need]
    idx = datatype.flat_indices(count)
    if check_addr(buf) == LOCUS_DEVICE:
        return _take_last(buf, jnp.asarray(idx), datatype.name)
    out = _native_pack(buf, datatype, count)
    if out is not None:
        return out
    return np.ascontiguousarray(buf[..., idx])


def unpack(out_buf, packed, datatype: Optional[Datatype], count: int):
    """Scatter packed contiguous data back into ``out_buf`` at the
    datatype's element positions; returns the updated buffer (functional
    on device, in-place on host)."""
    if datatype is None or datatype.is_contiguous:
        need = count * (datatype.count if datatype is not None else 1)
        if out_buf is None or (hasattr(out_buf, "shape")
                               and out_buf.shape[-1] == need):
            return packed
        if check_addr(out_buf) == LOCUS_DEVICE:
            return jax.lax.dynamic_update_slice_in_dim(
                out_buf, packed, 0, out_buf.ndim - 1)
        out_buf[..., :need] = packed
        return out_buf
    idx = datatype.flat_indices(count)
    if out_buf is None:
        raise ValueError("unpack of a non-contiguous datatype needs an "
                         "output buffer (extent holes are preserved)")
    if check_addr(out_buf) == LOCUS_DEVICE:
        return _scatter_last(out_buf, jnp.asarray(idx), packed, datatype.name)
    if _native_unpack(out_buf, packed, datatype, count):
        return out_buf
    out_buf[..., idx] = packed
    return out_buf


# ---------------------------------------------------------------------------
# MPI_Pack / MPI_Unpack with explicit position, and the external32
# canonical representation (MPI_Pack_external). Behavioral spec:
# ``ompi/datatype/ompi_datatype_pack_external.c`` and the convertor's
# resumable positioning (``opal_datatype_fake_stack.c``); external32 is
# the big-endian fixed-size wire format of MPI-3.1 §13.5.2 (reference
# tables in ``opal/datatype/opal_copy_functions_heterogeneous.c``).
# ---------------------------------------------------------------------------

def pack_size(datatype: Optional[Datatype], count: int,
              dtype=None) -> int:
    """MPI_Pack_size: bytes needed to pack ``count`` instances. With
    ``datatype=None`` the element width comes from ``dtype`` (the
    buffer's numpy dtype), defaulting to raw bytes."""
    if datatype is None:
        return count * (np.dtype(dtype).itemsize if dtype is not None else 1)
    return count * datatype.get_size()


def mpi_pack(buf, datatype: Optional[Datatype], count: int,
             outbuf: bytearray, position: int) -> int:
    """MPI_Pack: append ``count`` instances of ``datatype`` from ``buf``
    into ``outbuf`` at byte offset ``position``; returns the new
    position. Successive calls with the returned position concatenate
    (the reference convertor's resumable-positioning contract)."""
    packed = np.ascontiguousarray(np.asarray(pack(buf, datatype, count)))
    raw = packed.tobytes()
    end = position + len(raw)
    if len(outbuf) < end:
        outbuf.extend(b"\0" * (end - len(outbuf)))
    outbuf[position:end] = raw
    return end


def _base_dtype(datatype: Optional[Datatype], out_buf) -> np.dtype:
    """Element dtype for raw-byte APIs: the datatype's base, else the
    output buffer's dtype (datatype=None means "typed raw elements" of
    whatever the destination holds), else bytes."""
    if datatype is not None and datatype.base is not None:
        return datatype.base
    if out_buf is not None and hasattr(out_buf, "dtype"):
        return np.dtype(out_buf.dtype)
    return np.dtype(np.uint8)


def mpi_unpack(inbuf, position: int, out_buf, datatype: Optional[Datatype],
               count: int):
    """MPI_Unpack: read ``count`` instances from ``inbuf`` at byte offset
    ``position`` into ``out_buf``; returns (out, new_position)."""
    base = _base_dtype(datatype, out_buf)
    n = count * (datatype.count if datatype is not None else 1)
    raw = bytes(inbuf[position:position + n * base.itemsize])
    packed = np.frombuffer(raw, dtype=base).copy()
    if out_buf is not None and hasattr(out_buf, "shape"):
        packed = packed.reshape(out_buf.shape[:-1] + (n,))
    out = unpack(out_buf, packed, datatype, count)
    return out, position + n * base.itemsize


def pack_external(datatype: Optional[Datatype], buf, count: int) -> bytes:
    """MPI_Pack_external("external32"): canonical big-endian fixed-size
    representation, portable across architectures."""
    packed = np.ascontiguousarray(np.asarray(pack(buf, datatype, count)))
    return packed.astype(packed.dtype.newbyteorder(">"), copy=False).tobytes()


def unpack_external(datatype: Optional[Datatype], data: bytes, count: int,
                    out_buf=None):
    """MPI_Unpack_external: decode external32 bytes back to native
    layout (scattering into ``out_buf`` for non-contiguous types)."""
    base = _base_dtype(datatype, out_buf)
    n = count * (datatype.count if datatype is not None else 1)
    be = np.frombuffer(data, dtype=base.newbyteorder(">"), count=n)
    packed = be.astype(base)
    if out_buf is not None and hasattr(out_buf, "shape"):
        packed = packed.reshape(out_buf.shape[:-1] + (n,))
    return unpack(out_buf, packed, datatype, count)
