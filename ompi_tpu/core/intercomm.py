"""Intercommunicators — two disjoint rank groups communicating
(mirrors ``ompi/communicator`` intercomm create/merge + ``coll/inter``).

MPI intercomm collective semantics: operations are *between* groups —
allreduce reduces group A's contributions and delivers the result to
group B (and vice versa); bcast has a root in one group and receivers in
the other; alltoall sends local rank i's chunk j to remote rank j.

TPU-native realization: both groups live on one union mesh, so
inter-group data movement is shard movement on the same ICI fabric —
each side's reduction runs as a native intracomm collective on its
sub-mesh and the handoff is a device-to-device restack.
"""
from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import numpy as np

from ompi_tpu.core import op as op_mod
from ompi_tpu.core.communicator import Communicator
from ompi_tpu.core.errhandler import ERR_ARG, ERR_ROOT, MPIError
from ompi_tpu.core.group import Group


class Intercomm:
    def __init__(self, local: Communicator, remote: Communicator,
                 tag: int = 0):
        overlap = (set(local.group.world_ranks)
                   & set(remote.group.world_ranks))
        if overlap:
            raise MPIError(ERR_ARG,
                           f"intercomm groups must be disjoint: {overlap}")
        self.local_comm = local
        self.remote_comm = remote
        self.tag = tag

    # -- introspection (MPI_Comm_remote_size / _remote_group) ----------
    @property
    def size(self) -> int:
        return self.local_comm.size

    @property
    def remote_size(self) -> int:
        return self.remote_comm.size

    @property
    def group(self) -> Group:
        return self.local_comm.group

    @property
    def remote_group(self) -> Group:
        return self.remote_comm.group

    def is_inter(self) -> bool:
        return True

    # -- merge (MPI_Intercomm_merge) -----------------------------------
    def merge(self, high: bool = False) -> Communicator:
        """Union intracomm; ``high`` orders the local group last."""
        a, b = ((self.remote_comm, self.local_comm) if high
                else (self.local_comm, self.remote_comm))
        g = Group(a.group.world_ranks + b.group.world_ranks)
        return Communicator(g, a.devices + b.devices,
                            name="intercomm.merge",
                            errhandler=self.local_comm.errhandler)

    # -- collectives (coll/inter semantics) ----------------------------
    def bcast(self, sendbuf_root, root: int = 0, *,
              root_side: str = "local"):
        """Root (rank ``root`` of ``root_side`` group) broadcasts its
        buffer to every rank of the *other* group; returns the receiving
        group's stacked buffer."""
        src_comm = (self.local_comm if root_side == "local"
                    else self.remote_comm)
        dst_comm = (self.remote_comm if root_side == "local"
                    else self.local_comm)
        if not (0 <= root < src_comm.size):
            src_comm._err(ERR_ROOT, f"root {root} out of range")
        data = np.asarray(sendbuf_root)
        return dst_comm.stack([data] * dst_comm.size)

    def allreduce(self, local_stacked, remote_stacked,
                  op: op_mod.Op = op_mod.SUM) -> Tuple[Any, Any]:
        """Each group receives the reduction of the *other* group's
        contributions: returns (local_out, remote_out)."""
        lred = self.local_comm.allreduce(local_stacked, op)
        rred = self.remote_comm.allreduce(remote_stacked, op)
        lrow = np.asarray(lred)[0]
        rrow = np.asarray(rred)[0]
        local_out = self.local_comm.stack([rrow] * self.size)
        remote_out = self.remote_comm.stack([lrow] * self.remote_size)
        return local_out, remote_out

    def allgather(self, local_stacked, remote_stacked) -> Tuple[Any, Any]:
        """Each group receives the concatenation of the other group's
        buffers."""
        lh = np.asarray(local_stacked)
        rh = np.asarray(remote_stacked)
        local_out = self.local_comm.stack([rh] * self.size)
        remote_out = self.remote_comm.stack([lh] * self.remote_size)
        return local_out, remote_out

    def alltoall(self, local_stacked, remote_stacked) -> Tuple[Any, Any]:
        """Local rank i's chunk j goes to remote rank j (and vice
        versa). local_stacked: (lsize, rsize, *s); remote: (rsize,
        lsize, *s)."""
        lh = np.asarray(local_stacked)
        rh = np.asarray(remote_stacked)
        if lh.shape[1] != self.remote_size or rh.shape[1] != self.size:
            raise MPIError(ERR_ARG, "alltoall chunk counts must match "
                                    "the remote group size")
        local_out = self.local_comm.stack(
            [np.stack([rh[j, i] for j in range(self.remote_size)])
             for i in range(self.size)])
        remote_out = self.remote_comm.stack(
            [np.stack([lh[i, j] for i in range(self.size)])
             for j in range(self.remote_size)])
        return local_out, remote_out

    def barrier(self) -> None:
        self.local_comm.barrier()
        self.remote_comm.barrier()

    def free(self) -> None:
        pass

    def __repr__(self):
        return (f"Intercomm(local={self.size}, "
                f"remote={self.remote_size})")


def intercomm_create(local: Communicator, remote: Communicator,
                     tag: int = 0) -> Intercomm:
    """MPI_Intercomm_create (leaders collapse in single-controller)."""
    return Intercomm(local, remote, tag)
