"""Error handlers — mirrors ``ompi/errhandler``.

MPI error classes surface as ``MPIError`` exceptions; a communicator's
errhandler decides whether an error aborts the job (ERRORS_ARE_FATAL,
the MPI default for communicators), raises to the caller (ERRORS_RETURN —
the Pythonic 'return code'), or runs a user callback.
"""
from __future__ import annotations

import sys
from typing import Callable, Optional

SUCCESS = 0
ERR_BUFFER = 1
ERR_COUNT = 2
ERR_TYPE = 3
ERR_TAG = 4
ERR_COMM = 5
ERR_RANK = 6
ERR_REQUEST = 7
ERR_ROOT = 8
ERR_GROUP = 9
ERR_OP = 10
ERR_TOPOLOGY = 11
ERR_DIMS = 12
ERR_ARG = 13
ERR_UNKNOWN = 14
ERR_TRUNCATE = 15
ERR_OTHER = 16
ERR_INTERN = 17
ERR_PENDING = 18
ERR_IN_STATUS = 19
ERR_WIN = 45          # one-sided RMA (MPI-3 ch. 11)
ERR_BASE = 46
ERR_LOCKTYPE = 47
ERR_KEYVAL = 48
ERR_RMA_CONFLICT = 49
ERR_SPAWN = 50        # dynamic process management
ERR_PORT = 51
ERR_SERVICE = 52
ERR_NAME = 53
ERR_RMA_SYNC = 54     # RMA call outside its epoch discipline
ERR_REVOKED = 72      # ULFM
ERR_PROC_FAILED = 75  # ULFM

_CLASS_NAMES = {
    SUCCESS: "MPI_SUCCESS", ERR_BUFFER: "MPI_ERR_BUFFER",
    ERR_COUNT: "MPI_ERR_COUNT", ERR_TYPE: "MPI_ERR_TYPE",
    ERR_TAG: "MPI_ERR_TAG", ERR_COMM: "MPI_ERR_COMM",
    ERR_RANK: "MPI_ERR_RANK", ERR_REQUEST: "MPI_ERR_REQUEST",
    ERR_ROOT: "MPI_ERR_ROOT", ERR_GROUP: "MPI_ERR_GROUP",
    ERR_OP: "MPI_ERR_OP", ERR_TOPOLOGY: "MPI_ERR_TOPOLOGY",
    ERR_DIMS: "MPI_ERR_DIMS", ERR_ARG: "MPI_ERR_ARG",
    ERR_UNKNOWN: "MPI_ERR_UNKNOWN", ERR_TRUNCATE: "MPI_ERR_TRUNCATE",
    ERR_OTHER: "MPI_ERR_OTHER", ERR_INTERN: "MPI_ERR_INTERN",
    ERR_PENDING: "MPI_ERR_PENDING", ERR_IN_STATUS: "MPI_ERR_IN_STATUS",
    ERR_KEYVAL: "MPI_ERR_KEYVAL", ERR_SPAWN: "MPI_ERR_SPAWN",
    ERR_PORT: "MPI_ERR_PORT", ERR_SERVICE: "MPI_ERR_SERVICE",
    ERR_NAME: "MPI_ERR_NAME", ERR_WIN: "MPI_ERR_WIN",
    ERR_BASE: "MPI_ERR_BASE", ERR_LOCKTYPE: "MPI_ERR_LOCKTYPE",
    ERR_RMA_CONFLICT: "MPI_ERR_RMA_CONFLICT",
    ERR_RMA_SYNC: "MPI_ERR_RMA_SYNC", ERR_REVOKED: "MPIX_ERR_REVOKED",
    ERR_PROC_FAILED: "MPIX_ERR_PROC_FAILED",
}


class MPIError(Exception):
    def __init__(self, error_class: int, message: str = ""):
        self.error_class = error_class
        super().__init__(
            f"{_CLASS_NAMES.get(error_class, f'MPI_ERR({error_class})')}"
            f"{': ' + message if message else ''}")


def error_string(error_class: int) -> str:
    return _CLASS_NAMES.get(error_class, f"MPI_ERR({error_class})")


class Errhandler:
    def __init__(self, fn: Optional[Callable] = None, name: str = "user"):
        self.fn = fn
        self.name = name

    def invoke(self, comm, error_class: int, message: str = ""):
        if self.fn is not None:
            return self.fn(comm, error_class, message)
        raise MPIError(error_class, message)


def _fatal(comm, error_class, message):
    # User-facing diagnostics ride the show_help catalogs (the
    # opal_show_help pattern); the terse line stays for logs.
    try:
        from ompi_tpu.utils import show_help
        topic = {ERR_REVOKED: ("comm:revoked",
                               (getattr(comm, "name", "?"),)),
                 ERR_PROC_FAILED: ("comm:proc-failed",
                                   (getattr(comm, "name", "?"), message))
                 }.get(error_class)
        if topic is not None:
            show_help.show_help("help-mpi-errors.txt", topic[0],
                                *topic[1])
    except Exception:
        pass
    sys.stderr.write(
        f"*** An error occurred: {error_string(error_class)} {message}\n"
        f"*** MPI_ERRORS_ARE_FATAL (job will abort)\n")
    raise SystemExit(error_class or 1)


def _abort(comm, error_class, message):
    sys.stderr.write(f"*** {error_string(error_class)}: aborting\n")
    raise SystemExit(error_class or 1)


ERRORS_ARE_FATAL = Errhandler(_fatal, "MPI_ERRORS_ARE_FATAL")
ERRORS_RETURN = Errhandler(None, "MPI_ERRORS_RETURN")
ERRORS_ABORT = Errhandler(_abort, "MPI_ERRORS_ABORT")
