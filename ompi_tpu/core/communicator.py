"""Communicators — rank groups bound to device-mesh subsets.

Behavioral spec: ``ompi/communicator`` — ``ompi_communicator_t`` holds a
group, a CID, and the ``c_coll`` vtable of selected collective modules;
``ompi_comm_split`` (``comm.c:749``), split_type, dup; CID allocation is a
distributed agreement (``comm_cid.c:61-109``).

TPU-native re-design (single-controller SPMD): an MPI rank is a coordinate
on a ``jax.sharding.Mesh``. A communicator of size N owns N devices and a
private 1-D mesh over them (axis ``"mpi_r"``); a rank's local buffer is
one shard of a stacked ``jax.Array`` of shape ``(N, *local)`` sharded on
axis 0. ``MPI_Comm_split`` therefore *is* mesh subsetting: the child
communicator's mesh is built from the parent devices of its members, so
collectives on sub-communicators ride the same ICI links with no
re-wiring. CID agreement collapses to a deterministic controller-side
counter (every rank observes the same allocation order by construction —
the property the reference's iterative allreduce establishes).

Collectives here are the *framework-level* entry points: argument/locus
validation, datatype pack/unpack around the wire format, errhandler
invocation, SPC counters — then dispatch through the per-communicator
``c_coll`` vtable populated by priority selection
(``coll_base_comm_select.c:234-273``).
"""
from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ompi_tpu.accelerator import LOCUS_DEVICE, LOCUS_HOST, check_addr, to_device
from ompi_tpu.core import convertor
from ompi_tpu.core import op as op_mod
from ompi_tpu.core.datatype import Datatype, from_numpy_dtype
from ompi_tpu.core.errhandler import (ERR_ARG, ERR_COMM, ERR_COUNT, ERR_OP,
                                      ERR_RANK, ERR_ROOT, ERR_TYPE,
                                      ERRORS_ARE_FATAL, Errhandler, MPIError)
from ompi_tpu.core.group import Group, UNDEFINED
from ompi_tpu.core.info import Info
from ompi_tpu.core.request import Request, Status
from ompi_tpu.runtime import ft, spc
from ompi_tpu.utils import hooks

AXIS = "mpi_r"          # the private mesh axis name every communicator uses

# Sentinel mirroring MPI_IN_PLACE: "sendbuf is recvbuf".
class _InPlaceType:
    def __repr__(self):
        return "MPI_IN_PLACE"


IN_PLACE = _InPlaceType()

_cid_lock = threading.Lock()
_cid_counter = itertools.count(0)


def _next_cid() -> int:
    """CID agreement (comm_cid.c:61-109). Single-controller: allocation
    order is globally observed by construction, so the iterative
    allreduce over available CIDs reduces to a monotone counter."""
    with _cid_lock:
        return next(_cid_counter)


class Communicator:
    def __init__(self, group: Group, devices: Sequence[Any], *,
                 name: str = "", parent: Optional["Communicator"] = None,
                 info: Optional[Info] = None,
                 errhandler: Optional[Errhandler] = None):
        if len(devices) != group.size:
            raise MPIError(ERR_ARG, "devices must match group size")
        self.group = group
        self.devices = tuple(devices)
        self.cid = self._alloc_cid()
        self.name = name or f"comm#{self.cid}"
        self.info = info.dup() if info else Info()
        self.errhandler = errhandler or parent_errh(parent)
        self.attributes: Dict[int, Any] = {}
        self.topo = None               # set by topo layer (cart/graph)
        self._freed = False
        self._multiproc: Optional[bool] = None
        self._revoked = False          # ULFM
        self._acked_failures: frozenset = frozenset()  # ULFM failure_ack
        # Failure-knowledge domain: the process-wide default registry,
        # or (MPI-4 Sessions) the owning session's private registry —
        # inherited through parent so sub-communicators stay in their
        # instance's domain (instance.c per-instance state).
        self._ft = parent._ft if parent is not None else (
            ft.default_registry())
        # The communicator's data plane: a private 1-D mesh over its
        # devices. Stacked rank buffers shard along this axis.
        self.mesh = Mesh(np.array(self.devices, dtype=object), (AXIS,))
        self.sharding = NamedSharding(self.mesh, P(AXIS))
        self.c_coll: Dict[str, Any] = {}
        # sub-eager dispatch cache: per-(shape, dtype, op) resolution
        # of the hottest allreduce call shape straight to the selected
        # module's entry point — validation and wire-form decisions are
        # pure functions of the key and run once (the small-message
        # control-plane overhaul's single-controller leg)
        self._subeager: Dict[tuple, Any] = {}
        self._select_coll()

    def _alloc_cid(self) -> int:
        """CID allocation hook: the process-wide space by default;
        MPI-4 Sessions override to draw from the instance's own space
        (comm_cid.c allocates within the instance namespace)."""
        return _next_cid()

    # ------------------------------------------------------------------
    @property
    def size(self) -> int:
        return self.group.size

    def rank(self) -> int:
        """Single-controller: the controller drives all ranks; per-rank
        identity lives in the stacked axis. Returns 0 for API parity."""
        return 0

    def _select_coll(self) -> None:
        from ompi_tpu.coll.framework import comm_select_coll
        self.c_coll = comm_select_coll(self)
        from ompi_tpu.tools import comm_method
        comm_method.maybe_display(self)

    def _err(self, error_class: int, msg: str = ""):
        return self.errhandler.invoke(self, error_class, msg)

    def _check(self) -> None:
        if self._freed:
            raise MPIError(ERR_COMM, "communicator has been freed")
        if self._revoked:
            from ompi_tpu.core.errhandler import ERR_REVOKED
            raise MPIError(ERR_REVOKED, "communicator has been revoked")

    # -- buffer helpers -------------------------------------------------
    @property
    def is_multiprocess(self) -> bool:
        """True when any of this communicator's devices is not
        addressable from THIS controller (multi-controller SPMD: every
        controller runs the same program; each addresses only its local
        shards). Governs buffer placement/readback strategy."""
        if self._multiproc is None:
            pi = jax.process_index()
            self._multiproc = any(
                getattr(d, "process_index", 0) != pi for d in self.devices)
        return self._multiproc

    @property
    def spans_processes(self) -> bool:
        """True when the devices live on more than one controller
        process — the topology fact (distinct from addressability)
        that gates the hier/DCN two-tier algorithm path."""
        return len({getattr(d, "process_index", 0)
                    for d in self.devices}) > 1

    def put(self, host_array) -> Any:
        """Place a host array onto this communicator's mesh (stacked
        wire layout). Multi-controller: ``device_put`` cannot target
        non-addressable devices, so build the global array from each
        controller's local shards (the jax.make_array_from_callback
        path — every controller computes the same host value, the
        modex-like property PMIx establishes in the reference,
        ``instance.c:547-569``)."""
        arr = np.asarray(host_array)
        if not self.is_multiprocess:
            return jax.device_put(arr, self.sharding)
        return jax.make_array_from_callback(
            arr.shape, self.sharding, lambda idx: arr[idx])

    def alloc(self, local_shape: Tuple[int, ...], dtype=np.float32,
              fill: Optional[float] = None):
        """Allocate a stacked device buffer (size, *local_shape) sharded
        one-shard-per-rank over this communicator's mesh."""
        shape = (self.size,) + tuple(local_shape)
        if self.is_multiprocess:
            fill_v = 0.0 if fill is None else fill

            def _shard(idx):
                sshape = tuple(len(range(*sl.indices(dim)))
                               for sl, dim in zip(idx, shape))
                return np.full(sshape, fill_v, dtype=dtype)
            return jax.make_array_from_callback(shape, self.sharding,
                                                _shard)
        if fill is None:
            arr = jax.numpy.zeros(shape, dtype=dtype)
        else:
            arr = jax.numpy.full(shape, fill, dtype=dtype)
        return jax.device_put(arr, self.sharding)

    def stack(self, per_rank: Sequence[Any]):
        """Build a stacked device buffer from per-rank host/device arrays."""
        if len(per_rank) != self.size:
            self._err(ERR_COUNT, "need one array per rank")
        arr = np.stack([np.asarray(a) for a in per_rank])
        return self.put(arr)

    def shard(self, stacked, rank: int):
        """Rank ``rank``'s view of a stacked buffer (host copy). In a
        multi-controller world only locally-addressable ranks can be
        read; reading a remote rank raises (fetch it with a collective
        instead — gather/allgather — exactly as real MPI requires)."""
        if isinstance(stacked, jax.Array) and self.is_multiprocess:
            for s in stacked.addressable_shards:
                idx0 = s.index[0] if s.index else slice(None)
                if idx0.start is not None and idx0.start == rank:
                    return np.asarray(s.data)[0]
                if idx0.start is None:
                    # fully-replicated shard (index slice(None)): every
                    # rank's row is locally readable
                    return np.asarray(s.data)[rank]
            self._err(ERR_RANK,
                      f"rank {rank}'s shard is not addressable from "
                      f"process {jax.process_index()}")
        return np.asarray(stacked[rank])

    # -- validation + dispatch -----------------------------------------
    def _coll(self, func: str):
        self._check()
        self._check_ft_coll()
        m = self.c_coll.get(func)
        if m is None:
            self._err(ERR_ARG, f"no coll component provides {func} "
                               f"for {self.name}")
        spc.record(f"coll_{func}", 1)
        hooks.fire(f"coll_{func}", self, {})
        return m

    def _validate_op(self, op, pair_expected: bool = False):
        if not isinstance(op, op_mod.Op) or op.fn is None:
            self._err(ERR_OP, "invalid reduction op")
        return op

    def _validate_root(self, root: int):
        if not (0 <= root < self.size):
            self._err(ERR_ROOT, f"root {root} out of range")
        return root

    def _validate_stacked(self, buf, lead: int = 1):
        if check_addr(buf) is None:
            self._err(ERR_ARG, "buffer must be a jax or numpy array")
        if buf.ndim < lead or buf.shape[0] != self.size:
            self._err(ERR_COUNT,
                      f"stacked buffer must have leading axis {self.size}, "
                      f"got {getattr(buf, 'shape', None)}")
        return buf

    def _wire(self, buf, datatype: Optional[Datatype], count: Optional[int]):
        """Pack a stacked buffer to wire (contiguous) form; return
        (packed, unpack_fn)."""
        if datatype is None or datatype.is_contiguous:
            return buf, (lambda y, out=None: y)
        if count is None:
            count = buf.shape[-1] // max(datatype.extent, 1)
        packed = convertor.pack(buf, datatype, count)

        def unpack_fn(y, out=None):
            if out is None:
                if check_addr(y) == LOCUS_DEVICE:
                    out = jax.numpy.zeros(y.shape[:-1]
                                          + (count * datatype.extent,),
                                          dtype=y.dtype)
                else:
                    out = np.zeros(y.shape[:-1]
                                   + (count * datatype.extent,), dtype=y.dtype)
            return convertor.unpack(out, y, datatype, count)
        return packed, unpack_fn

    # ==================================================================
    # Collectives (blocking). Stacked-array functional API:
    # input leading axis = rank, result returned (device path is purely
    # functional; MPI_IN_PLACE is expressed by passing recvbuf as input).
    # ==================================================================
    def allreduce(self, sendbuf, op=op_mod.SUM, *,
                  datatype: Optional[Datatype] = None,
                  count: Optional[int] = None, recvbuf=None):
        in_place = sendbuf is IN_PLACE
        if in_place:
            sendbuf = recvbuf       # MPI_IN_PLACE (allreduce.c.in:54,78-79)
        # sub-eager fast path: contiguous device buffer, no recvbuf —
        # shape/dtype/op were validated when the key was filled
        # (validity is a pure function of the key), so a repeat call
        # is one dict probe plus the selected module's own memo. The
        # freed-op and ft checks stay per-call; the module re-checks
        # the var epoch itself.
        if (datatype is None and recvbuf is None
                and getattr(op, "fn", None) is not None
                and check_addr(sendbuf) == LOCUS_DEVICE):
            key = (sendbuf.shape, sendbuf.dtype.name, op.uid)
            fn = self._subeager.get(key)
            if fn is None:
                self._validate_stacked(sendbuf)
                self._validate_op(op)
                fn = self._subeager[key] = getattr(
                    self._coll("allreduce"), "allreduce")
                return fn(sendbuf, op)
            self._check()
            self._check_ft_coll()
            spc.record("coll_allreduce", 1)
            hooks.fire("coll_allreduce", self, {})
            return fn(sendbuf, op)
        self._validate_stacked(sendbuf)
        self._validate_op(op)
        # Fused derived-datatype fast path (VERDICT r4 weak #6): one
        # compiled gather->collective->scatter program instead of the
        # pack/collective/unpack dispatch chain. Device buffers only
        # (host buffers keep the convertor path); a DISTINCT recvbuf's
        # gaps cannot come from sendbuf, so that case keeps the
        # overlay path too.
        if (datatype is not None and not datatype.is_contiguous
                and not datatype.pair and op.fn is not None
                and not getattr(op, "is_loc", False)
                and (recvbuf is None or in_place)
                and check_addr(sendbuf) == LOCUS_DEVICE):
            mod = self._coll("allreduce")
            fd = getattr(mod, "allreduce_dtype", None)
            cnt = (count if count is not None else
                   sendbuf.shape[-1] // max(datatype.extent, 1))
            # shape contract: the fused program returns sendbuf's own
            # shape, so it may only serve exact-fit buffers (last dim
            # == count*extent) — otherwise the convertor path's
            # truncated image is the documented result
            if (fd is not None
                    and sendbuf.shape[-1] == cnt * datatype.extent):
                return fd(sendbuf, op, datatype, cnt, in_place)
        x, unpack_fn = self._wire(sendbuf, datatype, count)
        y = self._coll("allreduce").allreduce(x, op)
        # Unpack into recvbuf (even for IN_PLACE, where recvbuf is the
        # send buffer): MPI guarantees gap elements outside the
        # datatype's map are left untouched.
        return unpack_fn(y, recvbuf)

    def reduce(self, sendbuf, op=op_mod.SUM, root: int = 0, *,
               datatype: Optional[Datatype] = None,
               count: Optional[int] = None, recvbuf=None):
        if sendbuf is IN_PLACE:
            sendbuf = recvbuf
        self._validate_stacked(sendbuf)
        self._validate_op(op)
        self._validate_root(root)
        x, unpack_fn = self._wire(sendbuf, datatype, count)
        y = self._coll("reduce").reduce(x, op, root)
        return unpack_fn(y, recvbuf)

    def bcast(self, buf, root: int = 0, *,
              datatype: Optional[Datatype] = None,
              count: Optional[int] = None):
        self._validate_stacked(buf)
        self._validate_root(root)
        x, unpack_fn = self._wire(buf, datatype, count)
        y = self._coll("bcast").bcast(x, root)
        return unpack_fn(y)

    def allgather(self, sendbuf, *, datatype: Optional[Datatype] = None,
                  count: Optional[int] = None):
        """in (N, *s) -> out (N, N, *s): out[r, j] = rank j's sendbuf."""
        self._validate_stacked(sendbuf)
        x, _ = self._wire(sendbuf, datatype, count)
        return self._coll("allgather").allgather(x)

    def gather(self, sendbuf, root: int = 0, *,
               datatype: Optional[Datatype] = None,
               count: Optional[int] = None):
        """in (N, *s) -> out (N, N, *s), rows valid at root only."""
        self._validate_stacked(sendbuf)
        self._validate_root(root)
        x, _ = self._wire(sendbuf, datatype, count)
        return self._coll("gather").gather(x, root)

    def scatter(self, sendbuf, root: int = 0, *,
                datatype: Optional[Datatype] = None,
                count: Optional[int] = None):
        """in (N, N, *s) (root's row of chunks) -> out (N, *s)."""
        self._validate_stacked(sendbuf, lead=2)
        self._validate_root(root)
        x, _ = self._wire(sendbuf, datatype, count)
        return self._coll("scatter").scatter(x, root)

    def gather_root(self, sendbuf, root: int = 0):
        """Memory-optimal root-targeted gather (framework extension,
        the stacked API's analogue of MPI's root-only recvbuf): returns
        rank root's recvbuf, an (N, *local) array resident ONLY on
        root's device. Non-root devices allocate nothing — vs the
        in-graph gather, whose uniform SPMD output holds N rows on
        every device (the round-1 n-times-memory cost VERDICT flagged).
        The collect is a runtime D2D transfer over ICI: PJRT moves each
        shard straight to root (the binomial-gather role,
        coll_base_functions.h:185-320, with the tree supplied by the
        interconnect). Multi-controller worlds fall back to the
        in-graph gather and return its stacked result."""
        self._validate_stacked(sendbuf)
        self._validate_root(root)
        if self.is_multiprocess:
            return self.gather(sendbuf, root)   # does its own checks/SPC
        self._coll("gather")             # state checks + SPC/hooks
        sd = jax.sharding.SingleDeviceSharding(self.devices[root])
        return jax.device_put(sendbuf, sd)

    def scatter_root(self, chunks, root: int = 0):
        """Root-targeted scatter companion of :meth:`gather_root`:
        ``chunks`` is root's (N, *local) send buffer (host array or
        root-resident device array); returns the standard stacked
        (N, *local) buffer, one shard per rank. The fan-out is a
        runtime placement (device_put / comm.put) over ICI.

        Multi-controller: SPMD single-program semantics require every
        controller to pass the same host value (the controller-
        replicated convention every stacked builder uses — comm.put's
        modex property); device arrays are rejected there because a
        root-resident array is unreadable from the other controllers.
        """
        self._validate_root(root)
        if check_addr(chunks) is None:
            self._err(ERR_ARG, "chunks must be a jax or numpy array")
        if chunks.ndim < 1 or chunks.shape[0] != self.size:
            self._err(ERR_COUNT,
                      f"chunks must have leading axis {self.size}")
        self._coll("scatter")            # state checks + SPC/hooks
        if self.is_multiprocess:
            if isinstance(chunks, jax.Array):
                self._err(ERR_ARG,
                          "multi-controller scatter_root needs a host "
                          "array replicated on every controller (a "
                          "root-resident device array cannot be read "
                          "from the other controllers); use scatter() "
                          "with the stacked sendbuf instead")
            return self.put(np.asarray(chunks))
        return jax.device_put(chunks, self.sharding)

    def alltoall(self, sendbuf, *, datatype: Optional[Datatype] = None,
                 count: Optional[int] = None):
        """in (N, N, *s) -> out (N, N, *s): out[j, i] = in[i, j]."""
        self._validate_stacked(sendbuf, lead=2)
        if sendbuf.shape[1] != self.size:
            self._err(ERR_COUNT, "alltoall needs one chunk per peer")
        x, _ = self._wire(sendbuf, datatype, count)
        return self._coll("alltoall").alltoall(x)

    def reduce_scatter_block(self, sendbuf, op=op_mod.SUM, *,
                             datatype: Optional[Datatype] = None,
                             count: Optional[int] = None):
        """in (N, N, *s) -> out (N, *s): out[r] = reduce_i in[i, r]."""
        self._validate_stacked(sendbuf, lead=2)
        self._validate_op(op)
        x, _ = self._wire(sendbuf, datatype, count)
        return self._coll("reduce_scatter_block").reduce_scatter_block(x, op)

    def reduce_scatter(self, sendbuf, recvcounts: Sequence[int],
                       op=op_mod.SUM):
        """MPI_Reduce_scatter with per-rank counts. in (N, total) where
        total = sum(recvcounts); returns a list of per-rank DEVICE
        arrays (the variable-length result cannot be one stacked
        array).

        Round-2 lowering (VERDICT weak #6): segments are padded to the
        max count with ONE device gather (a static index map built from
        the counts), then ride ``reduce_scatter_block`` — psum_scatter
        on the device path — so the wire moves ~N*max(counts) elements
        instead of the round-1 full allreduce's total-everywhere, and
        nothing round-trips through the host."""
        self._validate_stacked(sendbuf)
        self._validate_op(op)
        self._require_local_views("reduce_scatter")
        if len(recvcounts) != self.size:
            self._err(ERR_COUNT, "recvcounts must have comm-size entries")
        total = int(sum(recvcounts))
        if sendbuf.shape[-1] != total:
            self._err(ERR_COUNT, f"sendbuf last axis must be {total}")
        n = self.size
        m = max(recvcounts) if recvcounts else 0
        if m == 0:
            return [sendbuf[r, ..., 0:0] for r in range(n)]
        # Static (n, m) index map: segment j's element k sits at
        # offset_j + k; entries past counts[j] are masked to zero.
        offs = np.concatenate([[0], np.cumsum(recvcounts)[:-1]])
        idx = np.minimum(offs[:, None] + np.arange(m)[None, :],
                         total - 1).astype(np.int32)
        mask = (np.arange(m)[None, :] <
                np.asarray(recvcounts)[:, None])
        if check_addr(sendbuf) == LOCUS_DEVICE:
            xs = jax.numpy.take(sendbuf, jax.numpy.asarray(idx.ravel()),
                                axis=-1)
            xs = xs.reshape(sendbuf.shape[:-1] + (n, m))
            xs = jax.numpy.where(jax.numpy.asarray(mask), xs, 0)
            # wire layout (N, N, m): chunk axis before payload axes
            xs = jax.numpy.moveaxis(xs, -2, 1)
        else:
            xs = np.take(np.asarray(sendbuf), idx.ravel(), axis=-1)
            xs = xs.reshape(sendbuf.shape[:-1] + (n, m))
            xs = np.where(mask, xs, 0)
            xs = np.moveaxis(xs, -2, 1)
        red = self.reduce_scatter_block(xs, op)        # (N, ..., m)
        return [red[r, ..., :recvcounts[r]] for r in range(n)]

    def scan(self, sendbuf, op=op_mod.SUM):
        self._validate_stacked(sendbuf)
        self._validate_op(op)
        return self._coll("scan").scan(sendbuf, op)

    def exscan(self, sendbuf, op=op_mod.SUM):
        self._validate_stacked(sendbuf)
        self._validate_op(op)
        return self._coll("exscan").exscan(sendbuf, op)

    def barrier(self) -> None:
        self._coll("barrier").barrier()

    # -- v-variants (variable counts): pad to max, run fixed, slice ----
    # The wire strategy for every *v collective is the same: pad ragged
    # per-peer chunks to the max count, ride the fixed-count device
    # collective over ICI, slice the valid prefixes off on the way out —
    # the TPU analogue of the reference's per-peer count headers
    # (ompi/mca/coll/base alltoallv/allgatherv pairwise exchanges).
    # Round 2 (VERDICT weak #5): device inputs are padded ON DEVICE and
    # results come back as device arrays (lazy slices of the collective
    # output) — the round-1 implementation round-tripped everything
    # through NumPy, the opposite of the framework's thesis.
    def _ragged(self, per_rank: Sequence[Any], what: str):
        self._require_local_views(what)
        if len(per_rank) != self.size:
            self._err(ERR_COUNT, f"{what} needs one entry per rank")
        if all(check_addr(a) == LOCUS_DEVICE for a in per_rank):
            arrs = [jax.numpy.ravel(a) for a in per_rank]
        else:
            arrs = [np.asarray(a).ravel() for a in per_rank]
        return arrs, [a.size for a in arrs]

    def _require_local_views(self, what: str) -> None:
        """The v-/neighbor-collectives return per-rank slices of the
        stacked result; on a multi-controller communicator the result is
        a non-fully-addressable global array those slices cannot read.
        Raise the same clean guard the coll path uses (_to_mesh) instead
        of jax's opaque non-addressable error."""
        if self.is_multiprocess:
            from ompi_tpu.core.errhandler import ERR_INTERN
            self._err(ERR_INTERN,
                      f"{what} returns per-rank views of the stacked "
                      f"result, which a multi-controller world cannot "
                      f"address; use fixed-count collectives, or the "
                      f"per-rank execution model (mpirun --per-rank)")

    def _pad_stack(self, arrs, counts, m):
        """(N, m) padded stack; device-side when the inputs are device
        arrays. Single-controller only — every v-collective entry point
        guards with _require_local_views first (the output side slices
        per-rank views a multi-controller world cannot read)."""
        if arrs and isinstance(arrs[0], jax.Array):
            segs = [jax.numpy.pad(a, (0, m - a.size)) for a in arrs]
            stacked = jax.numpy.stack(segs)
            if self.is_multiprocess:
                return self.put(np.asarray(stacked))   # local -> global
            return jax.device_put(stacked, self.sharding)
        padded = np.zeros((self.size, m), dtype=arrs[0].dtype)
        for i, a in enumerate(arrs):
            padded[i, :a.size] = a
        return self.put(padded)

    def allgatherv(self, per_rank: Sequence[Any]):
        """Takes per-rank arrays (ragged); returns a per-rank list of
        DEVICE arrays = the concatenation every rank receives. Pads to
        max count on the wire (the TPU analogue of the reference's
        per-peer count headers)."""
        arrs, counts = self._ragged(per_rank, "allgatherv")
        m = max(counts) if counts else 0
        if m == 0:
            return [a for a in arrs]
        g = self.allgather(self._pad_stack(arrs, counts, m))
        # per-rank device concat of the valid prefixes (lazy slices —
        # no host transfer)
        return [jax.numpy.concatenate(
                    [g[r, j, :counts[j]] for j in range(self.size)])
                for r in range(self.size)]

    def gatherv(self, per_rank: Sequence[Any], root: int = 0):
        """MPI_Gatherv: ragged per-rank contributions; returns the
        concatenation (a device array, valid at root)."""
        self._validate_root(root)
        arrs, counts = self._ragged(per_rank, "gatherv")
        m = max(counts) if counts else 0
        if m == 0:
            return arrs[0]
        g = self.gather(self._pad_stack(arrs, counts, m), root)
        return jax.numpy.concatenate(
            [g[root, j, :counts[j]] for j in range(self.size)])

    def scatterv(self, chunks: Sequence[Any], root: int = 0):
        """MPI_Scatterv: ``chunks`` is root's ragged per-destination list;
        returns a per-rank list of DEVICE arrays."""
        self._validate_root(root)
        arrs, counts = self._ragged(chunks, "scatterv")
        m = max(counts) if counts else 0
        if m == 0:
            return [a for a in arrs]
        row = self._pad_stack(arrs, counts, m)         # (N, m)
        if isinstance(row, jax.Array) and not self.is_multiprocess:
            # root-targeted runtime fan-out: no (N, N, m) stack needed
            s = self.scatter_root(row, root)
        else:
            padded = np.zeros((self.size, self.size, m),
                              dtype=np.asarray(row).dtype)
            padded[root] = np.asarray(row)
            s = self.scatter(self.put(padded), root)
        return [s[r, :counts[r]] for r in range(self.size)]

    def alltoallv(self, send_chunks: Sequence[Sequence[Any]]):
        """MPI_Alltoallv: ``send_chunks[i][j]`` is rank i's (ragged)
        chunk for rank j; returns ``recv`` with ``recv[j][i]`` = the
        chunk i sent to j (per-rank lists of DEVICE arrays)."""
        self._require_local_views("alltoallv")
        if len(send_chunks) != self.size:
            self._err(ERR_COUNT, "alltoallv needs one row per rank")
        device_in = all(check_addr(c) == LOCUS_DEVICE
                        for row in send_chunks for c in row)
        if device_in:
            rows = [[jax.numpy.ravel(c) for c in row]
                    for row in send_chunks]
        else:
            rows = [[np.asarray(c).ravel() for c in row]
                    for row in send_chunks]
        for row in rows:
            if len(row) != self.size:
                self._err(ERR_COUNT, "alltoallv needs one chunk per peer")
        counts = [[c.size for c in row] for row in rows]
        m = max((c for row in counts for c in row), default=0)
        if m == 0:
            return [[rows[i][j] for i in range(self.size)]
                    for j in range(self.size)]
        if device_in:
            padded = jax.numpy.stack(
                [jax.numpy.stack([jax.numpy.pad(c, (0, m - c.size))
                                  for c in row]) for row in rows])
            padded = (self.put(np.asarray(padded)) if self.is_multiprocess
                      else jax.device_put(padded, self.sharding))
        else:
            dt = rows[0][0].dtype
            host = np.zeros((self.size, self.size, m), dtype=dt)
            for i, row in enumerate(rows):
                for j, c in enumerate(row):
                    host[i, j, :c.size] = c
            padded = self.put(host)
        t = self.alltoall(padded)
        # out[j, i] = in[i, j]; slice each to the sender's count — lazy
        # device slices, no host round-trip.
        return [[t[j, i, :counts[i][j]] for i in range(self.size)]
                for j in range(self.size)]

    def alltoallw(self, send_chunks: Sequence[Sequence[Any]],
                  send_types: Sequence[Sequence[Optional[Datatype]]],
                  send_counts: Optional[Sequence[Sequence[int]]] = None):
        """MPI_Alltoallw: per-(src,dst) datatypes. Each chunk is packed
        with its own datatype before the exchange (host pack — the w
        variant's per-pair layouts preclude one device index map), then
        rides the padded alltoall. ``send_counts[i][j]`` is the instance
        count (MPI's explicit count argument); when omitted, the maximal
        count that fits the chunk is used — MPI buffer-length rule: the
        last instance needs only the type's true extent."""
        packed = []
        for i, (row, trow) in enumerate(zip(send_chunks, send_types)):
            prow = []
            for j, (c, t) in enumerate(zip(row, trow)):
                a = np.asarray(c)
                if t is not None and not t.is_contiguous:
                    extent = max(t.extent, 1)
                    lo, rng = t.get_true_extent()
                    if send_counts is not None:
                        cnt = send_counts[i][j]
                    elif a.shape[-1] < lo + rng:
                        cnt = 0
                    else:
                        cnt = 1 + (a.shape[-1] - lo - rng) // extent
                    if a.shape[-1] < ((cnt - 1) * extent + lo + rng
                                      if cnt else 0):
                        self._err(ERR_COUNT,
                                  f"alltoallw chunk length {a.shape[-1]} "
                                  f"cannot hold {cnt} instances "
                                  f"(extent {extent}, true extent "
                                  f"{lo + rng})")
                    a = (np.asarray(convertor.pack(a, t, cnt)) if cnt
                         else np.empty((0,), a.dtype))
                prow.append(a.ravel())
            packed.append(prow)
        return self.alltoallv(packed)

    # ==================================================================
    # Nonblocking variants: JAX async dispatch makes these natural — the
    # compiled collective is enqueued and a Request wraps the output.
    # ==================================================================
    def _nb(self, fn: Callable, *args, **kw) -> Request:
        out = fn(*args, **kw)
        arrays = [a for a in jax.tree_util.tree_leaves(out)
                  if isinstance(a, jax.Array)]
        return Request(result=out, arrays=arrays or None)

    def _isched(self, func: str):
        """The i-collective's vtable slot when a schedule component
        (coll/nbc) won it; None routes through async dispatch (_nb).
        Contiguous-buffer calls only — datatype/count kwargs take the
        blocking path, whose convertor handles packing. Runs the same
        entry checks/counters as _coll so state errors, FT, SPC and
        hooks behave identically on both paths."""
        return self._coll(func) if func in self.c_coll else None

    def iallreduce(self, sendbuf, op=op_mod.SUM, **kw) -> Request:
        if not kw:
            from ompi_tpu.coll import persistent as _pcoll
            if _pcoll.bucket_enabled():
                # DDP-style bucket fusion: concurrent small
                # iallreduces on the same (op, dtype) coalesce into
                # one flattened wire collective (docs/PERSISTENT.md)
                self._validate_stacked(sendbuf)
                self._validate_op(op)
                r = _pcoll.maybe_bucket_iallreduce(self, sendbuf, op)
                if r is not None:
                    return r
            m = self._isched("iallreduce")
            if m is not None:
                self._validate_stacked(sendbuf)
                self._validate_op(op)
                return m.iallreduce(sendbuf, op)
        return self._nb(self.allreduce, sendbuf, op, **kw)

    def ibcast(self, buf, root: int = 0, **kw) -> Request:
        if not kw:
            m = self._isched("ibcast")
            if m is not None:
                self._validate_stacked(buf)
                self._validate_root(root)
                return m.ibcast(buf, root)
        return self._nb(self.bcast, buf, root, **kw)

    def ireduce(self, sendbuf, op=op_mod.SUM, root: int = 0, **kw) -> Request:
        return self._nb(self.reduce, sendbuf, op, root, **kw)

    def iallgather(self, sendbuf, **kw) -> Request:
        if not kw:
            m = self._isched("iallgather")
            if m is not None:
                self._validate_stacked(sendbuf)
                return m.iallgather(sendbuf)
        return self._nb(self.allgather, sendbuf, **kw)

    def igather(self, sendbuf, root: int = 0, **kw) -> Request:
        return self._nb(self.gather, sendbuf, root, **kw)

    def iscatter(self, sendbuf, root: int = 0, **kw) -> Request:
        return self._nb(self.scatter, sendbuf, root, **kw)

    def ialltoall(self, sendbuf, **kw) -> Request:
        return self._nb(self.alltoall, sendbuf, **kw)

    def ireduce_scatter_block(self, sendbuf, op=op_mod.SUM, **kw) -> Request:
        return self._nb(self.reduce_scatter_block, sendbuf, op, **kw)

    def iscan(self, sendbuf, op=op_mod.SUM) -> Request:
        return self._nb(self.scan, sendbuf, op)

    def iexscan(self, sendbuf, op=op_mod.SUM) -> Request:
        return self._nb(self.exscan, sendbuf, op)

    def iallgatherv(self, per_rank: Sequence[Any]) -> Request:
        return self._nb(self.allgatherv, per_rank)

    def igatherv(self, per_rank: Sequence[Any], root: int = 0) -> Request:
        return self._nb(self.gatherv, per_rank, root)

    def iscatterv(self, chunks: Sequence[Any], root: int = 0) -> Request:
        return self._nb(self.scatterv, chunks, root)

    def ialltoallv(self, send_chunks: Sequence[Sequence[Any]]) -> Request:
        return self._nb(self.alltoallv, send_chunks)

    def ibarrier(self) -> Request:
        ms = self._isched("ibarrier")
        if ms is not None:
            return ms.ibarrier()
        m = self._coll("barrier")
        if hasattr(m, "ibarrier"):       # e.g. the monitoring shim
            return m.ibarrier()
        fn = getattr(m, "_ibarrier_arrays", None)
        if fn is not None:
            return Request(arrays=fn())
        # winner has no async form at all: a completed synchronous
        # barrier is still a correct MPI_Ibarrier
        m.barrier()
        return Request.completed()

    # -- persistent collectives (MPI-4 MPI_Allreduce_init etc.) --------
    # Contiguous-buffer inits build a pre-bound plan (coll/persistent:
    # algorithm decided, executable compiled, codec gates evaluated at
    # init; Start is launch-only, and bucketable starts fuse). The
    # datatype/count forms keep the generic re-dispatch marshaller.
    def allreduce_init(self, sendbuf, op=op_mod.SUM, **kw) -> Request:
        if not kw:
            from ompi_tpu.coll import persistent as _pcoll
            return _pcoll.coll_init(self, "allreduce", sendbuf, op)
        return Request(persistent_start=lambda: self.iallreduce(
            sendbuf, op, **kw))

    def allreduce_bind(self, example, op=op_mod.SUM) -> Callable:
        """Pre-bound hot-path handle — the TPU-native payoff of MPI-4
        persistent collectives (``MPI_Allreduce_init``'s entire purpose
        is to hoist per-call setup out of the loop): validation,
        decision tables, SPC/hook accounting and cache probes run ONCE
        here; the returned callable is the cached compiled executable
        plus a sharding identity check (~0.3 us). Buffers must have
        this communicator's stacked layout (comm.put/alloc results or
        prior outputs). Per-call cost is jax's compiled dispatch alone
        — the floor the framework cannot go below."""
        self._validate_stacked(example)
        self._validate_op(op)
        mod = self._coll("allreduce")
        dev = getattr(mod, "device", mod)
        bind = getattr(dev, "bind_allreduce", None)
        if bind is None:                 # host module won selection
            return lambda buf: mod.allreduce(buf, op)
        return bind(example, op)

    def bcast_init(self, buf, root: int = 0, **kw) -> Request:
        if not kw:
            from ompi_tpu.coll import persistent as _pcoll
            return _pcoll.coll_init(self, "bcast", buf, root)
        return Request(persistent_start=lambda: self.ibcast(buf, root, **kw))

    def allgather_init(self, sendbuf) -> Request:
        from ompi_tpu.coll import persistent as _pcoll
        return _pcoll.coll_init(self, "allgather", sendbuf)

    def reduce_scatter_block_init(self, sendbuf,
                                  op=op_mod.SUM) -> Request:
        from ompi_tpu.coll import persistent as _pcoll
        return _pcoll.coll_init(self, "reduce_scatter_block", sendbuf, op)

    def barrier_init(self) -> Request:
        from ompi_tpu.coll import persistent as _pcoll
        return _pcoll.coll_init(self, "barrier")

    # ==================================================================
    # Point-to-point (pml framework; matching spec pml_ob1_recvfrag.c)
    # ==================================================================
    @property
    def _pml(self):
        eng = getattr(self, "_pml_engine", None)
        if eng is None:
            if self.is_multiprocess:
                # The stacked matching engine is controller-local dict
                # handoff; in a multi-controller world a peer's shard
                # lives on another process and the handoff would be
                # silently wrong. Same clean guard the collectives path
                # raises (coll/xla._to_mesh). Genuine cross-process
                # pt2pt lives in the per-rank model (pml/perrank over
                # btl/tcp) — launch via mpirun --per-rank.
                from ompi_tpu.core.errhandler import ERR_INTERN
                raise MPIError(
                    ERR_INTERN,
                    "stacked pt2pt is single-controller only: this "
                    "communicator spans processes whose shards are not "
                    "addressable here. Use the per-rank execution "
                    "model (mpirun --per-rank) for cross-process "
                    "send/recv, or collectives on this communicator.")
            from ompi_tpu.mca import var
            from ompi_tpu.pml import vprotocol  # registers pml_v_protocol
            from ompi_tpu.pml.stacked import MatchingEngine
            if var.var_get("pml_v_protocol", "none") == "pessimist":
                eng = self._pml_engine = vprotocol.PessimistEngine(self)
            else:
                eng = self._pml_engine = MatchingEngine(self)
        return eng

    def _record_pml(self, event: str) -> None:
        from ompi_tpu.runtime import spc
        from ompi_tpu.utils import hooks
        spc.record(event, 1)
        hooks.fire(event, self, {})

    def send(self, data, src: int, dest: int, tag: int = 0) -> None:
        """MPI_Send from rank ``src`` to ``dest`` (single-controller: the
        sender rank is explicit; ``data`` is that rank's local buffer)."""
        self._check()
        self._check_peer_ft(dest)
        self._record_pml("pml_send")
        self._pml.send(data, src, dest, tag)

    def isend(self, data, src: int, dest: int, tag: int = 0) -> Request:
        self._check()
        self._check_peer_ft(dest)
        self._record_pml("pml_send")
        return self._pml.send(data, src, dest, tag)

    def ssend(self, data, src: int, dest: int, tag: int = 0) -> None:
        """MPI_Ssend: completes only if the receive has started; raises
        the deadlock otherwise (single-controller semantics)."""
        self._check()
        self._check_peer_ft(dest)
        self._record_pml("pml_send")
        self._pml.send(data, src, dest, tag, synchronous=True)

    def bsend(self, data, src: int, dest: int, tag: int = 0) -> None:
        """MPI_Bsend: the payload is buffered (copied) at send time."""
        self._check()
        self._check_peer_ft(dest)
        self._record_pml("pml_send")
        self._pml.send(data, src, dest, tag)

    def recv(self, source: int, tag: int = -1, *, dst: int = 0):
        """MPI_Recv executed by rank ``dst``: returns (data, Status).
        Raises instead of deadlocking if no matching send was posted."""
        self._check()
        if source == -1:  # ANY_SOURCE
            self._check_anysource_ft()
        else:
            self._check_peer_ft(source)
        self._record_pml("pml_recv")
        return self._pml.recv(dst, source, tag)

    def irecv(self, source: int, tag: int = -1, *, dst: int = 0) -> Request:
        # ULFM (req_ft.c): a *nonblocking* wildcard receive posts
        # normally even with unacknowledged failures — a live sender may
        # still match it; the pending error surfaces at test/wait
        # (PtpRequest._check_ft). Only blocking recv raises at entry.
        self._check()
        if source != -1:  # named peer: fail fast, as the reference does
            self._check_peer_ft(source)
        self._record_pml("pml_recv")
        return self._pml.irecv(dst, source, tag)

    def sendrecv(self, senddata, src: int, dest: int, recvsource: int,
                 sendtag: int = 0, recvtag: int = -1):
        """MPI_Sendrecv executed by rank ``src``: post the send, then
        receive (deadlock-free by construction, as in the reference)."""
        self._check()
        self._check_peer_ft(dest)
        if recvsource == -1:  # ANY_SOURCE
            self._check_anysource_ft()
        else:
            self._check_peer_ft(recvsource)
        self._record_pml("pml_send")
        self._record_pml("pml_recv")
        self._pml.send(senddata, src, dest, sendtag)
        return self._pml.recv(src, recvsource, recvtag)

    def probe(self, source: int, tag: int = -1, *, dst: int = 0) -> Status:
        self._check()
        return self._pml.probe(dst, source, tag)

    def iprobe(self, source: int, tag: int = -1, *, dst: int = 0):
        self._check()
        return self._pml.iprobe(dst, source, tag)

    def mprobe(self, source: int, tag: int = -1, *, dst: int = 0):
        self._check()
        return self._pml.mprobe(dst, source, tag)

    def improbe(self, source: int, tag: int = -1, *, dst: int = 0):
        """MPI_Improbe: nonblocking matched probe — (flag, message,
        Status); on no match returns (False, None, None)."""
        self._check()
        flag, status = self._pml.iprobe(dst, source, tag)
        if not flag:
            return False, None, None
        return True, self._pml.mprobe(dst, source, tag), status

    def mrecv(self, message):
        self._check()
        return self._pml.mrecv(message)

    def send_init(self, data, src: int, dest: int, tag: int = 0) -> Request:
        """MPI_Send_init (persistent)."""
        self._check()
        return Request(persistent_start=lambda: self._pml.send(
            data, src, dest, tag))

    def recv_init(self, source: int, tag: int = -1, *,
                  dst: int = 0) -> Request:
        self._check()
        return Request(persistent_start=lambda: self._pml.irecv(
            dst, source, tag))

    # -- partitioned pt2pt (MPI-4, mirrors ompi/mca/part/persist) ------
    def psend_init(self, parts: Sequence[Any], dest: int, tag: int = 0,
                   src: int = 0):
        """MPI_Psend_init: ``parts`` is the partition list; ``pready(i)``
        marks partition i; the message is sent when all are ready."""
        self._check()
        from ompi_tpu.pml.partitioned import PartitionedSend
        return PartitionedSend(self, parts, src, dest, tag)

    def precv_init(self, source: int, tag: int = 0, partitions: int = 1,
                   *, dst: int = 0):
        self._check()
        from ompi_tpu.pml.partitioned import PartitionedRecv
        return PartitionedRecv(self, source, tag, partitions, dst=dst)

    # ==================================================================
    # Communicator algebra
    # ==================================================================
    def dup(self, info: Optional[Info] = None) -> "Communicator":
        self._check()
        c = self.__class__(Group(self.group.world_ranks), self.devices,
                           name=f"{self.name}.dup", parent=self,
                         info=info or self.info,
                         errhandler=self.errhandler)
        try:
            propagate_attrs(self, c)
        except BaseException:
            c.free()                     # no half-built comm leaks
            raise
        return c

    def split(self, colors: Sequence[int], keys: Optional[Sequence[int]] = None
              ) -> List[Optional["Communicator"]]:
        """MPI_Comm_split (comm.c:749). ``colors[r]``/``keys[r]`` are rank
        r's arguments; returns one entry per rank — the new communicator
        containing that rank (shared object) or None (MPI_COMM_NULL) for
        color == UNDEFINED. Children's meshes are parent-device subsets."""
        self._check()
        if keys is None:
            keys = [0] * self.size
        if len(colors) != self.size or len(keys) != self.size:
            self._err(ERR_ARG, "need color/key per rank")
        by_color: Dict[int, List[int]] = {}
        for r, c in enumerate(colors):
            if c != UNDEFINED:
                by_color.setdefault(c, []).append(r)
        out: List[Optional[Communicator]] = [None] * self.size
        # Deterministic order over colors = identical CID allocation on
        # every rank (the agreement property of comm_cid.c).
        for c in sorted(by_color):
            members = sorted(by_color[c], key=lambda r: (keys[r], r))
            g = Group([self.group.world_ranks[r] for r in members])
            devs = [self.devices[r] for r in members]
            newc = self.__class__(
                g, devs, name=f"{self.name}.split({c})",
                parent=self, errhandler=self.errhandler)
            for r in members:
                out[r] = newc
        return out

    def split_type(self, split_type: int,
                   keys: Optional[Sequence[int]] = None):
        """MPI_Comm_split_type: group ranks by hardware locality. TPU
        concretization: COMM_TYPE_SHARED groups ranks whose devices share
        a host process (``device.process_index``); COMM_TYPE_NUMA uses
        the device's NUMA/slice index when exposed (falls back to the
        process); COMM_TYPE_HWTHREAD is one rank = one device, so every
        rank gets its own communicator; UNDEFINED yields MPI_COMM_NULL
        everywhere."""
        if split_type == UNDEFINED:
            return [None] * self.size
        if split_type == 2:           # COMM_TYPE_HWTHREAD
            colors = list(range(self.size))
        elif split_type == 3:         # COMM_TYPE_NUMA
            colors = [int(getattr(d, "numa_node",
                                  getattr(d, "process_index", 0)) or 0)
                      for d in self.devices]
        elif split_type == 1:         # COMM_TYPE_SHARED
            colors = [int(getattr(d, "process_index", 0))
                      for d in self.devices]
        else:
            self._err(ERR_ARG, f"unknown split_type {split_type}")
            return [None] * self.size
        return self.split(colors, keys)

    def create(self, group: Group) -> Optional["Communicator"]:
        """MPI_Comm_create: new communicator over a subgroup."""
        self._check()
        ranks = []
        for wr in group.world_ranks:
            lr = self.group.rank_of(wr)
            if lr == UNDEFINED:
                self._err(ERR_RANK, "group not a subset of communicator")
            ranks.append(lr)
        devs = [self.devices[r] for r in ranks]
        return self.__class__(group, devs, name=f"{self.name}.create",
                              parent=self, errhandler=self.errhandler)

    def compare(self, other: "Communicator") -> int:
        from ompi_tpu.core.group import CONGRUENT, IDENT, SIMILAR, UNEQUAL
        if self is other:
            return IDENT
        g = self.group.compare(other.group)
        if g == IDENT:
            return CONGRUENT
        return SIMILAR if g == SIMILAR else UNEQUAL

    def free(self) -> None:
        fire_delete_attrs(self)
        self._freed = True
        # pvar session semantics: instruments owned by this cid
        # (telemetry histograms, trace_skew_c<cid>) retire with it — a
        # later pvar read must not report a freed comm's keys
        from ompi_tpu import telemetry as _telemetry
        _telemetry.retire_comm(self.cid)

    # -- process topologies (topo framework) ---------------------------
    def create_cart(self, dims: Sequence[int],
                    periods: Optional[Sequence[bool]] = None,
                    reorder: bool = False) -> "Communicator":
        """MPI_Cart_create. ``reorder=True`` maps logical cart coords to
        physical device coords when the backend exposes them (the ICI
        mesh), so cart neighbors are physical neighbors — the TPU
        re-design of topo/treematch rank reordering."""
        import math
        from ompi_tpu.topo import CartTopology
        dims = list(dims)
        if periods is None:
            periods = [False] * len(dims)
        n = math.prod(dims)
        if n > self.size:
            self._err(ERR_ARG, f"cart size {n} exceeds comm size")
        devices = list(self.devices[:n])
        ranks = list(range(n))
        if reorder:
            def devkey(i):
                d = self.devices[i]
                return tuple(getattr(d, "coords", None) or (d.id,))
            ranks = sorted(range(n), key=devkey)
            devices = [self.devices[r] for r in ranks]
        g = Group([self.group.world_ranks[r] for r in ranks])
        c = self.__class__(g, devices, name=f"{self.name}.cart",
                           parent=self, errhandler=self.errhandler)
        c.topo = CartTopology(dims, periods)
        return c

    def _cart(self):
        from ompi_tpu.topo import CartTopology
        if not isinstance(self.topo, CartTopology):
            from ompi_tpu.core.errhandler import ERR_TOPOLOGY
            self._err(ERR_TOPOLOGY, "communicator has no cartesian topology")
        return self.topo

    def cart_rank(self, coords: Sequence[int]) -> int:
        return self._cart().rank(coords)

    def cart_coords(self, rank: int) -> Tuple[int, ...]:
        return self._cart().coords(rank)

    def cart_shift(self, rank: int, direction: int,
                   disp: int = 1) -> Tuple[int, int]:
        return self._cart().shift(rank, direction, disp)

    def cart_sub(self, remain: Sequence[bool]) -> List["Communicator"]:
        """MPI_Cart_sub: split into sub-cart communicators along kept
        dims; returns one entry per rank."""
        topo = self._cart()
        colors, new_topo = topo.sub_keep(remain)
        subs = self.split(colors)
        for s in subs:
            if s is not None and s.topo is None:
                from ompi_tpu.topo import CartTopology
                s.topo = CartTopology(new_topo.dims, new_topo.periods)
        return subs

    def create_graph(self, index: Sequence[int], edges: Sequence[int],
                     reorder: bool = False) -> "Communicator":
        """MPI_Graph_create. ``reorder=True`` runs the treematch
        placement: rank r is bound to the device whose ICI position
        minimizes the graph's weighted hop count (topo/treematch)."""
        from ompi_tpu.topo import GraphTopology
        topo = GraphTopology(index, edges)
        if topo.size > self.size:
            self._err(ERR_ARG, "graph larger than communicator")
        devices = list(self.devices[:topo.size])
        if reorder and topo.size > 1:
            from ompi_tpu.topo import treematch as tm
            cm = tm.comm_matrix_from_graph(index, edges)
            hw = tm.hardware_distance(devices)
            perm = tm.treematch_permutation(cm, hw)
            devices = [devices[perm[r]] for r in range(topo.size)]
        g = Group(self.group.world_ranks[:topo.size])
        c = self.__class__(g, devices,
                           name=f"{self.name}.graph", parent=self,
                           errhandler=self.errhandler)
        c.topo = topo
        return c

    def create_dist_graph_adjacent(self, sources, destinations
                                   ) -> "Communicator":
        from ompi_tpu.topo import DistGraphTopology
        c = self.dup()
        c.topo = DistGraphTopology(sources, destinations)
        c.name = f"{self.name}.dist_graph"
        return c

    def graph_neighbors(self, rank: int) -> List[int]:
        if self.topo is None:
            from ompi_tpu.core.errhandler import ERR_TOPOLOGY
            self._err(ERR_TOPOLOGY, "no topology attached")
        return self.topo.neighbors(rank)

    def neighbor_allgather(self, sendbuf) -> List[Any]:
        """MPI_Neighbor_allgather: each rank receives its neighbors'
        buffers (in neighbor order). Device inputs stay on device: the
        exchange lowers to edge-colored ppermute waves over the mesh
        (topo/neighbor.py — a cart halo exchange is 2 collective-
        permutes per dimension); host inputs take the NumPy path."""
        self._validate_stacked(sendbuf)
        if self.topo is None:
            from ompi_tpu.core.errhandler import ERR_TOPOLOGY
            self._err(ERR_TOPOLOGY, "no topology attached")
        self._require_local_views("neighbor_allgather")
        if isinstance(sendbuf, jax.Array):
            from ompi_tpu.topo import neighbor as nbr
            return nbr.device_neighbor_allgather(self, sendbuf)
        host = np.asarray(sendbuf)
        out = []
        for r in range(self.size):
            nb = [n for n in self.topo.neighbors(r) if n >= 0]
            out.append(np.stack([host[n] for n in nb])
                       if nb else np.empty((0,) + host.shape[1:],
                                           host.dtype))
        return out

    def neighbor_alltoall(self, sendbuf) -> List[Any]:
        """MPI_Neighbor_alltoall: sendbuf (N, max_out_deg, *s); rank r's
        j-th chunk goes to its j-th out-neighbor; each rank receives one
        chunk per in-neighbor (in neighbor order). Device inputs ride
        the ppermute-wave lowering (topo/neighbor.py), host inputs the
        NumPy path."""
        self._validate_stacked(sendbuf, lead=2)
        if self.topo is None:
            from ompi_tpu.core.errhandler import ERR_TOPOLOGY
            self._err(ERR_TOPOLOGY, "no topology attached")
        self._require_local_views("neighbor_alltoall")
        if isinstance(sendbuf, jax.Array):
            from ompi_tpu.topo import neighbor as nbr
            return nbr.device_neighbor_alltoall(self, sendbuf)
        from collections import deque
        host = np.asarray(sendbuf)
        out_nb = getattr(self.topo, "out_neighbors", self.topo.neighbors)
        in_nb = self.topo.neighbors
        # chunk sent from s to its j-th out-neighbor d lands at d at the
        # position of the matching occurrence of s in d's in-neighbor
        # list; FIFO per (sender, receiver) pair handles duplicate edges
        # (periodic dims of size <= 2, multigraph dist-graphs).
        recv = {}
        for s in range(self.size):
            for j, d in enumerate(out_nb(s)):
                if 0 <= d < self.size:
                    recv.setdefault((d, s), deque()).append(host[s, j])
        out = []
        for r in range(self.size):
            chunks = []
            for n in in_nb(r):
                if n < 0:
                    continue
                q = recv.get((r, n))
                chunks.append(q.popleft() if q
                              else np.zeros(host.shape[2:], host.dtype))
            out.append(np.stack(chunks) if chunks
                       else np.empty((0,) + host.shape[2:], host.dtype))
        return out

    def neighbor_allgatherv(self, per_rank: Sequence[Any]) -> List[Any]:
        """MPI_Neighbor_allgatherv: ragged contributions; rank r receives
        the concatenation of its neighbors' (variable-size) buffers in
        neighbor order."""
        if self.topo is None:
            from ompi_tpu.core.errhandler import ERR_TOPOLOGY
            self._err(ERR_TOPOLOGY, "no topology attached")
        arrs, counts = self._ragged(per_rank, "neighbor_allgatherv")
        if arrs and isinstance(arrs[0], jax.Array):
            # pad-to-max wire + ppermute waves, slice valid prefixes
            # back off (the v-collectives' device convention)
            from ompi_tpu.topo import neighbor as nbr
            m = max(counts) if counts else 0
            if m:
                padded = self._pad_stack(arrs, counts, m)
                res = nbr.device_neighbor_allgather(self, padded)
                out = []
                for r in range(self.size):
                    nb = [n for n in self.topo.neighbors(r)
                          if 0 <= n < self.size]
                    out.append(jax.numpy.concatenate(
                        [res[r][k][:counts[n]]
                         for k, n in enumerate(nb)]) if nb
                        else jax.numpy.empty((0,), arrs[0].dtype))
                return out
        out = []
        for r in range(self.size):
            nb = [n for n in self.topo.neighbors(r) if n >= 0]
            out.append(np.concatenate([np.asarray(arrs[n]) for n in nb])
                       if nb else np.empty((0,), arrs[0].dtype))
        return out

    def neighbor_alltoallv(self, send_chunks: Sequence[Sequence[Any]]
                           ) -> List[List[Any]]:
        """MPI_Neighbor_alltoallv: ``send_chunks[r][j]`` is rank r's
        ragged chunk for its j-th out-neighbor; rank r receives one chunk
        per in-neighbor, as a list aligned with its in-neighbor order
        (empty array where the sender provided no chunk — alignment is
        never silently shifted)."""
        if self.topo is None:
            from ompi_tpu.core.errhandler import ERR_TOPOLOGY
            self._err(ERR_TOPOLOGY, "no topology attached")
        if len(send_chunks) != self.size:
            self._err(ERR_COUNT, "need one chunk row per rank")
        self._require_local_views("neighbor_alltoallv")
        if all(isinstance(c, jax.Array)
               for row in send_chunks for c in row) and \
                any(len(row) for row in send_chunks):
            return self._neighbor_alltoallv_device(send_chunks)
        from collections import deque
        out_nb = getattr(self.topo, "out_neighbors", self.topo.neighbors)
        recv: Dict[Tuple[int, int], Any] = {}
        for s in range(self.size):
            for j, d in enumerate(out_nb(s)):
                if 0 <= d < self.size and j < len(send_chunks[s]):
                    recv.setdefault((d, s), deque()).append(
                        np.asarray(send_chunks[s][j]).ravel())
        empty = np.empty((0,), np.float32)
        out: List[List[Any]] = []
        for r in range(self.size):
            chunks = []
            for n in self.topo.neighbors(r):
                if n < 0:
                    chunks.append(empty)
                    continue
                q = recv.get((r, n))
                chunks.append(q.popleft() if q else empty)
            out.append(chunks)
        return out

    def _neighbor_alltoallv_device(self, send_chunks) -> List[List[Any]]:
        """Device lowering of neighbor_alltoallv: pad ragged chunks to
        the max count, ride the ppermute-wave alltoall, slice each
        received chunk back to its sender's length (counts resolved
        through the plan's FIFO edge pairing)."""
        from ompi_tpu.topo import neighbor as nbr
        plan = nbr._plan(self)
        rows = [[jax.numpy.ravel(c) for c in row] for row in send_chunks]
        counts = [[int(c.size) for c in row] for row in rows]
        m = max((c for row in counts for c in row), default=0)
        d_out = max(plan.max_out, 1)
        # dtype from the first actual chunk anywhere (an empty first row
        # must not promote integer payloads to float32)
        dt = next((c.dtype for row in rows for c in row),
                  jax.numpy.float32)
        if m == 0:
            empty = jax.numpy.empty((0,), dt)
            return [[empty for _ in plan.in_lists[r]]
                    for r in range(self.size)]
        padded = jax.numpy.stack([
            jax.numpy.stack(
                [jax.numpy.pad(row[j], (0, m - row[j].size))
                 if j < len(row)
                 else jax.numpy.zeros((m,), dt)
                 for j in range(d_out)])
            for row in rows])                       # (N, D_out, m)
        padded = jax.device_put(padded, NamedSharding(
            self.mesh, P(AXIS)))
        res = nbr.device_neighbor_alltoall(self, padded)
        # per-edge received length: the sender's chunk size for the
        # paired out slot (zero-length when the sender sent nothing)
        length = {}
        for (s, d, j, i) in plan.edges:
            if j is not None and j < len(counts[s]):
                length[(d, i)] = counts[s][j]
            else:
                length[(d, i)] = 0
        # row alignment matches the host path: one entry per in-slot,
        # empty where the slot is invalid (never silently shifted)
        out: List[List[Any]] = []
        empty = jax.numpy.empty((0,), dt)
        for r in range(self.size):
            vs = plan.valid_slots[r]
            row = []
            for i in range(len(plan.in_lists[r])):
                if i not in vs:
                    row.append(empty)
                else:
                    row.append(res[r][vs.index(i)]
                               [:length.get((r, i), 0)])
            out.append(row)
        return out

    # -- attributes (keyvals) ------------------------------------------
    def set_attr(self, keyval: int, value: Any) -> None:
        self.attributes[keyval] = value

    def get_attr(self, keyval: int) -> Tuple[bool, Any]:
        if keyval in self.attributes:
            return True, self.attributes[keyval]
        return False, None

    def delete_attr(self, keyval: int) -> None:
        val = self.attributes.pop(keyval, None)
        cb = _keyvals.get(keyval)
        if cb and cb[1] and val is not None:
            cb[1](self, keyval, val)

    def set_errhandler(self, errh: Errhandler) -> None:
        self.errhandler = errh

    def get_errhandler(self) -> Errhandler:
        return self.errhandler

    def set_name(self, name: str) -> None:
        self.name = name

    def get_name(self) -> str:
        return self.name

    def abort(self, errorcode: int = 1):
        import sys
        sys.stderr.write(f"MPI_Abort on {self.name} errorcode={errorcode}\n")
        raise SystemExit(errorcode)

    # -- ULFM (mpiext/ftmpi semantics; docs/features/ulfm.rst) ---------
    # The failure registry (runtime/ft.py) is the PMIx-event-stream
    # equivalent; these methods implement the MPIX_Comm_* surface over
    # it. Per ULFM, agree/shrink/failure_ack remain usable on revoked
    # communicators — they bypass _check().
    def _failed_local(self) -> List[int]:
        return [r for r, w in enumerate(self.group.world_ranks)
                if self._ft.is_failed(w)]

    def _check_ft_coll(self) -> None:
        """Collectives must not silently complete across a failure
        (ompi/request/req_ft.c behavior: ops involving failed procs
        raise MPIX_ERR_PROC_FAILED until the comm is shrunk)."""
        if not self._ft.any_failed():        # hot path: nothing has failed
            return
        failed = self._failed_local()
        if failed:
            from ompi_tpu.core.errhandler import ERR_PROC_FAILED
            self._err(ERR_PROC_FAILED,
                      f"rank(s) {failed} of {self.name} have failed "
                      f"(shrink or agree to continue)")

    def _check_peer_ft(self, peer: int) -> None:
        if peer is None or not (0 <= peer < self.size):
            return
        if self._ft.is_failed(self.group.world_ranks[peer]):
            from ompi_tpu.core.errhandler import ERR_PROC_FAILED
            self._err(ERR_PROC_FAILED, f"peer rank {peer} has failed")

    def _check_anysource_ft(self) -> None:
        """A wildcard receive with un-acknowledged failures raises
        MPIX_ERR_PROC_FAILED_PENDING semantics: the matching send might
        have come from the dead peer. failure_ack() re-arms wildcards."""
        unacked = [r for r in self._failed_local()
                   if self.group.world_ranks[r] not in self._acked_failures]
        if unacked:
            from ompi_tpu.core.errhandler import ERR_PROC_FAILED
            self._err(ERR_PROC_FAILED,
                      f"ANY_SOURCE receive with unacknowledged failed "
                      f"rank(s) {unacked}; call failure_ack() first")

    def revoke(self) -> None:
        """MPIX_Comm_revoke. Single-controller: the comm object is the
        shared state all ranks observe, so setting the flag *is* the
        reliable revocation broadcast (coll_base_revoke_local.c's job);
        pending pt2pt requests observe it at completion (pml.h:244
        revoke_comm hook ≈ the matching engine consulting the flag)."""
        self._revoked = True

    def is_revoked(self) -> bool:
        return self._revoked

    def shrink(self, failed_ranks: Optional[Sequence[int]] = None
               ) -> "Communicator":
        """MPIX_Comm_shrink: agree on the failed set, return a new
        communicator over the survivors. Works on revoked comms."""
        if self._freed:
            raise MPIError(ERR_COMM, "communicator has been freed")
        failed = set(failed_ranks or ())
        failed.update(self._failed_local())
        # Agreement on the failed set: encode each rank's view as a
        # bitmask and AND-agree (the ftagree pass the reference's shrink
        # performs to reach a uniform survivor list).
        mask = ~sum(1 << r for r in failed)
        agreed, _ = self._agree_module().agree([mask] * self.size)
        alive = [r for r in range(self.size)
                 if (agreed >> r) & 1 and r not in failed]
        g = Group([self.group.world_ranks[r] for r in alive])
        devs = [self.devices[r] for r in alive]
        child = self.__class__(g, devs, name=f"{self.name}.shrink",
                               parent=self, errhandler=self.errhandler)
        # the parent keeps living (ULFM shrink does not free it), but
        # its per-comm instruments describe the dead-rank era — retire
        # them so reads after the shrink start from the survivor set
        from ompi_tpu import telemetry as _telemetry
        _telemetry.retire_comm(self.cid)
        return child

    def ishrink(self):
        from ompi_tpu.core.request import Request
        return Request.completed(self.shrink())

    def _agree_module(self):
        m = self.c_coll.get("agree")
        if m is None:
            from ompi_tpu.coll.ftagree import FtAgreeModule
            return FtAgreeModule(self)
        return m

    def agree(self, flags: Sequence[int]) -> int:
        """MPIX_Comm_agree: uniform bitwise-AND agreement via
        coll/ftagree. Raises MPIX_ERR_PROC_FAILED (carrying the agreed
        value in ``.agreed_value``) when a participant failed and was not
        acknowledged — the ULFM contract: agreement is still reached."""
        if self._freed:
            raise MPIError(ERR_COMM, "communicator has been freed")
        value, failed = self._agree_module().agree(flags)
        unacked = [r for r in failed
                   if self.group.world_ranks[r] not in self._acked_failures]
        if unacked:
            from ompi_tpu.core.errhandler import ERR_PROC_FAILED
            err = MPIError(ERR_PROC_FAILED,
                           f"agreement reached over failed rank(s) "
                           f"{unacked}")
            err.agreed_value = value
            raise err
        return value

    def iagree(self, flags: Sequence[int]):
        from ompi_tpu.core.request import Request
        return Request.completed(self.agree(flags))

    def failure_ack(self) -> None:
        """MPIX_Comm_failure_ack: acknowledge all currently-known
        failures, re-arming ANY_SOURCE receives and quieting agree()."""
        self._acked_failures = frozenset(self._acked_failures | {
            w for w in self.group.world_ranks
            if self._ft.is_failed(w)})

    def failure_get_acked(self) -> Group:
        """MPIX_Comm_failure_get_acked: group of acknowledged failed
        processes."""
        return Group([w for w in self.group.world_ranks
                      if w in self._acked_failures])

    def get_failed(self) -> Group:
        """MPIX_Comm_get_failed (MPI-5 FT): all known-failed members."""
        return Group([w for w in self.group.world_ranks
                      if self._ft.is_failed(w)])

    def ack_failed(self, num_to_ack: Optional[int] = None) -> Group:
        """MPIX_Comm_ack_failed (MPI-5 FT): acknowledge the first
        ``num_to_ack`` failed members (all, when None); returns the
        acked group."""
        failed = [w for w in self.group.world_ranks if self._ft.is_failed(w)]
        if num_to_ack is not None:
            failed = failed[:num_to_ack]
        self._acked_failures = frozenset(self._acked_failures | set(failed))
        return Group(sorted(self._acked_failures))

    def __repr__(self):
        return (f"Communicator({self.name}, size={self.size}, "
                f"cid={self.cid})")


def parent_errh(parent: Optional[Communicator]) -> Errhandler:
    return parent.errhandler if parent is not None else ERRORS_ARE_FATAL


# -- keyval registry (MPI_Comm_create_keyval) ------------------------------
_keyvals: Dict[int, Tuple[Optional[Callable], Optional[Callable]]] = {}
_keyval_counter = itertools.count(100)


def create_keyval(copy_fn: Optional[Callable] = None,
                  delete_fn: Optional[Callable] = None) -> int:
    """MPI_Comm_create_keyval. ``copy_fn(comm, keyval, value) ->
    (keep: bool, new_value)`` runs at Comm_dup (no copy_fn => the
    attribute is not propagated, per MPI); ``delete_fn(comm, keyval,
    value)`` runs at attribute deletion / communicator free."""
    kv = next(_keyval_counter)
    _keyvals[kv] = (copy_fn, delete_fn)
    return kv


def free_keyval(keyval: int) -> None:
    _keyvals.pop(keyval, None)


def propagate_attrs(src, dst) -> None:
    """MPI attribute-copy semantics at Comm_dup (attribute.c:349-384):
    an attribute propagates only through its keyval's copy callback,
    which may veto or transform the value. Shared by both communicator
    classes — one copy of the semantics."""
    for kv, val in src.attributes.items():
        cb = _keyvals.get(kv)
        copy_fn = cb[0] if cb else None
        if copy_fn is None:
            continue
        keep, newval = copy_fn(src, kv, val)
        if keep:
            dst.attributes[kv] = newval


def fire_delete_attrs(comm) -> None:
    """Delete callbacks at communicator free (attribute.c free path).
    A raising callback propagates (MPI_Comm_free must report it)."""
    for kv, val in list(comm.attributes.items()):
        cb = _keyvals.get(kv)
        if cb and cb[1]:
            cb[1](comm, kv, val)
    comm.attributes.clear()
