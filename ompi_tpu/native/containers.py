"""Pythonic handles over the native container library (opal/class role).

Each class wraps one handle from ``native/containers.cpp``. The FIFO and
LIFO are genuinely lock-free (Vyukov MPMC queue; Treiber stack with ABA
tags) and safe to drive from multiple Python threads — ctypes releases
the GIL around calls, so the thread-stress tests exercise real
concurrency, mirroring ``test/class/opal_fifo.c`` / ``opal_lifo.c``.
"""
from __future__ import annotations

import ctypes
from typing import Optional, Tuple

from ompi_tpu.native.loader import get_lib


def available() -> bool:
    return get_lib() is not None


class _Native:
    kind = ""

    def __init__(self, capacity: int = 1024):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = getattr(lib, f"ompi_tpu_{self.kind}_create")(capacity)

    def close(self) -> None:
        if self._h:
            getattr(self._lib, f"ompi_tpu_{self.kind}_destroy")(self._h)
            self._h = 0

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _Queue(_Native):
    def push(self, value: int) -> bool:
        return bool(getattr(self._lib,
                            f"ompi_tpu_{self.kind}_push")(self._h, value))

    def pop(self) -> Optional[int]:
        out = ctypes.c_int64()
        ok = getattr(self._lib, f"ompi_tpu_{self.kind}_pop")(
            self._h, ctypes.byref(out))
        return int(out.value) if ok else None


class Fifo(_Queue):
    """Lock-free bounded MPMC FIFO (opal_fifo)."""
    kind = "fifo"


class Lifo(_Queue):
    """Lock-free LIFO / free-list (opal_lifo)."""
    kind = "lifo"


class RingBuffer(_Queue):
    """Fixed-capacity ring buffer (opal_ring_buffer)."""
    kind = "ring"


class Hotel(_Native):
    """Timeout manager (opal_hotel): occupants check into rooms with a
    deadline; expired occupants are evicted one at a time."""
    kind = "hotel"

    def checkin(self, occupant: int, deadline: int) -> int:
        """Returns the room number, or -1 when the hotel is full."""
        return int(self._lib.ompi_tpu_hotel_checkin(self._h, occupant,
                                                    deadline))

    def checkout(self, room: int) -> Optional[int]:
        out = ctypes.c_int64()
        ok = self._lib.ompi_tpu_hotel_checkout(self._h, room,
                                               ctypes.byref(out))
        return int(out.value) if ok else None

    def evict_one(self, now: int) -> Optional[Tuple[int, int]]:
        """Evict one occupant whose deadline has passed; returns
        (room, occupant) or None."""
        out = ctypes.c_int64()
        room = self._lib.ompi_tpu_hotel_evict_one(self._h, now,
                                                  ctypes.byref(out))
        return (int(room), int(out.value)) if room >= 0 else None

    @property
    def occupancy(self) -> int:
        return int(self._lib.ompi_tpu_hotel_occupancy(self._h))


class Bitmap(_Native):
    """Growable bitmap (opal_bitmap) with find-and-set allocation."""
    kind = "bitmap"

    def set(self, bit: int) -> None:
        self._lib.ompi_tpu_bitmap_set(self._h, bit)

    def clear(self, bit: int) -> None:
        self._lib.ompi_tpu_bitmap_clear(self._h, bit)

    def test(self, bit: int) -> bool:
        return bool(self._lib.ompi_tpu_bitmap_test(self._h, bit))

    def find_and_set(self) -> int:
        return int(self._lib.ompi_tpu_bitmap_find_and_set(self._h))


class PointerArray(_Native):
    """Index-recycling registry (opal_pointer_array)."""
    kind = "parray"

    def add(self, value: int) -> int:
        return int(self._lib.ompi_tpu_parray_add(self._h, value))

    def set(self, index: int, value: int) -> bool:
        return bool(self._lib.ompi_tpu_parray_set(self._h, index, value))

    def get(self, index: int) -> Optional[int]:
        out = ctypes.c_int64()
        ok = self._lib.ompi_tpu_parray_get(self._h, index,
                                           ctypes.byref(out))
        return int(out.value) if ok else None

    def remove(self, index: int) -> bool:
        return bool(self._lib.ompi_tpu_parray_remove(self._h, index))
