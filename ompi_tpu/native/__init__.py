"""Native (C++) runtime components, loaded via ctypes.

The shared library is built from ``native/*.cpp`` with g++ on first use
(cached next to the sources); everything here degrades gracefully to the
pure-NumPy paths when no compiler is available.
"""
from ompi_tpu.native.loader import get_lib, native_available  # noqa: F401
