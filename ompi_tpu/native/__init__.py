"""Native (C++) runtime components, loaded via ctypes.

The shared library is built from ``native/*.cpp`` with g++ on first use
(cached next to the sources); everything here degrades gracefully to the
pure-NumPy paths when no compiler is available. Components:

- ``convertor.cpp`` — run-coalesced pack/unpack (OPAL convertor role)
- ``ops.cpp``       — host reduction kernels (op/avx role)
- ``memheap.cpp``   — buddy allocator for the SHMEM symmetric heap
  (oshmem/mca/memheap/buddy role)
- ``matching.cpp``  — pt2pt matching core (ob1 recvfrag matching role)
"""
from ompi_tpu.native.loader import get_lib, native_available  # noqa: F401

import numpy as _np

# (op name -> id) and (numpy dtype -> id) tables mirroring ops.cpp enums.
_OP_IDS = {"sum": 0, "prod": 1, "max": 2, "min": 3, "band": 4, "bor": 5,
           "bxor": 6, "land": 7, "lor": 8, "lxor": 9}
_DT_IDS = {_np.dtype(k): v for k, v in {
    _np.int8: 0, _np.int16: 1, _np.int32: 2, _np.int64: 3,
    _np.uint8: 4, _np.uint16: 5, _np.uint32: 6, _np.uint64: 7,
    _np.float32: 8, _np.float64: 9}.items()}


def native_reduce_into(op_name: str, inbuf, inout) -> bool:
    """In-place ``inout = inbuf OP inout`` via the C++ kernel table.
    ``inout`` must be a C-contiguous writable ndarray (it is mutated).
    Returns False when the (op, dtype, layout) combination isn't native
    (caller falls back — the op/avx fallback pattern)."""
    lib = get_lib()
    if lib is None:
        return False
    op_id = _OP_IDS.get(op_name)
    if op_id is None:
        return False
    if not (isinstance(inbuf, _np.ndarray) and isinstance(inout, _np.ndarray)
            and inbuf.dtype == inout.dtype
            and inbuf.shape == inout.shape
            and inout.flags["C_CONTIGUOUS"] and inout.flags["WRITEABLE"]):
        return False
    dt_id = _DT_IDS.get(inbuf.dtype)
    if dt_id is None:
        return False
    a = _np.ascontiguousarray(inbuf)
    rc = lib.ompi_tpu_reduce_local(op_id, dt_id, a.ctypes.data,
                                   inout.ctypes.data, a.size)
    return rc == 0


def native_reduce_local(op_name: str, inbuf, inout):
    """Functional variant: returns the combined array (inout untouched),
    or None when not native."""
    if (get_lib() is None
            or _OP_IDS.get(op_name) is None
            or not (isinstance(inbuf, _np.ndarray)
                    and isinstance(inout, _np.ndarray)
                    and inbuf.dtype == inout.dtype
                    and inbuf.shape == inout.shape)
            or _DT_IDS.get(inbuf.dtype) is None):
        return None
    out = _np.ascontiguousarray(inout).copy()
    return out if native_reduce_into(op_name, inbuf, out) else None
