"""Build-on-demand loader for the native library (ctypes, no Python
headers needed — mirrors how the reference ships optional SIMD
components that fall back to base kernels when unavailable)."""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_DIR = os.path.dirname(_PKG_DIR)
_NATIVE_DIR = os.path.join(_REPO_DIR, "native")
_SRCS = [os.path.join(_NATIVE_DIR, f)
         for f in ("convertor.cpp", "ops.cpp", "memheap.cpp",
                   "matching.cpp", "containers.cpp")]
_SO = os.path.join(_NATIVE_DIR, "libompi_tpu_native.so")


def cached_native_build(deps, so_path: str, make_cmd,
                        timeout: int = 180,
                        on_error=None) -> Optional[str]:
    """Content-hash-cached native build, shared by this loader and
    tools/mpicc (one protocol, one place to fix it). ``deps`` are the
    source files hashed into the sidecar ``<so>.hash``; mtime is never
    consulted (git checkouts scramble it). ``make_cmd(tmp_path)``
    returns the compiler argv building to the private temp path, which
    is renamed into place only on success — concurrent builders never
    observe a half-written library. Returns ``so_path`` or None."""
    h = hashlib.sha256()
    for d in deps:
        with open(d, "rb") as f:
            h.update(f.read())
    digest = h.hexdigest()
    hash_file = so_path + ".hash"
    if os.path.exists(so_path) and os.path.exists(hash_file):
        try:
            with open(hash_file) as f:
                if f.read().strip() == digest:
                    return so_path
        except OSError:
            pass
    tmp = f"{so_path}.tmp.{os.getpid()}"
    try:
        subprocess.run(make_cmd(tmp), check=True, capture_output=True,
                       timeout=timeout)
        os.replace(tmp, so_path)
        try:
            with open(hash_file, "w") as f:
                f.write(digest)
        except OSError:
            pass          # the BUILD succeeded; a missing sidecar only
            #               costs a rebuild next process
        return so_path
    except subprocess.CalledProcessError as e:
        if on_error is not None:
            on_error(e)
        return None
    except (OSError, subprocess.SubprocessError):
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _build() -> Optional[str]:
    srcs = [s for s in _SRCS if os.path.exists(s)]
    if len(srcs) != len(_SRCS):
        # A partial tree would pass the ABI probe (one file owns the
        # version) yet miss symbols, which would disable everything at
        # bind time — refuse up front instead.
        return None
    return cached_native_build(
        srcs, _SO,
        lambda tmp: ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     *srcs, "-o", tmp],
        timeout=120)


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("OMPI_TPU_DISABLE_NATIVE"):
            return None
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
            if lib.ompi_tpu_native_abi() != 3:
                return None
            i64 = ctypes.c_int64
            lib.ompi_tpu_pack_runs_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, i64, i64, i64, i64, i64, i64, i64]
            lib.ompi_tpu_unpack_runs_rows.argtypes = \
                lib.ompi_tpu_pack_runs_rows.argtypes
            # reduction-op kernels (ops.cpp)
            lib.ompi_tpu_reduce_local.argtypes = [
                i64, i64, ctypes.c_void_p, ctypes.c_void_p, i64]
            lib.ompi_tpu_reduce_local.restype = ctypes.c_int
            # buddy allocator (memheap.cpp)
            for fn, nargs in (("ompi_tpu_buddy_create", 2),
                              ("ompi_tpu_buddy_alloc", 2),
                              ("ompi_tpu_buddy_free", 2),
                              ("ompi_tpu_buddy_used", 1)):
                f = getattr(lib, fn)
                f.argtypes = [i64] * nargs
                f.restype = i64
            lib.ompi_tpu_buddy_destroy.argtypes = [i64]
            lib.ompi_tpu_buddy_destroy.restype = None
            # matching core (matching.cpp)
            lib.ompi_tpu_match_create.argtypes = [i64]
            lib.ompi_tpu_match_create.restype = i64
            lib.ompi_tpu_match_destroy.argtypes = [i64]
            lib.ompi_tpu_match_destroy.restype = None
            for fn, nargs in (("ompi_tpu_match_send", 7),
                              ("ompi_tpu_match_take", 6),
                              ("ompi_tpu_match_post", 6),
                              ("ompi_tpu_match_cancel", 3)):
                f = getattr(lib, fn)
                f.argtypes = [i64] * nargs
                f.restype = i64
            # containers (containers.cpp, the opal/class role):
            # i64-in/i64-out symbols ride the same table as the
            # buddy/matching bindings; pointer-out and void-returning
            # symbols are listed separately.
            pi64 = ctypes.POINTER(ctypes.c_int64)
            for fn, nargs in (("ompi_tpu_fifo_create", 1),
                              ("ompi_tpu_fifo_push", 2),
                              ("ompi_tpu_lifo_create", 1),
                              ("ompi_tpu_lifo_push", 2),
                              ("ompi_tpu_ring_create", 1),
                              ("ompi_tpu_ring_push", 2),
                              ("ompi_tpu_hotel_create", 1),
                              ("ompi_tpu_hotel_checkin", 3),
                              ("ompi_tpu_hotel_occupancy", 1),
                              ("ompi_tpu_bitmap_create", 1),
                              ("ompi_tpu_bitmap_test", 2),
                              ("ompi_tpu_bitmap_find_and_set", 1),
                              ("ompi_tpu_parray_create", 1),
                              ("ompi_tpu_parray_add", 2),
                              ("ompi_tpu_parray_set", 3),
                              ("ompi_tpu_parray_remove", 2)):
                f = getattr(lib, fn)
                f.argtypes = [i64] * nargs
                f.restype = i64
            for fn in ("ompi_tpu_fifo_destroy", "ompi_tpu_lifo_destroy",
                       "ompi_tpu_ring_destroy", "ompi_tpu_hotel_destroy",
                       "ompi_tpu_bitmap_destroy",
                       "ompi_tpu_parray_destroy"):
                f = getattr(lib, fn)
                f.argtypes = [i64]
                f.restype = None
            for fn in ("ompi_tpu_bitmap_set", "ompi_tpu_bitmap_clear"):
                f = getattr(lib, fn)
                f.argtypes = [i64, i64]
                f.restype = None
            for fn, nargs in (("ompi_tpu_fifo_pop", 1),
                              ("ompi_tpu_lifo_pop", 1),
                              ("ompi_tpu_ring_pop", 1),
                              ("ompi_tpu_hotel_checkout", 2),
                              ("ompi_tpu_hotel_evict_one", 2),
                              ("ompi_tpu_parray_get", 2)):
                f = getattr(lib, fn)
                f.argtypes = [i64] * nargs + [pi64]
                f.restype = i64
            _lib = lib
        except (OSError, AttributeError):
            # AttributeError = missing symbol in a stale cached library;
            # fall back to the pure-Python paths like any load failure.
            _lib = None
    return _lib


def native_available() -> bool:
    return get_lib() is not None
