"""Build-on-demand loader for the native library (ctypes, no Python
headers needed — mirrors how the reference ships optional SIMD
components that fall back to base kernels when unavailable)."""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_DIR = os.path.dirname(_PKG_DIR)
_SRC = os.path.join(_REPO_DIR, "native", "convertor.cpp")
_SO = os.path.join(_REPO_DIR, "native", "libompi_tpu_native.so")


def _build() -> Optional[str]:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC,
             "-o", _SO],
            check=True, capture_output=True, timeout=120)
        return _SO
    except (OSError, subprocess.SubprocessError):
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("OMPI_TPU_DISABLE_NATIVE"):
            return None
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
            if lib.ompi_tpu_native_abi() != 1:
                return None
            i64 = ctypes.c_int64
            lib.ompi_tpu_pack_runs_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, i64, i64, i64, i64, i64, i64, i64]
            lib.ompi_tpu_unpack_runs_rows.argtypes = \
                lib.ompi_tpu_pack_runs_rows.argtypes
            _lib = lib
        except OSError:
            _lib = None
    return _lib


def native_available() -> bool:
    return get_lib() is not None
