"""Expert parallelism — Mixture-of-Experts token dispatch over a mesh
axis, built on ``InGraphComm.alltoall`` (the reference's alltoall family
— pairwise/bruck, ``coll_base_functions.h`` — is exactly the dispatch
primitive EP training uses; SURVEY.md §2.6 maps it to ``all_to_all``).

Switch-style top-1 routing with fixed expert capacity: each ep rank
hosts one expert; tokens are gathered into per-expert capacity slots,
exchanged with one ``all_to_all``, processed by the local expert, and
returned by a second ``all_to_all``; gate probabilities weight the
combine. Tokens over capacity are dropped (standard Switch semantics) —
capacity is the EP analogue of the reference's segment-size tuning knob.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ompi_tpu.parallel.ingraph import InGraphComm


def moe_apply(x, params: Dict[str, Any], ep: InGraphComm,
              capacity: int):
    """Top-1 MoE layer over the ``ep`` axis (1 expert per rank).

    Args:
      x: local tokens ``(T, D)`` (flatten batch x seq upstream).
      params: ``gate`` (D, E) replicated; ``w1`` (D, F), ``w2`` (F, D) —
        THIS rank's expert.
      ep: expert-parallel in-graph communicator (static size = E).
      capacity: per-(source rank, expert) token slots.
    Returns ``(T, D)`` combined expert outputs (dropped tokens get 0 —
    callers typically add a residual connection).
    """
    n = ep._size
    if n is None:
        raise ValueError("moe_apply needs InGraphComm(axis, size)")
    T, D = x.shape
    gate_logits = x @ params["gate"]                  # (T, E)
    gate_p = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(gate_p, axis=-1)              # (T,)
    prob = jnp.max(gate_p, axis=-1)                   # (T,)

    # Capacity slots: position of each token within its expert's queue.
    onehot = jax.nn.one_hot(expert, n, dtype=jnp.int32)      # (T, E)
    pos = (jnp.cumsum(onehot, axis=0) - 1) * onehot          # (T, E)
    slot = jnp.sum(pos, axis=-1)                             # (T,)
    keep = slot < capacity

    # dispatch[e, c, :] = the token routed to expert e at slot c
    disp_mask = (onehot.astype(jnp.bool_)
                 & keep[:, None])                            # (T, E)
    dispatch = jnp.zeros((n, capacity, D), x.dtype)
    scatter_e = jnp.where(disp_mask.any(-1), expert, 0)
    scatter_c = jnp.clip(slot, 0, capacity - 1)
    dispatch = dispatch.at[scatter_e, scatter_c].add(
        jnp.where(keep[:, None], x, 0))

    # Exchange: expert e receives its slots from every source rank.
    recv = ep.alltoall(dispatch, split_axis=0, concat_axis=0)
    # (n, capacity, D): n source-rank blocks for THIS rank's expert
    h = jax.nn.gelu(recv @ params["w1"])
    y = h @ params["w2"]                                     # (n, C, D)
    back = ep.alltoall(y, split_axis=0, concat_axis=0)       # (n, C, D)

    # Combine: token t reads back[expert[t], slot[t]] * prob[t].
    gathered = back[scatter_e, scatter_c]                    # (T, D)
    out = jnp.where(keep[:, None], gathered, 0.0)
    return (out * prob[:, None].astype(x.dtype)).astype(x.dtype)


def init_moe_params(key, d_model: int, d_ff: int, n_experts: int,
                    ep_rank_count: int = 1):
    """Replicated gate + this rank's expert weights."""
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "gate": jax.random.normal(k1, (d_model, n_experts),
                                  jnp.float32) * 0.02,
        "w1": jax.random.normal(k2, (d_model, d_ff), jnp.float32)
        * (d_model ** -0.5),
        "w2": jax.random.normal(k3, (d_ff, d_model), jnp.float32)
        * (d_ff ** -0.5),
    }
