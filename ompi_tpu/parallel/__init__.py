from ompi_tpu.parallel.ingraph import InGraphComm  # noqa: F401
