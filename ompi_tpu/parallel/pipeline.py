"""Pipeline parallelism — GPipe-style microbatch pipelining over a mesh
axis, built on ``InGraphComm.ring_shift`` (the chain/pipeline schedule of
the reference's bcast/reduce algorithms — ``coll_base_bcast.c`` pipeline/
chain — applied to activations instead of message segments).

Each ``pp`` rank owns one *stage* (a contiguous slice of the model);
microbatches flow through the ring: at tick t, rank r works on
microbatch t - r (bubble ticks are masked out). The schedule runs as a
``lax.scan`` inside shard_map, so XLA overlaps each tick's stage compute
with the next activation shift on ICI. Backward is JAX AD through the
scan (activation stashing; rematerialize with ``jax.checkpoint`` on the
stage function for long pipelines).
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from ompi_tpu.parallel.ingraph import InGraphComm


def pipeline_apply(stage_fn: Callable, stage_params: Any, x_micro,
                   pp: InGraphComm):
    """Run ``n_micro`` microbatches through an ``n_pp``-stage pipeline.

    Args:
      stage_fn: ``(stage_params, activation) -> activation`` — this
        rank's slice of the model (shapes uniform across stages).
      stage_params: this pp rank's stage parameters (shard_map-local).
      x_micro: ``(n_micro, B_m, ...)`` input microbatches. Only stage
        0's value is read; other ranks may pass zeros of equal shape.
      pp: the pipeline in-graph communicator (static size).

    Returns ``(n_micro, B_m, ...)`` outputs, valid on the LAST stage
    (other ranks hold garbage — the caller broadcasts or reduces as
    needed, exactly like rooted-collective semantics).
    """
    n = pp._size
    if n is None:
        raise ValueError("pipeline_apply needs InGraphComm(axis, size)")
    r = pp.rank()
    n_micro = x_micro.shape[0]
    act_shape = x_micro.shape[1:]
    n_ticks = n_micro + n - 1

    def tick(carry, t):
        prev_out, outputs = carry
        # Activation handoff: stage r receives stage r-1's last output.
        recv = pp.ring_shift(prev_out, 1)
        # Stage 0 injects microbatch t (while valid); others consume.
        m = t - r                          # microbatch index at this rank
        inject = jax.lax.dynamic_index_in_dim(
            x_micro, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
        a_in = jnp.where(r == 0, inject, recv)
        a_out = stage_fn(stage_params, a_in)
        # Only ticks with 0 <= m < n_micro carry real work for rank r;
        # masked lanes still compute (SPMD) but write nothing.
        valid_out = (r == n - 1) & (m >= 0) & (m < n_micro)
        updated = jax.lax.dynamic_update_index_in_dim(
            outputs, a_out, jnp.clip(m, 0, n_micro - 1), 0)
        outputs = jnp.where(valid_out, updated, outputs)
        return (a_out, outputs), None

    out0 = jnp.zeros((n_micro,) + act_shape, x_micro.dtype)
    (last, outputs), _ = jax.lax.scan(
        tick, (jnp.zeros(act_shape, x_micro.dtype), out0),
        jnp.arange(n_ticks))
    return outputs
