"""All-to-all sequence parallelism (the DeepSpeed-Ulysses schedule) —
the second of the two canonical long-context strategies (ring
attention is the first; `parallel/ring_attention.py`).

Where ring attention circulates K/V blocks with ``ppermute`` neighbor
traffic and recomputes softmax online, the all-to-all schedule
RESHARDS: two ``all_to_all`` collectives convert a sequence-sharded
layout ``(S/P, H, D)`` into a head-sharded one ``(S, H/P, D)``, each
rank runs PLAIN full-sequence attention over its head subset, and a
mirror ``all_to_all`` converts back. Communication volume is O(S*H*D/P)
per rank independent of sequence length's square, and the attention
kernel itself stays the unmodified dense one — the property that makes
this the practical choice when H >= P and the fabric has good
all-to-all bandwidth (ICI does; SURVEY.md §2.6 maps the alltoall
family to ``jax.lax.all_to_all``).

Trade-off vs ring (documented, not hidden): head-sharding requires the
head count to be divisible by the mesh axis; peak activation memory is
O(S) per rank for the attention matrix row (flash-style blocking can
be layered inside), while ring attention keeps O(S/P) — ring for the
longest contexts, all-to-all for bandwidth-bound regimes.
"""
from __future__ import annotations

import jax.numpy as jnp

from ompi_tpu.parallel.ingraph import InGraphComm

_NEG = -1e30


def ulysses_attention(q, k, v, sp: InGraphComm, *,
                      causal: bool = True,
                      scale: float | None = None):
    """Exact full attention with the two-alltoall resharding schedule.

    Args:
      q, k, v: local sequence blocks ``(B, S_local, H, D)`` on the
        ``sp`` axis (rank i holds global positions
        [i*S_local, (i+1)*S_local)); H must be divisible by the axis
        size.
      sp: the sequence-parallel in-graph communicator (static size).
      causal: apply the global causal mask.
    Returns the local output block ``(B, S_local, H, D)``.
    """
    n = sp._size
    if n is None:
        raise ValueError("ulysses_attention needs InGraphComm(axis, "
                         "size)")
    B, S, H, D = q.shape
    if H % n:
        raise ValueError(f"head count {H} not divisible by the "
                         f"sequence axis size {n} (use ring attention)")
    if scale is None:
        scale = D ** -0.5

    def reshard_in(x):
        # (B, S/P, H, D) -> (B, S, H/P, D): scatter heads, gather seq.
        # all_to_all wants the split axis leading per-shard; axis
        # numbers are per the (B, S, H, D) layout.
        return sp.alltoall(x, split_axis=2, concat_axis=1)

    def reshard_out(x):
        # (B, S, H/P, D) -> (B, S/P, H, D): the mirror exchange.
        return sp.alltoall(x, split_axis=1, concat_axis=2)

    qg = reshard_in(q).astype(jnp.float32) * scale     # (B, S_g, h, D)
    kg = reshard_in(k).astype(jnp.float32)
    vg = reshard_in(v).astype(jnp.float32)

    s = jnp.einsum("bqhd,bkhd->bhqk", qg, kg)          # full sequence
    if causal:
        S_g = qg.shape[1]
        tri = jnp.tril(jnp.ones((S_g, S_g), jnp.bool_))[None, None]
        s = jnp.where(tri, s, _NEG)
    p = jnp.exp(s - s.max(axis=-1, keepdims=True))
    p = p / p.sum(axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, vg)           # (B, S_g, h, D)
    return reshard_out(o).astype(q.dtype)
