"""Ring attention — sequence/context parallelism over a mesh axis.

The reference's machinery for scaling one operation beyond a single
buffer is message segmentation with pipelined ring rounds and
double-buffered ring steps (SURVEY.md §5 long-context:
``coll_base_allreduce.c:351-357``, pipeline/chain bcast). Ring attention
is exactly that schedule applied to attention: each sequence-parallel
rank holds one block of Q/K/V; K/V blocks circulate around the ring
(one ``ppermute`` per step — ICI neighbor traffic only, overlapped by
XLA with the local attention compute), while a flash-style online
softmax (running max/denominator) accumulates exact results blockwise.

Causality is handled per step from the circulating block's origin index:
blocks from later positions are fully masked, the diagonal block gets
the triangular mask, earlier blocks attend fully. The result is
numerically exact full attention over the global sequence with O(S/n)
memory per rank.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ompi_tpu.parallel.ingraph import InGraphComm

_NEG = -1e30


def ring_attention(q, k, v, sp: InGraphComm, *, causal: bool = True,
                   scale: float | None = None):
    """Blockwise-exact attention with K/V ring rotation.

    Args:
      q, k, v: local blocks ``(B, S_local, H, D)`` on the ``sp`` axis
        (rank i holds global positions [i*S_local, (i+1)*S_local)).
      sp: the sequence-parallel in-graph communicator (static size).
      causal: apply the global causal mask.
    Returns the local output block ``(B, S_local, H, D)``.
    """
    n = sp._size
    if n is None:
        raise ValueError("ring_attention needs InGraphComm(axis, size)")
    B, S, H, D = q.shape
    if scale is None:
        scale = D ** -0.5
    r = sp.rank()
    q32 = q.astype(jnp.float32) * scale

    def block(acc, k_cur, v_cur, src):
        """One online-softmax update of the accumulators against the
        K/V block whose global origin is block ``src``."""
        o, m, l = acc
        s = jnp.einsum("bqhd,bkhd->bhqk", q32,
                       k_cur.astype(jnp.float32))
        if causal:
            tri = jnp.tril(jnp.ones((S, S), jnp.bool_))[None, None]
            allow = jnp.where(src < r, jnp.bool_(True),
                              jnp.where(src == r, tri, jnp.bool_(False)))
            s = jnp.where(allow, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))            # (B,H,S)
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        o_new = (o * corr[..., None]
                 + jnp.einsum("bhqk,bkhd->bhqd", p,
                              v_cur.astype(jnp.float32)))
        return (o_new, m_new, l_new)

    # Resident diagonal block first, then n-1 rotate-then-attend steps —
    # no wasted final rotation (scan bodies are not DCE'd by XLA).
    acc0 = block((jnp.zeros((B, H, S, D), jnp.float32),
                  jnp.full((B, H, S), _NEG, jnp.float32),
                  jnp.zeros((B, H, S), jnp.float32)), k, v, r)

    def step(carry, t):
        o, m, l, k_cur, v_cur = carry
        k_cur = sp.ring_shift(k_cur, 1)       # double-buffered ring step
        v_cur = sp.ring_shift(v_cur, 1)
        src = jnp.mod(r - t - 1, n)           # origin block after t+1 hops
        o, m, l = block((o, m, l), k_cur, v_cur, src)
        return (o, m, l, k_cur, v_cur), None

    (o, m, l, _, _), _ = jax.lax.scan(step, acc0 + (k, v),
                                      jnp.arange(n - 1))
    l = jnp.where(l == 0.0, 1.0, l)          # fully-masked rows (none
    o = o / l[..., None]                     # in causal ring, but safe)
    return jnp.transpose(o, (0, 2, 1, 3)).astype(q.dtype)
