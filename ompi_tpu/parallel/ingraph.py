"""In-graph communicators: MPI collective semantics *inside* a compiled
SPMD program.

This is the TPU-native analogue of the reference's §2.6 mapping
(SURVEY.md): the communication primitives that DP/TP/PP/SP/EP parallel
strategies are built from, bound to a *mesh axis* instead of a process
group. An ``InGraphComm`` is used inside ``jax.shard_map`` (or ``pjit``)
bodies; its collectives are ``lax`` collective ops that XLA schedules on
ICI — zero dispatch overhead, fusable with surrounding compute. The
controller-level ``Communicator`` (ompi_tpu.core) and this class expose
the same operation set; ``coll/xla`` is in fact implemented on these
primitives.

Reference lineage per op: ring/segmented allreduce
(``coll_base_allreduce.c:281,345``) -> psum; ring pipelines & chain bcast
(``coll_base_bcast.c``) -> ``ring_shift``/``ppermute`` schedules (the
ancestor of ring-attention / context parallelism); sub-communicators
(``comm.c:749``) -> distinct mesh axes.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ompi_tpu.core import op as op_mod


# Megatron-style f/g operators: the pair that makes tensor-parallel AD
# produce exactly-correct gradients for replicated parameters without any
# post-hoc gradient allreduce. ``copy_in`` (f) is identity forward /
# psum backward — placed where a replicated activation enters a
# tp-sharded computation. ``reduce_out`` (g) is psum forward / identity
# backward — placed on row-parallel partial outputs.
@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _megatron_f(x, axis):
    return x


def _f_fwd(x, axis):
    return x, None


def _f_bwd(axis, _res, ct):
    return (jax.lax.psum(ct, axis),)


_megatron_f.defvjp(_f_fwd, _f_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _megatron_g(x, axis):
    return jax.lax.psum(x, axis)


def _g_fwd(x, axis):
    return jax.lax.psum(x, axis), None


def _g_bwd(axis, _res, ct):
    return (ct,)


_megatron_g.defvjp(_g_fwd, _g_bwd)


class InGraphComm:
    """MPI-style collectives over one mesh axis, callable only inside a
    traced SPMD region (shard_map / pjit body) over that axis."""

    def __init__(self, axis_name: str, axis_size: Optional[int] = None):
        self.axis = axis_name
        self._size = axis_size

    # -- identity ------------------------------------------------------
    def size(self):
        if self._size is not None:
            return self._size
        return jax.lax.axis_size(self.axis)

    def rank(self):
        return jax.lax.axis_index(self.axis)

    # -- collectives ---------------------------------------------------
    def allreduce(self, x, op: op_mod.Op = op_mod.SUM):
        if op.xla_prim == "sum":
            return jax.lax.psum(x, self.axis)
        if op.xla_prim == "max":
            return jax.lax.pmax(x, self.axis)
        if op.xla_prim == "min":
            return jax.lax.pmin(x, self.axis)
        g = jax.lax.all_gather(x, self.axis, axis=0, tiled=False)
        return op.reduce_tree(g, axis=0)

    def pmean(self, x):
        return jax.lax.pmean(x, self.axis)

    def reduce(self, x, op: op_mod.Op = op_mod.SUM, root: int = 0):
        return self.allreduce(x, op)       # symmetric-ICI design choice

    def bcast(self, x, root: int = 0):
        masked = jnp.where(self.rank() == root, x, jnp.zeros_like(x))
        return jax.lax.psum(masked, self.axis)

    def allgather(self, x, *, axis: int = 0, tiled: bool = False):
        return jax.lax.all_gather(x, self.axis, axis=axis, tiled=tiled)

    def reduce_scatter(self, x, op: op_mod.Op = op_mod.SUM, *,
                       scatter_axis: int = 0):
        if op.xla_prim == "sum":
            return jax.lax.psum_scatter(x, self.axis,
                                        scatter_dimension=scatter_axis,
                                        tiled=True)
        y = self.alltoall(x, split_axis=scatter_axis,
                          concat_axis=scatter_axis)
        # fold the received contributions (now stacked along scatter_axis)
        n = self.size()
        parts = jnp.split(y, n, axis=scatter_axis) if isinstance(n, int) \
            else None
        if parts is None:
            raise ValueError("generic-op reduce_scatter needs static size")
        acc = parts[0]
        for p in parts[1:]:
            acc = op.fn(acc, p)
        return acc

    def alltoall(self, x, *, split_axis: int = 0, concat_axis: int = 0):
        return jax.lax.all_to_all(x, self.axis, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)

    # -- point-to-point patterns (pml building blocks) ----------------
    def ppermute(self, x, perm: Sequence[Tuple[int, int]]):
        return jax.lax.ppermute(x, self.axis, perm=list(perm))

    def ring_shift(self, x, shift: int = 1):
        """Shift shards around the ring: rank r's data goes to rank
        (r+shift) mod n — the primitive under ring allreduce/bcast and
        ring attention."""
        n = self._size
        if n is None:
            raise ValueError("ring_shift needs static axis_size")
        perm = [(i, (i + shift) % n) for i in range(n)]
        return jax.lax.ppermute(x, self.axis, perm=perm)

    def sendrecv(self, x, dest: int, source: int):
        """Route rank ``source``'s shard to rank ``dest`` (one edge of a
        permutation); every other rank receives ppermute's fill value
        (zeros). SPMD arguments are uniform across ranks, so per-rank
        shift patterns belong to ``ring_shift``/``ppermute`` instead."""
        return jax.lax.ppermute(x, self.axis, perm=[(source, dest)])

    # -- tensor-parallel AD operators ---------------------------------
    def copy_in(self, x):
        """Identity forward, psum backward (Megatron 'f'): use where a
        replicated activation feeds a tp-sharded computation."""
        return _megatron_f(x, self.axis)

    def reduce_out(self, x):
        """psum forward, identity backward (Megatron 'g'): use on
        row-parallel partial outputs."""
        return _megatron_g(x, self.axis)

    # -- prefix ops ----------------------------------------------------
    def scan(self, x, op: op_mod.Op = op_mod.SUM):
        g = jax.lax.all_gather(x, self.axis, axis=0, tiled=False)
        if op.name == "sum":
            pre = jnp.cumsum(g, axis=0)
        else:
            pre = jax.lax.associative_scan(op.fn, g, axis=0)
        return jax.lax.dynamic_index_in_dim(pre, self.rank(), 0,
                                            keepdims=False)
