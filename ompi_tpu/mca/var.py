"""MCA variable system — the single typed config plane.

Behavioral spec from the reference: ``opal/mca/base/mca_base_var.c``
(registration :426-514, env sourcing :304, param files :426-438) — typed,
registered variables with precedence  default < param file < environment <
programmatic/CLI, and per-variable *source tracking* so tools can report
where a value came from (``mca_base_var.h:135,291``).

TPU-era concretization: variables are named ``<framework>_<component>_<name>``
(e.g. ``coll_xla_priority``); environment overrides use
``OMPI_TPU_MCA_<framework>_<component>_<name>``; the param file is JSON at
``$OMPI_TPU_PARAM_FILE`` or ``~/.ompi_tpu/mca-params.json``.
"""
from __future__ import annotations

import json
import os
import sys
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

ENV_PREFIX = "OMPI_TPU_MCA_"
PARAM_FILE_ENV = "OMPI_TPU_PARAM_FILE"

# Source precedence, low to high (mirrors MCA_BASE_VAR_SOURCE_*).
SOURCE_DEFAULT = "default"
SOURCE_FILE = "file"
SOURCE_ENV = "env"
SOURCE_SET = "api"          # programmatic var_set / CLI

_PRECEDENCE = {SOURCE_DEFAULT: 0, SOURCE_FILE: 1, SOURCE_ENV: 2, SOURCE_SET: 3}

_COERCE: Dict[str, Callable[[Any], Any]] = {
    "int": lambda v: int(v),
    "float": lambda v: float(v),
    "bool": lambda v: (v if isinstance(v, bool)
                       else str(v).strip().lower() in ("1", "true", "yes", "on")),
    "str": lambda v: str(v),
}


@dataclass
class _Var:
    name: str                      # full "<framework>_<component>_<name>"
    vtype: str
    default: Any
    help: str = ""
    value: Any = None
    source: str = SOURCE_DEFAULT
    read_only: bool = False
    enumerator: Optional[List[Any]] = None   # allowed values, if constrained
    flags: Dict[str, Any] = field(default_factory=dict)
    site: str = ""                 # "file.py:line" of the owning register


_lock = threading.Lock()
_registry: Dict[str, _Var] = {}
_param_file_cache: Optional[Dict[str, Any]] = None


def _load_param_file() -> Dict[str, Any]:
    global _param_file_cache
    if _param_file_cache is not None:
        return _param_file_cache
    path = os.environ.get(PARAM_FILE_ENV) or os.path.expanduser(
        "~/.ompi_tpu/mca-params.json")
    data: Dict[str, Any] = {}
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict):
            data = {}
    except (OSError, ValueError):
        data = {}
    _param_file_cache = data
    return data


def _reset_param_file_cache() -> None:   # for tests
    global _param_file_cache
    _param_file_cache = None


def _caller_site() -> str:
    """``file.py:line`` of the nearest frame outside this module — the
    owner identity for the double-register policy."""
    here = os.path.abspath(__file__)
    f = sys._getframe(1)
    while f is not None and os.path.abspath(f.f_code.co_filename) == here:
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


def var_register(framework: str, component: str, name: str, *,
                 vtype: str = "str", default: Any = None, help: str = "",
                 read_only: bool = False,
                 enumerator: Optional[List[Any]] = None) -> Any:
    """Register a typed variable; resolve its value through the precedence
    chain and return the resolved value (as ``mca_base_var_register`` does
    via its out-param).

    Double-register policy (mpilint's mca_var rule checks the static
    side of the same invariant): re-registering from the SAME call site
    (the idempotent ``register_params`` idiom) or with the same
    (vtype, default) shape is a no-op returning the live value; a
    DIFFERENT site claiming the name with a conflicting vtype/default
    raises — two owners with different ideas of the default is exactly
    the silent-misconfiguration bug the registry exists to prevent."""
    global _epoch
    full = "_".join(p for p in (framework, component, name) if p)
    coerce = _COERCE[vtype]
    site = _caller_site()
    with _lock:
        if full in _registry:
            v = _registry[full]
            if v.site != site and (v.vtype != vtype
                                   or v.default != default):
                raise ValueError(
                    f"MCA var '{full}' re-registered at {site} with "
                    f"conflicting type/default ({vtype!r}, {default!r})"
                    f" — owner is {v.site} ({v.vtype!r}, {v.default!r})")
            return v.value
        _epoch += 1
        v = _Var(name=full, vtype=vtype, default=default, help=help,
                 read_only=read_only, enumerator=enumerator, site=site)
        v.value, v.source = _resolve(full, coerce, default)
        if enumerator is not None and v.value not in enumerator:
            v.value, v.source = default, SOURCE_DEFAULT
        _registry[full] = v
        return v.value


def _resolve(full: str, coerce, default):
    value, source = default, SOURCE_DEFAULT
    fdata = _load_param_file()
    if full in fdata:
        try:
            value, source = coerce(fdata[full]), SOURCE_FILE
        except (ValueError, TypeError):
            pass
    env_key = ENV_PREFIX + full
    if env_key in os.environ:
        try:
            value, source = coerce(os.environ[env_key]), SOURCE_ENV
        except (ValueError, TypeError):
            pass
    return value, source


def var_get(full: str, default: Any = None) -> Any:
    scopes = _scope_stack.get()
    if scopes:                       # innermost active scope wins
        for sc in reversed(scopes):
            if full in sc.values:
                return sc.values[full]
    with _lock:
        v = _registry.get(full)
        return v.value if v is not None else default


class VarScope:
    """A private override layer for the var store — the per-instance
    parameter state of MPI-4 Sessions (``ompi/instance/instance.c``:
    each instance bootstraps its own MCA scope). Values set here are
    visible only while the scope is active (``with scope(s): ...``) and
    never bleed into the global store or other scopes."""

    def __init__(self):
        self.values: Dict[str, Any] = {}
        self._epoch = 0              # folded into var.epoch()

    def set(self, full: str, value: Any) -> None:
        with _lock:
            v = _registry.get(full)
        if v is not None:
            value = _COERCE[v.vtype](value)
        self.values[full] = value
        self._epoch += 1             # invalidate this scope's memo keys

    def unset(self, full: str) -> None:
        if self.values.pop(full, None) is not None:
            self._epoch += 1


import contextlib as _contextlib       # noqa: E402
import contextvars as _contextvars     # noqa: E402

_scope_stack: "_contextvars.ContextVar[tuple]" = _contextvars.ContextVar(
    "ompi_tpu_var_scopes", default=())


def current_scopes() -> tuple:
    """Snapshot of the active scope stack — for deferred work (e.g.
    nonblocking-collective rounds run later by the progress engine)
    that must observe the scopes of its *creation* context."""
    return _scope_stack.get()


@_contextlib.contextmanager
def scopes_active(stack: tuple):
    """Re-establish a snapshot taken with :func:`current_scopes`."""
    tok = _scope_stack.set(stack)
    try:
        yield
    finally:
        _scope_stack.reset(tok)


@_contextlib.contextmanager
def scope(s: "VarScope"):
    """Activate a VarScope for the dynamic extent (decision layers and
    component selection read through it). Scope identity is folded into
    ``epoch()`` rather than bumping the global counter: world-communicator
    memo entries stay hot while session and world collectives interleave,
    and each scope's entries key on its own (identity, epoch)."""
    tok = _scope_stack.set(_scope_stack.get() + (s,))
    try:
        yield s
    finally:
        _scope_stack.reset(tok)


_epoch = 0


def epoch():
    """Validity token for var-derived memos: the global mutation counter
    alone when no scope is active (the common hot path — a plain int),
    else a tuple folding in each active scope's (identity, epoch) so a
    session's overrides key its own memo entries without invalidating
    the world's. Compare with ``==``; never assume int."""
    scopes = _scope_stack.get()
    if not scopes:
        return _epoch
    return (_epoch,) + tuple((id(s), s._epoch) for s in scopes)


def bump_epoch() -> None:
    """Invalidate epoch-keyed memos for a decision-input change the var
    store itself cannot observe (e.g. the tuned dynamic-rules file
    reloading on mtime change)."""
    global _epoch
    with _lock:
        _epoch += 1


def var_set(full: str, value: Any, source: str = SOURCE_SET) -> None:
    """Programmatic override (highest precedence)."""
    global _epoch
    with _lock:
        v = _registry.get(full)
        if v is None:
            raise KeyError(f"MCA var not registered: {full}")
        if v.read_only:
            raise PermissionError(f"MCA var is read-only: {full}")
        if _PRECEDENCE[source] >= _PRECEDENCE[v.source]:
            v.value = _COERCE[v.vtype](value)
            v.source = source
            _epoch += 1


def var_source(full: str) -> Optional[str]:
    with _lock:
        v = _registry.get(full)
        return v.source if v is not None else None


def var_overridden(full: str) -> bool:
    """True when a non-default value is in effect for ``full`` — an
    active session VarScope override (which var_source cannot see) OR
    a global env/file/set source. Probe-earned defaults (the staged
    tier's switch point, the bml's sm threshold) must yield to both."""
    for sc in reversed(_scope_stack.get()):
        if full in sc.values:
            return True
    return var_source(full) not in (None, SOURCE_DEFAULT)


def var_dump() -> List[Dict[str, Any]]:
    """Introspect all registered vars (``ompi_info -a`` equivalent)."""
    with _lock:
        return [
            {"name": v.name, "type": v.vtype, "value": v.value,
             "default": v.default, "source": v.source, "help": v.help,
             "site": v.site}
            for v in sorted(_registry.values(), key=lambda v: v.name)
        ]


def var_list() -> List[Dict[str, Any]]:
    """Registered vars, symmetric to ``pvar.pvar_list()`` — name plus
    the metadata tools and the analyzer cross-check (the runtime side
    of mpilint's static registry)."""
    return var_dump()


def var_names() -> List[str]:
    """Names only, symmetric to ``pvar.pvar_names()``."""
    with _lock:
        return sorted(_registry)


def _reset_for_tests() -> None:
    global _epoch
    with _lock:
        _registry.clear()
        _epoch += 1
    _reset_param_file_cache()
