"""Performance variables (pvars) — the MPI_T performance-variable
backend, mirroring ``opal/mca/base/mca_base_pvar.c``.

Pvars are read-only named counters/levels sourced from SPC counters and
component-registered callables; ``ompi_tpu.api.tool`` exposes them with
MPI_T-shaped calls, and the info tool dumps them.
"""
from __future__ import annotations

import os
import sys
import threading
from typing import Any, Callable, Dict, List

_lock = threading.Lock()
_pvars: Dict[str, Dict[str, Any]] = {}

# MPI_T pvar classes (mca_base_pvar.h's MCA_BASE_PVAR_CLASS_* set, plus
# the telemetry plane's histogram class — a pvar whose read returns the
# merged {count, sum, max, p50, p90, p99, buckets} snapshot of an
# HDR-style log2-bucket histogram, ompi_tpu/telemetry/hist.py)
CLASS_COUNTER = "counter"
CLASS_LEVEL = "level"
CLASS_HIGHWATERMARK = "highwatermark"
CLASS_HISTOGRAM = "histogram"


def _caller_site() -> str:
    """``file.py:line`` of the nearest frame outside this module — the
    owner identity for the double-register policy (register_dict's own
    frames are skipped so the dict-registration idiom keys on ITS
    caller)."""
    here = os.path.abspath(__file__)
    f = sys._getframe(1)
    while f is not None and os.path.abspath(f.f_code.co_filename) == here:
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


def pvar_register(name: str, read_fn: Callable[[], Any], *,
                  unit: str = "count", help: str = "",
                  var_class: str = "counter",
                  comm: Any = None) -> None:
    """Register (or same-site rebind) one pvar.

    Double-register policy, mirroring ``var.var_register``: the SAME
    call site rebinding a name is the supported new-endpoint idiom
    (reads must follow the newest live counter dict); a DIFFERENT site
    claiming an existing name raises — two owners silently shadowing
    each other's counters is the bug class.

    ``comm`` tags a per-communicator pvar with its owner's cid (as a
    string) so ``pvar_retire_comm`` can drop the whole session when
    that communicator is freed or replaced by a shrink — MPI_T pvar
    *session* semantics: handles bound to a dead comm stop existing,
    they don't keep reporting dead-rank-era values."""
    site = _caller_site()
    with _lock:
        v = _pvars.get(name)
        if v is not None and v.get("site") not in (None, site):
            raise ValueError(
                f"pvar '{name}' re-registered at {site} — owner is "
                f"{v['site']}")
        _pvars[name] = {"read": read_fn, "unit": unit, "help": help,
                        "class": var_class, "site": site,
                        "comm": None if comm is None else str(comm)}


def pvar_read(name: str) -> Any:
    with _lock:
        v = _pvars.get(name)
    if v is None:
        raise KeyError(f"no such pvar: {name}")
    return v["read"]()


def pvar_write(name: str, value: Any) -> None:
    """MPI_T_pvar_write: SPC-backed counters accept writes (the
    watermark/reset tool idiom); read-only pvars refuse."""
    with _lock:
        v = _pvars.get(name)
    if v is None:
        raise KeyError(f"no such pvar: {name}")
    wf = v.get("write")
    if wf is None:
        raise PermissionError(f"pvar {name} is read-only")
    wf(value)


def pvar_register_dict(prefix: str, stats: Dict[str, Any], *,
                       help_prefix: str = "") -> None:
    """Register one pvar per key of a live counter dict (the btl/bml
    stats-dict idiom): reads always reflect the dict's CURRENT values,
    so hot paths keep their plain ``dict[k] += 1`` increments and the
    MPI_T surface still observes them. Re-registration (a new endpoint
    in the same process) rebinds the names to the newest dict."""
    def make_reader(d, k):
        return lambda: d.get(k, 0)

    for key in list(stats):
        pvar_register(f"{prefix}_{key}", make_reader(stats, key),
                      help=(f"{help_prefix}{key}" if help_prefix
                            else f"{prefix} counter {key}"))


def pvar_unregister(name: str) -> bool:
    """Drop one pvar (comm teardown / subsystem reset). Returns
    whether it existed; never raises on a missing name — retirement
    races comm-free paths by design."""
    with _lock:
        return _pvars.pop(name, None) is not None


def pvar_retire_comm(cid: Any) -> List[str]:
    """Retire every pvar tagged ``comm=cid`` (string-compared): the
    per-comm pvar-session teardown called from Communicator free/shrink
    so reads after a shrink can't report dead-rank-era keys. Returns
    the retired names (tests; the callers ignore it)."""
    scid = str(cid)
    with _lock:
        names = [n for n, v in _pvars.items() if v.get("comm") == scid]
        for n in names:
            del _pvars[n]
    return sorted(names)


def pvar_list() -> List[Dict[str, Any]]:
    with _lock:
        items = list(_pvars.items())
    return [{"name": n, "unit": v["unit"], "class": v["class"],
             "help": v["help"], "value": v["read"]()}
            for n, v in sorted(items)]


def _install_spc_pvars() -> None:
    """Surface every SPC counter as a pvar (the reference surfaces its
    ~110 SPC counters as MPI_T pvars, ompi_spc.c). The membership
    check and the registration happen under ONE ``_lock`` hold:
    concurrent ``refresh()`` calls (tool thread + app thread both
    enumerating pvars) used to race the unlocked check against
    writers, re-registering entries mid-mutation."""
    from ompi_tpu.runtime import spc

    def make_reader(key):
        return lambda: spc.read(key)

    def make_writer(key):
        return lambda value: spc.write(key, int(value))

    for key in spc.snapshot():
        full = f"spc_{key}"
        with _lock:
            if full in _pvars:
                continue
            _pvars[full] = {"read": make_reader(key), "unit": "count",
                            "help": f"SPC counter {key}",
                            "class": "counter",
                            "write": make_writer(key)}


def refresh() -> None:
    _install_spc_pvars()


def pvar_names() -> List[str]:
    """Names only — enumeration must not invoke every counter's read
    closure (the MPI_T index paths call this on hot tool loops)."""
    with _lock:
        return sorted(_pvars)


def pvar_info(name: str) -> Dict[str, Any]:
    """One pvar's metadata WITHOUT reading its value."""
    with _lock:
        v = _pvars.get(name)
    if v is None:
        raise KeyError(f"no such pvar: {name}")
    return {"name": name, "unit": v["unit"], "class": v["class"],
            "help": v["help"]}
