"""MCA — Modular Component Architecture machinery, re-designed in Python.

Mirrors the reference's load-bearing pattern (``opal/mca/base``): a
*framework* is a fixed interface, a *component* an implementation that can
be queried for a priority, a *module* a per-communicator instance.
"""
from ompi_tpu.mca.base import Framework, Component, register_framework, get_framework  # noqa: F401
from ompi_tpu.mca.var import var_register, var_get, var_set, var_dump, var_source  # noqa: F401
