"""Framework/component/module machinery with priority selection.

Mirrors the boundary (not the DSO machinery) of the reference's MCA:
framework open/close (``opal/mca/base/mca_base_framework.c``), component
discovery (``mca_base_component_find.c``) and priority-sorted selection at
communicator scope (``ompi/mca/coll/base/coll_base_comm_select.c:234-273``,
sort :353-360).

A component implements ``comm_query(comm) -> (priority, module)|None``.
Selection queries every registered component, keeps priority >= 0, sorts
descending, and lets the caller compose winners (coll composes a
per-function vtable, taking the highest-priority provider per function).

Components can be disabled/forced via the MCA var
``<framework>_base_include`` (comma list, empty = all), mirroring the
reference's ``--mca coll basic,tuned`` selection syntax.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from ompi_tpu.mca import var


class Component:
    """Base class for components. Subclasses set ``name`` and implement
    ``comm_query``."""

    name: str = "base"
    framework: str = ""

    def register_params(self) -> None:
        """Called once at framework open; register MCA vars here."""

    def comm_query(self, comm) -> Optional[Tuple[int, Any]]:
        """Return (priority, module) if this component can serve ``comm``,
        else None. Priority < 0 also means 'not me'."""
        raise NotImplementedError


class Framework:
    def __init__(self, name: str):
        self.name = name
        self.components: Dict[str, Component] = {}
        self._opened = False

    def register(self, component: Component) -> Component:
        component.framework = self.name
        self.components[component.name] = component
        return component

    def open(self) -> None:
        if self._opened:
            return
        var.var_register(self.name, "base", "include", vtype="str", default="",
                         help=f"Comma list of {self.name} components to allow "
                              "(empty = all)")
        var.var_register(self.name, "base", "verbose", vtype="int", default=0,
                         help=f"Verbosity for the {self.name} framework")
        for c in self.components.values():
            c.register_params()
        self._opened = True

    def _allowed(self) -> List[Component]:
        include = var.var_get(f"{self.name}_base_include", "") or ""
        names = [n.strip() for n in include.split(",") if n.strip()]
        if not names:
            return list(self.components.values())
        return [c for n, c in self.components.items() if n in names]

    def comm_select(self, comm) -> List[Tuple[int, Component, Any]]:
        """Query all allowed components for ``comm``; return
        [(priority, component, module)] sorted by descending priority.
        Mirrors coll_base_comm_select.c:234-273 (+ sort at :353-360)."""
        self.open()
        avail: List[Tuple[int, Component, Any]] = []
        for c in self._allowed():
            res = c.comm_query(comm)
            if res is None:
                continue
            prio, module = res
            if prio < 0:
                continue
            avail.append((prio, c, module))
        # Stable sort, descending priority; tie-break on component name so
        # selection is deterministic across ranks (the reference relies on
        # identical sort order on every rank for correctness).
        avail.sort(key=lambda t: (-t[0], t[1].name))
        return avail


_frameworks: Dict[str, Framework] = {}


def register_framework(name: str) -> Framework:
    fw = _frameworks.get(name)
    if fw is None:
        fw = Framework(name)
        _frameworks[name] = fw
    return fw


def get_framework(name: str) -> Framework:
    return _frameworks[name]


def all_frameworks() -> Dict[str, Framework]:
    return dict(_frameworks)
