from ompi_tpu.accelerator.framework import (  # noqa: F401
    LOCUS_DEVICE, LOCUS_HOST, check_addr, to_device, to_host, accel_framework,
)
