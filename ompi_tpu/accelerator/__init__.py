from ompi_tpu.accelerator.framework import (  # noqa: F401
    LOCUS_DEVICE, LOCUS_HOST, Event, Stream, accel_framework, check_addr,
    current_module, to_device, to_host,
)
