"""Accelerator framework — the device-memory abstraction.

Behavioral spec: ``opal/mca/accelerator/accelerator.h`` — ``check_addr``
:176 (is this buffer device memory?), async memcpy :280, streams/events
:189-258, device alloc :364. The CUDA component detects device pointers
via ``cuPointerGetAttributes`` (``accelerator_cuda.c:304-360``).

TPU-native re-design: there are no raw pointers. A buffer *is* either a
``jax.Array`` (device-resident: HBM shards committed to mesh devices) or a
NumPy array (host). ``check_addr`` is a type/placement test; staging is
``jax.device_put`` / ``np.asarray``; events collapse into JAX's async
dispatch (``block_until_ready``). Components: ``tpu`` (live PJRT
backend), ``null`` (host-only, mirrors ``accelerator/null``).
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import numpy as np

from ompi_tpu.mca.base import Component, register_framework

LOCUS_DEVICE = "device"
LOCUS_HOST = "host"

accel_framework = register_framework("accelerator")


def device_locality(device) -> Tuple[int, Tuple[int, ...]]:
    """(process_index, physical coords) of a device — the one place the
    JAX device-attribute extraction lives (affinity strings, treematch
    distances, device inventories all read through here)."""
    proc = int(getattr(device, "process_index", 0) or 0)
    coords = tuple(getattr(device, "coords", ()) or ())
    return proc, coords


def device_attrs(device) -> dict:
    """Fabric-position record for a device (the get_device_pci_attr
    analogue: mesh coordinates instead of a PCI BDF)."""
    proc, coords = device_locality(device)
    return {
        "id": int(device.id),
        "platform": str(device.platform),
        "process_index": proc,
        "coords": coords,
        "kind": str(getattr(device, "device_kind", "")),
    }


class Stream:
    """An ordered work queue (``accelerator.h:189-226`` streams).

    JAX orders operations per device automatically; what a stream adds
    is a *join point*: arrays enqueued on the stream are synchronized
    together, and ``sync`` drains in enqueue order — the semantics the
    reference's ``wait_event``/``synchronize`` pair provides."""

    def __init__(self):
        self._work: list = []

    def enqueue(self, arrays) -> None:
        self._work.append(arrays)

    def sync(self) -> None:
        if self._work:
            jax.block_until_ready(self._work)
            self._work.clear()

    @property
    def depth(self) -> int:
        return len(self._work)


class Event:
    """Completion marker (``accelerator.h:227-258``): ``record`` captures
    the arrays in flight; ``query`` polls; ``synchronize`` blocks."""

    def __init__(self):
        self._arrays: Any = None

    def record(self, arrays_or_stream) -> None:
        if isinstance(arrays_or_stream, Stream):
            self._arrays = list(arrays_or_stream._work)
        else:
            self._arrays = arrays_or_stream

    def query(self) -> bool:
        if self._arrays is None:
            return True
        from ompi_tpu.core.request import _is_ready
        leaves = [a for a in jax.tree_util.tree_leaves(self._arrays)]
        return all(_is_ready(a) for a in leaves)

    def synchronize(self) -> None:
        if self._arrays is not None:
            jax.block_until_ready(self._arrays)
            self._arrays = None


class TpuAccelComponent(Component):
    """Live PJRT-backed device memory (peer of accelerator/cuda|rocm|ze)."""

    name = "tpu"

    def __init__(self):
        self._ipc: dict = {}          # handle -> buffer (IPC registry)
        self._ipc_next = 1
        self._pinned: dict = {}       # id(buf) -> buf (host_register)

    def comm_query(self, comm):
        return (50, self)

    def check_addr(self, buf: Any) -> Optional[str]:
        if isinstance(buf, jax.Array):
            return LOCUS_DEVICE
        if isinstance(buf, (np.ndarray, np.generic)):
            return LOCUS_HOST
        return None

    def mem_copy_h2d(self, host_buf, sharding=None):
        return jax.device_put(np.asarray(host_buf), sharding)

    def mem_copy_d2h(self, dev_buf):
        return np.asarray(dev_buf)

    def mem_copy_d2h_async(self, dev_buf):
        """Begin the device-to-host copy WITHOUT forcing completion
        (the async memcpy of ``accelerator.h:280``): the caller
        finishes it later with ``mem_copy_d2h``. Backed by
        ``jax.Array.copy_to_host_async`` where the runtime offers it;
        degrades to a no-op start elsewhere — correctness never
        depends on the copy actually being in flight."""
        start = getattr(dev_buf, "copy_to_host_async", None)
        if start is not None:
            try:
                start()
            except Exception:            # noqa: BLE001 — deleted /
                pass                     # donated buffer: sync path
        return dev_buf

    # -- alloc (accelerator.h:364) -------------------------------------
    def mem_alloc(self, shape, dtype=np.float32, device=None):
        z = jax.numpy.zeros(shape, dtype)
        return jax.device_put(z, device) if device is not None else z

    # -- streams & events (accelerator.h:189-258) ----------------------
    def create_stream(self) -> Stream:
        return Stream()

    def create_event(self) -> Event:
        return Event()

    def event_synchronize(self, bufs):
        jax.block_until_ready(bufs)

    # -- IPC handles (accelerator.h:460-561) ---------------------------
    # The reference exports a device allocation to another process; the
    # single-controller analogue is an opaque handle another subsystem
    # (or spawned child world) can open without holding the array.
    def get_ipc_handle(self, buf) -> int:
        h = self._ipc_next
        self._ipc_next += 1
        self._ipc[h] = buf
        return h

    def open_ipc_handle(self, handle: int):
        buf = self._ipc.get(handle)
        if buf is None:
            raise KeyError(f"unknown IPC handle {handle}")
        return buf

    def close_ipc_handle(self, handle: int) -> None:
        self._ipc.pop(handle, None)

    # -- host registration (accelerator.h:574) -------------------------
    def host_register(self, buf: np.ndarray) -> None:
        """Pin a host buffer: kept referenced (no GC mid-transfer) and
        marked read-only to catch mutation during async use — the
        honest analogue of page pinning. The pre-registration
        writeability is restored at unregister."""
        entry = self._pinned.get(id(buf))
        if entry is not None:          # re-register: refcount only
            self._pinned[id(buf)] = (buf, entry[1], entry[2] + 1)
            return
        was_writeable = bool(buf.flags.writeable)
        if was_writeable:
            buf.flags.writeable = False
        self._pinned[id(buf)] = (buf, was_writeable, 1)

    def host_unregister(self, buf: np.ndarray) -> None:
        entry = self._pinned.get(id(buf))
        if entry is None:
            return
        if entry[2] > 1:               # matched register/unregister pairs
            self._pinned[id(buf)] = (buf, entry[1], entry[2] - 1)
            return
        del self._pinned[id(buf)]
        if entry[1]:
            buf.flags.writeable = True

    def is_host_registered(self, buf: np.ndarray) -> bool:
        return id(buf) in self._pinned

    # -- device info (accelerator.h:598-657) ---------------------------
    def get_device_info(self) -> Tuple[str, int]:
        devs = jax.devices()
        return (devs[0].platform, len(devs))

    def get_device_attributes(self, device) -> dict:
        attrs = device_attrs(device)
        attrs["memory_stats"] = (device.memory_stats()
                                 if hasattr(device, "memory_stats")
                                 else None)
        return attrs

    def device_can_access_peer(self, dev_a, dev_b) -> bool:
        """Same fabric = peer-accessible (ICI); cross-process pairs go
        through DCN (the reference returns false for non-peer PCIe)."""
        return device_locality(dev_a)[0] == device_locality(dev_b)[0]


class NullAccelComponent(TpuAccelComponent):
    """Host-only component (mirrors accelerator/null): every buffer is
    host memory, device copies degrade to numpy, and the rest of the
    surface (streams/events/IPC/register/attrs) is the trivial host
    implementation — accelerator/null implements the full API too."""

    name = "null"

    def comm_query(self, comm):
        return (0, self)

    def check_addr(self, buf: Any) -> Optional[str]:
        if isinstance(buf, (np.ndarray, np.generic, jax.Array)):
            return LOCUS_HOST
        return None

    def mem_copy_h2d(self, host_buf, sharding=None):
        return np.asarray(host_buf)

    def mem_copy_d2h(self, dev_buf):
        return np.asarray(dev_buf)

    def mem_alloc(self, shape, dtype=np.float32, device=None):
        return np.zeros(shape, dtype)

    def event_synchronize(self, bufs):
        pass


accel_framework.register(TpuAccelComponent())
accel_framework.register(NullAccelComponent())

_module: Optional[Component] = None


def _mod() -> Component:
    global _module
    if _module is None:
        accel_framework.open()
        sel = accel_framework.comm_select(None)
        _module = sel[0][2]
    return _module


def current_module() -> Component:
    """The selected accelerator module (framework-level accessor for
    streams/events/IPC/host-register/device-attr operations)."""
    return _mod()


def check_addr(buf: Any) -> Optional[str]:
    """Locus of a buffer: LOCUS_DEVICE, LOCUS_HOST, or None (not a
    buffer). The re-designed ``accelerator.check_addr`` (:176)."""
    return _mod().check_addr(buf)


def to_device(buf: Any, sharding=None):
    return _mod().mem_copy_h2d(buf, sharding)


def to_host(buf: Any):
    return _mod().mem_copy_d2h(buf)


def to_host_async(buf: Any):
    """Start a D2H copy (returns the in-flight buffer); finish with
    ``to_host``. The double-buffering primitive behind
    ``btl/devxfer.SegmentStager``."""
    return _mod().mem_copy_d2h_async(buf)


def _reset_for_tests():
    global _module
    _module = None
