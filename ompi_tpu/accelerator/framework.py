"""Accelerator framework — the device-memory abstraction.

Behavioral spec: ``opal/mca/accelerator/accelerator.h`` — ``check_addr``
:176 (is this buffer device memory?), async memcpy :280, streams/events
:189-258, device alloc :364. The CUDA component detects device pointers
via ``cuPointerGetAttributes`` (``accelerator_cuda.c:304-360``).

TPU-native re-design: there are no raw pointers. A buffer *is* either a
``jax.Array`` (device-resident: HBM shards committed to mesh devices) or a
NumPy array (host). ``check_addr`` is a type/placement test; staging is
``jax.device_put`` / ``np.asarray``; events collapse into JAX's async
dispatch (``block_until_ready``). Components: ``tpu`` (live PJRT
backend), ``null`` (host-only, mirrors ``accelerator/null``).
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import numpy as np

from ompi_tpu.mca.base import Component, register_framework

LOCUS_DEVICE = "device"
LOCUS_HOST = "host"

accel_framework = register_framework("accelerator")


class TpuAccelComponent(Component):
    """Live PJRT-backed device memory (peer of accelerator/cuda|rocm|ze)."""

    name = "tpu"

    def comm_query(self, comm):
        return (50, self)

    def check_addr(self, buf: Any) -> Optional[str]:
        if isinstance(buf, jax.Array):
            return LOCUS_DEVICE
        if isinstance(buf, (np.ndarray, np.generic)):
            return LOCUS_HOST
        return None

    def mem_copy_h2d(self, host_buf, sharding=None):
        return jax.device_put(np.asarray(host_buf), sharding)

    def mem_copy_d2h(self, dev_buf):
        return np.asarray(dev_buf)

    def event_synchronize(self, bufs):
        jax.block_until_ready(bufs)

    def get_device_info(self) -> Tuple[str, int]:
        devs = jax.devices()
        return (devs[0].platform, len(devs))


class NullAccelComponent(Component):
    """Host-only component (mirrors accelerator/null): every buffer is
    host memory; device copies degrade to numpy."""

    name = "null"

    def comm_query(self, comm):
        return (0, self)

    def check_addr(self, buf: Any) -> Optional[str]:
        if isinstance(buf, (np.ndarray, np.generic, jax.Array)):
            return LOCUS_HOST
        return None

    def mem_copy_h2d(self, host_buf, sharding=None):
        return np.asarray(host_buf)

    def mem_copy_d2h(self, dev_buf):
        return np.asarray(dev_buf)

    def event_synchronize(self, bufs):
        pass


accel_framework.register(TpuAccelComponent())
accel_framework.register(NullAccelComponent())

_module: Optional[Component] = None


def _mod() -> Component:
    global _module
    if _module is None:
        accel_framework.open()
        sel = accel_framework.comm_select(None)
        _module = sel[0][2]
    return _module


def check_addr(buf: Any) -> Optional[str]:
    """Locus of a buffer: LOCUS_DEVICE, LOCUS_HOST, or None (not a
    buffer). The re-designed ``accelerator.check_addr`` (:176)."""
    return _mod().check_addr(buf)


def to_device(buf: Any, sharding=None):
    return _mod().mem_copy_h2d(buf, sharding)


def to_host(buf: Any):
    return _mod().mem_copy_d2h(buf)


def _reset_for_tests():
    global _module
    _module = None
