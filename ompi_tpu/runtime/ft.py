"""Failure detection and propagation — the ULFM runtime plane.

Behavioral spec: the reference's ULFM support (``docs/features/ulfm.rst``)
detects process failure through PMIx/PRRTE events and propagates it to
every layer: requests complete with ``MPI_ERR_PROC_FAILED``
(``ompi/request/req_ft.c``), collectives bail out, revocation spreads via
a reliable broadcast (``ompi/mca/coll/base/coll_base_revoke_local.c``),
and the pml exposes a ``revoke_comm`` hook (``ompi/mca/pml/pml.h:244``).

TPU-native re-design: the "process" is a rank bound to a device on the
controller's mesh. Failure events come from two sources — a device health
probe (a failed chip surfaces as an XLA execution error) and explicit
injection (the fault-injection entry the reference lacks; here it is the
test surface). A :class:`Registry` is the source of truth a stack
consults: communicator collectives, the pt2pt matching engine, and the
ftagree component all read their communicator's registry. The
module-level functions operate on the process-wide default registry (the
World Process Model); MPI-4 Sessions own private registries
(``instance.c:361-720`` — per-instance state), so failure knowledge
injected in one session never bleeds into another. Epochs order failure
knowledge the way PMIx event sequence numbers do.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, FrozenSet, List, NamedTuple


class FailureEvent(NamedTuple):
    """One epoch-ordered failure record (the PMIx event payload shape:
    who, why, when, and the sequence number ordering the knowledge)."""
    rank: int
    reason: str
    epoch: int
    timestamp: float


class Registry:
    """One failure-knowledge domain (per instance/session)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._failed: Dict[int, str] = {}      # world rank -> reason
        self._epoch = 0
        self._events: List[FailureEvent] = []
        self._listeners: List[Callable[[int, str], None]] = []
        # last failure-detection latency in microseconds (written by the
        # heartbeat detector, read by the ft_detect_latency_us pvar)
        self.detect_latency_us = 0

    def fail_rank(self, world_rank: int, reason: str = "injected") -> None:
        """Report rank failure (detector ingress + fault injection)."""
        with self._lock:
            if world_rank in self._failed:
                return
            self._failed[world_rank] = reason
            self._epoch += 1
            self._events.append(FailureEvent(world_rank, reason,
                                             self._epoch, time.time()))
            listeners = list(self._listeners)
        for cb in listeners:
            cb(world_rank, reason)

    def any_failed(self) -> bool:
        """Fast-path check for the per-call FT guards (hot path: every
        collective entry)."""
        return bool(self._failed)

    def is_failed(self, world_rank: int) -> bool:
        return world_rank in self._failed

    def failed_ranks(self) -> FrozenSet[int]:
        with self._lock:
            return frozenset(self._failed)

    def failure_reason(self, world_rank: int) -> str:
        return self._failed.get(world_rank, "")

    def epoch(self) -> int:
        return self._epoch

    def events(self) -> List[FailureEvent]:
        """Epoch-ordered failure history (MPIX get_failed's ordering
        contract: later knowledge never reorders earlier events)."""
        with self._lock:
            return list(self._events)

    def add_listener(self, cb: Callable[[int, str], None]) -> None:
        """Register a failure-event callback (the PMIx event-handler
        role)."""
        with self._lock:
            self._listeners.append(cb)

    def remove_listener(self, cb: Callable[[int, str], None]) -> None:
        """Deregister (router/detector teardown — a listener surviving
        its owner would fire into a closed object on the next event)."""
        with self._lock:
            if cb in self._listeners:
                self._listeners.remove(cb)

    def probe_devices(self, devices, world_ranks=None) -> List[int]:
        """Health-check each rank's device with a trivial computation;
        mark ranks whose device errors as failed. Returns newly failed
        *world* ranks. ``world_ranks[i]`` is the world rank owning
        ``devices[i]`` (identity when omitted — correct only for
        COMM_WORLD-shaped device lists). (The active side of the
        detector; in the reference the PRRTE daemon notices a dead
        process and PMIx fans the event out.)"""
        import jax
        import numpy as np
        if world_ranks is None:
            world_ranks = range(len(devices))
        newly = []
        for w, d in zip(world_ranks, devices):
            if self.is_failed(w):
                continue
            try:
                x = jax.device_put(np.ones((1,), np.float32), d)
                float(np.asarray(x)[0])
            except Exception as e:      # noqa: BLE001 — any device error
                self.fail_rank(w, f"device probe: {type(e).__name__}")
                newly.append(w)
        return newly

    def _reset(self) -> None:
        with self._lock:
            self._failed.clear()
            self._listeners.clear()
            self._events.clear()
            self._epoch = 0
            self.detect_latency_us = 0


# -- process-wide default domain (World Process Model) ---------------------
_default = Registry()


def default_registry() -> Registry:
    return _default


def fail_rank(world_rank: int, reason: str = "injected") -> None:
    _default.fail_rank(world_rank, reason)


def any_failed() -> bool:
    return _default.any_failed()


def is_failed(world_rank: int) -> bool:
    return _default.is_failed(world_rank)


def failed_ranks() -> FrozenSet[int]:
    return _default.failed_ranks()


def failure_reason(world_rank: int) -> str:
    return _default.failure_reason(world_rank)


def epoch() -> int:
    return _default.epoch()


def add_listener(cb: Callable[[int, str], None]) -> None:
    _default.add_listener(cb)


def remove_listener(cb: Callable[[int, str], None]) -> None:
    _default.remove_listener(cb)


def events() -> List[FailureEvent]:
    return _default.events()


def probe_devices(devices, world_ranks=None) -> List[int]:
    return _default.probe_devices(devices, world_ranks)


def _reset_for_tests() -> None:
    _default._reset()
