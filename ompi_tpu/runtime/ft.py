"""Failure detection and propagation — the ULFM runtime plane.

Behavioral spec: the reference's ULFM support (``docs/features/ulfm.rst``)
detects process failure through PMIx/PRRTE events and propagates it to
every layer: requests complete with ``MPI_ERR_PROC_FAILED``
(``ompi/request/req_ft.c``), collectives bail out, revocation spreads via
a reliable broadcast (``ompi/mca/coll/base/coll_base_revoke_local.c``),
and the pml exposes a ``revoke_comm`` hook (``ompi/mca/pml/pml.h:244``).

TPU-native re-design: the "process" is a rank bound to a device on the
controller's mesh. Failure events come from two sources — a device health
probe (a failed chip surfaces as an XLA execution error) and explicit
injection (the fault-injection entry the reference lacks; here it is the
test surface). A :class:`Registry` is the source of truth a stack
consults: communicator collectives, the pt2pt matching engine, and the
ftagree component all read their communicator's registry. The
module-level functions operate on the process-wide default registry (the
World Process Model); MPI-4 Sessions own private registries
(``instance.c:361-720`` — per-instance state), so failure knowledge
injected in one session never bleeds into another. Epochs order failure
knowledge the way PMIx event sequence numbers do.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, FrozenSet, List


class Registry:
    """One failure-knowledge domain (per instance/session)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._failed: Dict[int, str] = {}      # world rank -> reason
        self._epoch = 0
        self._listeners: List[Callable[[int, str], None]] = []

    def fail_rank(self, world_rank: int, reason: str = "injected") -> None:
        """Report rank failure (detector ingress + fault injection)."""
        with self._lock:
            if world_rank in self._failed:
                return
            self._failed[world_rank] = reason
            self._epoch += 1
            listeners = list(self._listeners)
        for cb in listeners:
            cb(world_rank, reason)

    def any_failed(self) -> bool:
        """Fast-path check for the per-call FT guards (hot path: every
        collective entry)."""
        return bool(self._failed)

    def is_failed(self, world_rank: int) -> bool:
        return world_rank in self._failed

    def failed_ranks(self) -> FrozenSet[int]:
        with self._lock:
            return frozenset(self._failed)

    def failure_reason(self, world_rank: int) -> str:
        return self._failed.get(world_rank, "")

    def epoch(self) -> int:
        return self._epoch

    def add_listener(self, cb: Callable[[int, str], None]) -> None:
        """Register a failure-event callback (the PMIx event-handler
        role)."""
        with self._lock:
            self._listeners.append(cb)

    def probe_devices(self, devices, world_ranks=None) -> List[int]:
        """Health-check each rank's device with a trivial computation;
        mark ranks whose device errors as failed. Returns newly failed
        *world* ranks. ``world_ranks[i]`` is the world rank owning
        ``devices[i]`` (identity when omitted — correct only for
        COMM_WORLD-shaped device lists). (The active side of the
        detector; in the reference the PRRTE daemon notices a dead
        process and PMIx fans the event out.)"""
        import jax
        import numpy as np
        if world_ranks is None:
            world_ranks = range(len(devices))
        newly = []
        for w, d in zip(world_ranks, devices):
            if self.is_failed(w):
                continue
            try:
                x = jax.device_put(np.ones((1,), np.float32), d)
                float(np.asarray(x)[0])
            except Exception as e:      # noqa: BLE001 — any device error
                self.fail_rank(w, f"device probe: {type(e).__name__}")
                newly.append(w)
        return newly

    def _reset(self) -> None:
        with self._lock:
            self._failed.clear()
            self._listeners.clear()
            self._epoch = 0


# -- process-wide default domain (World Process Model) ---------------------
_default = Registry()


def default_registry() -> Registry:
    return _default


def fail_rank(world_rank: int, reason: str = "injected") -> None:
    _default.fail_rank(world_rank, reason)


def any_failed() -> bool:
    return _default.any_failed()


def is_failed(world_rank: int) -> bool:
    return _default.is_failed(world_rank)


def failed_ranks() -> FrozenSet[int]:
    return _default.failed_ranks()


def failure_reason(world_rank: int) -> str:
    return _default.failure_reason(world_rank)


def epoch() -> int:
    return _default.epoch()


def add_listener(cb: Callable[[int, str], None]) -> None:
    _default.add_listener(cb)


def probe_devices(devices, world_ranks=None) -> List[int]:
    return _default.probe_devices(devices, world_ranks)


def _reset_for_tests() -> None:
    _default._reset()
