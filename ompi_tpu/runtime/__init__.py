"""Runtime: init/finalize, world binding, SPC counters, progress."""
