"""MPI-4 Sessions — mirrors ``ompi/instance`` (``ompi_instance_t``,
refcounted bring-up, ``instance.c:825`` / common path ``:361-720``).

A Session is an independent handle onto the runtime: it exposes process
sets ("mpi://WORLD", "mpi://SELF", plus one pset per mesh axis group the
runtime knows), builds Groups from psets, and creates communicators from
groups without touching COMM_WORLD — the World Process Model
(``Init``/``Finalize``) is layered on top of this, as in the reference.
"""
from __future__ import annotations

from typing import List, Optional

from ompi_tpu.core.communicator import Communicator
from ompi_tpu.core.errhandler import ERR_ARG, MPIError
from ompi_tpu.core.group import Group
from ompi_tpu.core.info import Info

_session_count = 0


class Session:
    def __init__(self, info: Optional[Info] = None):
        global _session_count
        import jax
        self.info = info or Info()
        self.devices = list(jax.devices())
        self._finalized = False
        _session_count += 1
        self._psets = {
            "mpi://WORLD": list(range(len(self.devices))),
            "mpi://SELF": [0],
        }

    # -- pset enumeration ----------------------------------------------
    def get_num_psets(self) -> int:
        return len(self._psets)

    def get_nth_pset(self, n: int) -> str:
        return list(self._psets.keys())[n]

    def get_pset_info(self, name: str) -> Info:
        if name not in self._psets:
            raise MPIError(ERR_ARG, f"unknown pset {name}")
        i = Info()
        i.set("size", str(len(self._psets[name])))
        return i

    # -- group / communicator construction -----------------------------
    def group_from_pset(self, name: str) -> Group:
        if name not in self._psets:
            raise MPIError(ERR_ARG, f"unknown pset {name}")
        return Group(self._psets[name])

    def comm_create_from_group(self, group: Group,
                               tag: str = "",
                               info: Optional[Info] = None) -> Communicator:
        devs = [self.devices[r] for r in group.world_ranks]
        return Communicator(group, devs,
                            name=tag or f"session_comm", info=info)

    def finalize(self) -> None:
        self._finalized = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.finalize()
        return False
