"""MPI-4 Sessions — mirrors ``ompi/instance`` (``ompi_instance_t``,
refcounted bring-up, ``instance.c:825`` / common path ``:361-720``).

A Session is an independent handle onto the runtime: it exposes process
sets ("mpi://WORLD", "mpi://SELF", plus one per shared-memory domain),
builds Groups from psets, and creates communicators from groups without
touching COMM_WORLD — the World Process Model (``Init``/``Finalize``) is
layered on top of this, as in the reference.

Round-3 isolation (VERDICT missing #4 — the 70-LoC enumerator shared
every piece of global state): each Session now owns, per
``instance.c:361-720``'s per-instance bootstrap,

- a private **MCA var scope** (:class:`ompi_tpu.mca.var.VarScope`):
  ``session.var_set`` overrides are visible only inside this session's
  communicator creation and collective dispatch — two concurrent
  sessions can select different coll components/algorithms without
  bleeding into each other or the global store;
- a private **CID space**: session communicators draw from the
  session's counter (the reference allocates CIDs within the instance's
  communicator namespace, ``comm_cid.c``);
- a private **failure registry** (:class:`ompi_tpu.runtime.ft.Registry`):
  failures injected/observed in one session never poison another's
  collectives;
- a refcount on the shared runtime bring-up (``instance.c:825``
  ``ompi_mpi_instance_retain``), released at ``finalize``.
"""
from __future__ import annotations

import itertools
import os
import threading
from typing import Any, Dict, List, Optional

from ompi_tpu.core.communicator import Communicator
from ompi_tpu.core.errhandler import ERR_ARG, ERR_OTHER, MPIError
from ompi_tpu.core.group import Group
from ompi_tpu.core.info import Info
from ompi_tpu.mca import var
from ompi_tpu.runtime import ft

_instance_lock = threading.Lock()
_instance_refcount = 0

# Per-rank comm_create_from_group call ordinals, keyed (tag, group):
# process-global (NOT per-session) because the CID they feed must
# agree across processes regardless of how many local Session objects
# exist. SPMD collective-call order keeps the counters aligned.
_pr_seq_lock = threading.Lock()
_pr_create_seq: Dict[Any, int] = {}


def _instance_retain() -> None:
    global _instance_refcount
    with _instance_lock:
        _instance_refcount += 1


def _instance_release() -> None:
    global _instance_refcount
    with _instance_lock:
        _instance_refcount = max(0, _instance_refcount - 1)


def instance_refcount() -> int:
    return _instance_refcount


class SessionCommunicator(Communicator):
    """A communicator owned by a Session: every public operation runs
    inside the session's var scope (so decision layers and component
    selection read the session's overrides), draws CIDs from the
    session's space, and consults the session's failure registry.
    Children (split/dup/cart/...) inherit all of it through ``parent``."""

    def __init__(self, group, devices, *, session: "Session" = None,
                 parent: Optional[Communicator] = None, **kw):
        sess = session or getattr(parent, "_session", None)
        if sess is None:
            raise MPIError(ERR_ARG,
                           "SessionCommunicator needs a session or a "
                           "session-owned parent")
        self._session = sess
        with var.scope(sess.scope):
            super().__init__(group, devices, parent=parent, **kw)
        self._ft = sess.ft_registry
        # every session communicator — including dup/split/cart/shrink
        # children — registers with its instance so finalize quiesces
        # all of them (instance.c: instance teardown frees its comms)
        sess._comms.append(self)

    def _alloc_cid(self) -> int:
        # set before super().__init__ runs (attribute assignment order
        # in __init__), so the session is always bound here
        return self._session._next_cid()


def _scoped(name: str):
    base = getattr(Communicator, name)

    def wrapper(self, *args, **kw):
        with var.scope(self._session.scope):
            return base(self, *args, **kw)
    wrapper.__name__ = name
    wrapper.__doc__ = base.__doc__
    return wrapper


# Public operations whose behavior can depend on MCA vars (algorithm
# decisions, staging thresholds, schedule knobs, component priorities in
# child-communicator creation).
for _name in ("allreduce", "reduce", "bcast", "allgather", "gather",
              "scatter", "gather_root", "scatter_root", "alltoall",
              "reduce_scatter_block", "reduce_scatter", "scan", "exscan",
              "barrier", "allgatherv", "gatherv", "scatterv", "alltoallv",
              "alltoallw", "iallreduce", "ibcast", "ireduce",
              "iallgather", "igather", "iscatter", "ialltoall",
              "ibarrier", "dup", "split", "split_type", "create",
              "create_cart", "create_graph", "shrink",
              "allreduce_bind", "allreduce_init", "bcast_init"):
    setattr(SessionCommunicator, _name, _scoped(_name))


_session_names = itertools.count(0)


class Session:
    def __init__(self, info: Optional[Info] = None,
                 errhandler=None):
        # the Init-free tier (MPI-4 Sessions) touches the backend
        # first here — same sitecustomize defense as world init
        from ompi_tpu.runtime.init import assert_platform_pin
        assert_platform_pin()
        import jax
        self.info = info or Info()
        self.errhandler = errhandler
        self.devices = list(jax.devices())
        self._finalized = False
        self.name = f"session#{next(_session_names)}"
        # -- per-instance state (instance.c:361-720) -------------------
        self.scope = var.VarScope()
        self.ft_registry = ft.Registry()
        self._cids = itertools.count(0)
        self._cid_lock = threading.Lock()
        self._comms: List[Communicator] = []
        _instance_retain()
        # Per-rank world (one OS process == one rank): psets enumerate
        # PROCESSES, and session communicators are RankCommunicators
        # drawing CIDs from this session's private space. The router
        # (endpoints, modex) is the shared instance state the refcount
        # guards — exactly the reference's instance-owned RTE.
        from ompi_tpu.runtime import init as _rt
        self._router = _rt._state.get("router")
        if self._router is None and os.environ.get(
                "OMPI_TPU_MCA_mpi_base_per_rank"):
            # A per-rank process without a live router: falling back
            # to the device-pset path would build in-process comms
            # whose "collectives" silently see only local data. The
            # full Init-free instance bootstrap is not implemented —
            # fail loudly instead of wrong answers.
            raise MPIError(ERR_OTHER,
                           "Session in a per-rank job requires the "
                           "runtime to be up (call Init first; "
                           "Init-free session bootstrap is not yet "
                           "supported)")
        if self._router is not None:
            import jax as _jax
            n = _jax.process_count()
            self._my_world = _jax.process_index()
            self._psets: Dict[str, List[int]] = {
                "mpi://WORLD": list(range(n)),
                "mpi://SELF": [self._my_world],
            }
            return
        self._my_world = None
        self._psets = {
            "mpi://WORLD": list(range(len(self.devices))),
            "mpi://SELF": [0],
        }
        # one pset per shared-memory domain (host process), the
        # reference's mpix:// locality psets
        by_proc: Dict[int, List[int]] = {}
        for i, d in enumerate(self.devices):
            by_proc.setdefault(getattr(d, "process_index", 0),
                               []).append(i)
        if len(by_proc) > 1:
            for pi, ranks in sorted(by_proc.items()):
                self._psets[f"mpix://shared/{pi}"] = ranks

    def _check(self) -> None:
        if self._finalized:
            raise MPIError(ERR_OTHER, "session has been finalized")

    def _next_cid(self) -> int:
        with self._cid_lock:
            return next(self._cids)

    # -- per-session config (the instance's MCA scope) -----------------
    def var_set(self, full: str, value: Any) -> None:
        """Override an MCA var for THIS session only."""
        self._check()
        self.scope.set(full, value)

    def var_get(self, full: str, default: Any = None) -> Any:
        if full in self.scope.values:
            return self.scope.values[full]
        return var.var_get(full, default)

    # -- pset enumeration ----------------------------------------------
    def get_num_psets(self) -> int:
        return len(self._psets)

    def get_nth_pset(self, n: int) -> str:
        return list(self._psets.keys())[n]

    def get_pset_info(self, name: str) -> Info:
        if name not in self._psets:
            raise MPIError(ERR_ARG, f"unknown pset {name}")
        i = Info()
        i.set("size", str(len(self._psets[name])))
        return i

    # -- group / communicator construction -----------------------------
    def group_from_pset(self, name: str) -> Group:
        self._check()
        if name not in self._psets:
            raise MPIError(ERR_ARG, f"unknown pset {name}")
        return Group(self._psets[name])

    def comm_create_from_group(self, group: Group,
                               tag: str = "",
                               info: Optional[Info] = None) -> Communicator:
        self._check()
        if self._router is not None:
            # Per-rank world: the CID must AGREE across processes, and
            # sessions are process-local objects (a rank may create
            # extra ones), so session identity CANNOT be part of it.
            # MPI-4's own matching rule for comm_create_from_group is
            # (group, tag) in collective-call order — we stamp
            # ("s", tag, group, per-(tag, group) call ordinal), which
            # every participant derives identically because the call
            # is collective over the group. Sequential same-tag calls
            # therefore get distinct channels too.
            from ompi_tpu.core.rankcomm import RankCommunicator
            if self._my_world not in group.world_ranks:
                return None
            gkey = tuple(group.world_ranks)
            with _pr_seq_lock:
                ordinal = _pr_create_seq.get((tag, gkey), 0)
                _pr_create_seq[(tag, gkey)] = ordinal + 1
            c = RankCommunicator(
                group, self._my_world, self._router,
                cid=("s", tag, gkey, ordinal),
                name=tag or f"{self.name}.comm", info=info,
                errhandler=self.errhandler)
            # the ownership list rides parent linkage: derived comms
            # (dup/split/cart/shrink) self-register so finalize
            # quiesces the whole family
            c._owner_list = self._comms
            self._comms.append(c)
            return c
        devs = [self.devices[r] for r in group.world_ranks]
        return SessionCommunicator(
            group, devs, session=self,
            name=tag or f"{self.name}.comm", info=info,
            errhandler=self.errhandler)

    def finalize(self) -> None:
        """``MPI_Session_finalize``: communicators created from the
        session must already be freed (we free them, as ERRORS_RETURN
        quality-of-implementation); releases the instance refcount."""
        if self._finalized:
            return
        for c in self._comms:
            if not c._freed:
                c.free()
        self._comms.clear()
        self._finalized = True
        _instance_release()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.finalize()
        return False
