"""ompi_mpi_init / finalize — world bring-up.

Behavioral spec: ``ompi/runtime/ompi_mpi_init.c:397`` through
``ompi/instance/instance.c:361-720``: OPAL up -> PMIx/coordination init ->
peer table -> transport selection -> modex/fence -> COMM_WORLD/SELF
creation -> per-communicator coll selection.

TPU-native re-design: the "transport" is the ICI mesh itself, reached
only through XLA; wire-up collapses to PJRT device enumeration. On a
multi-host deployment ``jax.distributed.initialize`` (the JAX
coordination service: distributed KV + barrier) stands in for PMIx
modex/fence — controlled here by MCA vars; single-host needs none. MPI
ranks bind 1:1 to mesh devices at init, exactly the north-star
requirement (rank topology bound to the device mesh).
"""
from __future__ import annotations

import os
import socket
import time
from typing import List, Optional

import jax

from ompi_tpu.core.communicator import Communicator
from ompi_tpu.core.errhandler import MPIError, ERR_OTHER
from ompi_tpu.core.group import Group
from ompi_tpu.core.info import INFO_ENV
from ompi_tpu.mca import var

THREAD_SINGLE = 0
THREAD_FUNNELED = 1
THREAD_SERIALIZED = 2
THREAD_MULTIPLE = 3

_state = {
    "initialized": False,
    "finalized": False,
    "world": None,
    "self": None,
    "thread_level": THREAD_SINGLE,
    "t0": 0.0,
}

# the parent-job intercommunicator of a spawned world (MPI_Comm_spawn
# child side); None in a directly-launched job
_parent_intercomm = None


def _register_base_vars() -> None:
    var.var_register("mpi", "base", "num_ranks", vtype="int", default=0,
                     help="Number of MPI ranks (0 = one per local device)")
    var.var_register("mpi", "base", "distributed", vtype="bool", default=False,
                     help="Call jax.distributed.initialize (multi-host "
                          "coordination service, the PMIx equivalent)")
    var.var_register("mpi", "base", "coordinator", vtype="str", default="",
                     help="coordinator_address for jax.distributed")
    var.var_register("mpi", "base", "process_id", vtype="int", default=-1,
                     help="process_id for jax.distributed (-1 = from env)")
    var.var_register("mpi", "base", "num_processes", vtype="int", default=0,
                     help="num_processes for jax.distributed (0 = from env)")
    var.var_register("mpi", "base", "per_rank", vtype="bool", default=False,
                     help="Per-rank execution model: one OS process == "
                          "one MPI rank (rank() == jax.process_index()); "
                          "pt2pt over btl/tcp, collectives over XLA or "
                          "textbook p2p algorithms")


def assert_platform_pin() -> None:
    """A sitecustomize may pin jax_platforms to a hardware plugin at
    interpreter startup, silently overriding the JAX_PLATFORMS env
    the launcher set — the rank would then wire up against the
    plugin's (shared, persistent) coordination plane instead of the
    job's own, failing with stale-key ALREADY_EXISTS / barrier
    timeouts. Re-assert the env pin before any backend use; called by
    EVERY init tier (world init here, the Init-free Sessions model in
    runtime/session.py, and the C ABI through both)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax as _jax
        try:
            _jax.config.update("jax_platforms", plat)
        except Exception:                  # noqa: BLE001 — older jax
            pass


def init(requested: int = THREAD_SINGLE,
         devices: Optional[List] = None) -> int:
    """MPI_Init / MPI_Init_thread. Returns the provided thread level."""
    if _state["initialized"]:
        raise MPIError(ERR_OTHER, "MPI already initialized")
    assert_platform_pin()
    _register_base_vars()
    # arm the lock-order witness BEFORE transport/progress bring-up so
    # endpoint locks are created wrapped; off = threading.Lock untouched
    from ompi_tpu.analyze import lockwitness as _lockwitness
    _lockwitness.maybe_install_from_var()
    from ompi_tpu.pml import stacked as _pml_stacked  # noqa: F401
    # (imports register the pml MCA vars — components register at open,
    # mca_base convention)

    if var.var_get("mpi_base_distributed", False):
        kw = {}
        coord = var.var_get("mpi_base_coordinator", "")
        if coord:
            kw["coordinator_address"] = coord
        pid = var.var_get("mpi_base_process_id", -1)
        if pid >= 0:
            kw["process_id"] = pid
        nproc = var.var_get("mpi_base_num_processes", 0)
        if nproc > 0:
            kw["num_processes"] = nproc
        try:
            # CPU backend needs a cross-process collectives transport
            # (the DCN tier the reference reaches via btl/tcp); gloo is
            # jax's host implementation. Harmless on TPU, where ICI/DCN
            # collectives are native.
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:                      # option absent: fine
            pass
        jax.distributed.initialize(**kw)       # PMIx-equivalent wire-up

    # arm the tracer when the MCA var (env/param-file) asks for it —
    # BEFORE any communicator exists, so the coll composer sees it and
    # wraps every vtable (docs/OBSERVABILITY.md)
    from ompi_tpu import trace
    trace.maybe_enable_from_var()
    # same timing contract for the telemetry plane (histogram pvars,
    # health monitor, flight recorder): armed before the composers run
    from ompi_tpu import telemetry
    telemetry.maybe_enable_from_var()

    if var.var_get("mpi_base_per_rank", False):
        return _init_per_rank(requested)

    if devices is None:
        devices = list(jax.devices())
        nr = var.var_get("mpi_base_num_ranks", 0)
        if nr and nr <= len(devices):
            devices = devices[:nr]
    n = len(devices)

    world = Communicator(Group(range(n)), devices, name="MPI_COMM_WORLD")
    self_comm = Communicator(Group([0]), [devices[0]], name="MPI_COMM_SELF")

    INFO_ENV.set("command", os.environ.get("_", ""))
    INFO_ENV.set("maxprocs", str(n))
    INFO_ENV.set("soft", str(n))
    INFO_ENV.set("host", socket.gethostname())
    INFO_ENV.set("arch", jax.devices()[0].platform)

    _state.update(initialized=True, finalized=False, world=world,
                  self=self_comm, t0=time.perf_counter(),
                  thread_level=min(requested, THREAD_MULTIPLE))
    return _state["thread_level"]


def _kv_client():
    """The coordination-service KV store (PMIx modex equivalent)."""
    from jax._src import distributed
    client = distributed.global_state.client
    if client is None:
        raise MPIError(ERR_OTHER,
                       "per-rank mode requires jax.distributed "
                       "(set mpi_base_distributed or launch via "
                       "mpirun --per-rank)")
    return client


def _init_per_rank(requested: int) -> int:
    """Per-rank world bring-up: rank() == jax.process_index(), one
    COMM_WORLD member per process, pt2pt endpoints modex'd through the
    coordination-service KV (the reference's add_procs + modex steps,
    instance.c:508-569)."""
    from ompi_tpu.core.group import Group
    from ompi_tpu.core.rankcomm import RankCommunicator
    from ompi_tpu.pml.perrank import Router

    client = _kv_client()
    rank = jax.process_index()
    nprocs = jax.process_count()
    # every span this process records carries its world rank — the
    # exporter's pid and the attribution layer's participant identity
    from ompi_tpu import trace
    trace.set_process_rank(rank)
    router = Router(rank, nprocs, client.key_value_set,
                    lambda k: client.blocking_key_value_get(k, 120_000))
    world = RankCommunicator(Group(range(nprocs)), rank, router,
                             cid="w", name="MPI_COMM_WORLD")
    self_comm = RankCommunicator(Group([rank]), rank, router,
                                 cid=("self", rank),
                                 name="MPI_COMM_SELF")
    # init fence (ompi_mpi_init.c:434-447): nobody proceeds until every
    # rank's endpoint is published; then wire every pair eagerly
    # (add_procs — also completes the failure detector's coverage).
    client.wait_at_barrier("ompi_tpu_init", 120_000)
    router.wire_up()

    # Ring heartbeat failure detector (ft/detector, docs/RESILIENCE.md):
    # off unless mpi_base_ft_hb_period > 0. Heartbeats ride the
    # UNSEQUENCED tcp ctl path — they must not consume _sq slots the
    # ordered data plane accounts for, and a wedged peer's frames
    # mustn't queue behind data. Started AFTER wire_up so the first
    # check tick finds identified connections, not connect storms.
    from ompi_tpu.ft.detector import Detector
    from ompi_tpu.runtime import ft as _ftreg

    def _send_hb(peer: int, _r=router) -> None:
        hb = {"ctl": "hb", "peer": _r.rank}
        from ompi_tpu import telemetry as _tele
        if _tele.active:
            # RTT stamp, only while telemetry is on — the receiver
            # echoes it back as "hbr" (pml/perrank Router); with the
            # plane off the frame is byte-identical to the seed's
            hb["ht"] = time.perf_counter()
        _r.endpoint.tcp.send_frame(peer, hb)

    det = Detector(rank, nprocs, _send_hb, _ftreg.default_registry())
    det.departed = lambda r, _r=router: r in _r._departed
    if det.start():
        router.detector = det

    # telemetry plane per-rank wiring (docs/OBSERVABILITY.md): the
    # straggler health monitor samples from the progress loop and the
    # pml recv ingress; the flight recorder listens for proc failures
    from ompi_tpu import telemetry as _telemetry
    if _telemetry.active:
        from ompi_tpu.telemetry import flightrec as _flightrec
        from ompi_tpu.telemetry import health as _health
        _health.install(rank, nprocs)
        _flightrec.arm(rank)

    # Staged-tier threshold modex (VERDICT r4 next #3): the staging
    # switch point is probe-earned, but the probe is timing-based and
    # the staging decision must be rank-symmetric — so rank 0 measures
    # and publishes; every rank adopts the SAME value. A user-set
    # coll_tuned_stage_min_bytes suppresses the probe (checked inside
    # stage_min_for too; the skip here just avoids the measurement).
    from ompi_tpu.coll import tuned as _tuned
    if not var.var_overridden("coll_tuned_stage_min_bytes"):
        import json as _json
        key = "ompi_tpu/coll/stage_probe"
        if rank == 0:
            try:
                pb = dict(getattr(router.endpoint, "probe_basis",
                                  {}) or {})
                g = None
                if pb.get("ran"):
                    g = (pb.get("sm_gbps") if not pb.get("sm_demoted")
                         else pb.get("tcp_gbps"))
                if not g:
                    # routing probe suppressed (sm disabled or user-set
                    # btl_sm_min_bytes) — the tcp half still measured
                    # the wire, and a host tier modeled with NO
                    # transport cost routed 8 MB against its own A/B
                    # (the r08 tcp route_ok break)
                    g = pb.get("rail_gbps")
                bps = g * 1e9 if g else None
                value, basis = _tuned.staging_probe(
                    transport_bps=bps, nranks=nprocs)
            except Exception:            # noqa: BLE001 — advisory
                value, basis = 1 << 20, {"ran": False, "error": True}
            client.key_value_set(key, _json.dumps({"v": value, **basis}))
        blob = client.blocking_key_value_get(key, 120_000)
        if isinstance(blob, bytes):
            blob = blob.decode()
        d = _json.loads(blob)
        _tuned.adopt_probed_stage_min(int(d.pop("v")), d)

    INFO_ENV.set("command", os.environ.get("_", ""))
    INFO_ENV.set("maxprocs", str(nprocs))
    INFO_ENV.set("host", socket.gethostname())
    INFO_ENV.set("arch", jax.devices()[0].platform)

    _state.update(initialized=True, finalized=False, world=world,
                  self=self_comm, router=router, t0=time.perf_counter(),
                  thread_level=min(requested, THREAD_MULTIPLE))

    # Spawned world: dial back to the parent job through the dpm port
    # plane (MPI_Comm_spawn's PMIx parent-nspace handshake over this
    # runtime's coordination plane); MPI_Comm_get_parent returns the
    # resulting intercommunicator (dpm.c:108-170, comm_get_parent
    # .c.in).
    parent_port = os.environ.get("OMPI_TPU_PARENT_PORT")
    if parent_port:
        from ompi_tpu.core import dpm_perrank as _dpm
        global _parent_intercomm
        _parent_intercomm = _dpm.comm_connect(parent_port, world,
                                              root=0)
    return _state["thread_level"]


def finalize() -> None:
    if not _state["initialized"] or _state["finalized"]:
        raise MPIError(ERR_OTHER, "MPI not initialized or already finalized")
    # Drain async work so "all communication is complete at finalize".
    # With known-dead peers the drain barrier can never complete (a
    # live peer may itself be blocked on the dead one): skip it.
    from ompi_tpu.runtime import ft as _ftmod
    try:
        w = _state["world"]
        if w is not None and not w._freed and not _ftmod.any_failed():
            w.barrier()
    except Exception:
        pass
    # telemetry teardown first: the health monitor's progress callback
    # and the flight recorder's registry listener must not outlive the
    # world they observe
    from ompi_tpu import telemetry as _telemetry
    try:
        _telemetry.shutdown()
    except Exception:                # noqa: BLE001
        pass
    router = _state.pop("router", None)
    if router is not None:
        router.begin_shutdown()      # later EOFs are teardown, not death
        from ompi_tpu.runtime import ft as _ft
        if not _ft.any_failed():     # a dead rank can never reach the
            try:                     # fini fence; survivors skip it
                _kv_client().wait_at_barrier("ompi_tpu_fini", 120_000)
            except Exception:
                pass
        router.close()
        # drop the device-transfer plane with the router: connections,
        # the server, and any unpulled registrations (a stale server
        # address must never leak into a later job's modex)
        from ompi_tpu.btl import devxfer
        devxfer.reset()
    _state["finalized"] = True
    _state["world"] = None
    _state["self"] = None


def initialized() -> bool:
    return _state["initialized"]


def finalized() -> bool:
    return _state["finalized"]


def query_thread() -> int:
    return _state["thread_level"]


def comm_world() -> Communicator:
    if not _state["initialized"] or _state["finalized"]:
        raise MPIError(ERR_OTHER, "MPI is not active (call Init first)")
    return _state["world"]


def comm_self() -> Communicator:
    if not _state["initialized"] or _state["finalized"]:
        raise MPIError(ERR_OTHER, "MPI is not active (call Init first)")
    return _state["self"]


def wtime() -> float:
    return time.perf_counter()


def wtick() -> float:
    return 1e-9


def processor_name() -> str:
    d = jax.devices()[0]
    return f"{socket.gethostname()}/{d.platform}:{d.id}"


def _reset_for_tests() -> None:
    _state.update(initialized=False, finalized=False, world=None, self=None)
    from ompi_tpu.runtime import ft
    ft._reset_for_tests()
