"""The progress engine — mirrors ``opal/runtime/opal_progress.c``.

Reference behavior: a flat array of registered callbacks
(``opal_progress.c:58-65``) spun by every blocking wait (``:216``); a
low-priority list for rarely-needed progress; an event counter so idle
detection can yield.

TPU-native re-design: XLA execution progresses without host help, so the
engine's remaining job is exactly what libnbc used it for — advancing
*software-pipelined schedules* (round-by-round collective dispatch) and
any other host-side state machine. ``progress()`` runs every registered
callback once and returns the number of events they reported; blocking
waits on schedule-backed requests spin it.
"""
from __future__ import annotations

import threading
from typing import Callable, List

from ompi_tpu.trace import core as _trace

_callbacks: List[Callable[[], int]] = []
_low_priority: List[Callable[[], int]] = []
_low_tick = 0
_LOW_EVERY = 8          # low-priority cbs run every Nth spin (opal's idea)


def register(cb: Callable[[], int], low_priority: bool = False) -> None:
    (_low_priority if low_priority else _callbacks).append(cb)


def unregister(cb: Callable[[], int]) -> None:
    for lst in (_callbacks, _low_priority):
        if cb in lst:
            lst.remove(cb)


def progress() -> int:
    """One spin: run every callback, return total events produced."""
    global _low_tick
    events = 0
    for cb in list(_callbacks):
        events += int(cb() or 0)
    _low_tick += 1
    if _low_priority and _low_tick % _LOW_EVERY == 0:
        for cb in list(_low_priority):
            events += int(cb() or 0)
    return events


def callback_count() -> int:
    return len(_callbacks) + len(_low_priority)


# ---------------------------------------------------------------------
# Wakeup coalescing — the small-message control plane's second tax.
#
# Before: every delivered frame that completed a match fired its own
# ``Event.set`` from the btl reader thread, so a burst of N frames cost
# N cross-thread wakes, each one inviting the scheduler to preempt the
# still-draining reader (a GIL convoy measured as the gap between the
# two 8 B allreduce rows on the round-5 record). Now: delivery loops
# open a *wake batch*; completions inside the batch are deferred and
# deduplicated by Event identity, and ONE flush at batch end services
# every completed match in the reorder buffer. Batches nest (the sm
# ring drain runs inside the bml's ordered drain); only the outermost
# ``wake_end`` flushes. Outside any batch, ``wake`` degrades to an
# immediate ``Event.set`` — isolated frames keep their latency.
#
# Counters ride the MPI_T pvar plumbing (``mca/pvar.py``):
# ``pml_wakeups`` (flushed Event.set calls), ``pml_completions``
# (matches completed), ``pml_frames_delivered`` (frames that crossed a
# delivery loop), and the derived ``pml_frames_per_wakeup``.
# ---------------------------------------------------------------------

_wake_tls = threading.local()
_wake_lock = threading.Lock()
_wake_stats = {"wakeups": 0, "completions": 0, "frames": 0,
               "batches": 0}


def wake_begin() -> None:
    """Open (or nest into) this thread's wake batch."""
    depth = getattr(_wake_tls, "depth", 0)
    if depth == 0:
        _wake_tls.events = {}
        _wake_tls.frames = 0
        _wake_tls.completions = 0
    _wake_tls.depth = depth + 1


def wake_note_frame(n: int = 1) -> None:
    """Account ``n`` delivered frames against the active batch (or
    directly against the totals when no batch is open)."""
    if getattr(_wake_tls, "depth", 0):
        _wake_tls.frames += n
    else:
        with _wake_lock:
            _wake_stats["frames"] += n


def wake(event: "threading.Event") -> None:
    """Complete a waiter: defer into the active batch, or set now.
    Setting an already-set Event is idempotent, so double wakes across
    batch boundaries are harmless."""
    if getattr(_wake_tls, "depth", 0):
        _wake_tls.events[id(event)] = event
        _wake_tls.completions += 1
        return
    event.set()
    with _wake_lock:
        _wake_stats["wakeups"] += 1
        _wake_stats["completions"] += 1


def wake_end() -> None:
    """Close the batch; the outermost close flushes every deferred
    wake exactly once."""
    depth = getattr(_wake_tls, "depth", 0)
    if depth > 1:
        _wake_tls.depth = depth - 1
        return
    _wake_tls.depth = 0
    events = getattr(_wake_tls, "events", {})
    frames = getattr(_wake_tls, "frames", 0)
    completions = getattr(_wake_tls, "completions", 0)
    _wake_tls.events = {}
    for ev in events.values():
        ev.set()
    with _wake_lock:
        _wake_stats["wakeups"] += len(events)
        _wake_stats["completions"] += completions
        _wake_stats["frames"] += frames
        _wake_stats["batches"] += 1
    # timeline marker for the coalescing win: one instant per flushed
    # batch; free when tracing is off (one attribute read)
    if (events or frames) and _trace.active:
        _trace.instant("pml_wakeup_flush", wakeups=len(events),
                       completions=completions, frames=frames)


def wake_stats() -> dict:
    with _wake_lock:
        return dict(_wake_stats)


def _wake_reset_for_tests() -> None:
    with _wake_lock:
        for k in _wake_stats:
            _wake_stats[k] = 0


def _frames_per_wakeup() -> float:
    s = wake_stats()
    return round(s["frames"] / max(s["wakeups"], 1), 3)


def _register_wake_pvars() -> None:
    from ompi_tpu.mca import pvar
    pvar.pvar_register(
        "pml_wakeups", lambda: wake_stats()["wakeups"],
        help="Cross-thread Event.set calls flushed by the delivery "
             "path (coalesced: one per drain batch, not per frame)")
    pvar.pvar_register(
        "pml_completions", lambda: wake_stats()["completions"],
        help="Matches/acks completed by the delivery path")
    pvar.pvar_register(
        "pml_frames_delivered", lambda: wake_stats()["frames"],
        help="Frames that crossed a btl delivery loop")
    pvar.pvar_register(
        "pml_frames_per_wakeup", _frames_per_wakeup, unit="ratio",
        var_class="level",
        help="Delivered frames per flushed wakeup — the wakeup-"
             "coalescing win (1.0 == one wake per frame)")


_register_wake_pvars()


def _reset_for_tests() -> None:
    global _low_tick
    _callbacks.clear()
    _low_priority.clear()
    _low_tick = 0
