"""The progress engine — mirrors ``opal/runtime/opal_progress.c``.

Reference behavior: a flat array of registered callbacks
(``opal_progress.c:58-65``) spun by every blocking wait (``:216``); a
low-priority list for rarely-needed progress; an event counter so idle
detection can yield.

TPU-native re-design: XLA execution progresses without host help, so the
engine's remaining job is exactly what libnbc used it for — advancing
*software-pipelined schedules* (round-by-round collective dispatch) and
any other host-side state machine. ``progress()`` runs every registered
callback once and returns the number of events they reported; blocking
waits on schedule-backed requests spin it.
"""
from __future__ import annotations

from typing import Callable, List

_callbacks: List[Callable[[], int]] = []
_low_priority: List[Callable[[], int]] = []
_low_tick = 0
_LOW_EVERY = 8          # low-priority cbs run every Nth spin (opal's idea)


def register(cb: Callable[[], int], low_priority: bool = False) -> None:
    (_low_priority if low_priority else _callbacks).append(cb)


def unregister(cb: Callable[[], int]) -> None:
    for lst in (_callbacks, _low_priority):
        if cb in lst:
            lst.remove(cb)


def progress() -> int:
    """One spin: run every callback, return total events produced."""
    global _low_tick
    events = 0
    for cb in list(_callbacks):
        events += int(cb() or 0)
    _low_tick += 1
    if _low_priority and _low_tick % _LOW_EVERY == 0:
        for cb in list(_low_priority):
            events += int(cb() or 0)
    return events


def callback_count() -> int:
    return len(_callbacks) + len(_low_priority)


def _reset_for_tests() -> None:
    global _low_tick
    _callbacks.clear()
    _low_priority.clear()
    _low_tick = 0
