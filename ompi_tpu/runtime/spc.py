"""SPC — software performance counters.

Mirrors ``ompi/runtime/ompi_spc.h:47-159`` (~110 counters recorded via
SPC_RECORD macros in hot paths, surfaced as MPI_T pvars). Here: a flat
counter table keyed by name, recorded from the collective/pt2pt entry
points, surfaced through ``ompi_tpu.mca.pvar`` and the info tool.

Sharding (the tracing + SPC coexistence fix): ``record`` used to take
one process-global lock on every hot-path increment, serializing the
btl reader threads against the app thread precisely on the paths the
trace subsystem also observes. Counters are now sharded per thread —
each thread increments its own plain dict (no lock, GIL-atomic per
op); readers (``read``/``snapshot``) merge the base table with every
shard under the lock. ``write`` (MPI_T_pvar_write resets) adjusts the
BASE so the merged view equals the requested value without mutating
another thread's shard mid-increment.
"""
from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List

from ompi_tpu.mca import var

_lock = threading.Lock()
# merged-view base: written values and (on reset) the zero point
_base: Dict[str, int] = defaultdict(int)
# every live thread shard, for the readers to merge; threads register
# their shard once (bounded by thread count — reader/ctl threads are
# long-lived daemons, this does not accrete)
_shards: List[Dict[str, int]] = []
_tls = threading.local()
_enabled = None


def _on() -> bool:
    global _enabled
    if _enabled is None:
        _enabled = bool(var.var_register(
            "mpi", "base", "spc_enable", vtype="bool", default=True,
            help="Enable software performance counters"))
    return _enabled


def record(name: str, value: int = 1) -> None:
    """Hot path: one TLS fetch + one dict increment, no lock."""
    if not _on():
        return
    d = getattr(_tls, "d", None)
    if d is None:
        d = _tls.d = defaultdict(int)
        with _lock:
            _shards.append(d)
    d[name] += value


def _merged(name: str) -> int:
    # caller holds _lock
    return _base.get(name, 0) + sum(s.get(name, 0) for s in _shards)


def read(name: str) -> int:
    with _lock:
        return _merged(name)


def write(name: str, value: int) -> None:
    """Set a counter outright (MPI_T_pvar_write backing; tools reset
    watermarks this way). Implemented as a base adjustment so no other
    thread's shard is mutated under its feet."""
    with _lock:
        _base[name] = int(value) - sum(s.get(name, 0) for s in _shards)


def snapshot() -> Dict[str, int]:
    with _lock:
        out: Dict[str, int] = dict(_base)
        for s in _shards:
            for k, v in list(s.items()):
                out[k] = out.get(k, 0) + v
        return out


def reset() -> None:
    global _enabled
    with _lock:
        _base.clear()
        for s in _shards:
            s.clear()
    _enabled = None
